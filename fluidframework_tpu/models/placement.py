"""Unified placement plane: doc -> device-slot indirection shared by both
engine families.

Both batched engines (``DocBatchEngine`` for strings,
``TreeBatchEngine`` for trees) serve D documents out of a sharded
``[capacity, ...]`` device state, where ``capacity`` rounds the fleet up
to a mesh multiple plus ``spare_slots`` reserved free rows.  Everything
placement-shaped about that arrangement used to live inside the string
engine only; this module is the lift:

- ``PlacementPlane`` — the doc -> slot map with per-shard spare-slot free
  pools.  Docs distribute in contiguous blocks over ALL shards (identity
  when there are no spare slots), so each engine's staging buffer is
  packed by doc placement and a shard-layout ``device_put`` splits it per
  chip; spare slots spread across shards as the free pool a live
  ``migrate_doc`` lands in.  The plane owns the reserve/commit/release
  protocol of a move; the engines own the checkpoint-codec handoff of the
  row itself (``state_to_summary -> summary_to_state`` for strings,
  trunk-fold -> re-materialization for trees).
- ``rebalance_hot_shards`` / ``hot_shards`` / ``shard_load`` — the
  engine-agnostic hot-shard detection + move-selection skeleton.
- ``adopt_boot_snapshot`` — the client half of the fan-out plane's
  ``{"t":"resync","boot":true}`` contract, riding each engine's refresh
  re-seed path; returns an ``AdoptResult`` so consumers can distinguish
  "adopted, consume from the new floor" from "refused below floor, fall
  to the supervisor" (the old int return conflated them, which is how the
  tree fleet's no-op adoption silently looked healthy).
- ``restore_candidates`` — the shared scan guard of
  ``restore_from_checkpoints(refresh=...)`` (first-boot vs warm-standby
  trailing vs in-place re-seed of an already-adopted doc).

Locking: ``PlacementPlane._lock`` is a LEAF lock — it guards only the
slot map and free pools, is held for pure bookkeeping, and never wraps an
engine call, a device dispatch, or I/O (declared in
``analysis/layers.json`` so fftpu-check's blocking-under-lock pass
enforces exactly that).  Engines serialize whole migrations under their
own ``ckpt_lock``; the plane lock additionally keeps the map consistent
for lock-free readers (health snapshots, placement exports).
"""

from __future__ import annotations

import threading
from typing import Callable, NamedTuple

import numpy as np

from ..observability.flight_recorder import instant

__all__ = [
    "AdoptResult",
    "OneRecordStore",
    "PlacementError",
    "PlacementPlane",
    "adopt_boot_snapshot",
    "hot_shards",
    "rebalance_hot_shards",
    "restore_candidates",
    "shard_load",
]


class PlacementError(RuntimeError):
    """A doc cannot migrate because it is pinned off the batch path.

    Raised LOUDLY (not a False return) for docs parked in a parallel lane
    (segment-sharded or overflow strings, fallback-routed trees): their
    serving state lives outside the doc's fleet slot, so a slot handoff
    would silently strand it.  Callers must drain/demote the doc back
    onto the batch path first."""


class AdoptResult(NamedTuple):
    """Outcome of ``adopt_boot_snapshot``.

    ``adopted``
        True when the record re-seeded the doc; the consumer re-subscribes
        from ``floor`` (the record's seq).  False when the record was at
        or below the doc's applied floor — the snapshot cannot help, and
        since the server already declared the consumer's range gone, a
        re-subscribe from the doc's own floor would just draw another
        boot marker: fall to the supervisor path instead.
    ``floor``
        The doc's applied seq floor after the call.
    """

    adopted: bool
    floor: int


class OneRecordStore:
    """A single-record checkpoint 'store': the adapter that lets one
    historian snapshot ride the engines' normal ``_restore`` machinery
    (lanes, quorum, prop/mark tables and the replay floor all reset
    through the one audited path)."""

    def __init__(self, key: str, record: dict) -> None:
        self._key = key
        self._record = record

    def load(self, doc_id: str):
        return self._record if doc_id == self._key else None


class PlacementPlane:
    """doc -> slot indirection with per-shard spare-slot free pools."""

    def __init__(self, n_docs: int, n_shards: int, spare_slots: int = 0) -> None:
        if n_shards < 1:
            raise ValueError(f"n_shards must be >= 1, got {n_shards}")
        if spare_slots < 0:
            raise ValueError(f"spare_slots must be >= 0, got {spare_slots}")
        self.n_docs = n_docs
        self.n_shards = n_shards
        self.spare_slots = spare_slots
        # Device capacity rounds up to a mesh multiple (padding slots are
        # inert: empty queues only ever apply noops); ``spare_slots``
        # reserves extra free rows beyond the fleet so live migration
        # always has landing slots on every shard.
        self.capacity = -(-(n_docs + spare_slots) // n_shards) * n_shards
        self.docs_per_shard = self.capacity // n_shards
        self._lock = threading.Lock()
        per = -(-n_docs // n_shards)  # docs per shard at construction
        self._slot = np.array(
            [
                (d // per) * self.docs_per_shard + (d % per)
                for d in range(n_docs)
            ],
            dtype=np.int64,
        )
        used = set(map(int, self._slot))
        self._free_slots: dict[int, list[int]] = {
            s: [] for s in range(n_shards)
        }
        for slot in range(self.capacity):
            if slot not in used:
                self._free_slots[slot // self.docs_per_shard].append(slot)

    # --------------------------------------------------------------- queries
    def slot(self, doc_idx: int) -> int:
        return int(self._slot[doc_idx])

    @property
    def slots(self) -> np.ndarray:
        """The live doc -> slot array (engines alias it for hot-path
        packing; treat as read-only outside the plane)."""
        return self._slot

    def shard_of(self, doc_idx: int) -> int:
        """The mesh shard currently hosting this doc's device row."""
        return int(self._slot[doc_idx]) // self.docs_per_shard

    def placement(self, doc_keys: list[str]) -> dict[str, int]:
        """doc key -> mesh shard: the summary-ownership alignment surface
        (server.partition_manager.ScribePool.align_to_placement)."""
        return {doc_keys[d]: self.shard_of(d) for d in range(self.n_docs)}

    def free_slots(self, shard: int) -> int:
        return len(self._free_slots[shard])

    # ----------------------------------------------------------------- moves
    def validate(self, doc_idx: int, dst_shard: int) -> None:
        if not (0 <= dst_shard < self.n_shards):
            raise ValueError(
                f"no shard {dst_shard} in a {self.n_shards}-shard mesh"
            )
        if not (0 <= doc_idx < self.n_docs):
            raise ValueError(f"no doc {doc_idx}")

    def require_migratable(self, doc_idx: int, lane: str | None) -> None:
        """The loud precondition of every migration: a doc pinned to a
        parallel lane must drain/demote before its slot may hand off."""
        if lane is not None:
            raise PlacementError(
                f"doc {doc_idx} is pinned to the {lane} lane; drain or "
                "demote it back onto the batch path before migrating"
            )

    def reserve(self, doc_idx: int, dst_shard: int) -> tuple[int, int] | None:
        """Claim a free destination slot for a move: -> (src_slot,
        dst_slot), or None when the doc already lives on ``dst_shard`` or
        the destination pool is empty.  The reservation must be resolved
        with ``commit`` (slot map flips, src slot retires to its pool) or
        ``release`` (handoff failed, dst slot returns to its pool)."""
        self.validate(doc_idx, dst_shard)
        with self._lock:
            src_slot = int(self._slot[doc_idx])
            if src_slot // self.docs_per_shard == dst_shard:
                return None
            pool = self._free_slots[dst_shard]
            if not pool:
                return None
            return src_slot, pool.pop()

    def commit(self, doc_idx: int, src_slot: int, dst_slot: int) -> None:
        with self._lock:
            self._slot[doc_idx] = dst_slot
            self._free_slots[src_slot // self.docs_per_shard].append(src_slot)

    def release(self, dst_slot: int) -> None:
        with self._lock:
            self._free_slots[dst_slot // self.docs_per_shard].append(dst_slot)


# --------------------------------------------------------------------------
# Engine-agnostic orchestration (both engines delegate here).
# --------------------------------------------------------------------------

def shard_load(engine) -> tuple[np.ndarray, np.ndarray]:
    """Per-shard (applied ops since the last ``hot_shards`` reset,
    currently queued ops) — host-side accounting only, no device
    readback."""
    depth = np.zeros((engine.n_shards,), np.int64)
    for d in range(engine.n_docs):
        q = len(engine.hosts[d].queue)
        if q:
            depth[engine.shard_of(d)] += q
    return engine._shard_ops.copy(), depth


def hot_shards(engine, factor: float = 2.0, reset: bool = False,
               load=None) -> list[int]:
    """Shards whose load (applied + queued ops) exceeds ``factor`` x the
    fleet mean — the live-migration trigger.  ``reset`` zeroes the
    applied-op counters so the next window measures fresh traffic;
    callers that already hold a ``shard_load()`` result pass its sum as
    ``load`` to skip the O(n_docs) rewalk."""
    if load is None:
        ops, depth = engine.shard_load()
        load = ops + depth
    if reset:
        engine._shard_ops[:] = 0
    if engine.n_shards <= 1 or not load.any():
        return []
    mean = float(load.mean())
    return [int(s) for s in np.flatnonzero(load > factor * mean)]


def rebalance_hot_shards(
    engine,
    plane: PlacementPlane,
    factor: float = 2.0,
    max_moves: int = 1,
    *,
    in_lane: Callable[[int], bool],
    promote_hot_doc: Callable[[int], bool] | None = None,
) -> list[tuple[int, int, int]]:
    """Detect hot shards and live-migrate their deepest-queued docs to
    the coldest shards with free slots (one checkpoint-codec handoff per
    move — the engine's ``migrate_doc``).  Returns the ``(doc, src_shard,
    dst_shard)`` moves made; callers re-align the scribe pool afterwards
    (``ScribePool.align_to_placement``) so summary ownership follows the
    docs.

    Hysteresis: a doc whose OWN queue exceeds ``factor`` x the fleet mean
    IS the hotspot — migrating it just moves the hot shard (and would
    ping-pong it every interval, paying a full handoff each time).  Such
    docs are the hot-document-parallelism problem, not a placement
    problem; with ``promote_hot_doc`` provided (the string engine's
    segment-parallel promotion) the doc is promoted instead and appears
    in the result with ``dst_shard == -1`` (its placement slot stays
    reserved)."""
    ops, depth = engine.shard_load()
    load = ops + depth
    hot = engine.hot_shards(factor, reset=True, load=load)
    if not hot:
        return []
    mean = float(load.mean())
    moves: list[tuple[int, int, int]] = []
    for s in hot:
        if len(moves) >= max_moves:
            break
        candidates = [
            d for d in range(engine.n_docs)
            if engine.shard_of(d) == s and not in_lane(d)
            and len(engine.hosts[d].queue) <= factor * mean
        ]
        if not candidates:
            engine.counters.bump("hot_shard_moves_skipped")
            # The skipped case IS the hot-document problem: a doc whose
            # own queue exceeds the fleet mean cannot be placed away.
            if promote_hot_doc is not None:
                hot_docs = sorted(
                    (
                        d for d in range(engine.n_docs)
                        if engine.shard_of(d) == s and not in_lane(d)
                        and len(engine.hosts[d].queue) > factor * mean
                    ),
                    key=lambda dd: -len(engine.hosts[dd].queue),
                )
                for d in hot_docs:
                    if promote_hot_doc(d):
                        moves.append((d, s, -1))
                        break
            continue
        d = max(candidates, key=lambda dd: len(engine.hosts[dd].queue))
        for dst in map(int, np.argsort(depth)):
            if dst == s or not plane.free_slots(dst):
                continue
            if engine.migrate_doc(d, dst):
                depth[dst] += len(engine.hosts[d].queue)
                moves.append((d, s, dst))
                break
    if moves:
        engine.counters.bump("hot_shard_rebalances", len(moves))
        instant("rebalance", moves=len(moves), hot_shards=len(hot))
    return moves


def adopt_boot_snapshot(
    engine,
    doc_idx: int,
    record: dict,
    clear_staged: Callable[[int], None],
) -> AdoptResult:
    """Client half of the fan-out plane's ``{"t":"resync","boot":true}``
    contract: a consumer that fell off the retained log re-seeds the
    document from a historian snapshot record and re-consumes from the
    returned floor.  Staged pre-gap work is dropped up front
    (``clear_staged`` — the refresh guard refuses docs with pending ops,
    but a boot resync REPLACES the doc), and the adoption rides the
    engine's refresh re-seed path, so lanes, quorum/trunk windows and the
    replay floor all reset consistently.

    Returns ``AdoptResult(adopted=False, floor=...)`` for a record at or
    below the doc's applied floor (see AdoptResult for why the caller
    must NOT just re-subscribe), and raises ``ValueError`` for a record
    the engine cannot load at all (engine mismatch / schema drift) — the
    supervisor-restart path."""
    with engine.ckpt_lock:
        h = engine.hosts[doc_idx]
        seq = int(record["seq"])
        if seq <= h.last_seq:
            engine.counters.bump("boot_snapshots_stale")
            return AdoptResult(False, h.last_seq)
        clear_staged(doc_idx)
        key = engine.doc_keys[doc_idx]
        adopted = engine._restore(
            OneRecordStore(key, record), parallel=False, max_workers=None,
            refresh=True,
        )
        if doc_idx not in adopted:
            # The record was unusable: fail LOUDLY — returning a stale
            # floor would send the consumer back to a range the server
            # already declared gone, an infinite resync loop that looks
            # healthy.
            raise ValueError(
                f"boot snapshot for doc {key!r} not adoptable "
                f"(engine={record.get('engine')!r})"
            )
        engine.counters.bump("boot_snapshots_adopted")
        return AdoptResult(True, h.last_seq)


def restore_candidates(
    engine, store, refresh: bool, staged_depth: Callable[[int], int],
) -> tuple[list[int], dict[int, float]]:
    """The shared scan guard of ``restore_from_checkpoints``: which docs
    are candidates for (re-)adoption this pass, and the record mtimes to
    stamp after a successful load.

    - First boot (``refresh=False``): every doc not yet restored.
    - Trailing/refresh: already-restored docs stay candidates (the
      in-place re-seed path — the engine skips any whose record is not
      strictly newer), docs with staged work are skipped (trailing never
      races serving), and unchanged record files skip via one mtime stat
      per doc instead of a record re-read."""
    candidates: list[int] = []
    cand_mtime: dict[int, float] = {}
    for d in range(engine.n_docs):
        h = engine.hosts[d]
        if h.restored and not refresh:
            continue
        if refresh and staged_depth(d):
            continue
        if refresh:
            # Stamped as seen only after a successful load — a transient
            # read failure must not permanently exclude the doc.
            mt = getattr(store, "mtime", lambda _k: None)(
                engine.doc_keys[d]
            )
            if mt is not None and engine._trail_mtime.get(d) == mt:
                continue
            if mt is not None:
                cand_mtime[d] = mt
        candidates.append(d)
    return candidates, cand_mtime
