"""Metrics plane: Prometheus-text ``/metrics`` + JSON ``/status`` serving.

A ``MetricsPlane`` aggregates any number of named sources — callables
returning plain dicts (engine ``health()``, fleet transport counters,
scribe pool state, ordered-log depths) whose leaves may be numbers, bools,
lists of numbers (rendered as one labeled series per index, e.g. per-shard
queue depth), or ``utils.telemetry.Histogram`` instances (rendered as
summary-style quantile series plus ``_count``/``_sum``).  Non-numeric
leaves appear in ``/status`` (full JSON) but are skipped by ``/metrics``.

``MetricsServer`` is a tiny ThreadingHTTPServer exposing the plane at
``GET /metrics`` (Prometheus text exposition format 0.0.4) and
``GET /status`` (the raw aggregate as JSON) — a soak run becomes
inspectable live with ``curl``, no debugger attached.  ``fleet_main
--metrics-port`` serves one per fleet member; ``netserver`` mounts the
same routes on its HTTP front for the ordering tier.
"""

from __future__ import annotations

import json
import re
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable

_NAME_RE = re.compile(r"[^a-zA-Z0-9_]")
_QUANTILES = (0.5, 0.9, 0.99)


def _metric_name(*parts: str) -> str:
    name = "_".join(_NAME_RE.sub("_", p).strip("_") for p in parts if p)
    return f"fftpu_{name}"


def _is_histogram(v: Any) -> bool:
    # Duck-typed: anything with record/percentile/count quacks like
    # utils.telemetry.Histogram (avoids an import cycle with utils).
    return (
        hasattr(v, "percentile") and hasattr(v, "count") and hasattr(v, "sum")
    )


def render_prometheus(tree: dict[str, Any]) -> str:
    """Flatten a nested dict of metric leaves into Prometheus text.

    Nested dict keys join with ``_``; numeric lists become one series per
    index with an ``idx`` label; histograms render as quantile series.
    """
    lines: list[str] = []

    def emit(name: str, value: Any, labels: str = "") -> None:
        # repr, not '%g': 6-significant-digit formatting would quantize
        # counters past ~1e6 (rate() over scrapes would plateau + spike).
        lines.append(f"{name}{labels} {float(value)!r}")

    def walk(prefix: tuple[str, ...], node: Any) -> None:
        if isinstance(node, dict):
            for k in sorted(node):
                walk(prefix + (str(k),), node[k])
            return
        name = _metric_name(*prefix)
        if _is_histogram(node):
            for q in _QUANTILES:
                p = node.percentile(q)
                if p is not None:
                    emit(name, p, f'{{quantile="{q:g}"}}')
            emit(f"{name}_count", node.count)
            emit(f"{name}_sum", node.sum)
            return
        if isinstance(node, bool):
            emit(name, int(node))
            return
        if isinstance(node, (int, float)):
            emit(name, node)
            return
        if isinstance(node, (list, tuple)) and all(
            isinstance(v, (int, float)) and not isinstance(v, bool)
            for v in node
        ):
            for i, v in enumerate(node):
                emit(name, v, f'{{idx="{i}"}}')
            return
        # Non-numeric leaf (strings, mixed lists): /status carries it.

    walk((), tree)
    return "\n".join(lines) + "\n" if lines else ""


def parse_prometheus(text: str) -> dict[tuple[str, tuple[tuple[str, str], ...]], float]:
    """Parse the exposition text back into ``{(name, labels): value}`` —
    the round-trip half the tests (and any scraper) rely on."""
    out: dict[tuple[str, tuple[tuple[str, str], ...]], float] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        m = re.match(
            r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(?:\{([^}]*)\})?\s+(\S+)$", line
        )
        if m is None:
            raise ValueError(f"unparseable metrics line: {line!r}")
        name, raw_labels, value = m.groups()
        labels: list[tuple[str, str]] = []
        if raw_labels:
            for part in raw_labels.split(","):
                k, _eq, v = part.partition("=")
                labels.append((k.strip(), v.strip().strip('"')))
        out[(name, tuple(sorted(labels)))] = float(value)
    return out


def _status_jsonable(node: Any) -> Any:
    """The /status view: histograms summarize to their percentile dict,
    everything else passes through json-encodable or repr-falls-back."""
    if isinstance(node, dict):
        return {str(k): _status_jsonable(v) for k, v in node.items()}
    if isinstance(node, (list, tuple)):
        return [_status_jsonable(v) for v in node]
    if _is_histogram(node):
        return node.snapshot()
    if isinstance(node, (str, int, float, bool)) or node is None:
        return node
    return repr(node)


class MetricsPlane:
    """Named metric sources aggregated into one scrapeable surface."""

    def __init__(self) -> None:
        self._sources: dict[str, Callable[[], dict[str, Any]]] = {}
        self._lock = threading.Lock()

    def register(self, name: str, fn: Callable[[], dict[str, Any]]) -> None:
        with self._lock:
            self._sources[name] = fn

    def collect(self) -> dict[str, Any]:
        """One aggregate tree: ``{source_name: source_dict}``.  A failing
        source reports its error instead of sinking the whole scrape."""
        with self._lock:
            sources = dict(self._sources)
        out: dict[str, Any] = {}
        for name, fn in sources.items():
            try:
                out[name] = fn()
            except Exception as e:  # noqa: BLE001 — scrape must stay up
                out[name] = {"scrape_error": repr(e)[-200:]}
        return out

    def metrics_text(self) -> str:
        return render_prometheus(self.collect())

    def status_json(self) -> str:
        return json.dumps(_status_jsonable(self.collect()))


class _MetricsHandler(BaseHTTPRequestHandler):
    def log_message(self, *a) -> None:  # quiet
        pass

    def do_GET(self) -> None:  # noqa: N802
        plane: MetricsPlane = self.server.plane  # type: ignore[attr-defined]
        path = self.path.split("?", 1)[0].rstrip("/") or "/"
        if path == "/metrics":
            body = plane.metrics_text().encode()
            ctype = "text/plain; version=0.0.4; charset=utf-8"
        elif path == "/status":
            body = plane.status_json().encode()
            ctype = "application/json"
        else:
            body = b'{"error": "routes: /metrics, /status"}'
            self.send_response(404)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            return
        self.send_response(200)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)


class MetricsServer:
    """``/metrics`` + ``/status`` over one MetricsPlane (port 0 = ephemeral)."""

    def __init__(self, plane: MetricsPlane, port: int = 0,
                 host: str = "127.0.0.1") -> None:
        self.plane = plane
        self._http = ThreadingHTTPServer((host, port), _MetricsHandler)
        self._http.plane = plane  # type: ignore[attr-defined]
        self.port = self._http.server_address[1]
        self._thread = threading.Thread(
            target=self._http.serve_forever, daemon=True
        )

    def start(self) -> "MetricsServer":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._http.shutdown()
        self._http.server_close()
