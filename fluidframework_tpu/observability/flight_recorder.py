"""Flight recorder: a low-overhead fixed-size ring of trace events.

The serving path (ingest -> staging upload -> megastep dispatch ->
error-latch readback, plus scribe fold/summarize/ack, checkpoint writes,
and migration events) brackets its phases with ``span(name, **labels)``
and drops point events with ``instant(name, **labels)``.  While no
recorder is installed both are no-ops costing one module-global read —
the instrumentation can stay compiled into the hot path permanently.

Events live in a preallocated ring (old events overwrite, ``dropped``
counts what fell off) and export to Chrome trace-event JSON ("X" complete
events + "i" instants), which Perfetto and chrome://tracing load
directly.  Timestamps are ``time.perf_counter_ns()`` (monotonic, one
clock for every thread of the process), so span nesting is exact within a
thread and cross-thread ordering is meaningful within the process.

A ``RecompileWatchdog`` registers named jitted programs and polls their
executable-cache sizes (``_cache_size``): growth after the first dispatch
means a program shape de-specialized (new geometry, a de-specializing
megastep trace) and paid an XLA compile mid-run — each growth bumps a
counter and emits an instant event naming the program.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Any, NamedTuple


class TraceEvent(NamedTuple):
    name: str
    ph: str  # "X" complete span | "i" instant
    ts_ns: int  # perf_counter_ns at span START (or instant time)
    dur_ns: int  # 0 for instants
    tid: int
    args: dict[str, Any] | None


class _Span:
    """Context manager recording one complete ("X") event on exit."""

    __slots__ = ("_rec", "_name", "_args", "_t0")

    def __init__(self, rec: "FlightRecorder", name: str, args) -> None:
        self._rec = rec
        self._name = name
        self._args = args

    def __enter__(self) -> "_Span":
        self._t0 = time.perf_counter_ns()
        return self

    def __exit__(self, *_exc) -> None:
        t0 = self._t0
        self._rec._push(TraceEvent(
            self._name, "X", t0, time.perf_counter_ns() - t0,
            threading.get_ident(), self._args,
        ))


class _NullSpan:
    """Shared no-op span: what ``span()`` hands out with no recorder."""

    __slots__ = ()

    def __enter__(self) -> "_NullSpan":
        return self

    def __exit__(self, *_exc) -> None:
        pass


_NULL_SPAN = _NullSpan()


class FlightRecorder:
    """Fixed-capacity trace-event ring with Chrome-trace export."""

    def __init__(self, capacity: int = 65536) -> None:
        if capacity <= 0:
            raise ValueError("capacity must be positive")
        self.capacity = capacity
        self._buf: list[TraceEvent | None] = [None] * capacity
        self._n = 0  # total events ever pushed (ring cursor = _n % capacity)
        self._lock = threading.Lock()

    # ------------------------------------------------------------- recording
    def _push(self, ev: TraceEvent) -> None:
        # One lock round per event: events are recorded per *phase* (a few
        # per dispatch), never per op, so contention is negligible and the
        # ring stays consistent under the consumer/server threads.
        with self._lock:
            self._buf[self._n % self.capacity] = ev
            self._n += 1

    def span(self, name: str, **labels: Any) -> _Span:
        return _Span(self, name, labels or None)

    def instant(self, name: str, **labels: Any) -> None:
        t = time.perf_counter_ns()
        self._push(TraceEvent(
            name, "i", t, 0, threading.get_ident(), labels or None
        ))

    # --------------------------------------------------------------- reading
    @property
    def dropped(self) -> int:
        """Events that fell off the ring (overwritten by wraparound)."""
        return max(0, self._n - self.capacity)

    def __len__(self) -> int:
        return min(self._n, self.capacity)

    def events(self) -> list[TraceEvent]:
        """Retained events, oldest first (ring unrolled)."""
        with self._lock:
            n, cap = self._n, self.capacity
            if n <= cap:
                out = self._buf[:n]
            else:
                cut = n % cap
                out = self._buf[cut:] + self._buf[:cut]
        return list(out)  # type: ignore[arg-type]

    def clear(self) -> None:
        with self._lock:
            self._buf = [None] * self.capacity
            self._n = 0

    # --------------------------------------------------------------- export
    def chrome_trace(self, pid: int = 1) -> dict:
        """The Chrome trace-event JSON object (Perfetto-loadable).

        Span starts are recorded in ``perf_counter_ns``; Chrome wants
        microseconds.  Instants carry ``"s": "t"`` (thread scope)."""
        trace_events = []
        for ev in self.events():
            rec: dict[str, Any] = {
                "name": ev.name,
                "ph": ev.ph,
                "ts": ev.ts_ns / 1e3,
                "pid": pid,
                "tid": ev.tid,
            }
            if ev.ph == "X":
                rec["dur"] = ev.dur_ns / 1e3
            else:
                rec["s"] = "t"
            if ev.args:
                rec["args"] = dict(ev.args)
            trace_events.append(rec)
        return {"traceEvents": trace_events, "displayTimeUnit": "ms"}

    def export_chrome_trace(self, path: str, pid: int = 1) -> int:
        """Write the Chrome trace JSON; returns the event count written."""
        trace = self.chrome_trace(pid=pid)
        with open(path, "w") as f:
            json.dump(trace, f)
            f.write("\n")
        return len(trace["traceEvents"])


# ---------------------------------------------------------------------------
# Module-global recorder: the instrumentation seam the serving path calls
# ---------------------------------------------------------------------------

_RECORDER: FlightRecorder | None = None


def install(rec: FlightRecorder | None = None) -> FlightRecorder:
    """Install (and return) the process-global recorder.  Instrumented
    code starts recording immediately; pass None to install a fresh
    default-capacity ring."""
    global _RECORDER
    _RECORDER = rec if rec is not None else FlightRecorder()
    return _RECORDER


def uninstall() -> FlightRecorder | None:
    """Remove the global recorder (returns it); spans become no-ops."""
    global _RECORDER
    rec, _RECORDER = _RECORDER, None
    return rec


def recorder() -> FlightRecorder | None:
    return _RECORDER


def span(name: str, **labels: Any):
    """A span against the global recorder; free no-op when none installed."""
    rec = _RECORDER
    if rec is None:
        return _NULL_SPAN
    return rec.span(name, **labels)


def instant(name: str, **labels: Any) -> None:
    rec = _RECORDER
    if rec is not None:
        rec.instant(name, **labels)


# ---------------------------------------------------------------------------
# Trace analysis (shared by bench phase_shares and the fftpu-trace CLI)
# ---------------------------------------------------------------------------

def phase_totals(events: list[TraceEvent]) -> dict[str, float]:
    """Total wall seconds per span name (nested spans each count their own
    full duration — shares are per-phase attribution, not a partition)."""
    totals: dict[str, float] = {}
    for ev in events:
        if ev.ph == "X":
            totals[ev.name] = totals.get(ev.name, 0.0) + ev.dur_ns / 1e9
    return totals


def phase_shares(events: list[TraceEvent]) -> dict[str, float]:
    """Per-phase share of the summed span time, rounded (bench artifact
    rows; the fftpu-trace CLI prints the same view)."""
    totals = phase_totals(events)
    grand = sum(totals.values())
    if grand <= 0:
        return {}
    return {
        name: round(t / grand, 4)
        for name, t in sorted(totals.items(), key=lambda kv: -kv[1])
    }


# ---------------------------------------------------------------------------
# Recompile watchdog
# ---------------------------------------------------------------------------

class RecompileWatchdog:
    """Count executable-cache growth of registered jitted programs.

    ``jax.jit`` (and the jit(shard_map) fleet programs) keep one compiled
    executable per input-shape signature; ``_cache_size()`` reads that
    cache's size without touching the dispatch path.  Growth after the
    program's warmup dispatch means a NEW shape specialized — a megastep
    trace de-specializing (obliterate gate flip at a new geometry, a fresh
    cohort ladder rung, a restart at different capacity) and paying a
    multi-second XLA compile mid-serve.  ``poll()`` is host-side and
    cheap (one int read per program); engines call it once per ``step``.

    One caveat follows from the design: the registered programs are
    module-level / lru-cached on purpose (engine instances SHARE compile
    caches), so cache growth is a process-wide fact — when several engines
    serve in one process, each polling watchdog reports compiles any of
    them triggered.  ``recompiles`` counts every cache miss (warmup
    included — a clean boot compiles each program once per shape);
    ``despecializations`` counts only growth AFTER a program had already
    specialized, which is the mid-serve alarm signal and the only growth
    that emits a ``recompile`` instant event.
    """

    def __init__(self) -> None:
        self._progs: dict[str, tuple[Any, int]] = {}
        self.recompiles = 0  # every cache miss seen (warmup included)
        self.despecializations = 0  # growth after first specialization
        self.per_program: dict[str, int] = {}

    def register(self, name: str, fn: Any) -> None:
        """Track ``fn`` (idempotent; ignores non-jitted callables).  The
        baseline is the CURRENT cache size, so compiles that already
        happened (warmup, shared module-level caches) are not charged."""
        if name in self._progs:
            return
        probe = getattr(fn, "_cache_size", None)
        if probe is None:
            return
        try:
            size = int(probe())
        except Exception:  # noqa: BLE001 — a probe failure must never break serving
            return
        self._progs[name] = (fn, size)
        self.per_program.setdefault(name, 0)

    def poll(self) -> int:
        """Check every registered program; returns NEW compiles seen this
        call.  Each growth emits a ``recompile`` instant event."""
        grew = 0
        for name, (fn, last) in list(self._progs.items()):
            try:
                size = int(fn._cache_size())
            except Exception:  # noqa: BLE001 — see register
                continue
            if size > last:
                delta = size - last
                grew += delta
                self.recompiles += delta
                self.per_program[name] = self.per_program.get(name, 0) + delta
                if last > 0:
                    # The program had already specialized: this growth is a
                    # mid-serve DE-specialization (new shape), not warmup.
                    self.despecializations += delta
                    instant(
                        "recompile", program=name, cache_size=size,
                        added=delta,
                    )
            self._progs[name] = (fn, size)
        return grew
