"""Observability plane: flight recorder, recompile watchdog, metrics plane.

Three coordinated pieces (ISSUE 7), all host-side and all off the device
hot path:

- ``flight_recorder``: a fixed-size ring buffer of trace events
  (begin/end/instant, monotonic ns timestamps, thread + label args) with a
  Chrome trace-event JSON exporter (Perfetto-loadable), plus the module
  globals ``install``/``recorder``/``span``/``instant`` the serving path
  calls — every call is a no-op costing one global read while no recorder
  is installed.
- ``RecompileWatchdog`` (in ``flight_recorder``): counts jit/shard_map
  executable-cache growth per registered program and emits an instant
  event when a fleet trace de-specializes mid-run.
- ``metrics_plane``: Prometheus-text ``/metrics`` + JSON ``/status``
  rendering and a tiny HTTP server, aggregating any number of registered
  sources (engine health, histograms, staging gauges, scribe state,
  ordered-log depths).
"""

from .flight_recorder import (
    FlightRecorder,
    RecompileWatchdog,
    TraceEvent,
    install,
    instant,
    phase_totals,
    recorder,
    span,
    uninstall,
)
from .metrics_plane import (
    MetricsPlane,
    MetricsServer,
    parse_prometheus,
    render_prometheus,
)

__all__ = [
    "FlightRecorder",
    "MetricsPlane",
    "MetricsServer",
    "RecompileWatchdog",
    "TraceEvent",
    "install",
    "instant",
    "parse_prometheus",
    "phase_totals",
    "recorder",
    "render_prometheus",
    "span",
    "uninstall",
]
