"""Seeded load schedules: the deterministic half of the traffic plant.

Same determinism contract as ``testing.chaos.ChaosSchedule``: the same
seed produces the same schedule, and a schedule survives a JSON
round-trip bit-identically — so a run's exact workload can be committed
next to its artifact and replayed.  The coordinator builds ONE
``LoadSchedule`` and hands each worker process its ``WorkerSchedule``
(plus the shared doc/scope tables) through a config file; everything a
worker does — op counts, op mix, Zipf doc picks, churn points, presence
scopes — derives from its per-worker seed.
"""

from __future__ import annotations

import json
import random
from dataclasses import asdict, dataclass, field

# Phase order is the barrier order the coordinator drives: connect and
# warm every family, sustain the mixed load (with reconnect churn and
# presence), hammer the historian snapshot tier, then settle + verdict.
PHASES = ("ramp", "steady", "boot_storm", "drain")

# Workload matrix: one replica family per doc.  ``string``/``tree`` docs
# are additionally consumed by device fleet processes; the channel-level
# families converge writer-to-writer and against host oracle replays.
FAMILIES = ("string", "tree", "map", "matrix", "chan_string")

# Presence scope universe: workers subscribe to a strict subset and
# publish across the whole universe, so the fanout plane's scoped-drop
# path is exercised on every run.
DEFAULT_SCOPES = ("audience", "cursor", "editor", "viewport")


@dataclass
class DocSpec:
    """One document in the plant: its replica family and home shard."""

    doc_id: str
    family: str  # one of FAMILIES
    shard: int   # index into the topology's shard list


@dataclass
class WorkerSchedule:
    """One worker process's seeded script."""

    worker_id: int
    seed: int
    ramp_ops: int         # ops after the per-doc warmup edits
    steady_ops: int       # mixed-load ops in the steady phase
    boots: int            # historian cold boots in the boot-storm phase
    reconnect_every: int  # steady: tear a random session every N ops (0 = never)
    signal_every: int     # steady: presence signal every N ops (0 = never)
    interests: list = field(default_factory=list)  # subscribed scope keys


@dataclass
class LoadSchedule:
    """The whole run's script: docs, scopes, and every worker's share."""

    seed: int
    zipf_a: float
    scopes: list = field(default_factory=list)
    docs: list = field(default_factory=list)     # DocSpec
    workers: list = field(default_factory=list)  # WorkerSchedule

    def to_json(self) -> str:
        return json.dumps(
            {
                "seed": self.seed,
                "zipf_a": self.zipf_a,
                "scopes": list(self.scopes),
                "docs": [asdict(d) for d in self.docs],
                "workers": [asdict(w) for w in self.workers],
            },
            indent=2,
        )

    @staticmethod
    def from_json(raw: str) -> "LoadSchedule":
        d = json.loads(raw)
        return LoadSchedule(
            seed=d["seed"],
            zipf_a=d["zipf_a"],
            scopes=list(d["scopes"]),
            docs=[DocSpec(**s) for s in d["docs"]],
            workers=[WorkerSchedule(**w) for w in d["workers"]],
        )


def zipf_weights(n: int, a: float) -> list:
    """Zipf popularity over ranks 0..n-1 (rank 0 hottest) — the same
    ranking idiom the chaos harness uses, so doc heat is comparable."""
    return [1.0 / (i + 1) ** a for i in range(n)]


def make_load_schedule(
    seed: int,
    n_workers: int,
    docs: list,
    ramp_ops: int = 8,
    steady_ops: int = 24,
    boots: int = 6,
    zipf_a: float = 1.2,
    scopes=DEFAULT_SCOPES,
    reconnect_every: int = 9,
    signal_every: int = 4,
) -> LoadSchedule:
    """Deterministic schedule from a seed.

    Per-worker op counts jitter ±25% so workers are heterogeneous (the
    barrier sees stragglers), and every worker subscribes to a strict
    subset of the scope universe — publishing across the full universe
    then GUARANTEES scoped-presence drops at the fanout plane.
    """
    rng = random.Random(seed)
    scope_list = list(scopes)
    workers: list = []
    for wid in range(n_workers):
        w_seed = rng.getrandbits(32)
        k = rng.randint(1, max(1, len(scope_list) - 1))
        interests = sorted(rng.sample(scope_list, k))
        workers.append(WorkerSchedule(
            worker_id=wid,
            seed=w_seed,
            ramp_ops=max(1, ramp_ops + rng.randint(-(ramp_ops // 4), ramp_ops // 4)),
            steady_ops=max(
                1, steady_ops + rng.randint(-(steady_ops // 4), steady_ops // 4)
            ),
            boots=boots,
            reconnect_every=reconnect_every,
            signal_every=signal_every,
            interests=interests,
        ))
    return LoadSchedule(
        seed=seed,
        zipf_a=zipf_a,
        scopes=sorted(scope_list),
        docs=list(docs),
        workers=workers,
    )
