"""loadgen: the multi-process traffic plant.

A coordinator process spawns N worker OS processes, each owning real
client sessions over real TCP sockets against the composed service stack
(netserver fronts + sequencer + historian snapshot tier + checkpointed
device fleets behind FleetConsumer, the deploy/compose.yaml topology).
Workers run seeded mixed workloads (SharedString, SharedTree, SharedMap,
SharedMatrix, channel-level strings with interval collections and
undo-redo, scoped presence signals) through phase barriers
(ramp -> steady -> boot_storm -> drain) and ship lossless latency
histograms back; the coordinator merges them, scrapes the fleet and
historian metrics surfaces, and ends with a per-family byte-identity
convergence verdict against host oracle replays.

Entry points: ``coordinator.run_loadgen`` (in-process orchestration, used
by ``bench.py --config loadgen`` and the tier-1 smoke test) and
``python -m fluidframework_tpu.loadgen.worker`` (one worker process).
"""

from .schedule import (
    FAMILIES,
    PHASES,
    DocSpec,
    LoadSchedule,
    WorkerSchedule,
    make_load_schedule,
)

__all__ = [
    "FAMILIES",
    "PHASES",
    "DocSpec",
    "LoadSchedule",
    "WorkerSchedule",
    "make_load_schedule",
]
