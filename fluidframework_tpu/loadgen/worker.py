"""One loadgen worker: an OS process owning real client sessions.

    python -m fluidframework_tpu.loadgen.worker --config worker3.json

The worker dials the coordinator's control socket (JSON lines), announces
itself, then runs phases on command — each phase is a barrier: the
coordinator releases all workers into a phase together and waits for
every ``phase_done`` before moving on.

Sessions are the REAL client stack: every writer rides a
``NetworkDeltaConnection`` over TCP with stop-and-wait submission,
admission-nack backoff, and delta-storage catch-up — the exact
flow-control contract ``testing.chaos`` established (the string and tree
writers ARE the chaos writers; the map / matrix / channel-string writers
extend the same ``_ChaosWireClient`` base).  Op end-to-end latency
(edit staged -> sequenced ack dispatched) samples into per-phase
``utils.telemetry.Histogram``s and ships back losslessly (``to_wire``)
for coordinator-side merge.

The boot-storm phase drives the historian snapshot tier over HTTP: cold
GETs (ETag recorded) and conditional re-GETs (304 expected), both timed.
Scoped presence rides a signals-only session per worker subscribed to a
strict subset of the scope universe; signals published outside a
worker's interest set must never arrive (``foreign`` stays 0) — the
receiver-side check paired with the fanout plane's
``presence_scope_drops`` counter.
"""

from __future__ import annotations

import argparse
import contextlib
import http.client
import json
import random
import socket
import sys
import time
import traceback
from collections import deque

from ..dds.channels import SharedStringChannel
from ..dds.shared_map import SharedMap
from ..dds.shared_matrix import SharedMatrix
from ..driver.network_driver import NetworkDeltaConnection
from ..framework.undo_redo import UndoRedoStackManager
from ..protocol.channel import (
    ChannelDeltaConnection,
    ChannelMessage,
    MessageCollection,
    MessageEnvelope,
)
from ..protocol.messages import MessageType
from ..testing.chaos import (
    ChaosTreeWriter,
    ChaosWriter,
    TornConnection,
    _ChaosWireClient,
)
from ..utils.telemetry import Histogram
from .schedule import DocSpec, WorkerSchedule, zipf_weights


# --------------------------------------------------------------- families
class MapWriter(_ChaosWireClient):
    """One raw-wire SharedMap client.  The map replica ignores JOIN
    messages (last-write-wins needs no quorum shorts), so join tracking
    lives here at the wire-client level."""

    def _init_replica(self) -> None:
        self.replica = SharedMap(self.client_id)
        self._joined = False

    def _assert_joined(self) -> None:
        assert self._joined, "join not delivered"

    def _apply(self, msg) -> None:
        if msg.seq <= self.last_seq:
            return  # catch-up / live-stream overlap
        self.last_seq = msg.seq
        if (
            msg.type == MessageType.JOIN
            and msg.contents.get("clientId") == self.client_id
        ):
            self._joined = True
        self.replica.process(msg)

    def edit(self) -> None:
        rng = self._rng
        r = rng.random()
        keys = sorted(self.replica.keys())
        if r < 0.78 or not keys:
            self.replica.set(f"k{rng.randrange(12)}", rng.randrange(10_000))
        elif r < 0.97:
            self.replica.delete(rng.choice(keys))
        else:
            self.replica.clear()

    def flush(self) -> int:
        sent = 0
        for m in self.replica.take_outbox():
            self._submit_one(m)
            sent += 1
        return sent

    def digest(self):
        return {k: self.replica.get(k) for k in sorted(self.replica.keys())}


class MatrixWriter(_ChaosWireClient):
    """One raw-wire SharedMatrix client (the matrix replica tracks the
    quorum itself — same join contract as SharedString)."""

    def _init_replica(self) -> None:
        self.replica = SharedMatrix(self.client_id)

    def _assert_joined(self) -> None:
        assert self.replica.short_client >= 0, "join not delivered"

    def _apply(self, msg) -> None:
        if msg.seq <= self.last_seq:
            return
        self.last_seq = msg.seq
        self.replica.process(msg)

    def edit(self) -> None:
        m, rng = self.replica, self._rng
        r, c = m.row_count, m.col_count
        if r == 0 or (r < 5 and rng.random() < 0.3):
            m.insert_rows(rng.randint(0, r), rng.randint(1, 2))
            return
        if c == 0 or (c < 5 and rng.random() < 0.3):
            m.insert_cols(rng.randint(0, c), rng.randint(1, 2))
            return
        x = rng.random()
        if x < 0.7 or (r <= 1 and c <= 1):
            m.set_cell(rng.randrange(r), rng.randrange(c), rng.randrange(1000))
        elif x < 0.85 and r > 1:
            m.remove_rows(rng.randrange(r), 1)
        elif c > 1:
            m.remove_cols(rng.randrange(c), 1)
        else:
            m.remove_rows(rng.randrange(r), 1)

    def flush(self) -> int:
        sent = 0
        for m in self.replica.take_outbox():
            self._submit_one(m)
            sent += 1
        return sent

    def digest(self):
        return self.replica.to_grid()


class ChanStringWriter(_ChaosWireClient):
    """A CHANNEL-level SharedString client: the full
    ``SharedStringChannel`` (interval collections, undo-redo) bridged to
    the wire through a ``ChannelDeltaConnection`` shim, the
    ``ChaosTreeWriter`` idiom.  Staged contents + local metadata pairs
    queue in submit order; our own sequenced ops pop the metadata FIFO
    (the container PendingStateManager zip, collapsed to one channel).

    The quorum table builds from JOIN messages — catch-up replays the log
    from seq 1, so every client that ever sequenced an op resolves."""

    def _init_replica(self) -> None:
        self.channel = SharedStringChannel("s")
        self._quorum: dict[str, int] = {}
        self._joined = False
        self._outbox: list = []
        self._md_fifo: deque = deque()
        self._client_seq = 0
        self._iv_serial = 0
        shim = ChannelDeltaConnection(
            submit_fn=self._stage,
            quorum_fn=lambda cid: self._quorum[cid],
            client_id_fn=lambda: self.client_id,
            ref_seq_fn=lambda: self.last_seq,
        )
        shim.connected = True
        self.channel.connect(shim)
        self.intervals = self.channel.get_interval_collection("marks")
        self.undo = UndoRedoStackManager()

    def _stage(self, contents, local_metadata=None, internal=False) -> None:
        self._outbox.append(contents)
        self._md_fifo.append(local_metadata)

    def _assert_joined(self) -> None:
        assert self._joined, "join not delivered"

    def _apply(self, msg) -> None:
        if msg.seq <= self.last_seq:
            return
        self.last_seq = msg.seq
        if msg.type == MessageType.JOIN:
            self._quorum[msg.contents["clientId"]] = msg.contents["short"]
            if msg.contents.get("clientId") == self.client_id:
                self._joined = True
            return
        if msg.type != MessageType.OP:
            return
        local = msg.client_id == self.client_id
        md = self._md_fifo.popleft() if local else None
        self.channel.process_messages(MessageCollection(
            envelope=MessageEnvelope(
                client_id=msg.client_id, seq=msg.seq,
                min_seq=msg.min_seq, ref_seq=msg.ref_seq,
            ),
            messages=[ChannelMessage(
                contents=msg.contents, local=local, local_metadata=md,
            )],
        ))

    def edit(self) -> None:
        """One mixed channel edit: string insert/remove through the
        undo-redo capture path, undo/redo replays, and interval collection
        add/change/delete.  Every call stages at least one op (fallbacks
        land on an insert), so the latency histogram never times a no-op."""
        rng = self._rng
        n = len(self.channel.text)
        kind = rng.choices(
            ["ins", "rm", "undo", "redo", "ivadd", "ivmut"],
            [6, 2, 1, 1, 2, 2],
        )[0]
        if kind == "undo" and self.undo.undoable:
            if self.undo.undo() and self._outbox:
                return
        elif kind == "redo" and self.undo.redoable:
            if self.undo.redo() and self._outbox:
                return
        elif kind == "ivadd" and n >= 2:
            a = rng.randint(0, n - 1)
            self._iv_serial += 1
            self.intervals.add(
                a, rng.randint(a, n - 1),
                props={"w": self.client_id},
                interval_id=f"{self.client_id}-iv{self._iv_serial}",
            )
            return
        elif kind == "ivmut" and n >= 2:
            ids = sorted(self.intervals.sequenced)
            if ids:
                iid = rng.choice(ids)
                if rng.random() < 0.6:
                    a = rng.randint(0, n - 1)
                    self.intervals.change(iid, start=a, end=rng.randint(a, n - 1))
                else:
                    self.intervals.delete(iid)
                return
        if kind == "rm" and n >= 4:
            p = rng.randint(0, n - 2)
            self.undo.capture_string_remove(self.channel, p, p + 1)
        else:
            self.undo.capture_string_insert(
                self.channel, rng.randint(0, n),
                "".join(rng.choice("mnopqrst")
                        for _ in range(rng.randint(1, 5))),
            )
        self.undo.close_current_operation()

    def flush(self) -> int:
        from ..protocol.messages import UnsequencedMessage

        sent = 0
        out, self._outbox = self._outbox, []
        for contents in out:
            self._client_seq += 1
            self._submit_one(UnsequencedMessage(
                client_id=self.client_id, client_seq=self._client_seq,
                ref_seq=self.last_seq, type=MessageType.OP,
                contents=contents,
            ))
            sent += 1
        return sent

    def digest(self):
        return chan_string_digest(self.channel, self.intervals)


def chan_string_digest(channel: SharedStringChannel, coll) -> dict:
    """The channel family's identity surface: visible text + every
    sequenced interval's (id, endpoints) — JSON-stable, so digests
    compare equal across the control-socket round trip."""
    return {
        "text": channel.text,
        "intervals": sorted(
            [iid, iv.start, iv.end] for iid, iv in coll.sequenced.items()
        ),
    }


WRITER_CLASSES = {
    "string": ChaosWriter,
    "tree": ChaosTreeWriter,
    "map": MapWriter,
    "matrix": MatrixWriter,
    "chan_string": ChanStringWriter,
}


def family_digest(writer, family: str):
    if family == "string":
        return writer.replica.text
    if family == "tree":
        return writer.root_json()
    return writer.digest()


# ----------------------------------------------------------- host oracles
def oracle_map(log) -> dict:
    """Fault-free replay of a sequenced log through a host SharedMap."""
    replica = SharedMap("__oracle__")
    for msg in log:
        replica.process(msg)
    return {k: replica.get(k) for k in sorted(replica.keys())}


def oracle_matrix(log) -> list:
    """Fault-free replay through a host SharedMatrix (grid view)."""
    replica = SharedMatrix("__oracle__")
    for msg in log:
        replica.process(msg)
    return replica.to_grid()


def oracle_chan_string(log) -> dict:
    """Fault-free replay through a read-only SharedStringChannel (every
    message remote — the oracle identity never appears in the log)."""
    quorum: dict[str, int] = {}
    channel = SharedStringChannel("s")
    shim = ChannelDeltaConnection(
        submit_fn=lambda contents, md=None, internal=False: None,
        quorum_fn=lambda cid: quorum[cid],
        client_id_fn=lambda: "__oracle__",
        ref_seq_fn=lambda: 0,
    )
    shim.connected = True
    channel.connect(shim)
    coll = channel.get_interval_collection("marks")
    for msg in log:
        if msg.type == MessageType.JOIN:
            quorum[msg.contents["clientId"]] = msg.contents["short"]
        elif msg.type == MessageType.OP:
            channel.process_messages(MessageCollection(
                envelope=MessageEnvelope(
                    client_id=msg.client_id, seq=msg.seq,
                    min_seq=msg.min_seq, ref_seq=msg.ref_seq,
                ),
                messages=[ChannelMessage(contents=msg.contents, local=False)],
            ))
    return chan_string_digest(channel, coll)


# ------------------------------------------------------------- presence
class PresenceAgent:
    """A signals-only session: subscribes a scoped interest set at
    connect, publishes presence across the FULL scope universe, and
    verifies the receiver half of the contract — a signal scoped outside
    our interests must never arrive (``foreign`` stays 0)."""

    def __init__(self, host, port, doc_id, client_id, interests) -> None:
        self.interests = set(interests)
        self.sent = 0
        self.recv = 0
        self.foreign = 0
        self.conn = NetworkDeltaConnection(
            host, port, doc_id, client_id, "read",
            listener=lambda m: None, nack_listener=None,
            signal_listener=self._on_signal,
            interests=sorted(self.interests),
        )

    def _on_signal(self, sig) -> None:
        c = sig.contents
        if not isinstance(c, dict) or c.get("type") != "presence":
            return
        self.recv += 1
        scope = c.get("scope")
        if scope is not None and scope not in self.interests:
            self.foreign += 1

    def publish(self, scope: str, payload) -> None:
        self.conn.submit_signal(
            {"type": "presence", "scope": scope, "data": payload}
        )
        self.sent += 1

    def pump(self) -> int:
        return self.conn.pump()

    def close(self) -> None:
        with contextlib.suppress(Exception):
            self.conn.disconnect()


# -------------------------------------------------------------- the loop
def _historian_get(host, port, doc_id, etag=None):
    """One timed historian snapshot GET; returns (status, etag, dt_s)."""
    conn = http.client.HTTPConnection(host, port, timeout=30)
    try:
        headers = {"If-None-Match": etag} if etag else {}
        t0 = time.perf_counter()
        conn.request("GET", f"/doc/{doc_id}/snapshot", headers=headers)
        resp = conn.getresponse()
        resp.read()
        return resp.status, resp.getheader("ETag"), time.perf_counter() - t0
    finally:
        conn.close()


class WorkerRuntime:
    """The phase machine for one worker process (also drivable in-process
    by tests — the control socket is the only process-shaped seam)."""

    def __init__(self, cfg: dict) -> None:
        self.cfg = cfg
        self.host = cfg.get("host", "127.0.0.1")
        self.ws = WorkerSchedule(**cfg["worker"])
        self.docs = [DocSpec(**d) for d in cfg["docs"]]
        self.shards = cfg["shards"]  # [{"port","http_port","historian_port"}]
        self.scopes = list(cfg["scopes"])
        self.rng = random.Random(self.ws.seed)
        self.weights = zipf_weights(len(self.docs), cfg["zipf_a"])
        self.writers: dict[str, object] = {}
        self.hists: dict[str, Histogram] = {}
        self.presence: PresenceAgent | None = None
        self._serial = 0
        self.counters = {
            "ops": 0,
            "ops_sequenced": 0,
            "nack_backoffs": 0,
            "reconnects": 0,
            "torn": 0,
            "boots_cold": 0,
            "boots_304": 0,
            "boot_errors": 0,
        }

    # ------------------------------------------------------------ sessions
    def _make_writer(self, doc: DocSpec):
        self._serial += 1
        shard = self.shards[doc.shard]
        cls = WRITER_CLASSES[doc.family]
        return cls(
            self.host, shard["port"], shard["http_port"], doc.doc_id,
            f"w{self.ws.worker_id}.{doc.doc_id}.{self._serial}",
            random.Random(self.rng.getrandbits(32)),
        )

    def _retire(self, doc_id: str) -> None:
        w = self.writers.pop(doc_id, None)
        if w is None:
            return
        self.counters["ops_sequenced"] += w.ops_submitted
        self.counters["nack_backoffs"] += w.nack_backoffs
        w.close()

    def _writer(self, doc: DocSpec):
        w = self.writers.get(doc.doc_id)
        if w is None:
            w = self._make_writer(doc)
            self.writers[doc.doc_id] = w
        return w

    def _one_op(self, hist: Histogram) -> None:
        doc = self.rng.choices(self.docs, self.weights)[0]
        try:
            w = self._writer(doc)
            w.edit()
            t0 = time.perf_counter()
            w.flush()
            hist.record(time.perf_counter() - t0)
            self.counters["ops"] += 1
        except TornConnection:
            # A torn session is replaced with a fresh identity the next
            # time the doc is picked (delta-storage catch-up) — the
            # reconnect-churn contract the chaos harness established.
            self.counters["torn"] += 1
            self._retire(doc.doc_id)

    # -------------------------------------------------------------- phases
    def run_phase(self, name: str) -> dict:
        hist = self.hists.setdefault(name, Histogram())
        if name == "ramp":
            # Warm every doc (every family joins + edits at least once),
            # then the seeded remainder by Zipf popularity.
            for doc in self.docs:
                self._one_op_on(doc, hist)
            for _ in range(self.ws.ramp_ops):
                self._one_op(hist)
            if self.presence is None:
                self.presence = PresenceAgent(
                    self.host, self.shards[self.docs[0].shard]["port"],
                    self.docs[0].doc_id,
                    f"presence-w{self.ws.worker_id}",
                    self.ws.interests,
                )
        elif name == "steady":
            for i in range(1, self.ws.steady_ops + 1):
                self._one_op(hist)
                if self.ws.signal_every and i % self.ws.signal_every == 0:
                    self.presence.publish(
                        self.rng.choice(self.scopes),
                        {"worker": self.ws.worker_id, "op": i},
                    )
                    self.presence.pump()
                if self.ws.reconnect_every and i % self.ws.reconnect_every == 0:
                    live = sorted(self.writers)
                    if live:
                        doc_id = self.rng.choice(live)
                        self.writers[doc_id].tear()
                        self._retire(doc_id)
                        self.counters["reconnects"] += 1
            self.presence.pump()
        elif name == "boot_storm":
            cold = self.hists.setdefault("boot_cold", Histogram())
            warm = self.hists.setdefault("boot_304", Histogram())
            fleet_docs = [d for d in self.docs if d.family in ("string", "tree")]
            fw = zipf_weights(len(fleet_docs), self.cfg["zipf_a"])
            for _ in range(self.ws.boots):
                doc = self.rng.choices(fleet_docs, fw)[0]
                hport = self.shards[doc.shard]["historian_port"]
                status, etag, dt = _historian_get(self.host, hport, doc.doc_id)
                if status != 200 or not etag:
                    self.counters["boot_errors"] += 1
                    continue
                cold.record(dt)
                self.counters["boots_cold"] += 1
                status, _, dt = _historian_get(
                    self.host, hport, doc.doc_id, etag=etag
                )
                if status == 304:
                    warm.record(dt)
                    self.counters["boots_304"] += 1
                else:
                    self.counters["boot_errors"] += 1
        elif name == "drain":
            return self._drain()
        else:
            raise ValueError(f"unknown phase {name!r}")
        return {"ops": self.counters["ops"]}

    def _one_op_on(self, doc: DocSpec, hist: Histogram) -> None:
        try:
            w = self._writer(doc)
            w.edit()
            t0 = time.perf_counter()
            w.flush()
            hist.record(time.perf_counter() - t0)
            self.counters["ops"] += 1
        except TornConnection:
            self.counters["torn"] += 1
            self._retire(doc.doc_id)

    def _drain(self) -> dict:
        """Settle every session and ship the final report: per-doc
        digests, per-phase histograms (lossless), counters, presence."""
        digests = {}
        for doc in self.docs:
            w = self.writers.get(doc.doc_id)
            if w is None:
                # The session was torn/churned away: a fresh replica
                # catches up from delta storage — it must converge too.
                w = self._make_writer(doc)
                self.writers[doc.doc_id] = w
            w.settle()
            digests[doc.doc_id] = family_digest(w, doc.family)
        presence_stats = {"sent": 0, "recv": 0, "foreign": 0}
        if self.presence is not None:
            self.presence.pump()
            presence_stats = {
                "sent": self.presence.sent,
                "recv": self.presence.recv,
                "foreign": self.presence.foreign,
            }
        for doc_id in sorted(self.writers):
            self._retire(doc_id)
        if self.presence is not None:
            self.presence.close()
        return {
            "digests": digests,
            "hists": {k: h.to_wire() for k, h in self.hists.items()},
            "counters": dict(self.counters),
            "presence": presence_stats,
        }

    def close(self) -> None:
        for doc_id in sorted(self.writers):
            with contextlib.suppress(Exception):
                self._retire(doc_id)
        if self.presence is not None:
            self.presence.close()


# --------------------------------------------------------- process entry
def _send_line(sock: socket.socket, obj: dict) -> None:
    sock.sendall((json.dumps(obj) + "\n").encode())


def run(config_path: str) -> int:
    with open(config_path) as f:
        cfg = json.load(f)
    rt = WorkerRuntime(cfg)
    sock = socket.create_connection(
        (rt.host, cfg["control_port"]), timeout=300
    )
    rfile = sock.makefile("r", encoding="utf-8")
    try:
        _send_line(sock, {"t": "hello", "worker": rt.ws.worker_id})
        for line in rfile:
            req = json.loads(line)
            kind = req.get("t")
            if kind == "phase":
                name = req["name"]
                try:
                    stats = rt.run_phase(name)
                except Exception:
                    _send_line(sock, {
                        "t": "error",
                        "worker": rt.ws.worker_id,
                        "phase": name,
                        "trace": traceback.format_exc(),
                    })
                    return 1
                _send_line(sock, {
                    "t": "phase_done",
                    "worker": rt.ws.worker_id,
                    "phase": name,
                    "stats": stats,
                })
            elif kind == "bye":
                return 0
        return 1  # coordinator hung up without a bye
    finally:
        rt.close()
        with contextlib.suppress(OSError):
            sock.close()


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(prog="loadgen-worker")
    p.add_argument("--config", required=True,
                   help="path to the worker config JSON the coordinator "
                        "wrote (schedule share + topology + control port)")
    args = p.parse_args(argv)
    return run(args.config)


if __name__ == "__main__":
    sys.exit(main())
