"""loadgen coordinator: spawn the plant, drive the phases, one verdict.

Topology (the deploy/compose.yaml shape, ports ephemeral): N netserver
shard processes (TCP nexus + HTTP alfred + historian snapshot tier), one
device-fleet process per (shard, family) behind ``FleetConsumer``
(``--family tree`` runs the TreeBatchEngine tier), and M worker
processes, each dialed into the coordinator's control socket for phase
barriers and stats shipping.

The coordinator additionally mirrors every doc's sequenced log over the
HTTP deltas front into its own durable topic + scribe pool (the
deployment's scribe tier), which gives it three things at drain time:
the per-doc target seqs for coordinated fleet drain, the fault-free host
oracle replays for the byte-identity verdict, and the no-double-ack scan
over the scribe plane.

``run_loadgen`` returns the report dict that ``bench.py --config
loadgen`` commits as the run artifact; any invariant violation raises
``LoadgenVerdictError`` instead of reporting success.
"""

from __future__ import annotations

import contextlib
import http.client
import json
import os
import select
import socket
import subprocess
import sys
import time
from dataclasses import dataclass, field

from ..dds.mergetree_ref import RefMergeTree
from ..dds.tree.changeset import apply_commit, commit_from_json
from ..dds.tree.editmanager import EditManager
from ..dds.tree.forest import Forest
from ..driver.definitions import DriverError
from ..driver.network_driver import (
    HttpDeltaStorageService,
    HttpStorageService,
    _Http,
)
from ..protocol.messages import DeltaType, MessageType, SequencedMessage
from ..runtime.summary import parse_scribe_ack
from ..server.ordered_log import DurableTopic
from ..server.partition_manager import ScribePool
from ..server.scribe import ScribeConfig
from ..utils.telemetry import Histogram
from .schedule import (
    FAMILIES,
    DocSpec,
    LoadSchedule,
    make_load_schedule,
)
from .worker import oracle_chan_string, oracle_map, oracle_matrix

FLEET_FAMILIES = ("string", "tree")


class LoadgenVerdictError(AssertionError):
    """An invariant failed at drain: divergence, double-ack, or foreign
    presence delivery.  Carries every failure, not just the first."""

    def __init__(self, failures: list) -> None:
        super().__init__("; ".join(failures))
        self.failures = failures


# ----------------------------------------------------------- host oracles
def oracle_text(log) -> str:
    """Fault-free replay through the host reference merge tree (the
    string family's byte-identity oracle — the chaos harness contract)."""
    tree = RefMergeTree()
    quorum: dict[str, int] = {}
    for msg in log:
        if msg.type == MessageType.JOIN:
            quorum[msg.contents["clientId"]] = msg.contents["short"]
        elif msg.type == MessageType.OP:
            c = msg.contents
            kind = c["type"]
            client = quorum[msg.client_id]
            if kind == DeltaType.INSERT:
                tree.apply_insert(c["pos1"], c["seg"], msg.seq, client, msg.ref_seq)
            elif kind == DeltaType.REMOVE:
                tree.apply_remove(c["pos1"], c["pos2"], msg.seq, client, msg.ref_seq)
            elif kind == DeltaType.ANNOTATE:
                for prop, value in c["props"].items():
                    tree.apply_annotate(
                        c["pos1"], c["pos2"], int(prop), value,
                        msg.seq, client, msg.ref_seq,
                    )
    return tree.visible_text()


def oracle_tree(log) -> list:
    """Fault-free replay through a host EditManager + Forest (the tree
    family's byte-identity oracle: root-field node JSON)."""
    em, forest = EditManager(), Forest()
    for msg in log:
        if msg.type != MessageType.OP:
            continue
        c = msg.contents
        trunk = em.add_sequenced(
            client_id=msg.client_id,
            revision=(c["sid"], c["rev"]),
            change=commit_from_json(c["changes"]),
            ref_seq=msg.ref_seq,
            seq=msg.seq,
        )
        em.advance_min_seq(msg.min_seq)
        apply_commit(forest.root, trunk)
    return [n.to_json() for n in forest.root_field]


ORACLES = {
    "string": oracle_text,
    "tree": oracle_tree,
    "map": oracle_map,
    "matrix": oracle_matrix,
    "chan_string": oracle_chan_string,
}


def _norm(value):
    """JSON round-trip normalization: worker digests crossed the control
    socket as JSON, so the oracle side must compare in the same space."""
    return json.loads(json.dumps(value))


# ------------------------------------------------------------ subprocesses
@dataclass
class _ShardProc:
    proc: subprocess.Popen
    reader: _LineReader
    port: int
    http_port: int
    historian_port: int


@dataclass
class _FleetProc:
    proc: subprocess.Popen
    reader: _LineReader
    family: str
    docs: list
    drain_file: str
    metrics_port: int | None = None
    final: dict = field(default_factory=dict)


def _http_json(host: str, port: int, path: str) -> dict:
    conn = http.client.HTTPConnection(host, port, timeout=30)
    try:
        conn.request("GET", path)
        resp = conn.getresponse()
        return json.loads(resp.read() or b"{}")
    finally:
        conn.close()


class _LineReader:
    """Deadline-bounded line reads off a subprocess pipe.

    Owns its own byte buffer over a non-blocking fd: a buffered
    ``readline()`` would slurp multiple lines off the OS pipe and leave
    ``select()`` reporting nothing readable while a complete line sits in
    the Python-level buffer — the classic select-vs-stdio deadlock."""

    def __init__(self, stream) -> None:
        self._fd = stream.fileno()
        os.set_blocking(self._fd, False)
        self._buf = bytearray()
        self._eof = False

    def readline(self, deadline: float, what: str) -> str:
        while True:
            i = self._buf.find(b"\n")
            if i >= 0:
                line = bytes(self._buf[: i + 1])
                del self._buf[: i + 1]
                return line.decode()
            if self._eof:
                raise RuntimeError(f"unexpected EOF from {what}")
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise TimeoutError(f"timed out waiting for {what}")
            r, _, _ = select.select([self._fd], [], [], min(remaining, 1.0))
            if r:
                chunk = os.read(self._fd, 65536)
                if chunk:
                    self._buf += chunk
                else:
                    self._eof = True


class LoadPlant:
    """The live plant: processes, control plane, mirror, verdict."""

    def __init__(
        self,
        workdir: str,
        schedule: LoadSchedule,
        host: str = "127.0.0.1",
        deadline_s: float = 600.0,
        max_pending: int = 4096,
        max_consumer_backlog: int = 1024,
    ) -> None:
        self.workdir = workdir
        self.sched = schedule
        self.host = host
        self.deadline = time.monotonic() + deadline_s
        self.max_pending = max_pending
        self.max_consumer_backlog = max_consumer_backlog
        self.n_shards = 1 + max(d.shard for d in schedule.docs)
        self.shards: list[_ShardProc] = []
        self.fleets: list[_FleetProc] = []
        self.workers: list[subprocess.Popen] = []
        self.control: dict[int, tuple] = {}  # worker_id -> (sock, rfile)
        self._control_srv: socket.socket | None = None
        self.logs: dict[str, list[SequencedMessage]] = {
            d.doc_id: [] for d in schedule.docs
        }
        self._cursor = {d.doc_id: 0 for d in schedule.docs}
        os.makedirs(workdir, exist_ok=True)
        with open(os.path.join(workdir, "schedule.json"), "w") as f:
            f.write(schedule.to_json() + "\n")
        self.topic = DurableTopic(
            "deltas", 2, os.path.join(workdir, "topic"),
            encode=lambda m: m.to_json(),
            decode=SequencedMessage.from_json,
        )
        self.pool = ScribePool(
            self.topic, os.path.join(workdir, "scribe"),
            config=ScribeConfig(max_ops=16),
        )
        for i in range(2):
            self.pool.add_member(f"scribe-{i}")
        self._env = dict(os.environ)
        self._env.setdefault("JAX_PLATFORMS", "cpu")

    # --------------------------------------------------------------- spawn
    def _spawn(self, name: str, cmd: list, pipe: bool = True) -> subprocess.Popen:
        return subprocess.Popen(
            cmd,
            stdout=subprocess.PIPE if pipe
            else open(os.path.join(self.workdir, f"{name}.out"), "w"),
            stderr=open(os.path.join(self.workdir, f"{name}.err"), "w"),
            env=self._env,
        )

    def start_shards(self) -> None:
        for i in range(self.n_shards):
            proc = self._spawn(f"shard{i}", [
                sys.executable, "-m", "fluidframework_tpu.server.netserver",
                "--port", "0", "--http-port", "0", "--historian-port", "0",
                "--max-pending", str(self.max_pending),
                "--max-consumer-backlog", str(self.max_consumer_backlog),
            ])
            reader = _LineReader(proc.stdout)
            ready = json.loads(reader.readline(
                self.deadline, f"shard{i} readiness"
            ))
            self.shards.append(_ShardProc(
                proc=proc, reader=reader, port=ready["port"],
                http_port=ready["httpPort"],
                historian_port=ready["historianPort"],
            ))

    def start_fleets(self) -> None:
        """One fleet process per (shard, family) with docs there — each a
        checkpointed batched engine behind FleetConsumer, exactly the
        compose.yaml application tier."""
        serial = 0
        for si, shard in enumerate(self.shards):
            for family in FLEET_FAMILIES:
                fdocs = [
                    d.doc_id for d in self.sched.docs
                    if d.shard == si and d.family == family
                ]
                if not fdocs:
                    continue
                drain_file = os.path.join(
                    self.workdir, f"drain-{serial}.json"
                )
                cmd = [
                    sys.executable, "-m",
                    "fluidframework_tpu.server.fleet_main",
                    "--host", self.host, "--port", str(shard.port),
                    "--docs", ",".join(fdocs), "--family", family,
                    "--checkpoint-dir",
                    os.path.join(self.workdir, f"ckpt-{serial}"),
                    "--checkpoint-every", "32",
                    "--drain-file", drain_file,
                    "--status-every", "3600",
                    "--idle-sleep", "0.005",
                    "--megastep-k", "2",
                    "--metrics-port", "0",
                ]
                if family == "tree":
                    cmd += [
                        "--capacity", "256", "--pool-capacity", "1024",
                        "--max-insert-len", "4", "--ops-per-step", "8",
                    ]
                else:
                    cmd += [
                        "--capacity", "512", "--text-capacity", "8192",
                        "--max-insert-len", "8", "--ops-per-step", "8",
                    ]
                proc = self._spawn(f"fleet{serial}", cmd)
                fleet = _FleetProc(
                    proc=proc, reader=_LineReader(proc.stdout),
                    family=family, docs=fdocs, drain_file=drain_file,
                )
                # Readiness: skip restored/metricsPort preamble lines.
                while True:
                    line = json.loads(fleet.reader.readline(
                        self.deadline, f"fleet{serial} readiness",
                    ))
                    if "metricsPort" in line and "ready" not in line:
                        fleet.metrics_port = line["metricsPort"]
                    if line.get("ready"):
                        break
                self.fleets.append(fleet)
                serial += 1

    def start_workers(self) -> None:
        srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        srv.bind((self.host, 0))
        srv.listen(len(self.sched.workers))
        srv.settimeout(max(1.0, self.deadline - time.monotonic()))
        self._control_srv = srv
        control_port = srv.getsockname()[1]
        shards_cfg = [
            {
                "port": s.port,
                "http_port": s.http_port,
                "historian_port": s.historian_port,
            }
            for s in self.shards
        ]
        for ws in self.sched.workers:
            cfg = {
                "host": self.host,
                "control_port": control_port,
                "zipf_a": self.sched.zipf_a,
                "scopes": self.sched.scopes,
                "docs": [
                    {"doc_id": d.doc_id, "family": d.family, "shard": d.shard}
                    for d in self.sched.docs
                ],
                "shards": shards_cfg,
                "worker": {
                    "worker_id": ws.worker_id,
                    "seed": ws.seed,
                    "ramp_ops": ws.ramp_ops,
                    "steady_ops": ws.steady_ops,
                    "boots": ws.boots,
                    "reconnect_every": ws.reconnect_every,
                    "signal_every": ws.signal_every,
                    "interests": ws.interests,
                },
            }
            path = os.path.join(self.workdir, f"worker{ws.worker_id}.json")
            with open(path, "w") as f:
                json.dump(cfg, f, indent=2)
            self.workers.append(self._spawn(f"worker{ws.worker_id}", [
                sys.executable, "-m", "fluidframework_tpu.loadgen.worker",
                "--config", path,
            ], pipe=False))
        for _ in self.sched.workers:
            conn, _addr = srv.accept()
            conn.settimeout(max(1.0, self.deadline - time.monotonic()))
            rfile = conn.makefile("r", encoding="utf-8")
            hello = json.loads(rfile.readline())
            assert hello.get("t") == "hello", f"bad hello: {hello}"
            self.control[hello["worker"]] = (conn, rfile)
        assert len(self.control) == len(self.sched.workers)

    # ------------------------------------------------------------- barriers
    def run_barrier_phase(self, name: str) -> dict:
        """Release every worker into ``name`` together; block until every
        ``phase_done`` arrives.  Returns per-worker stats keyed by id."""
        for wid in sorted(self.control):
            sock, _ = self.control[wid]
            sock.sendall(
                (json.dumps({"t": "phase", "name": name}) + "\n").encode()
            )
        out = {}
        for wid in sorted(self.control):
            _, rfile = self.control[wid]
            line = rfile.readline()
            if not line:
                raise RuntimeError(
                    f"worker {wid} hung up during {name}: "
                    + self._worker_err_tail(wid)
                )
            resp = json.loads(line)
            if resp.get("t") == "error":
                raise RuntimeError(
                    f"worker {wid} failed in {name}:\n{resp['trace']}"
                )
            assert resp.get("phase") == name, f"barrier skew: {resp}"
            out[wid] = resp["stats"]
        return out

    def _worker_err_tail(self, wid: int) -> str:
        path = os.path.join(self.workdir, f"worker{wid}.err")
        try:
            with open(path) as f:
                return f.read()[-2000:]
        except OSError:
            return "<no stderr captured>"

    # --------------------------------------------------------------- mirror
    def mirror(self) -> None:
        """Page every doc's sequenced log over the HTTP deltas front into
        the coordinator's durable topic (the deployment's deltas-topic
        produce seam, here across a real process boundary) and fold the
        scribe pool over the new tail."""
        for doc in self.sched.docs:
            shard = self.shards[doc.shard]
            svc = HttpDeltaStorageService(
                _Http(self.host, shard.http_port), doc.doc_id
            )
            while True:
                cur = self._cursor[doc.doc_id]
                try:
                    batch = svc.get_deltas(cur + 1, cur + 512)
                except DriverError:
                    break  # doc not created yet (no traffic landed)
                if not batch:
                    break
                for m in batch:
                    self.topic.produce(doc.doc_id, m)
                    self.logs[doc.doc_id].append(m)
                self._cursor[doc.doc_id] = batch[-1].seq
        self.pool.pump()

    # ----------------------------------------------------------- boot storm
    def seed_snapshots(self) -> None:
        """Make the boot-storm phase REAL: upload each fleet doc's current
        oracle state as its snapshot (the scribe-summary analog over the
        HTTP storage front), so the historian serves representative
        payloads with live ETags."""
        for doc in self.sched.docs:
            if doc.family not in FLEET_FAMILIES:
                continue
            log = self.logs[doc.doc_id]
            seq = max((m.seq for m in log), default=0)
            state = ORACLES[doc.family](log)
            storage = HttpStorageService(
                _Http(self.host, self.shards[doc.shard].http_port),
                doc.doc_id,
            )
            storage.write_snapshot(seq, {"family": doc.family, "state": state})

    def historian_stats(self) -> dict:
        totals: dict[str, int] = {}
        for shard in self.shards:
            st = _http_json(self.host, shard.historian_port, "/status")
            for k, v in st.items():
                if isinstance(v, int):
                    totals[k] = totals.get(k, 0) + v
        return totals

    def shard_status(self) -> list:
        return [
            _http_json(self.host, s.http_port, "/status")
            for s in self.shards
        ]

    # ---------------------------------------------------------------- drain
    def drain_fleets(self) -> None:
        """Coordinated drain: drop per-doc target seqs (the mirrored OP
        head) into each fleet's drain file, then collect the final
        byte-identity state (texts/trees) from its done=true line."""
        want = {
            d.doc_id: max(
                (m.seq for m in self.logs[d.doc_id]
                 if m.type == MessageType.OP),
                default=0,
            )
            for d in self.sched.docs
            if d.family in FLEET_FAMILIES
        }
        for fleet in self.fleets:
            tmp = fleet.drain_file + ".tmp"
            with open(tmp, "w") as f:
                json.dump({"want": {d: want[d] for d in fleet.docs}}, f)
            os.replace(tmp, fleet.drain_file)  # never a torn read
        for fleet in self.fleets:
            while True:
                line = json.loads(fleet.reader.readline(
                    self.deadline,
                    f"fleet drain ({fleet.family}: {fleet.docs})",
                ))
                if line.get("done"):
                    fleet.final = line
                    break
            rc = fleet.proc.wait(
                timeout=max(1.0, self.deadline - time.monotonic())
            )
            assert rc == 0, f"fleet exited {rc}: {fleet.docs}"

    # -------------------------------------------------------------- verdict
    def verdict(self, drain_stats: dict) -> dict:
        failures: list = []
        converged = {f: 0 for f in FAMILIES}

        # Fleet tier: device state vs host oracle replay, byte identity.
        for fleet in self.fleets:
            states = fleet.final.get(
                "trees" if fleet.family == "tree" else "texts", {}
            )
            for doc_id in fleet.docs:
                want = _norm(ORACLES[fleet.family](self.logs[doc_id]))
                got = _norm(states.get(doc_id))
                if got != want:
                    failures.append(
                        f"{doc_id}: fleet diverged from oracle "
                        f"(got {got!r}, want {want!r})"
                    )

        # Every worker replica vs its family oracle.
        for doc in self.sched.docs:
            want = _norm(ORACLES[doc.family](self.logs[doc.doc_id]))
            ok = True
            for wid, stats in drain_stats.items():
                got = stats["digests"].get(doc.doc_id)
                if got != want:
                    ok = False
                    failures.append(
                        f"{doc.doc_id}: worker {wid} replica diverged "
                        f"(got {got!r}, want {want!r})"
                    )
            if ok:
                converged[doc.family] += 1

        # No double-acks across the scribe plane's topic.
        seen: set = set()
        doubles: list = []
        for p in range(self.topic.n_partitions):
            part = self.topic.partition(p)
            for rec in part.read(part.base):
                ack = parse_scribe_ack(rec.payload)
                if ack is not None:
                    key = (ack[0], ack[1])
                    if key in seen:
                        doubles.append(key)
                    seen.add(key)
        if doubles:
            failures.append(f"double-acked summaries: {doubles}")

        # Scoped presence: no worker ever received a foreign-scope signal,
        # and the fanout plane really dropped filtered deliveries.
        presence = {"sent": 0, "recv": 0, "foreign": 0}
        for stats in drain_stats.values():
            for k in presence:
                presence[k] += stats["presence"][k]
        if presence["foreign"]:
            failures.append(
                f"{presence['foreign']} foreign-scope presence deliveries"
            )
        statuses = self.shard_status()
        scope_drops = sum(
            s.get("fanout", {}).get("presence_scope_drops", 0)
            for s in statuses
        )
        if presence["sent"] and not scope_drops:
            failures.append(
                "presence published across the scope universe but the "
                "fanout plane recorded zero scoped drops"
            )

        if failures:
            raise LoadgenVerdictError(failures)
        return {
            "converged_docs": converged,
            "summary_acks": len(seen),
            "double_acks": 0,
            "presence": {**presence, "fanout_scope_drops": scope_drops},
            "shard_status": statuses,
        }

    # ----------------------------------------------------------------- run
    def run(self) -> dict:
        self.start_shards()
        self.start_fleets()
        self.start_workers()

        self.run_barrier_phase("ramp")
        self.mirror()
        self.run_barrier_phase("steady")
        self.mirror()

        self.seed_snapshots()
        hist_before = self.historian_stats()
        boot_stats = self.run_barrier_phase("boot_storm")
        hist_after = self.historian_stats()

        drain_stats = self.run_barrier_phase("drain")
        self.mirror()
        self.drain_fleets()
        verdict = self.verdict(drain_stats)

        for wid in sorted(self.control):
            sock, _ = self.control[wid]
            with contextlib.suppress(OSError):
                sock.sendall(b'{"t": "bye"}\n')
        for proc in self.workers:
            proc.wait(timeout=max(1.0, self.deadline - time.monotonic()))

        return self._report(drain_stats, boot_stats, verdict,
                            hist_before, hist_after)

    def _report(self, drain_stats, boot_stats, verdict,
                hist_before, hist_after) -> dict:
        # Lossless histogram merge: per-phase client op e2e latency across
        # every worker, exactly as if sampled in one process.
        merged: dict[str, Histogram] = {}
        counters: dict[str, int] = {}
        for stats in drain_stats.values():
            for name, wire in stats["hists"].items():
                h = Histogram.from_wire(wire)
                if name in merged:
                    merged[name].merge(h)
                else:
                    merged[name] = h
            for k, v in stats["counters"].items():
                counters[k] = counters.get(k, 0) + v

        def hist_row(h: Histogram | None) -> dict:
            if h is None or h.count == 0:
                return {"count": 0}
            return {
                "count": h.count,
                "p50_ms": round(h.percentile(0.5) * 1e3, 3),
                "p99_ms": round(h.percentile(0.99) * 1e3, 3),
                "max_ms": round(h.max * 1e3, 3),
            }

        fleet_rows = [
            {
                "family": f.family,
                "docs": f.docs,
                "rows": f.final.get("rows"),
                "bytes": f.final.get("bytes"),
                "pump_pauses": f.final.get("pump_pauses"),
                "pump_resumes": f.final.get("pump_resumes"),
            }
            for f in self.fleets
        ]
        shard_statuses = verdict.pop("shard_status")
        server = {
            "torn_sockets": sum(
                s.get("torn_sockets", 0) for s in shard_statuses
            ),
            "admission_shed_ops": sum(
                s.get("admission", {}).get("shed_ops", 0)
                for s in shard_statuses
            ),
            "admission_overload_events": sum(
                s.get("admission", {}).get("overload_events", 0)
                for s in shard_statuses
            ),
            "fleets": fleet_rows,
        }
        historian = {
            k: hist_after.get(k, 0) - hist_before.get(k, 0)
            for k in ("requests", "cold_serves", "not_modified_304")
        }
        return {
            "seed": self.sched.seed,
            "workers": len(self.sched.workers),
            "shards": self.n_shards,
            "docs": [
                {"doc_id": d.doc_id, "family": d.family, "shard": d.shard}
                for d in self.sched.docs
            ],
            "phases": {
                name: hist_row(merged.get(name))
                for name in ("ramp", "steady")
            },
            "boot_storm": {
                "cold": hist_row(merged.get("boot_cold")),
                "not_modified": hist_row(merged.get("boot_304")),
                "historian": historian,
                "per_worker_boots": {
                    str(w): s for w, s in sorted(boot_stats.items())
                },
            },
            "client": counters,
            "server": server,
            "convergence": {
                "verdict": "byte-identical",
                "converged_docs": verdict["converged_docs"],
            },
            "scribe": {
                "summary_acks": verdict["summary_acks"],
                "double_acks": verdict["double_acks"],
            },
            "presence": verdict["presence"],
        }

    # ------------------------------------------------------------- teardown
    def close(self) -> None:
        for wid in sorted(self.control):
            sock, rfile = self.control[wid]
            with contextlib.suppress(OSError):
                rfile.close()
                sock.close()
        if self._control_srv is not None:
            with contextlib.suppress(OSError):
                self._control_srv.close()
        procs = self.workers + [f.proc for f in self.fleets] + [
            s.proc for s in self.shards
        ]
        for proc in procs:
            if proc.poll() is None:
                proc.terminate()
        for proc in procs:
            with contextlib.suppress(subprocess.TimeoutExpired):
                proc.wait(timeout=10)
            if proc.poll() is None:
                proc.kill()
                proc.wait(timeout=10)
            if proc.stdout is not None:
                proc.stdout.close()
        self.pool.close()


DEFAULT_DOC_MATRIX = {
    "string": 2, "tree": 1, "map": 1, "matrix": 1, "chan_string": 1,
}


def run_loadgen(
    workdir: str,
    seed: int = 17,
    n_workers: int = 4,
    n_shards: int = 2,
    doc_matrix: dict | None = None,
    ramp_ops: int = 6,
    steady_ops: int = 18,
    boots: int = 4,
    deadline_s: float = 600.0,
    host: str = "127.0.0.1",
) -> dict:
    """Build the plant, run every phase, return the report dict (raises
    ``LoadgenVerdictError`` on any invariant violation)."""
    matrix = dict(doc_matrix or DEFAULT_DOC_MATRIX)
    docs: list = []
    i = 0
    for family in FAMILIES:
        for k in range(matrix.get(family, 0)):
            docs.append(DocSpec(
                doc_id=f"{family}{k}", family=family, shard=i % n_shards,
            ))
            i += 1
    assert any(d.family in FLEET_FAMILIES for d in docs), (
        "loadgen needs at least one fleet-consumed doc (string/tree)"
    )
    schedule = make_load_schedule(
        seed, n_workers, docs,
        ramp_ops=ramp_ops, steady_ops=steady_ops, boots=boots,
    )
    plant = LoadPlant(workdir, schedule, host=host, deadline_s=deadline_s)
    try:
        return plant.run()
    finally:
        plant.close()
