"""fftpu-check: AST/import-graph static analysis over the package.

Reference parity: the Fluid repo machine-enforces its architecture
(``layerInfo.json`` + the ``layer-check`` build command, SURVEY §1).  This
package is that idea widened to the hazard classes this repro's own history
documents: the PR 4 staging-aliasing bug was a use-after-donate, the PR 7
recompile watchdog only catches trace despecialization at *runtime*,
byte-identity convergence (BASELINE.json's core invariant) dies silently to
any nondeterministic host-path construct, and the PR 11-13 concurrency
plane's lock/donation laws lived only in CHANGES.md prose.  Eleven passes,
pure AST (no JAX import), findings suppressible via a committed
``baseline.json``:

- ``layer_check``      — downward-only imports per ``layers.json``
- ``jit_safety``       — trace hazards reachable from jit/shard_map entries
- ``donation``         — use-after-donate of ``donate_argnums`` arguments
- ``determinism``      — nondeterministic constructs in byte-identity paths
- ``threads``          — unlocked cross-thread attribute mutation
- ``swallowed``        — silently dropped exceptions in serving layers
- ``markchurn``        — mark-object churn back in the pooled tree fold
- ``lock_order``       — static deadlock detection (lock-acquisition graph)
- ``lock_consistency`` — lockset guard checking (lock A here, B there)
- ``blocking``         — blocking syscalls under declared critical locks
- ``mesh_safety``      — collective axis/spec/donation hazards in
  shard_map programs

The lock passes share one call-graph/lock-inheritance engine
(``core.PackageView``/``LockFlowScan``/``walk_lock_flow``) — per-pass
visitors over one worklist, not four private walkers.

Run ``fftpu-check fluidframework_tpu/`` (registered in pyproject), or see
``tests/test_analysis.py::test_package_is_clean`` — the tier-1 gate that
keeps every future PR clean.
"""

from .core import Baseline, Finding, PackageIndex, load_package  # noqa: F401

__all__ = ["Baseline", "Finding", "PackageIndex", "load_package"]
