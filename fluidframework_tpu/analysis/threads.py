"""Pass 5 — thread-shared-state: unlocked cross-thread attribute mutation.

The serving tier runs real threads — socketserver per-connection handlers,
``_QueuedWriter`` drain threads, the launcher's crash-restart supervisor,
metrics scrape handlers.  An attribute written from a thread body and read
from the host path without a common lock is a data race that presents as
a once-a-week flaky test (or a torn port number mid-rebalance).

Mechanics (per module, pure AST):

1. **Thread entries** — ``threading.Thread(target=X)`` where ``X`` is
   ``self.method``, a module function, or ``var.method`` with ``var``'s
   class known (constructor assignment or annotation); plus ``handle`` /
   ``do_*`` methods of ``socketserver``/``http.server`` handler subclasses
   (the library spawns those per request).
2. **Reachability** — entry bodies plus transitively called same-class
   ``self.`` methods, module functions, and methods on locally-typed vars.
   A callee reached ONLY from under a lock inherits the lock.
3. **Lock model** — ``with <name-or-attr>:`` counts as lock-held (covers
   ``Lock``/``RLock``/``Condition`` attributes; non-call context
   expressions are overwhelmingly locks in this codebase).
4. **Finding** — ``thread-unlocked-write``: an attribute assigned inside
   thread-reachable code outside any lock, where the same attribute name
   is also touched by non-thread code of the module (``__init__`` bodies
   are exempt on both sides: init-before-start is the safe idiom).

Thread-safe containers (``queue.Queue``, ``collections.deque`` method
calls) never trip this pass: method *calls* are not attribute writes.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass

from .core import Finding, Module, PackageIndex, dotted_name, resolve

HANDLER_BASES = {
    "StreamRequestHandler", "BaseRequestHandler", "DatagramRequestHandler",
    "BaseHTTPRequestHandler", "SimpleHTTPRequestHandler",
}


@dataclass(frozen=True)
class FuncKey:
    class_name: str | None
    name: str


class _ModuleView:
    """Per-module symbol tables the pass needs."""

    def __init__(self, mod: Module) -> None:
        self.mod = mod
        self.aliases = mod.aliases()
        self.functions: dict = {}    # FuncKey -> FunctionDef
        self.classes: dict = {}      # name -> ClassDef
        for node in mod.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.functions[FuncKey(None, node.name)] = node
            elif isinstance(node, ast.ClassDef):
                self.classes[node.name] = node
                for sub in node.body:
                    if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                        self.functions[FuncKey(node.name, sub.name)] = sub

    def handler_classes(self) -> set:
        out = set()
        for name, node in self.classes.items():
            for base in node.bases:
                dn = dotted_name(base) or ""
                if dn.split(".")[-1] in HANDLER_BASES:
                    out.add(name)
        return out


def _local_types(fn: ast.AST, view: _ModuleView) -> dict:
    """var name -> class name, from ``x = ClassName(...)`` and ``x: T``
    annotations (string annotations included)."""
    out: dict = {}
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and isinstance(node.value, ast.Call):
            dn = dotted_name(node.value.func)
            if dn in view.classes:
                out[node.targets[0].id] = dn
        elif isinstance(node, ast.AnnAssign) and isinstance(node.target, ast.Name):
            ann = node.annotation
            txt = (ann.value if isinstance(ann, ast.Constant)
                   else ast.unparse(ann))
            head = str(txt).strip().strip('"\'').split("[")[0].split(".")[-1]
            if head in view.classes:
                out[node.target.id] = head
    # Parameter annotations.
    args = getattr(fn, "args", None)
    if args is not None:
        for p in args.posonlyargs + args.args + args.kwonlyargs:
            if p.annotation is not None:
                txt = (p.annotation.value if isinstance(p.annotation, ast.Constant)
                       else ast.unparse(p.annotation))
                head = str(txt).strip().strip('"\'').split("[")[0].split(".")[-1]
                if head in view.classes:
                    out[p.arg] = head
    return out


_EXECUTOR_NAMES = (
    "concurrent.futures.ThreadPoolExecutor", "futures.ThreadPoolExecutor",
    "ThreadPoolExecutor",
)


def _note_entry(target, fn_key: FuncKey, types: dict, view: _ModuleView,
                entries: list) -> None:
    """Resolve a callable expression handed to a thread runtime (Thread
    target, Timer function, executor submit/map fn) to a FuncKey."""
    if isinstance(target, ast.Attribute) and isinstance(target.value, ast.Name):
        base, meth = target.value.id, target.attr
        if base == "self" and fn_key.class_name:
            entries.append(FuncKey(fn_key.class_name, meth))
        elif base in types:
            entries.append(FuncKey(types[base], meth))
    elif isinstance(target, ast.Name):
        if FuncKey(None, target.id) in view.functions:
            entries.append(FuncKey(None, target.id))


def _executor_vars(fn: ast.AST, aliases) -> set:
    """Local names bound to a ThreadPoolExecutor: ``x = ThreadPoolExecutor
    (...)`` and ``with ThreadPoolExecutor(...) as x:`` — the pool's worker
    threads run whatever ``x.submit``/``x.map`` is handed (the background
    restore fan-out shape this pass must cover)."""
    out: set = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and isinstance(node.value, ast.Call):
            if resolve(node.value.func, aliases) in _EXECUTOR_NAMES:
                out.add(node.targets[0].id)
        elif isinstance(node, ast.With):
            for item in node.items:
                if (
                    isinstance(item.context_expr, ast.Call)
                    and resolve(item.context_expr.func, aliases)
                    in _EXECUTOR_NAMES
                    and isinstance(item.optional_vars, ast.Name)
                ):
                    out.add(item.optional_vars.id)
    return out


def _thread_entries(view: _ModuleView) -> list:
    """FuncKeys the runtime invokes on their own thread: Thread targets,
    Timer functions, ThreadPoolExecutor submit/map callables, and
    socketserver/http handler methods."""
    entries: list = []
    for fn_key, fn in view.functions.items():
        types = _local_types(fn, view)
        executors = _executor_vars(fn, view.aliases)
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            fname = resolve(node.func, view.aliases)
            if fname in ("threading.Thread", "Thread"):
                for kw in node.keywords:
                    if kw.arg == "target":
                        _note_entry(kw.value, fn_key, types, view, entries)
            elif fname in ("threading.Timer", "Timer"):
                # Timer(interval, function): the function runs on the
                # timer's own thread.
                if len(node.args) >= 2:
                    _note_entry(node.args[1], fn_key, types, view, entries)
                for kw in node.keywords:
                    if kw.arg == "function":
                        _note_entry(kw.value, fn_key, types, view, entries)
            elif (
                isinstance(node.func, ast.Attribute)
                and node.func.attr in ("submit", "map")
                and isinstance(node.func.value, ast.Name)
                and node.func.value.id in executors
                and node.args
            ):
                # pool.submit(fn, ...) / pool.map(fn, items): fn runs on
                # the pool's worker threads.
                _note_entry(node.args[0], fn_key, types, view, entries)
    for cls in view.handler_classes():
        for fn_key in view.functions:
            if fn_key.class_name == cls and (
                    fn_key.name == "handle" or fn_key.name.startswith("do_")):
                entries.append(fn_key)
    return entries


def _is_lock_with(item: ast.withitem) -> bool:
    return isinstance(item.context_expr, (ast.Name, ast.Attribute))


class _ReachScan:
    """Collect call edges + attribute writes, tracking lock depth."""

    def __init__(self, view: _ModuleView, fn_key: FuncKey, locked: bool) -> None:
        self.view = view
        self.fn_key = fn_key
        self.types = _local_types(view.functions[fn_key], view)
        self.base_locked = locked
        self.writes: list = []     # (attr, line, locked)
        self.edges: list = []      # (FuncKey, locked_at_callsite)

    def run(self) -> None:
        fn = self.view.functions[self.fn_key]
        self._scan(fn.body, self.base_locked)

    def _scan(self, stmts: list, locked: bool) -> None:  # noqa: C901
        for st in stmts:
            if isinstance(st, ast.With):
                inner = locked or any(_is_lock_with(i) for i in st.items)
                for i in st.items:
                    self._expr(i.context_expr, locked)
                self._scan(st.body, inner)
                continue
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
                continue
            if isinstance(st, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                targets = (st.targets if isinstance(st, ast.Assign)
                           else [st.target])
                for t in targets:
                    self._note_write(t, locked)
                if getattr(st, "value", None) is not None:
                    self._expr(st.value, locked)
                continue
            if isinstance(st, (ast.If, ast.While)):
                self._expr(st.test, locked)
                self._scan(st.body, locked)
                self._scan(st.orelse, locked)
                continue
            if isinstance(st, ast.For):
                self._expr(st.iter, locked)
                self._scan(st.body, locked)
                self._scan(st.orelse, locked)
                continue
            if isinstance(st, ast.Try):
                self._scan(st.body, locked)
                for h in st.handlers:
                    self._scan(h.body, locked)
                self._scan(st.orelse, locked)
                self._scan(st.finalbody, locked)
                continue
            for node in ast.walk(st):
                if isinstance(node, ast.expr):
                    self._expr(node, locked, walk=False)

    def _note_write(self, target: ast.AST, locked: bool) -> None:
        if isinstance(target, (ast.Tuple, ast.List)):
            for e in target.elts:
                self._note_write(e, locked)
            return
        if isinstance(target, ast.Starred):
            self._note_write(target.value, locked)
            return
        if isinstance(target, ast.Subscript) and isinstance(
                target.value, ast.Attribute):
            # self.x[k] = v mutates the container held by attr x.
            target = target.value
        if isinstance(target, ast.Attribute):
            is_self = (isinstance(target.value, ast.Name)
                       and target.value.id == "self")
            self.writes.append((target.attr, target.lineno, locked, is_self))

    def _expr(self, node: ast.AST, locked: bool, walk: bool = True) -> None:
        nodes = ast.walk(node) if walk else [node]
        for n in nodes:
            if isinstance(n, ast.Call):
                self._call(n, locked)

    def _call(self, call: ast.Call, locked: bool) -> None:
        func = call.func
        if isinstance(func, ast.Name):
            key = FuncKey(None, func.id)
            if key in self.view.functions:
                self.edges.append((key, locked))
        elif isinstance(func, ast.Attribute) and isinstance(func.value, ast.Name):
            base, meth = func.value.id, func.attr
            cls = None
            if base == "self":
                cls = self.fn_key.class_name
            elif base in self.types:
                cls = self.types[base]
            if cls is not None:
                key = FuncKey(cls, meth)
                if key in self.view.functions:
                    self.edges.append((key, locked))


def run(index: PackageIndex) -> list[Finding]:
    findings: list[Finding] = []
    for mod in index.modules:
        view = _ModuleView(mod)
        entries = _thread_entries(view)
        if not entries:
            continue

        # Reachability with lock inheritance: state[key] = unlocked-reached?
        # (reached unlocked anywhere wins over locked).
        state: dict = {}
        work: list = [(k, False) for k in entries]
        scans: dict = {}
        while work:
            key, locked = work.pop()
            prev = state.get(key)
            if prev is not None and (prev is False or locked):
                continue  # already at least this exposed
            state[key] = locked if prev is None else (prev and locked)
            if key not in view.functions:
                continue
            scan = _ReachScan(view, key, state[key])
            scan.run()
            scans[key] = scan
            for callee, callsite_locked in scan.edges:
                work.append((callee, callsite_locked or state[key]))

        thread_keys = set(scans)

        # Attribute touches from NON-thread code (reads or writes), minus
        # __init__ everywhere (init-before-start is the safe idiom).  Each
        # entry remembers whether it was a ``self.X`` access and from which
        # class, so a thread-side ``self.X`` write in class C never matches
        # another class's own ``self.X`` (different objects, no race).
        outside: dict = {}
        for fn_key, fn in view.functions.items():
            if fn_key in thread_keys or fn_key.name == "__init__":
                continue
            for node in ast.walk(fn):
                if isinstance(node, ast.Attribute):
                    is_self = (isinstance(node.value, ast.Name)
                               and node.value.id == "self")
                    outside.setdefault(node.attr, []).append(
                        (fn_key, node.lineno, is_self))

        for key, scan in scans.items():
            if key.name == "__init__":
                continue
            fn_label = (f"{key.class_name}.{key.name}" if key.class_name
                        else key.name)
            for attr, line, locked, write_is_self in scan.writes:
                if locked or attr not in outside:
                    continue
                candidates = outside[attr]
                if write_is_self:
                    candidates = [
                        c for c in candidates
                        if not c[2] or c[0].class_name == key.class_name
                    ]
                if not candidates:
                    continue
                other_key, other_line, _self = candidates[0]
                other_label = (f"{other_key.class_name}.{other_key.name}"
                               if other_key.class_name else other_key.name)
                findings.append(Finding(
                    rule="thread-unlocked-write",
                    file=mod.rel, line=line,
                    message=(
                        f"{fn_label} (thread body) writes `.{attr}` without "
                        f"a lock; `{other_label}` (line {other_line}) touches "
                        "it from outside the thread"
                    ),
                    hint=(
                        "guard both sides with the owning object's lock, or "
                        "baseline with a rationale if the race is benign"
                    ),
                    detail=f"{fn_label}: unlocked write to .{attr}",
                ))
    # Dedup per (rule, file, detail): a loop writing the same attr twice is
    # one finding per write site though — keep line in the key.
    seen: set = set()
    out: list = []
    for f in findings:
        k = (f.rule, f.file, f.line, f.detail)
        if k not in seen:
            seen.add(k)
            out.append(f)
    return out
