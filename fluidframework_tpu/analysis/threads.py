"""Pass 5 — thread-shared-state: unlocked cross-thread attribute mutation.

The serving tier runs real threads — socketserver per-connection handlers,
the fanout writer drain thread, the launcher's crash-restart supervisor,
metrics scrape handlers.  An attribute written from a thread body and read
from the host path without a common lock is a data race that presents as
a once-a-week flaky test (or a torn port number mid-rebalance).

Mechanics (per module, on the shared ``core`` walkers):

1. **Thread entries** — ``threading.Thread(target=X)`` where ``X`` is
   ``self.method``, a module function, or ``var.method`` with ``var``'s
   class known (constructor assignment or annotation); ``threading.Timer``
   functions; ``ThreadPoolExecutor`` submit/map callables; plus ``handle``
   / ``do_*`` methods of ``socketserver``/``http.server`` handler
   subclasses (the library spawns those per request).
2. **Reachability** — ``core.walk_lock_flow``: entry bodies plus
   transitively called same-class ``self.`` methods, module functions, and
   methods on locally-typed vars.  A callee reached ONLY from under a lock
   inherits the lock (the held set rides the call edge).
3. **Lock model** — ``with <name-or-attr>:`` counts as lock-held (covers
   ``Lock``/``RLock``/``Condition`` attributes; non-call context
   expressions are overwhelmingly locks in this codebase).
4. **Finding** — ``thread-unlocked-write``: an attribute assigned inside
   thread-reachable code outside any lock, where the same attribute name
   is also touched by non-thread code of the module (``__init__`` bodies
   are exempt on both sides: init-before-start is the safe idiom).

Thread-safe containers (``queue.Queue``, ``collections.deque`` method
calls) never trip this pass: method *calls* are not attribute writes.

The *which-lock* refinement — a write guarded by lock A here and lock B
(or nothing) there — is the ``lock-consistency`` pass, which shares this
pass's entry discovery and walker.
"""

from __future__ import annotations

import ast

from .core import (
    Finding,
    FuncKey,
    LockFlowScan,
    LockNamer,
    ModuleView,
    PackageIndex,
    local_types,
    resolve,
    walk_lock_flow,
)

_EXECUTOR_NAMES = (
    "concurrent.futures.ThreadPoolExecutor", "futures.ThreadPoolExecutor",
    "ThreadPoolExecutor",
)


def _note_entry(target, fn_key: FuncKey, types: dict, view: ModuleView,
                entries: list) -> None:
    """Resolve a callable expression handed to a thread runtime (Thread
    target, Timer function, executor submit/map fn) to a FuncKey."""
    if isinstance(target, ast.Attribute) and isinstance(target.value, ast.Name):
        base, meth = target.value.id, target.attr
        if base == "self" and fn_key.class_name:
            entries.append(FuncKey(fn_key.class_name, meth))
        elif base in types:
            entries.append(FuncKey(types[base], meth))
    elif isinstance(target, ast.Name):
        if FuncKey(None, target.id) in view.functions:
            entries.append(FuncKey(None, target.id))


def _executor_vars(fn: ast.AST, aliases) -> set:
    """Local names bound to a ThreadPoolExecutor: ``x = ThreadPoolExecutor
    (...)`` and ``with ThreadPoolExecutor(...) as x:`` — the pool's worker
    threads run whatever ``x.submit``/``x.map`` is handed (the background
    restore fan-out shape this pass must cover)."""
    out: set = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and isinstance(node.value, ast.Call):
            if resolve(node.value.func, aliases) in _EXECUTOR_NAMES:
                out.add(node.targets[0].id)
        elif isinstance(node, ast.With):
            for item in node.items:
                if (
                    isinstance(item.context_expr, ast.Call)
                    and resolve(item.context_expr.func, aliases)
                    in _EXECUTOR_NAMES
                    and isinstance(item.optional_vars, ast.Name)
                ):
                    out.add(item.optional_vars.id)
    return out


def thread_entries(view: ModuleView) -> list:
    """FuncKeys the runtime invokes on their own thread: Thread targets,
    Timer functions, ThreadPoolExecutor submit/map callables, and
    socketserver/http handler methods.  Shared with lock-consistency."""
    entries: list = []
    for fn_key, fn in view.functions.items():
        types = local_types(fn, view)
        executors = _executor_vars(fn, view.aliases)
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            fname = resolve(node.func, view.aliases)
            if fname in ("threading.Thread", "Thread"):
                for kw in node.keywords:
                    if kw.arg == "target":
                        _note_entry(kw.value, fn_key, types, view, entries)
            elif fname in ("threading.Timer", "Timer"):
                # Timer(interval, function): the function runs on the
                # timer's own thread.
                if len(node.args) >= 2:
                    _note_entry(node.args[1], fn_key, types, view, entries)
                for kw in node.keywords:
                    if kw.arg == "function":
                        _note_entry(kw.value, fn_key, types, view, entries)
            elif (
                isinstance(node.func, ast.Attribute)
                and node.func.attr in ("submit", "map")
                and isinstance(node.func.value, ast.Name)
                and node.func.value.id in executors
                and node.args
            ):
                # pool.submit(fn, ...) / pool.map(fn, items): fn runs on
                # the pool's worker threads.
                _note_entry(node.args[0], fn_key, types, view, entries)
    for cls in view.handler_classes():
        for fn_key in view.functions:
            if fn_key.class_name == cls and (
                    fn_key.name == "handle" or fn_key.name.startswith("do_")):
                entries.append(fn_key)
    return entries


def local_resolver(view: ModuleView, key: FuncKey, types: dict):
    """Module-scoped call resolution (the per-module passes' flavor of
    ``PackageView.resolve_call``): module functions, ``self.`` methods,
    locally-typed var methods."""
    def _resolve(call: ast.Call, _types=types) -> FuncKey | None:
        func = call.func
        if isinstance(func, ast.Name):
            k = FuncKey(None, func.id)
            if k in view.functions:
                return k
        elif isinstance(func, ast.Attribute) and isinstance(
                func.value, ast.Name):
            base, meth = func.value.id, func.attr
            cls = key.class_name if base == "self" else _types.get(base)
            if cls is not None:
                k = FuncKey(cls, meth)
                if k in view.functions:
                    return k
        return None
    return _resolve


def module_lock_scans(view: ModuleView, entries: list,
                      shared_locks: frozenset = frozenset()) -> dict:
    """Walk a module's thread-reachable code with lock inheritance; returns
    ``{FuncKey: {held_frozenset: LockFlowScan | None}}``.  Shared by the
    threads and lock-consistency passes."""
    namer = LockNamer(shared_locks)
    mod = view.mod

    def make_scan(key, held):
        fn = view.functions.get(key)
        if fn is None:
            return None
        types = local_types(fn, view)
        return LockFlowScan(
            fn, held, namer, modname=mod.modname,
            class_name=key.class_name, types=types,
            resolver=local_resolver(view, key, types),
        ).run()

    return walk_lock_flow([(k, frozenset()) for k in entries], make_scan)


def run(index: PackageIndex) -> list[Finding]:
    findings: list[Finding] = []
    for mod in index.modules:
        view = ModuleView(mod)
        entries = thread_entries(view)
        if not entries:
            continue

        scans = module_lock_scans(view, entries)
        thread_keys = {
            k for k, ctxs in scans.items()
            if any(s is not None for s in ctxs.values())
        }

        # Attribute touches from NON-thread code (reads or writes), minus
        # __init__ everywhere (init-before-start is the safe idiom).  Each
        # entry remembers whether it was a ``self.X`` access and from which
        # class, so a thread-side ``self.X`` write in class C never matches
        # another class's own ``self.X`` (different objects, no race).
        outside: dict = {}
        for fn_key, fn in view.functions.items():
            if fn_key in thread_keys or fn_key.name == "__init__":
                continue
            for node in ast.walk(fn):
                if isinstance(node, ast.Attribute):
                    is_self = (isinstance(node.value, ast.Name)
                               and node.value.id == "self")
                    outside.setdefault(node.attr, []).append(
                        (fn_key, node.lineno, is_self))

        for key, ctxs in scans.items():
            if key.name == "__init__":
                continue
            fn_label = key.label()
            for scan in ctxs.values():
                if scan is None:
                    continue
                for attr, line, held, write_is_self, _owner in scan.writes:
                    if held or attr not in outside:
                        continue
                    candidates = outside[attr]
                    if write_is_self:
                        candidates = [
                            c for c in candidates
                            if not c[2] or c[0].class_name == key.class_name
                        ]
                    if not candidates:
                        continue
                    other_key, other_line, _self = candidates[0]
                    findings.append(Finding(
                        rule="thread-unlocked-write",
                        file=mod.rel, line=line,
                        message=(
                            f"{fn_label} (thread body) writes `.{attr}` "
                            f"without a lock; `{other_key.label()}` (line "
                            f"{other_line}) touches it from outside the "
                            "thread"
                        ),
                        hint=(
                            "guard both sides with the owning object's "
                            "lock, or baseline with a rationale if the "
                            "race is benign"
                        ),
                        detail=f"{fn_label}: unlocked write to .{attr}",
                    ))
    # Dedup per (rule, file, line, detail): multiple reach contexts can
    # re-observe the same write site.
    seen: set = set()
    out: list = []
    for f in findings:
        k = (f.rule, f.file, f.line, f.detail)
        if k not in seen:
            seen.add(k)
            out.append(f)
    return out
