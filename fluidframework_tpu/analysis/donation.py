"""Pass 3 — donation: use-after-donate of ``donate_argnums`` arguments.

The PR 4 ``staging_aliased_swaps`` bug was exactly this class: a buffer
handed to a donated dispatch and then touched again on the host while XLA
already owned (and was overwriting) it.  On CPU the aliasing makes it a
silent corruption; on TPU a deleted-buffer error *if you're lucky*.

Mechanics: the jit-registration scan (shared with jit_safety) records every
callable wrapped with a non-empty ``donate_argnums`` — module-level
``X = jax.jit(f, donate_argnums=(0,))``, decorated defs,
``self._prog = mesh_fleet_program(...)`` (donates arg 0) — then every
function body is walked with a small dataflow: calling a donating callable
marks the argument expressions at donated positions (plain names or
``self.attr`` chains) as *surrendered*; any later read before a rebinding
is a ``donate-use-after-dispatch`` finding.  Branches analyze both arms
(union — donated in either arm is donated after), and loop bodies run
twice so a donation at the bottom of a loop poisons uses at the top of the
next iteration (the classic "dispatch in a loop without rebinding" bug).

The idiomatic pattern stays silent::

    self._state = self._megastep(self._state, ops, pays)   # rebind kills it
"""

from __future__ import annotations

import ast

from .core import Finding, Module, PackageIndex, build_func_index, dotted_name
from .jit_safety import scan_registrations


def _donators(index: PackageIndex) -> dict:
    """Callable key -> donated positions.

    Keys: fully-qualified bound names (``pkg.mod.X``) and bare ``self.X``
    attribute names (matched per call site on ``self.X(...)``)."""
    func_index = build_func_index(index)
    out: dict = {}
    for reg in scan_registrations(index, func_index):
        if not reg.wrap.donate_argnums or reg.bound_to is None:
            continue
        out[reg.bound_to] = frozenset(reg.wrap.donate_argnums)
    return out


class _FuncDonationScan:
    def __init__(self, mod: Module, donators: dict, display: str,
                 findings: list) -> None:
        self.mod = mod
        self.aliases = mod.aliases()
        self.donators = donators
        self.display = display
        self.findings = findings

    def _call_donates(self, call: ast.Call) -> frozenset | None:
        dn = dotted_name(call.func)
        if dn is None:
            return None
        if dn in self.donators:               # self.X(...) form
            return self.donators[dn]
        head = dn.split(".")[0]
        fq = self.aliases.get(head, None)
        if fq is not None:
            rest = dn.split(".", 1)
            cand = fq if len(rest) == 1 else f"{fq}.{rest[1]}"
            if cand in self.donators:
                return self.donators[cand]
        cand = f"{self.mod.modname}.{dn}"
        return self.donators.get(cand)

    @staticmethod
    def _argkey(expr: ast.AST) -> str | None:
        """Donated-argument tracking key: plain name or dotted attr chain."""
        return dotted_name(expr)

    def _loads_in(self, node: ast.AST) -> list:
        """(key, line) for every Name/Attribute *load* chain in ``node``."""
        out = []
        for n in ast.walk(node):
            if isinstance(n, (ast.Name, ast.Attribute)) and isinstance(
                    getattr(n, "ctx", None), ast.Load):
                # Only take maximal chains: skip if parent is an Attribute
                # load (handled at the parent).  Cheap approximation: emit
                # every chain; duplicates are harmless for matching.
                k = dotted_name(n)
                if k:
                    out.append((k, getattr(n, "lineno", 0)))
        return out

    def scan(self, stmts: list, donated: dict) -> dict:  # noqa: C901
        """``donated``: key -> line of the donating call.  Returns the
        donated set live at the end of the block."""
        for st in stmts:
            if isinstance(st, ast.If):
                # The test evaluates FIRST: a donating call inside it (e.g.
                # ``if prog(state, ops) is None:``) poisons both arms.
                self._check_expr(st.test, donated)
                d1 = self.scan(st.body, dict(donated))
                d2 = self.scan(st.orelse, dict(donated))
                donated = {**d1, **d2}
                continue
            if isinstance(st, (ast.For, ast.While)):
                if isinstance(st, ast.While):
                    self._check_expr(st.test, donated)
                else:
                    self._check_expr(st.iter, donated)
                # Two passes: donations at the bottom of the body reach
                # uses at the top on the next iteration.
                d = self.scan(st.body, dict(donated))
                d = self.scan(st.body, d)
                d = self.scan(st.orelse, d)
                donated = {**donated, **d}
                continue
            if isinstance(st, ast.Try):
                d = self.scan(st.body, dict(donated))
                for h in st.handlers:
                    d = self.scan(h.body, d)
                d = self.scan(st.orelse, d)
                donated = self.scan(st.finalbody, {**donated, **d})
                continue
            if isinstance(st, ast.With):
                for item in st.items:
                    self._check_expr(item.context_expr, donated)
                donated = self.scan(st.body, donated)
                continue
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
                continue  # nested scope
            # Straight-line statement: check uses, record fresh donations,
            # THEN apply rebindings — `x = prog(x)` donates x and rebinds
            # it in the same statement, leaving nothing donated after.
            new_donations = self._check_stmt_uses_and_calls(st, donated)
            for k, line in new_donations.items():
                donated[k] = line
            if isinstance(st, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                targets = st.targets if isinstance(st, ast.Assign) else [st.target]
                for t in targets:
                    self._kill(t, donated)
        return donated

    def _kill(self, target: ast.AST, donated: dict) -> None:
        if isinstance(target, (ast.Tuple, ast.List)):
            for e in target.elts:
                self._kill(e, donated)
            return
        if isinstance(target, ast.Starred):
            self._kill(target.value, donated)
            return
        k = dotted_name(target)
        if k is None and isinstance(target, ast.Subscript):
            k = dotted_name(target.value)
        if k:
            donated.pop(k, None)

    def _check_stmt_uses_and_calls(self, st: ast.AST, donated: dict) -> dict:
        """Flag reads of donated keys in ``st``; return fresh donations made
        by calls inside it (applied by the caller AFTER same-line rebinds
        are NOT yet visible -> a use in the very statement that donates is
        the call's own argument list, which is fine)."""
        new: dict = {}
        calls = [n for n in ast.walk(st) if isinstance(n, ast.Call)]
        donating_arg_nodes: set = set()
        for call in calls:
            positions = self._call_donates(call)
            if not positions:
                continue
            for i, arg in enumerate(call.args):
                if i in positions:
                    k = self._argkey(arg)
                    if k:
                        new[k] = call.lineno
                    for n in ast.walk(arg):
                        donating_arg_nodes.add(id(n))
        # Uses of previously-donated keys anywhere in this statement.  The
        # donating call's own arguments are exempt only for donations this
        # statement makes — feeding a buffer donated by an EARLIER dispatch
        # back in (the loop-without-rebind bug) is a use like any other.
        if donated:
            for n in ast.walk(st):
                if isinstance(n, (ast.Name, ast.Attribute)) and isinstance(
                        getattr(n, "ctx", None), ast.Load):
                    k = dotted_name(n)
                    if id(n) in donating_arg_nodes and k not in donated:
                        continue
                    if k in donated:
                        self.findings.append(Finding(
                            rule="donate-use-after-dispatch",
                            file=self.mod.rel,
                            line=getattr(n, "lineno", 0),
                            message=(
                                f"{self.display}: `{k}` read after being "
                                f"donated to a dispatch at line {donated[k]} "
                                "(XLA owns that buffer now)"
                            ),
                            hint=(
                                "rebind the name to the dispatch result "
                                "(x = prog(x, ...)) or pass a copy"
                            ),
                            detail=f"{self.display}: use of `{k}` after donation",
                        ))
                        donated.pop(k, None)  # one finding per donation
        return new

    def _check_expr(self, expr: ast.AST | None, donated: dict) -> None:
        if expr is None:
            return
        fake = ast.Expr(value=expr)
        ast.copy_location(fake, expr)
        new = self._check_stmt_uses_and_calls(fake, donated)
        for k, line in new.items():
            donated[k] = line


def run(index: PackageIndex) -> list[Finding]:
    donators = _donators(index)
    findings: list[Finding] = []
    if not donators:
        return findings
    for mod in index.modules:
        for node in ast.walk(mod.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                scan = _FuncDonationScan(mod, donators, node.name, findings)
                scan.scan(node.body, {})
    return findings
