"""``fftpu-check``: run every pass over the package, apply the baseline.

Usage::

    fftpu-check fluidframework_tpu/            # exit 0 iff clean
    fftpu-check fluidframework_tpu/ --json     # machine-readable (bench/CI)
    fftpu-check pkg/ --rules layer-check,determinism
    fftpu-check pkg/ --no-baseline             # include suppressed findings

Exit codes: 0 clean, 1 unsuppressed findings, 2 usage/config error.

The default layers/baseline configs are the committed
``<pkg>/analysis/layers.json`` and ``<pkg>/analysis/baseline.json``; both
are overridable so tests (and other repos) can point at fixtures.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

from . import (
    determinism, donation, jit_safety, layer_check, markchurn, swallowed,
    threads,
)
from .core import Baseline, Finding, load_package

PASSES = (
    "layer-check", "jit-safety", "donation", "determinism", "threads",
    "swallowed-exception", "fold-mark-churn",
)


def run_all(
    pkg_dir: Path | str,
    layers_path: Path | str | None = None,
    baseline_path: Path | str | None = None,
    rules: list | None = None,
) -> dict:
    """Run the suite; -> {"findings", "suppressed", "stale_baseline",
    "counts", "n_modules"} with findings sorted by (file, line)."""
    pkg_dir = Path(pkg_dir).resolve()
    if not pkg_dir.is_dir():
        raise FileNotFoundError(f"not a package directory: {pkg_dir}")
    if layers_path is None:
        layers_path = pkg_dir / "analysis" / "layers.json"
    if baseline_path is None:
        cand = pkg_dir / "analysis" / "baseline.json"
        baseline_path = cand if cand.exists() else None

    index = load_package(pkg_dir)
    layers_cfg = json.loads(Path(layers_path).read_text())
    layer_map = layer_check.load_layers(layers_path)
    det_scope = layers_cfg.get("determinism_scope", [])

    selected = set(rules or PASSES)
    unknown = selected - set(PASSES)
    if unknown:
        raise ValueError(f"unknown pass(es): {sorted(unknown)} (know {PASSES})")

    findings: list[Finding] = []
    if "layer-check" in selected:
        findings += layer_check.run(index, layer_map)
    if "jit-safety" in selected:
        findings += jit_safety.run(index)
    if "donation" in selected:
        findings += donation.run(index)
    if "determinism" in selected:
        findings += determinism.run(index, det_scope)
    if "threads" in selected:
        findings += threads.run(index)
    if "swallowed-exception" in selected:
        findings += swallowed.run(
            index, layer_map, layers_cfg.get("swallowed_scope")
        )
    if "fold-mark-churn" in selected:
        findings += markchurn.run(index, layers_cfg.get("fold_churn_scope"))
    findings.sort(key=lambda f: (f.file, f.line, f.rule))

    baseline = Baseline.load(baseline_path) if baseline_path else Baseline()
    unsuppressed, suppressed, stale = baseline.apply(findings)
    counts: dict = {}
    for f in unsuppressed:
        counts[f.rule] = counts.get(f.rule, 0) + 1
    return {
        "findings": unsuppressed,
        "suppressed": suppressed,
        "stale_baseline": stale,
        "counts": counts,
        "n_modules": len(index.modules),
    }


def main(argv: list | None = None) -> int:
    p = argparse.ArgumentParser(
        prog="fftpu-check",
        description="layer-check + JAX-safety static analysis (pure AST)",
    )
    p.add_argument("package", nargs="?", default="fluidframework_tpu",
                   help="package directory to analyze")
    p.add_argument("--layers", default=None, help="layers.json override")
    p.add_argument("--baseline", default=None, help="baseline.json override")
    p.add_argument("--no-baseline", action="store_true",
                   help="report suppressed findings too")
    p.add_argument("--rules", default=None,
                   help=f"comma-separated subset of {','.join(PASSES)}")
    p.add_argument("--json", dest="as_json", action="store_true",
                   help="machine-readable output (bench/CI artifacts)")
    args = p.parse_args(argv)

    try:
        result = run_all(
            args.package,
            layers_path=args.layers,
            baseline_path=args.baseline,
            rules=args.rules.split(",") if args.rules else None,
        )
    except SyntaxError as e:
        # A malformed file in the analyzed tree is a usage-class error
        # (exit 2), not a crash: report the offending file:line.
        print(f"fftpu-check: cannot parse {e.filename}:{e.lineno}: {e.msg}",
              file=sys.stderr)
        return 2
    except (FileNotFoundError, ValueError, json.JSONDecodeError,
            UnicodeDecodeError, OSError) as e:
        print(f"fftpu-check: {e}", file=sys.stderr)
        return 2

    shown = list(result["findings"])
    if args.no_baseline:
        shown += result["suppressed"]
        shown.sort(key=lambda f: (f.file, f.line, f.rule))

    if args.as_json:
        print(json.dumps({
            "clean": not result["findings"],
            "n_modules": result["n_modules"],
            "counts": result["counts"],
            "n_suppressed": len(result["suppressed"]),
            "stale_baseline": result["stale_baseline"],
            "findings": [f.to_json() for f in shown],
        }, indent=2))
    else:
        for f in shown:
            print(f.render())
        for e in result["stale_baseline"]:
            print(
                f"stale-baseline  {e.get('file')}  entry no longer matches "
                f"anything: {e.get('rule')} {e.get('detail')!r} — remove it"
            )
        n = len(result["findings"])
        print(
            f"fftpu-check: {result['n_modules']} modules, "
            f"{n} finding{'s' if n != 1 else ''}, "
            f"{len(result['suppressed'])} baselined, "
            f"{len(result['stale_baseline'])} stale baseline entr"
            f"{'ies' if len(result['stale_baseline']) != 1 else 'y'}"
        )
    return 1 if result["findings"] else 0


if __name__ == "__main__":
    raise SystemExit(main())
