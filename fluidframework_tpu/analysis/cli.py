"""``fftpu-check``: run every pass over the package, apply the baseline.

Usage::

    fftpu-check fluidframework_tpu/            # exit 0 iff clean
    fftpu-check fluidframework_tpu/ --json     # machine-readable (bench/CI)
    fftpu-check pkg/ --rules layer-check,determinism
    fftpu-check pkg/ --no-baseline             # include suppressed findings
    fftpu-check pkg/ --changed-only            # pre-commit: git-diff scope

Exit codes: 0 clean, 1 unsuppressed findings, 2 usage/config error.

``--changed-only`` scopes the REPORT to modules touched by the working
tree's ``git diff`` (staged + unstaged + untracked).  The analysis itself
still runs package-wide — the cross-module passes (layer-check edges,
lock-order cycles, blocking-under-lock reach) need the whole call/import
graph to be sound — so the scoping degrades gracefully: a changed module
that completes a cross-module hazard still reports it, an unchanged
module's legacy findings stay out of the pre-commit loop.  Per-pass wall
time ships in ``--json`` (``pass_times_ms``) either way, so the bench/CI
artifacts can watch the gate's own budget.

The default layers/baseline configs are the committed
``<pkg>/analysis/layers.json`` and ``<pkg>/analysis/baseline.json``; both
are overridable so tests (and other repos) can point at fixtures.
"""

from __future__ import annotations

import argparse
import json
import subprocess
import sys
import time
from pathlib import Path

from . import (
    blocking, determinism, donation, jit_safety, layer_check,
    lock_consistency, lock_order, markchurn, mesh_safety, swallowed, threads,
)
from .core import Baseline, Finding, load_package

PASSES = (
    "layer-check", "jit-safety", "donation", "determinism", "threads",
    "swallowed-exception", "fold-mark-churn", "lock-order",
    "lock-consistency", "blocking-under-lock", "mesh-safety",
)


def run_all(
    pkg_dir: Path | str,
    layers_path: Path | str | None = None,
    baseline_path: Path | str | None = None,
    rules: list | None = None,
    only_files: set | None = None,
) -> dict:
    """Run the suite; -> {"findings", "suppressed", "stale_baseline",
    "counts", "n_modules", "pass_times_ms"} with findings sorted by
    (file, line).  ``only_files`` (relative posix paths) filters the
    REPORTED findings — the analysis is package-wide regardless (see
    --changed-only)."""
    pkg_dir = Path(pkg_dir).resolve()
    if not pkg_dir.is_dir():
        raise FileNotFoundError(f"not a package directory: {pkg_dir}")
    if layers_path is None:
        layers_path = pkg_dir / "analysis" / "layers.json"
    if baseline_path is None:
        cand = pkg_dir / "analysis" / "baseline.json"
        baseline_path = cand if cand.exists() else None

    index = load_package(pkg_dir)
    layers_cfg = json.loads(Path(layers_path).read_text())
    layer_map = layer_check.load_layers(layers_path)
    det_scope = layers_cfg.get("determinism_scope", [])
    concurrency_scope = layers_cfg.get("concurrency_scope")
    mesh_scope = layers_cfg.get("mesh_scope")

    selected = set(rules or PASSES)
    unknown = selected - set(PASSES)
    if unknown:
        raise ValueError(f"unknown pass(es): {sorted(unknown)} (know {PASSES})")

    runners = {
        "layer-check": lambda: layer_check.run(index, layer_map),
        "jit-safety": lambda: jit_safety.run(index),
        "donation": lambda: donation.run(index),
        "determinism": lambda: determinism.run(index, det_scope),
        "threads": lambda: threads.run(index),
        "swallowed-exception": lambda: swallowed.run(
            index, layer_map, layers_cfg.get("swallowed_scope")
        ),
        "fold-mark-churn": lambda: markchurn.run(
            index, layers_cfg.get("fold_churn_scope")
        ),
        "lock-order": lambda: lock_order.run(index, concurrency_scope),
        "lock-consistency": lambda: lock_consistency.run(
            index, concurrency_scope
        ),
        "blocking-under-lock": lambda: blocking.run(index, concurrency_scope),
        "mesh-safety": lambda: mesh_safety.run(index, mesh_scope),
    }

    findings: list[Finding] = []
    pass_times_ms: dict = {}
    for name in PASSES:
        if name not in selected:
            continue
        t0 = time.perf_counter()
        findings += runners[name]()
        pass_times_ms[name] = round((time.perf_counter() - t0) * 1e3, 2)
    findings.sort(key=lambda f: (f.file, f.line, f.rule))

    baseline = Baseline.load(baseline_path) if baseline_path else Baseline()
    unsuppressed, suppressed, stale = baseline.apply(findings)
    if only_files is not None:
        unsuppressed = [f for f in unsuppressed if f.file in only_files]
        suppressed = [f for f in suppressed if f.file in only_files]
        stale = []  # full-tree bookkeeping: not a pre-commit concern
    counts: dict = {}
    for f in unsuppressed:
        counts[f.rule] = counts.get(f.rule, 0) + 1
    return {
        "findings": unsuppressed,
        "suppressed": suppressed,
        "stale_baseline": stale,
        "counts": counts,
        "n_modules": len(index.modules),
        "pass_times_ms": pass_times_ms,
    }


def changed_files(pkg_dir: Path | str) -> set:
    """Working-tree changes vs HEAD (staged + unstaged + untracked),
    as the package-root-relative posix paths findings carry."""
    pkg_dir = Path(pkg_dir).resolve()
    root = pkg_dir.parent
    out: set = set()
    for cmd in (
        # --relative: paths against OUR cwd (the package parent), not the
        # git root — the two differ when the repo nests the package.
        ["git", "diff", "--name-only", "--relative", "HEAD"],
        ["git", "ls-files", "--others", "--exclude-standard"],
    ):
        proc = subprocess.run(
            cmd, cwd=root, capture_output=True, text=True, timeout=30,
        )
        if proc.returncode != 0:
            raise RuntimeError(
                f"--changed-only needs a git checkout: {' '.join(cmd)} "
                f"failed: {proc.stderr.strip() or proc.stdout.strip()}"
            )
        for line in proc.stdout.splitlines():
            line = line.strip()
            if line:
                out.add(Path(line).as_posix())
    return out


def main(argv: list | None = None) -> int:
    p = argparse.ArgumentParser(
        prog="fftpu-check",
        description="layer-check + JAX-safety static analysis (pure AST)",
    )
    p.add_argument("package", nargs="?", default="fluidframework_tpu",
                   help="package directory to analyze")
    p.add_argument("--layers", default=None, help="layers.json override")
    p.add_argument("--baseline", default=None, help="baseline.json override")
    p.add_argument("--no-baseline", action="store_true",
                   help="report suppressed findings too")
    p.add_argument("--rules", default=None,
                   help=f"comma-separated subset of {','.join(PASSES)}")
    p.add_argument("--changed-only", action="store_true",
                   help="report findings only in git-diff-touched modules "
                        "(analysis still runs package-wide)")
    p.add_argument("--json", dest="as_json", action="store_true",
                   help="machine-readable output (bench/CI artifacts)")
    args = p.parse_args(argv)

    try:
        only = changed_files(args.package) if args.changed_only else None
        result = run_all(
            args.package,
            layers_path=args.layers,
            baseline_path=args.baseline,
            rules=args.rules.split(",") if args.rules else None,
            only_files=only,
        )
    except SyntaxError as e:
        # A malformed file in the analyzed tree is a usage-class error
        # (exit 2), not a crash: report the offending file:line.
        print(f"fftpu-check: cannot parse {e.filename}:{e.lineno}: {e.msg}",
              file=sys.stderr)
        return 2
    except (FileNotFoundError, ValueError, RuntimeError, json.JSONDecodeError,
            UnicodeDecodeError, OSError) as e:
        print(f"fftpu-check: {e}", file=sys.stderr)
        return 2

    shown = list(result["findings"])
    if args.no_baseline:
        shown += result["suppressed"]
        shown.sort(key=lambda f: (f.file, f.line, f.rule))

    if args.as_json:
        print(json.dumps({
            "clean": not result["findings"],
            "n_modules": result["n_modules"],
            "counts": result["counts"],
            "n_suppressed": len(result["suppressed"]),
            "stale_baseline": result["stale_baseline"],
            "pass_times_ms": result["pass_times_ms"],
            **({"changed_only": True, "n_changed": len(only)}
               if only is not None else {}),
            "findings": [f.to_json() for f in shown],
        }, indent=2))
    else:
        for f in shown:
            print(f.render())
        for e in result["stale_baseline"]:
            print(
                f"stale-baseline  {e.get('file')}  entry no longer matches "
                f"anything: {e.get('rule')} {e.get('detail')!r} — remove it"
            )
        n = len(result["findings"])
        scope = f" ({len(only)} changed files)" if only is not None else ""
        print(
            f"fftpu-check: {result['n_modules']} modules{scope}, "
            f"{n} finding{'s' if n != 1 else ''}, "
            f"{len(result['suppressed'])} baselined, "
            f"{len(result['stale_baseline'])} stale baseline entr"
            f"{'ies' if len(result['stale_baseline']) != 1 else 'y'}"
        )
    return 1 if result["findings"] else 0


if __name__ == "__main__":
    raise SystemExit(main())
