"""Pass 9 — lock-consistency: lockset-style guard checking.

The threads pass (pass 5) catches the *unlocked* thread-side write that
non-thread code also touches.  This pass catches the subtler siblings: a
shared attribute guarded by lock A at one write site and lock B — or
nothing — at another.  Both sites look "locked enough" in review; at
runtime they exclude nobody.  The classic way this arises here: a counter
written under ``self._lock`` by a drain thread, then reset by a
supervisor helper that forgot the lock.

Mechanics (per module, on the shared ``core`` walkers — same entry
discovery as the threads pass):

1. **Entries** — thread entries (Thread/Timer/executor/handler bodies)
   plus every function with no same-module caller (the module's public
   surface); callees inherit the exact held-lock SET along call edges.
2. **Sites** — attribute writes attributed to an owning class (``self.X``
   or a locally-typed var), ``__init__`` exempt (init-before-start).  A
   site observed under several reach contexts keeps each context's held
   set; its *guard* is their intersection (always-held locks only).
3. **Finding** — ``lock-inconsistent-guard``: an attribute with at least
   one thread-reachable write site and no ONE lock common to every write
   site, while at least one site IS guarded.  An unlocked
   thread-reachable site is excluded only when the threads pass already
   owns it (the attribute is also touched by non-thread code) — a
   locked-vs-unlocked race between two *threads* has no non-thread
   toucher and fires HERE, not nowhere.

Reads are deliberately out of scope (the lock-free stale-read of a
monotonic counter is a sanctioned idiom in this codebase — see
``Deployment._stopping``); writes are where torn state comes from.
"""

from __future__ import annotations

import ast

from .core import (
    Finding,
    LockFlowScan,
    LockNamer,
    ModuleView,
    PackageIndex,
    local_types,
    walk_lock_flow,
)
from .threads import local_resolver, thread_entries


def run(index: PackageIndex,
        concurrency_scope: dict | None) -> list[Finding]:
    cfg = concurrency_scope or {}
    shared = frozenset(cfg.get("shared_locks", []))
    findings: list[Finding] = []
    for mod in index.modules:
        view = ModuleView(mod)
        t_entries = thread_entries(view)
        if not t_entries:
            continue

        namer = LockNamer(shared)
        cache: dict = {}

        def make_scan(key, held, view=view, namer=namer, cache=cache,
                      mod=mod):
            ck = (key, held)
            if ck in cache:
                return cache[ck]
            fn = view.functions.get(key)
            if fn is None:
                cache[ck] = None
                return None
            types = local_types(fn, view)
            scan = LockFlowScan(
                fn, held, namer, modname=mod.modname,
                class_name=key.class_name, types=types,
                resolver=local_resolver(view, key, types),
            ).run()
            cache[ck] = scan
            return scan

        # One direct unlocked scan per function doubles as (a) the
        # call-graph probe for the no-caller entry set and (b) the walk's
        # cached base contexts — no separate probe walk.
        called: set = set()
        for key in view.functions:
            scan = make_scan(key, frozenset())
            if scan is not None:
                called.update(c for c, _h, _l in scan.edges)
        entries = list(t_entries) + [
            k for k in view.functions if k not in called
        ]

        scans = walk_lock_flow(
            [(k, frozenset()) for k in entries], make_scan
        )

        # Thread-reachable closure: BFS over the edges the walk already
        # collected, seeded by the thread entries (a third walk would
        # recompute the same scans).
        thread_keys: set = set(t_entries)
        stack = list(t_entries)
        while stack:
            k = stack.pop()
            for scan in scans.get(k, {}).values():
                if scan is None:
                    continue
                for callee, _h, _l in scan.edges:
                    if callee not in thread_keys:
                        thread_keys.add(callee)
                        stack.append(callee)

        # Attribute touches from NON-thread code — the threads pass's
        # precondition, mirrored so the two passes split the space
        # exactly: its finding requires an outside toucher; ours takes
        # over when there is none.
        outside: dict = {}
        for fn_key, fn in view.functions.items():
            if fn_key in thread_keys or fn_key.name == "__init__":
                continue
            for node in ast.walk(fn):
                if isinstance(node, ast.Attribute):
                    is_self = (isinstance(node.value, ast.Name)
                               and node.value.id == "self")
                    outside.setdefault(node.attr, []).append(
                        (fn_key, is_self))

        def threads_pass_owns(owner: str, attr: str) -> bool:
            return any(
                not is_self or fk.class_name == owner
                for fk, is_self in outside.get(attr, [])
            )

        # (owner_class, attr) -> {(fn_key, line): [held, ...]}
        sites: dict = {}
        for key, ctxs in scans.items():
            if key.name == "__init__":
                continue
            for scan in ctxs.values():
                if scan is None:
                    continue
                for attr, line, held, _is_self, owner in scan.writes:
                    if owner is None:
                        continue
                    sites.setdefault((owner, attr), {}).setdefault(
                        (key, line), []
                    ).append(held)

        for (owner, attr), by_site in sorted(
            sites.items(), key=lambda kv: (kv[0][0], kv[0][1])
        ):
            if len(by_site) < 2:
                continue
            if not any(k in thread_keys for (k, _l) in by_site):
                continue
            # Guard per site: locks held on EVERY reach context.
            guards = {
                site: frozenset.intersection(*map(frozenset, helds))
                for site, helds in by_site.items()
            }
            if not any(guards.values()):
                continue  # fully unlocked attr: the threads pass's beat
            # Exclude the sites the threads pass already owns
            # (thread-reachable + unlocked + touched by non-thread code);
            # what remains must agree.
            considered = {
                site: g for site, g in guards.items()
                if g or site[0] not in thread_keys
                or not threads_pass_owns(owner, attr)
            }
            if len(considered) < 2:
                continue
            if frozenset.intersection(*considered.values()):
                continue  # one common lock guards every site
            locked = [(s, g) for s, g in sorted(
                considered.items(), key=lambda kv: kv[0][1]) if g]
            odd = [(s, g) for s, g in sorted(
                considered.items(), key=lambda kv: kv[0][1]) if not g]
            (a_site, a_guard) = locked[0]
            if odd:
                (b_site, b_guard) = odd[0]
            else:
                # >= 3 sites can be pairwise-overlapping yet share no ONE
                # lock; fall back to the last site for the witness pair.
                (b_site, b_guard) = next(
                    ((s, g) for s, g in locked[1:] if not (g & a_guard)),
                    locked[-1],
                )
            a_lock = "+".join(sorted(a_guard))
            b_lock = "+".join(sorted(b_guard)) if b_guard else "no lock"
            findings.append(Finding(
                rule="lock-inconsistent-guard",
                file=mod.rel, line=b_site[1],
                message=(
                    f"`.{attr}` of {owner} is written under `{a_lock}` by "
                    f"{a_site[0].label()} (line {a_site[1]}) but under "
                    f"{b_lock} by {b_site[0].label()} (line {b_site[1]}) — "
                    "the two sites exclude nobody"
                ),
                hint=(
                    "guard every write to the attribute with the SAME "
                    "lock (or baseline with a rationale if one side is "
                    "provably quiescent)"
                ),
                detail=(
                    f"{owner}.{attr}: guarded by {a_lock} vs "
                    f"{b_lock}"
                ),
            ))
    return findings
