"""Pass 1 — layer-check: downward-only imports per ``layers.json``.

The reference Fluid repo commits ``layerInfo.json`` and fails the build on
any dependency pointing upward (SURVEY §1); this is that check for the
repro.  ``analysis/layers.json`` assigns every ``fluidframework_tpu``
subpackage to one named layer (index 0 = bottom); a module may import only
from its own layer or below.  ``if TYPE_CHECKING:`` imports are exempt
(erased at runtime — the sanctioned cross-layer type-hint channel); lazy
function-local imports are NOT exempt (a deferred upward import is still an
upward dependency, just one that hides from the import graph until the hot
path runs).

Rules:
- ``layer-upward-import``      — import targets a higher layer
- ``layer-undeclared-package`` — subpackage missing from layers.json (new
  subpackages must declare their layer before they ship)
"""

from __future__ import annotations

import json
from pathlib import Path

from .core import Finding, PackageIndex, iter_imports


def load_layers(path: Path | str) -> dict:
    """-> {subpackage: (rank, layer_name)}."""
    data = json.loads(Path(path).read_text())
    out: dict = {}
    for rank, layer in enumerate(data["layers"]):
        for pkg in layer["packages"]:
            if pkg in out:
                raise ValueError(f"layers.json assigns {pkg!r} twice")
            out[pkg] = (rank, layer["name"])
    return out


def run(index: PackageIndex, layers: dict) -> list[Finding]:
    findings: list[Finding] = []
    known = set(layers)
    flagged_undeclared: set = set()
    for mod in index.modules:
        if mod.subpackage == "<root>":
            # The package facade (__init__) may re-export from anywhere.
            continue
        if mod.subpackage not in known:
            if mod.subpackage not in flagged_undeclared:
                flagged_undeclared.add(mod.subpackage)
                findings.append(Finding(
                    rule="layer-undeclared-package",
                    file=mod.rel,
                    line=1,
                    message=f"subpackage {mod.subpackage!r} has no layer in layers.json",
                    hint="add it to analysis/layers.json at the layer it belongs to",
                    detail=f"undeclared subpackage {mod.subpackage}",
                ))
            continue
        src_rank, src_layer = layers[mod.subpackage]
        for imp in iter_imports(mod):
            if imp.type_checking:
                continue
            if not imp.target.startswith(index.name + "."):
                continue
            tparts = imp.target.split(".")
            tsub = tparts[1] if len(tparts) > 1 else None
            if tsub is None or tsub == mod.subpackage:
                continue
            if tsub not in known:
                # Target may be a top-level module ("fluidframework_tpu.x")
                # or a symbol re-exported by the facade — not a layer edge.
                continue
            dst_rank, dst_layer = layers[tsub]
            if dst_rank > src_rank:
                # Trim symbol imports back to module granularity for a
                # stable fingerprint: "...mesh.doc_mesh" and "...mesh"
                # are the same dependency edge.
                target_mod = imp.target
                if index.by_modname(target_mod) is None:
                    target_mod = target_mod.rsplit(".", 1)[0]
                findings.append(Finding(
                    rule="layer-upward-import",
                    file=mod.rel,
                    line=imp.line,
                    message=(
                        f"{mod.subpackage!r} (layer {src_layer}) imports "
                        f"{target_mod} ({dst_layer!r} is above it)"
                    ),
                    hint=(
                        "invert the dependency (move the shared contract "
                        "down a layer) or baseline it with a rationale"
                    ),
                    detail=f"imports {target_mod}",
                ))
    # One finding per (file, target-module): a module importing two symbols
    # from the same upward module is one edge, not two findings.
    seen: set = set()
    deduped: list[Finding] = []
    for f in findings:
        k = f.key()
        if k not in seen:
            seen.add(k)
            deduped.append(f)
    return deduped
