"""Pass 6 — swallowed-exception: bare ``except ...: pass`` in serving code.

The host and service layers (driver/framework/loader + server/tools/
testing/analysis) are where a silently swallowed exception turns a crash
into an invisible wedge: a supervisor that "handles" a failed respawn by
dropping it relaunches nothing; a front that eats an OSError mid-teardown
leaks sessions; a consumer that swallows a decode error serves stale state
forever.  The kernels/state layers get latitude — probing device features
and unwinding optimistic paths legitimately discard exceptions — so this
pass runs ONLY on modules at or above the ``host`` layer.

Finding: ``swallowed-exception`` — an ``except`` handler whose entire body
is ``pass``.  A handler that at least counts, logs, re-raises, breaks, or
returns is not flagged (the point is that SOMETHING observable or
control-flow-relevant must happen).  Vetted swallows (e.g. "peer went away
during teardown, cleanup happens in the finally") live in the baseline
with a mandatory rationale, same contract as every other pass — or are
rewritten as ``contextlib.suppress(...)``, the stdlib's explicit
this-is-intentional spelling, which this pass deliberately does not chase.

The fingerprint (``detail``) is the squashed handler header + enclosing
function, so a baseline entry survives unrelated line drift.
"""

from __future__ import annotations

import ast

from .core import Finding, Module, PackageIndex

# Default layers this pass covers.  The committed layers.json pins the
# scope EXPLICITLY via its "swallowed_scope" key — an explicit scope naming
# a layer that no longer exists fails loudly, so a layer reshuffle can
# never silently narrow coverage.  Packages without the key (fixture
# trees) get the default intersected with whatever layers they define.
COVERED_LAYERS = ("host", "service")


def _covered_packages(layers: dict, scope_names=None) -> set:
    """Subpackages assigned to a covered layer; ``layers`` is
    ``load_layers`` output ({subpackage: (rank, layer_name)})."""
    defined = {name for _rank, name in layers.values()}
    if scope_names is not None:
        unknown = set(scope_names) - defined
        if unknown:
            raise ValueError(
                f"swallowed_scope names unknown layer(s) {sorted(unknown)} "
                "— swallowed-exception pass has no scope there"
            )
        covered_names = set(scope_names)
    else:
        covered_names = set(COVERED_LAYERS) & defined
    return {
        pkg for pkg, (_rank, name) in layers.items()
        if name in covered_names
    }


def _enclosing_functions(tree: ast.Module) -> dict:
    """handler-id -> dotted enclosing scope name (for the fingerprint)."""
    out: dict = {}

    def walk(node: ast.AST, scope: str) -> None:
        for child in ast.iter_child_nodes(node):
            name = scope
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef)):
                name = f"{scope}.{child.name}" if scope else child.name
            if isinstance(child, ast.ExceptHandler):
                out[id(child)] = scope or "<module>"
            walk(child, name)

    walk(tree, "")
    return out


def _handler_types(handler: ast.ExceptHandler) -> str:
    if handler.type is None:
        return "<bare>"
    return " ".join(ast.unparse(handler.type).split())


def run(index: PackageIndex, layers: dict, scope_names=None) -> list[Finding]:
    covered = _covered_packages(layers, scope_names)
    findings: list[Finding] = []
    for mod in index.modules:
        if mod.subpackage not in covered:
            continue
        findings.extend(_run_module(mod))
    return findings


def _run_module(mod: Module) -> list[Finding]:
    out: list[Finding] = []
    scopes = _enclosing_functions(mod.tree)
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        if not (len(node.body) == 1 and isinstance(node.body[0], ast.Pass)):
            continue
        types = _handler_types(node)
        scope = scopes.get(id(node), "<module>")
        out.append(Finding(
            rule="swallowed-exception",
            file=mod.rel,
            line=node.lineno,
            message=(
                f"except {types}: pass in {scope} swallows the failure "
                "silently"
            ),
            hint=(
                "count/log/re-raise it, narrow it into "
                "contextlib.suppress(...) if discarding is the intent, "
                "or baseline it with a rationale"
            ),
            detail=f"except {types}: pass in {scope}",
        ))
    return out
