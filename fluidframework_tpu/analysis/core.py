"""Shared machinery for the fftpu-check passes.

Everything here is pure stdlib ``ast``: the passes must run on a box with
no JAX installed (CI lint tier) and must never import the code under
analysis (importing the package would pull in jax + device init, and an
import-time crash in analyzed code would take the analyzer down with it).
"""

from __future__ import annotations

import ast
import json
from dataclasses import dataclass, field
from pathlib import Path


# --------------------------------------------------------------------------
# Findings + baseline
# --------------------------------------------------------------------------

@dataclass
class Finding:
    """One analyzer hit.

    ``detail`` is the stable fingerprint half: baseline entries match on
    ``(rule, file, detail)`` and deliberately NOT on ``line``, so a vetted
    suppression survives unrelated edits shifting line numbers.
    """

    rule: str
    file: str  # posix path relative to the package root's parent
    line: int
    message: str
    hint: str = ""
    detail: str = ""

    def key(self) -> tuple:
        return (self.rule, self.file, self.detail or self.message)

    def render(self) -> str:
        loc = f"{self.file}:{self.line}"
        out = f"{self.rule}  {loc}  {self.message}"
        if self.hint:
            out += f"\n    hint: {self.hint}"
        return out

    def to_json(self) -> dict:
        return {
            "rule": self.rule,
            "file": self.file,
            "line": self.line,
            "message": self.message,
            "hint": self.hint,
            "detail": self.detail or self.message,
        }


class Baseline:
    """Committed suppressions for vetted legacy findings.

    Schema (``analysis/baseline.json``)::

        {"version": 1,
         "suppressions": [
            {"rule": ..., "file": ..., "detail": ..., "rationale": ...},
         ]}

    Every entry MUST carry a non-empty rationale — the analyzer refuses a
    baseline with silent entries (a suppression nobody can explain is a
    finding in itself).  Entries that no longer match any finding are
    reported as *stale* so the baseline shrinks as fixes land.
    """

    def __init__(self, entries: list[dict] | None = None) -> None:
        self.entries = entries or []
        for e in self.entries:
            if not str(e.get("rationale", "")).strip():
                raise ValueError(
                    f"baseline entry without rationale: "
                    f"{e.get('rule')} {e.get('file')} {e.get('detail')!r}"
                )

    @classmethod
    def load(cls, path: Path | str) -> "Baseline":
        data = json.loads(Path(path).read_text())
        return cls(data.get("suppressions", []))

    @staticmethod
    def entry_key(e: dict) -> tuple:
        return (e.get("rule"), e.get("file"), e.get("detail"))

    def apply(
        self, findings: list[Finding]
    ) -> tuple[list[Finding], list[Finding], list[dict]]:
        """-> (unsuppressed, suppressed, stale_entries)."""
        index = {self.entry_key(e): e for e in self.entries}
        used: set = set()
        keep: list[Finding] = []
        quiet: list[Finding] = []
        for f in findings:
            if f.key() in index:
                used.add(f.key())
                quiet.append(f)
            else:
                keep.append(f)
        stale = [e for e in self.entries if self.entry_key(e) not in used]
        return keep, quiet, stale


# --------------------------------------------------------------------------
# Package loading
# --------------------------------------------------------------------------

@dataclass
class Module:
    path: Path
    rel: str          # "fluidframework_tpu/server/scribe.py"
    modname: str      # "fluidframework_tpu.server.scribe"
    subpackage: str   # "server" ("<root>" for top-level modules)
    tree: ast.Module
    source: str

    def segment(self, node: ast.AST, limit: int = 60) -> str:
        """Source text of a node, squashed for finding details."""
        try:
            seg = ast.get_source_segment(self.source, node) or ""
        except Exception:
            seg = ""
        seg = " ".join(seg.split())
        return seg[:limit] + ("…" if len(seg) > limit else "")

    def aliases(self) -> dict:
        """Memoized ``alias_map`` — the passes resolve names per function
        and recomputing the import table per function is quadratic."""
        cached = getattr(self, "_aliases", None)
        if cached is None:
            cached = alias_map(self)
            object.__setattr__(self, "_aliases", cached)
        return cached


@dataclass
class PackageIndex:
    pkg_dir: Path
    name: str
    modules: list[Module] = field(default_factory=list)

    def by_modname(self, name: str) -> Module | None:
        for m in self.modules:
            if m.modname == name:
                return m
        return None

    @property
    def subpackages(self) -> set:
        return {m.subpackage for m in self.modules if m.subpackage != "<root>"}


def load_package(pkg_dir: Path | str) -> PackageIndex:
    pkg_dir = Path(pkg_dir).resolve()
    idx = PackageIndex(pkg_dir=pkg_dir, name=pkg_dir.name)
    root = pkg_dir.parent
    for path in sorted(pkg_dir.rglob("*.py")):
        if "__pycache__" in path.parts:
            continue
        source = path.read_text()
        tree = ast.parse(source, filename=str(path))
        rel = path.relative_to(root).as_posix()
        parts = path.relative_to(root).with_suffix("").parts
        modname = ".".join(parts[:-1] + (parts[-1],))
        if parts[-1] == "__init__":
            modname = ".".join(parts[:-1])
        if path.parent == pkg_dir:
            sub = "<root>"  # top-level module / the package __init__
        else:
            sub = path.relative_to(pkg_dir).parts[0]
        idx.modules.append(
            Module(path=path, rel=rel, modname=modname, subpackage=sub,
                   tree=tree, source=source)
        )
    return idx


# --------------------------------------------------------------------------
# Import resolution
# --------------------------------------------------------------------------

@dataclass
class ResolvedImport:
    target: str        # fully-qualified module (or symbol) name
    line: int
    type_checking: bool


def _type_checking_lines(tree: ast.Module) -> set:
    """Line ranges of ``if TYPE_CHECKING:`` bodies (imports there are
    erased at runtime — the sanctioned way to type-hint across layers).
    Only the exact guard counts: ``if not TYPE_CHECKING:`` or
    ``if TYPE_CHECKING or X:`` bodies DO run and get no exemption."""
    def is_guard(test: ast.AST) -> bool:
        if isinstance(test, ast.Name):
            return test.id == "TYPE_CHECKING"
        if isinstance(test, ast.Attribute):
            return test.attr == "TYPE_CHECKING"
        return False

    out: set = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.If) and is_guard(node.test):
            for sub in node.body:
                for n in ast.walk(sub):
                    if hasattr(n, "lineno"):
                        out.add(n.lineno)
    return out


def iter_imports(mod: Module) -> list[ResolvedImport]:
    """Every import in the module resolved to absolute dotted names.

    Relative imports resolve against the module's own package path; for
    ``from PKG import name`` each alias resolves one level deeper (the
    alias may itself be a subpackage — ``from fluidframework_tpu import
    parallel``)."""
    tc = _type_checking_lines(mod.tree)
    # Package path the relative imports resolve against.
    is_pkg_init = mod.path.name == "__init__.py"
    self_pkg = mod.modname if is_pkg_init else mod.modname.rsplit(".", 1)[0]
    out: list[ResolvedImport] = []
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                out.append(ResolvedImport(a.name, node.lineno, node.lineno in tc))
        elif isinstance(node, ast.ImportFrom):
            if node.level == 0:
                base = node.module or ""
            else:
                comps = self_pkg.split(".")
                comps = comps[: len(comps) - (node.level - 1)]
                base = ".".join(comps + ([node.module] if node.module else []))
            for a in node.names:
                target = f"{base}.{a.name}" if base else a.name
                out.append(ResolvedImport(target, node.lineno, node.lineno in tc))
    return out


def alias_map(mod: Module) -> dict:
    """Local name -> fully-qualified dotted target, for resolving
    ``mk.apply_ops`` / ``jnp.any`` / ``partial`` style references."""
    is_pkg_init = mod.path.name == "__init__.py"
    self_pkg = mod.modname if is_pkg_init else mod.modname.rsplit(".", 1)[0]
    out: dict = {}
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                out[a.asname or a.name.split(".")[0]] = (
                    a.name if a.asname else a.name.split(".")[0]
                )
                if a.asname:
                    out[a.asname] = a.name
        elif isinstance(node, ast.ImportFrom):
            if node.level == 0:
                base = node.module or ""
            else:
                comps = self_pkg.split(".")
                comps = comps[: len(comps) - (node.level - 1)]
                base = ".".join(comps + ([node.module] if node.module else []))
            for a in node.names:
                target = f"{base}.{a.name}" if base else a.name
                out[a.asname or a.name] = target
    return out


def dotted_name(expr: ast.AST) -> str | None:
    """``a.b.c`` attribute/name chain as a string, else None."""
    parts: list[str] = []
    node = expr
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def resolve(expr: ast.AST, aliases: dict) -> str | None:
    """Resolve an expression to a fully-qualified dotted name using the
    module's import aliases (``mk.apply_ops`` ->
    ``fluidframework_tpu.ops.mergetree_kernel.apply_ops``)."""
    dn = dotted_name(expr)
    if dn is None:
        return None
    head, _, rest = dn.partition(".")
    fq = aliases.get(head, head)
    return f"{fq}.{rest}" if rest else fq


def resolve_in(mod: Module, aliases: dict, expr: ast.AST) -> str | None:
    """``resolve`` + fallback: unqualified references (no import alias on
    the head) are module-local definitions -> ``<modname>.<name>``."""
    dn = dotted_name(expr)
    if dn is None:
        return None
    if dn.split(".")[0] in aliases:
        return resolve(expr, aliases)
    pkg_root = mod.modname.split(".")[0]
    if dn.startswith(pkg_root + ".") or dn == pkg_root:
        return dn
    return f"{mod.modname}.{dn}"


# --------------------------------------------------------------------------
# Function index (shared by jit-safety / donation / mesh-safety)
# --------------------------------------------------------------------------

@dataclass
class FuncInfo:
    mod: Module
    node: ast.AST                 # FunctionDef | Lambda
    qualname: str                 # "pkg.mod.f" / "pkg.mod.Class.m"
    class_name: str | None = None

    def params(self) -> list[str]:
        a = self.node.args
        names = [p.arg for p in a.posonlyargs + a.args]
        return names

    def kwonly(self) -> list[str]:
        return [p.arg for p in self.node.args.kwonlyargs]


def build_func_index(index: PackageIndex) -> dict:
    out: dict = {}
    for mod in index.modules:
        for node in mod.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                out[f"{mod.modname}.{node.name}"] = FuncInfo(
                    mod, node, f"{mod.modname}.{node.name}")
            elif isinstance(node, ast.ClassDef):
                for sub in node.body:
                    if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                        q = f"{mod.modname}.{node.name}.{sub.name}"
                        out[q] = FuncInfo(mod, sub, q, class_name=node.name)
    return out


# --------------------------------------------------------------------------
# Symbol tables for the concurrency passes (threads / lock-order /
# lock-consistency / blocking-under-lock).  One copy here: the suite is 11
# passes and cannot afford private walkers per pass.
# --------------------------------------------------------------------------

HANDLER_BASES = {
    "StreamRequestHandler", "BaseRequestHandler", "DatagramRequestHandler",
    "BaseHTTPRequestHandler", "SimpleHTTPRequestHandler",
}


@dataclass(frozen=True)
class FuncKey:
    """A function's identity for the concurrency walkers.  ``modname`` is
    None for the per-module passes (threads), set for the package-wide
    walks (lock-order / blocking-under-lock)."""

    class_name: str | None
    name: str
    modname: str | None = None

    def label(self) -> str:
        return (f"{self.class_name}.{self.name}" if self.class_name
                else self.name)


class ModuleView:
    """Per-module symbol tables: top-level functions, classes + their
    methods, socketserver/http handler subclasses, and per-class
    ``self.X = ClassName(...)`` attribute types."""

    def __init__(self, mod: Module) -> None:
        self.mod = mod
        self.aliases = mod.aliases()
        self.functions: dict = {}    # FuncKey(class, name) -> FunctionDef
        self.classes: dict = {}      # name -> ClassDef
        for node in mod.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.functions[FuncKey(None, node.name)] = node
            elif isinstance(node, ast.ClassDef):
                self.classes[node.name] = node
                for sub in node.body:
                    if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                        self.functions[FuncKey(node.name, sub.name)] = sub

    def handler_classes(self) -> set:
        out = set()
        for name, node in self.classes.items():
            for base in node.bases:
                dn = dotted_name(base) or ""
                if dn.split(".")[-1] in HANDLER_BASES:
                    out.add(name)
        return out


def local_types(fn: ast.AST, view: ModuleView) -> dict:
    """var name -> class name, from ``x = ClassName(...)`` and ``x: T``
    annotations (string annotations included)."""
    out: dict = {}
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and isinstance(node.value, ast.Call):
            dn = dotted_name(node.value.func)
            if dn in view.classes:
                out[node.targets[0].id] = dn
        elif isinstance(node, ast.AnnAssign) and isinstance(node.target, ast.Name):
            ann = node.annotation
            txt = (ann.value if isinstance(ann, ast.Constant)
                   else ast.unparse(ann))
            head = str(txt).strip().strip('"\'').split("[")[0].split(".")[-1]
            if head in view.classes:
                out[node.target.id] = head
    # Parameter annotations.
    args = getattr(fn, "args", None)
    if args is not None:
        for p in args.posonlyargs + args.args + args.kwonlyargs:
            if p.annotation is not None:
                txt = (p.annotation.value if isinstance(p.annotation, ast.Constant)
                       else ast.unparse(p.annotation))
                head = str(txt).strip().strip('"\'').split("[")[0].split(".")[-1]
                if head in view.classes:
                    out[p.arg] = head
    return out


class PackageView:
    """Package-wide symbol tables + cross-module call resolution for the
    lock passes.  Resolution covers: module-local functions, ``self.``
    methods, locally-typed vars (constructor assignment / annotation —
    imported classes included), ``self.attr`` objects whose class is known
    from a constructor assignment anywhere in the owning class, and
    imported module functions (``recovery.write_checkpoint_records`` /
    ``from .recovery import write_checkpoint_records``)."""

    def __init__(self, index: PackageIndex) -> None:
        self.index = index
        self.pkg_root = index.name
        self.views: dict = {m.modname: ModuleView(m) for m in index.modules}
        # fq class name -> (modname, ClassName)
        self.classes: dict = {}
        for m in index.modules:
            for cname in self.views[m.modname].classes:
                self.classes[f"{m.modname}.{cname}"] = (m.modname, cname)
        self._attr_types: dict = {}   # (modname, Class) -> {attr: (mod, Cls)}
        self._fn_types: dict = {}     # FuncKey -> local var types

    @classmethod
    def of(cls, index: PackageIndex) -> "PackageView":
        """The memoized view for an index: three passes share one run's
        symbol tables instead of rebuilding them (the gate runs in every
        Docker build and pre-commit loop)."""
        pv = getattr(index, "_package_view", None)
        if pv is None:
            pv = cls(index)
            index._package_view = pv
        return pv

    def function(self, key: FuncKey) -> ast.AST | None:
        view = self.views.get(key.modname)
        if view is None:
            return None
        return view.functions.get(FuncKey(key.class_name, key.name))

    def all_functions(self):
        for modname, view in self.views.items():
            for k in view.functions:
                yield FuncKey(k.class_name, k.name, modname)

    # ------------------------------------------------------------- typing
    def _resolve_class(self, mod: Module, view: ModuleView,
                       ctor: ast.AST) -> tuple | None:
        """A ``ClassName(...)`` constructor expression -> (modname, Class)
        for classes defined anywhere in the package.  Imports through a
        subpackage facade (``from ..fanout import FanoutPlane`` riding the
        ``fanout/__init__`` re-export) chase one hop through the facade's
        own alias map."""
        dn = dotted_name(ctor)
        if dn is None:
            return None
        if dn in view.classes:
            return (mod.modname, dn)
        fq = resolve_in(mod, view.aliases, ctor)
        if not fq:
            return None
        loc = self.classes.get(fq)
        if loc is not None:
            return loc
        head, _, name = fq.rpartition(".")
        facade = self.views.get(head)
        if facade is not None and name:
            fq2 = facade.aliases.get(name)
            if fq2:
                return self.classes.get(fq2)
        return None

    def fn_local_types(self, key: FuncKey) -> dict:
        """var name -> (modname, Class), package-wide class resolution."""
        cached = self._fn_types.get(key)
        if cached is not None:
            return cached
        view = self.views[key.modname]
        mod = view.mod
        fn = self.function(key)
        out: dict = {}
        if fn is not None:
            for node in ast.walk(fn):
                if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                        and isinstance(node.targets[0], ast.Name) \
                        and isinstance(node.value, ast.Call):
                    cls = self._resolve_class(mod, view, node.value.func)
                    if cls is not None:
                        out[node.targets[0].id] = cls
            # Annotations (parameter + AnnAssign), by bare class name.
            for var, cname in local_types(fn, view).items():
                out.setdefault(var, (mod.modname, cname))
            args = getattr(fn, "args", None)
            if args is not None:
                for p in args.posonlyargs + args.args + args.kwonlyargs:
                    if p.annotation is None:
                        continue
                    txt = (p.annotation.value
                           if isinstance(p.annotation, ast.Constant)
                           else ast.unparse(p.annotation))
                    head = (str(txt).strip().strip('"\'')
                            .split("[")[0].split(".")[-1])
                    for fqc, loc in self.classes.items():
                        if fqc.rsplit(".", 1)[-1] == head:
                            out.setdefault(p.arg, loc)
                            break
        self._fn_types[key] = out
        return out

    def attr_types(self, modname: str, class_name: str) -> dict:
        """self-attribute name -> (modname, Class) from constructor
        assignments (``self.X = ClassName(...)``) in the class body."""
        cache_key = (modname, class_name)
        cached = self._attr_types.get(cache_key)
        if cached is not None:
            return cached
        view = self.views[modname]
        mod = view.mod
        out: dict = {}
        cls = view.classes.get(class_name)
        if cls is not None:
            for node in ast.walk(cls):
                if isinstance(node, ast.Assign) and len(node.targets) == 1:
                    t = node.targets[0]
                    if (isinstance(t, ast.Attribute)
                            and isinstance(t.value, ast.Name)
                            and t.value.id == "self"
                            and isinstance(node.value, ast.Call)):
                        loc = self._resolve_class(mod, view, node.value.func)
                        if loc is not None:
                            out[t.attr] = loc
        self._attr_types[cache_key] = out
        return out

    # --------------------------------------------------------- call edges
    def resolve_call(self, key: FuncKey, types: dict,
                     call: ast.Call) -> FuncKey | None:
        """Resolve a call site to a package FuncKey (None: not ours /
        not statically resolvable)."""
        view = self.views[key.modname]
        mod = view.mod
        func = call.func
        if isinstance(func, ast.Name):
            # Module-local function, or a from-imported one.
            if FuncKey(None, func.id) in view.functions:
                return FuncKey(None, func.id, key.modname)
            fq = view.aliases.get(func.id)
            if fq and fq.startswith(self.pkg_root + "."):
                return self._by_fq(fq)
            return None
        if not isinstance(func, ast.Attribute):
            return None
        base = func.value
        meth = func.attr
        if isinstance(base, ast.Name):
            if base.id == "self" and key.class_name:
                if FuncKey(key.class_name, meth) in view.functions:
                    return FuncKey(key.class_name, meth, key.modname)
                return None
            loc = types.get(base.id)
            if loc is not None:
                m2, c2 = loc
                if FuncKey(c2, meth) in self.views[m2].functions:
                    return FuncKey(c2, meth, m2)
                return None
            # alias.module_fn(...)  (e.g. ``recovery.write_checkpoint...``)
            fq = view.aliases.get(base.id)
            if fq and fq.startswith(self.pkg_root + "."):
                return self._by_fq(f"{fq}.{meth}")
            return None
        # self.attr.meth(): the attr's class from constructor assignments.
        if (isinstance(base, ast.Attribute)
                and isinstance(base.value, ast.Name)
                and base.value.id == "self" and key.class_name):
            loc = self.attr_types(key.modname, key.class_name).get(base.attr)
            if loc is not None:
                m2, c2 = loc
                if FuncKey(c2, meth) in self.views[m2].functions:
                    return FuncKey(c2, meth, m2)
        # mod-qualified deep chains (pkg.sub.mod.fn).
        fq = resolve_in(mod, view.aliases, func)
        if fq and fq.startswith(self.pkg_root + "."):
            return self._by_fq(fq)
        return None

    def _by_fq(self, fq: str) -> FuncKey | None:
        """``pkg.a.b.f`` / ``pkg.a.b.Class.m`` -> FuncKey."""
        head, _, last = fq.rpartition(".")
        if head in self.views:
            if FuncKey(None, last) in self.views[head].functions:
                return FuncKey(None, last, head)
            return None
        m_head, _, cls = head.rpartition(".")
        if m_head in self.views and cls in self.views[m_head].classes:
            if FuncKey(cls, last) in self.views[m_head].functions:
                return FuncKey(cls, last, m_head)
        return None

    # ---------------------------------------------------- module constants
    def module_constants(self, modname: str) -> dict:
        """NAME -> str value for simple top-level string assignments
        (``SEG_AXIS = "segs"``) — the mesh-safety axis resolver's table."""
        view = self.views.get(modname)
        if view is None:
            return {}
        cached = getattr(view, "_constants", None)
        if cached is None:
            cached = {}
            for node in view.mod.tree.body:
                if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                        and isinstance(node.targets[0], ast.Name) \
                        and isinstance(node.value, ast.Constant) \
                        and isinstance(node.value.value, str):
                    cached[node.targets[0].id] = node.value.value
            view._constants = cached
        return cached


# --------------------------------------------------------------------------
# Lock identity
# --------------------------------------------------------------------------

class LockNamer:
    """Name the lock behind a ``with <expr>:`` item.

    Identity scheme (the precision the lock passes need without a type
    system): an attribute named in the ``shared_locks`` registry unifies
    package-wide on its bare name (``self.ckpt_lock`` in the engine and
    ``engine.ckpt_lock`` in models/recovery are ONE lock); otherwise the
    id is class-qualified (``FanoutPlane._lock``) when the base object's
    class is known, and module-qualified (``mod:?.attr``) when not — so
    the dozen unrelated ``_lock`` attributes never collapse into false
    cycles."""

    def __init__(self, shared: frozenset) -> None:
        self.shared = frozenset(shared)

    def name(self, expr: ast.AST, *, modname: str, class_name: str | None,
             types: dict) -> str | None:
        if isinstance(expr, ast.Name):
            if expr.id in self.shared:
                return expr.id
            return f"{modname}:{expr.id}"
        if isinstance(expr, ast.Attribute):
            attr = expr.attr
            if attr in self.shared:
                return attr
            base = expr.value
            if isinstance(base, ast.Name):
                if base.id == "self" and class_name:
                    return f"{class_name}.{attr}"
                loc = types.get(base.id)
                if loc is not None:
                    cls = loc[1] if isinstance(loc, tuple) else loc
                    return f"{cls}.{attr}"
            return f"{modname}:?.{attr}"
        return None


# --------------------------------------------------------------------------
# The lock-flow scanner + worklist engine
# --------------------------------------------------------------------------

class LockFlowScan:
    """One function body scanned under an inherited held-lock set.

    Collects, with the exact held set at each site:

    - ``writes``   — attribute assignments: (attr, line, held, is_self,
      owner_class or None for untyped bases)
    - ``acquires`` — ``with <lock>:`` items: (lock_id, line, held_before)
    - ``edges``    — resolved package call sites: (FuncKey|local key,
      held, line)
    - ``calls``    — EVERY call site: (ast.Call, held) — the
      blocking-under-lock classifier's feed

    ``resolver(call, types) -> key | None`` abstracts module-local
    (threads) vs package-wide (lock passes) call resolution, so this is
    the ONE walker all four lock-aware passes share."""

    def __init__(self, fn: ast.AST, held: frozenset, namer: LockNamer, *,
                 modname: str, class_name: str | None, types: dict,
                 resolver) -> None:
        self.fn = fn
        self.base_held = frozenset(held)
        self.namer = namer
        self.modname = modname
        self.class_name = class_name
        self.types = types
        self.resolver = resolver
        self.writes: list = []
        self.acquires: list = []
        self.edges: list = []
        self.calls: list = []

    def run(self) -> "LockFlowScan":
        self._scan(self.fn.body, self.base_held)
        return self

    def _scan(self, stmts: list, held: frozenset) -> None:  # noqa: C901
        for st in stmts:
            if isinstance(st, ast.With):
                # Items evaluate LEFT TO RIGHT with earlier items' locks
                # already held: ``with a, b:`` acquires b under a (the
                # a -> b edge), and a blocking context expr in a later
                # item runs under the earlier locks.
                inner = set(held)
                for item in st.items:
                    self._expr(item.context_expr, frozenset(inner))
                    if isinstance(item.context_expr, (ast.Name, ast.Attribute)):
                        lid = self.namer.name(
                            item.context_expr, modname=self.modname,
                            class_name=self.class_name, types=self.types,
                        )
                        if lid is not None:
                            self.acquires.append(
                                (lid, item.context_expr.lineno,
                                 frozenset(inner))
                            )
                            inner.add(lid)
                self._scan(st.body, frozenset(inner))
                continue
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
                continue
            if isinstance(st, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                targets = (st.targets if isinstance(st, ast.Assign)
                           else [st.target])
                for t in targets:
                    self._note_write(t, held)
                if getattr(st, "value", None) is not None:
                    self._expr(st.value, held)
                continue
            if isinstance(st, (ast.If, ast.While)):
                self._expr(st.test, held)
                self._scan(st.body, held)
                self._scan(st.orelse, held)
                continue
            if isinstance(st, ast.For):
                self._expr(st.iter, held)
                self._scan(st.body, held)
                self._scan(st.orelse, held)
                continue
            if isinstance(st, ast.Try):
                self._scan(st.body, held)
                for h in st.handlers:
                    self._scan(h.body, held)
                self._scan(st.orelse, held)
                self._scan(st.finalbody, held)
                continue
            for node in ast.walk(st):
                if isinstance(node, ast.expr):
                    self._expr(node, held, walk=False)

    def _note_write(self, target: ast.AST, held: frozenset) -> None:
        if isinstance(target, (ast.Tuple, ast.List)):
            for e in target.elts:
                self._note_write(e, held)
            return
        if isinstance(target, ast.Starred):
            self._note_write(target.value, held)
            return
        if isinstance(target, ast.Subscript) and isinstance(
                target.value, ast.Attribute):
            # self.x[k] = v mutates the container held by attr x.
            target = target.value
        if isinstance(target, ast.Attribute):
            is_self = (isinstance(target.value, ast.Name)
                       and target.value.id == "self")
            owner = None
            if is_self:
                owner = self.class_name
            elif isinstance(target.value, ast.Name):
                loc = self.types.get(target.value.id)
                if loc is not None:
                    owner = loc[1] if isinstance(loc, tuple) else loc
            self.writes.append(
                (target.attr, target.lineno, held, is_self, owner))

    def _expr(self, node: ast.AST, held: frozenset, walk: bool = True) -> None:
        nodes = ast.walk(node) if walk else [node]
        for n in nodes:
            if isinstance(n, ast.Call):
                self.calls.append((n, held))
                callee = self.resolver(n, self.types)
                if callee is not None:
                    self.edges.append((callee, held, getattr(n, "lineno", 0)))


def walk_lock_flow(entries, make_scan, max_items: int = 200000,
                   canonical=None) -> dict:
    """The shared worklist: ``entries`` is [(key, held_frozenset)];
    ``make_scan(key, held) -> LockFlowScan | None`` (None: key has no
    body we can scan).  Each (key, held) context is scanned exactly once;
    call edges enqueue the callee under the callsite's held set, passed
    through ``canonical`` when given (the blocking pass projects held
    sets onto the critical locks there, bounding the context count).
    Returns {key: {held: scan}}.

    Exhausting ``max_items`` RAISES: a truncated walk would report clean
    on an unfinished analysis — the gate must fail loudly, never
    false-clean (the current package uses ~3k items; the ceiling exists
    only to turn a pathological context explosion into a visible error).
    """
    done: dict = {}
    work = list(entries)
    budget = max_items
    while work:
        if budget <= 0:
            raise RuntimeError(
                f"lock-flow walk exceeded its {max_items}-item work "
                "budget — context explosion; raise max_items or add a "
                "canonicalizer"
            )
        budget -= 1
        key, held = work.pop()
        ctxs = done.setdefault(key, {})
        if held in ctxs:
            continue
        scan = make_scan(key, held)
        ctxs[held] = scan
        if scan is None:
            continue
        for callee, cheld, _line in scan.edges:
            work.append(
                (callee, canonical(cheld) if canonical is not None else cheld)
            )
    return done
