"""Shared machinery for the fftpu-check passes.

Everything here is pure stdlib ``ast``: the passes must run on a box with
no JAX installed (CI lint tier) and must never import the code under
analysis (importing the package would pull in jax + device init, and an
import-time crash in analyzed code would take the analyzer down with it).
"""

from __future__ import annotations

import ast
import json
from dataclasses import dataclass, field
from pathlib import Path


# --------------------------------------------------------------------------
# Findings + baseline
# --------------------------------------------------------------------------

@dataclass
class Finding:
    """One analyzer hit.

    ``detail`` is the stable fingerprint half: baseline entries match on
    ``(rule, file, detail)`` and deliberately NOT on ``line``, so a vetted
    suppression survives unrelated edits shifting line numbers.
    """

    rule: str
    file: str  # posix path relative to the package root's parent
    line: int
    message: str
    hint: str = ""
    detail: str = ""

    def key(self) -> tuple:
        return (self.rule, self.file, self.detail or self.message)

    def render(self) -> str:
        loc = f"{self.file}:{self.line}"
        out = f"{self.rule}  {loc}  {self.message}"
        if self.hint:
            out += f"\n    hint: {self.hint}"
        return out

    def to_json(self) -> dict:
        return {
            "rule": self.rule,
            "file": self.file,
            "line": self.line,
            "message": self.message,
            "hint": self.hint,
            "detail": self.detail or self.message,
        }


class Baseline:
    """Committed suppressions for vetted legacy findings.

    Schema (``analysis/baseline.json``)::

        {"version": 1,
         "suppressions": [
            {"rule": ..., "file": ..., "detail": ..., "rationale": ...},
         ]}

    Every entry MUST carry a non-empty rationale — the analyzer refuses a
    baseline with silent entries (a suppression nobody can explain is a
    finding in itself).  Entries that no longer match any finding are
    reported as *stale* so the baseline shrinks as fixes land.
    """

    def __init__(self, entries: list[dict] | None = None) -> None:
        self.entries = entries or []
        for e in self.entries:
            if not str(e.get("rationale", "")).strip():
                raise ValueError(
                    f"baseline entry without rationale: "
                    f"{e.get('rule')} {e.get('file')} {e.get('detail')!r}"
                )

    @classmethod
    def load(cls, path: Path | str) -> "Baseline":
        data = json.loads(Path(path).read_text())
        return cls(data.get("suppressions", []))

    @staticmethod
    def entry_key(e: dict) -> tuple:
        return (e.get("rule"), e.get("file"), e.get("detail"))

    def apply(
        self, findings: list[Finding]
    ) -> tuple[list[Finding], list[Finding], list[dict]]:
        """-> (unsuppressed, suppressed, stale_entries)."""
        index = {self.entry_key(e): e for e in self.entries}
        used: set = set()
        keep: list[Finding] = []
        quiet: list[Finding] = []
        for f in findings:
            if f.key() in index:
                used.add(f.key())
                quiet.append(f)
            else:
                keep.append(f)
        stale = [e for e in self.entries if self.entry_key(e) not in used]
        return keep, quiet, stale


# --------------------------------------------------------------------------
# Package loading
# --------------------------------------------------------------------------

@dataclass
class Module:
    path: Path
    rel: str          # "fluidframework_tpu/server/scribe.py"
    modname: str      # "fluidframework_tpu.server.scribe"
    subpackage: str   # "server" ("<root>" for top-level modules)
    tree: ast.Module
    source: str

    def segment(self, node: ast.AST, limit: int = 60) -> str:
        """Source text of a node, squashed for finding details."""
        try:
            seg = ast.get_source_segment(self.source, node) or ""
        except Exception:
            seg = ""
        seg = " ".join(seg.split())
        return seg[:limit] + ("…" if len(seg) > limit else "")

    def aliases(self) -> dict:
        """Memoized ``alias_map`` — the passes resolve names per function
        and recomputing the import table per function is quadratic."""
        cached = getattr(self, "_aliases", None)
        if cached is None:
            cached = alias_map(self)
            object.__setattr__(self, "_aliases", cached)
        return cached


@dataclass
class PackageIndex:
    pkg_dir: Path
    name: str
    modules: list[Module] = field(default_factory=list)

    def by_modname(self, name: str) -> Module | None:
        for m in self.modules:
            if m.modname == name:
                return m
        return None

    @property
    def subpackages(self) -> set:
        return {m.subpackage for m in self.modules if m.subpackage != "<root>"}


def load_package(pkg_dir: Path | str) -> PackageIndex:
    pkg_dir = Path(pkg_dir).resolve()
    idx = PackageIndex(pkg_dir=pkg_dir, name=pkg_dir.name)
    root = pkg_dir.parent
    for path in sorted(pkg_dir.rglob("*.py")):
        if "__pycache__" in path.parts:
            continue
        source = path.read_text()
        tree = ast.parse(source, filename=str(path))
        rel = path.relative_to(root).as_posix()
        parts = path.relative_to(root).with_suffix("").parts
        modname = ".".join(parts[:-1] + (parts[-1],))
        if parts[-1] == "__init__":
            modname = ".".join(parts[:-1])
        if path.parent == pkg_dir:
            sub = "<root>"  # top-level module / the package __init__
        else:
            sub = path.relative_to(pkg_dir).parts[0]
        idx.modules.append(
            Module(path=path, rel=rel, modname=modname, subpackage=sub,
                   tree=tree, source=source)
        )
    return idx


# --------------------------------------------------------------------------
# Import resolution
# --------------------------------------------------------------------------

@dataclass
class ResolvedImport:
    target: str        # fully-qualified module (or symbol) name
    line: int
    type_checking: bool


def _type_checking_lines(tree: ast.Module) -> set:
    """Line ranges of ``if TYPE_CHECKING:`` bodies (imports there are
    erased at runtime — the sanctioned way to type-hint across layers).
    Only the exact guard counts: ``if not TYPE_CHECKING:`` or
    ``if TYPE_CHECKING or X:`` bodies DO run and get no exemption."""
    def is_guard(test: ast.AST) -> bool:
        if isinstance(test, ast.Name):
            return test.id == "TYPE_CHECKING"
        if isinstance(test, ast.Attribute):
            return test.attr == "TYPE_CHECKING"
        return False

    out: set = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.If) and is_guard(node.test):
            for sub in node.body:
                for n in ast.walk(sub):
                    if hasattr(n, "lineno"):
                        out.add(n.lineno)
    return out


def iter_imports(mod: Module) -> list[ResolvedImport]:
    """Every import in the module resolved to absolute dotted names.

    Relative imports resolve against the module's own package path; for
    ``from PKG import name`` each alias resolves one level deeper (the
    alias may itself be a subpackage — ``from fluidframework_tpu import
    parallel``)."""
    tc = _type_checking_lines(mod.tree)
    # Package path the relative imports resolve against.
    is_pkg_init = mod.path.name == "__init__.py"
    self_pkg = mod.modname if is_pkg_init else mod.modname.rsplit(".", 1)[0]
    out: list[ResolvedImport] = []
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                out.append(ResolvedImport(a.name, node.lineno, node.lineno in tc))
        elif isinstance(node, ast.ImportFrom):
            if node.level == 0:
                base = node.module or ""
            else:
                comps = self_pkg.split(".")
                comps = comps[: len(comps) - (node.level - 1)]
                base = ".".join(comps + ([node.module] if node.module else []))
            for a in node.names:
                target = f"{base}.{a.name}" if base else a.name
                out.append(ResolvedImport(target, node.lineno, node.lineno in tc))
    return out


def alias_map(mod: Module) -> dict:
    """Local name -> fully-qualified dotted target, for resolving
    ``mk.apply_ops`` / ``jnp.any`` / ``partial`` style references."""
    is_pkg_init = mod.path.name == "__init__.py"
    self_pkg = mod.modname if is_pkg_init else mod.modname.rsplit(".", 1)[0]
    out: dict = {}
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                out[a.asname or a.name.split(".")[0]] = (
                    a.name if a.asname else a.name.split(".")[0]
                )
                if a.asname:
                    out[a.asname] = a.name
        elif isinstance(node, ast.ImportFrom):
            if node.level == 0:
                base = node.module or ""
            else:
                comps = self_pkg.split(".")
                comps = comps[: len(comps) - (node.level - 1)]
                base = ".".join(comps + ([node.module] if node.module else []))
            for a in node.names:
                target = f"{base}.{a.name}" if base else a.name
                out[a.asname or a.name] = target
    return out


def dotted_name(expr: ast.AST) -> str | None:
    """``a.b.c`` attribute/name chain as a string, else None."""
    parts: list[str] = []
    node = expr
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def resolve(expr: ast.AST, aliases: dict) -> str | None:
    """Resolve an expression to a fully-qualified dotted name using the
    module's import aliases (``mk.apply_ops`` ->
    ``fluidframework_tpu.ops.mergetree_kernel.apply_ops``)."""
    dn = dotted_name(expr)
    if dn is None:
        return None
    head, _, rest = dn.partition(".")
    fq = aliases.get(head, head)
    return f"{fq}.{rest}" if rest else fq
