"""Pass 7 — fold-mark-churn: per-commit mark-object allocation in the
pooled tree fold.

PR 14 moved the tree family's host fold to the pooled columnar mark store
(dds/tree/mark_pool.py): marks live as int32/object columns, rebase runs
as column passes, and ``Mark.__init__`` left the profile.  The idiom this
pass keeps out is the one that put it there: constructing a mark dataclass
(``Skip``/``Insert``/``Remove``/``Modify``/``MoveOut``/``MoveIn``) inside
a loop in the fold modules — one object per mark per commit per window
entry, the exact churn the pool replaced.  The object ORACLE
(changeset.py) legitimately allocates marks everywhere; it is therefore
not in scope — the scope is the pooled fold itself, where a mark
constructor in a loop means someone quietly re-introduced per-commit
materialization on the hot path.

Scope is declared in layers.json under ``fold_churn_scope``::

    "fold_churn_scope": {
        "files":   ["fluidframework_tpu/dds/tree/mark_pool.py", ...],
        "classes": ["Skip", "Insert", "Remove", ...],
        "exempt_functions": ["to_marks", ...]
    }

``exempt_functions`` names the sanctioned materialization boundaries (the
oracle handoff, e.g. ``PooledMarks.to_marks``): those exist precisely to
build object marks, and exempting them by NAME keeps the exemption
reviewable in the same committed config as the scope.  A missing
``fold_churn_scope`` key disables the pass (fixture packages), matching
how ``determinism_scope`` gates the determinism pass.

Finding: ``fold-mark-churn`` — file:line of the constructor call, with the
enclosing function and loop line in the detail fingerprint.
"""

from __future__ import annotations

import ast

from .core import Finding, Module, PackageIndex


# Comprehensions allocate per element — the same churn shape as a loop.
_LOOPS = (ast.For, ast.AsyncFor, ast.While,
          ast.ListComp, ast.SetComp, ast.DictComp, ast.GeneratorExp)


def _enclosing(tree: ast.Module) -> dict:
    """node-id -> (dotted function scope, innermost enclosing loop node;
    comprehensions count as loops)."""
    out: dict = {}

    def walk(node: ast.AST, scope: str, loop) -> None:
        for child in ast.iter_child_nodes(node):
            cscope, cloop = scope, loop
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef)):
                cscope = f"{scope}.{child.name}" if scope else child.name
                cloop = None  # a nested def starts its own loop context
            elif isinstance(child, _LOOPS):
                cloop = child
            out[id(child)] = (cscope, cloop)
            walk(child, cscope, cloop)

    walk(tree, "", None)
    return out


def _call_name(func: ast.AST) -> str | None:
    if isinstance(func, ast.Name):
        return func.id
    if isinstance(func, ast.Attribute):
        return func.attr
    return None


def run(index: PackageIndex, scope_cfg: dict | None) -> list[Finding]:
    if not scope_cfg:
        return []
    files = set(scope_cfg.get("files", []))
    classes = set(scope_cfg.get("classes", []))
    exempt = set(scope_cfg.get("exempt_functions", []))
    if not files or not classes:
        return []
    findings: list[Finding] = []
    for mod in index.modules:
        if mod.rel not in files:
            continue
        findings.extend(_run_module(mod, classes, exempt))
    return findings


def _run_module(mod: Module, classes: set, exempt: set) -> list[Finding]:
    out: list[Finding] = []
    ctx = _enclosing(mod.tree)
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        name = _call_name(node.func)
        if name not in classes:
            continue
        scope, loop = ctx.get(id(node), ("<module>", None))
        if loop is None:
            continue  # one-off construction: not the churn shape
        fn = scope.rsplit(".", 1)[-1] if scope else "<module>"
        if fn in exempt:
            continue
        # Line-free fingerprint (baseline entries survive line drift).
        loop_kind = (
            "loop" if isinstance(loop, (ast.For, ast.AsyncFor, ast.While))
            else "comprehension"
        )
        out.append(Finding(
            rule="fold-mark-churn",
            file=mod.rel,
            line=node.lineno,
            message=(
                f"{name}(...) constructed per iteration in {scope or '<module>'} "
                "— per-commit mark materialization on the pooled fold path"
            ),
            hint=(
                "emit pooled column rows instead (mark_pool builder/seal); "
                "if this IS a sanctioned oracle boundary, add the function "
                "to fold_churn_scope.exempt_functions in layers.json"
            ),
            detail=f"{name} in {scope or '<module>'} ({loop_kind})",
        ))
    return out
