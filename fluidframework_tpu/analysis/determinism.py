"""Pass 4 — determinism: nondeterministic constructs in byte-identity paths.

BASELINE.json's core invariant is byte-identical convergence: every replica
folding the same ordered op stream must produce the same bytes — states,
digests, summaries, object-store shas.  The scribe fold, the op-apply
kernels, and the summary codecs are therefore *deterministic functions* of
the log, and any host construct whose output depends on interpreter
identity or wall clock silently breaks them on exactly one replica,
which the divergence watchdog then reports as data corruption.

Scope: the module paths listed under ``determinism_scope`` in
``analysis/layers.json`` (op-apply kernels, scribe fold, summary codecs,
object store).  Rules:

- ``det-set-iteration``  — iterating / materializing a set (``for x in s``,
  ``list(s)``): PYTHONHASHSEED-dependent order.  ``sorted(s)``, ``min``/
  ``max``, membership tests stay silent.
- ``det-id-ordering``    — ``id()`` use: interpreter-run-dependent values
  (deadly as sort keys or serialized content).
- ``det-wallclock``      — ``time.time``/``monotonic``/``datetime.now``
  etc. (``time.sleep`` is pacing, not output — exempt).
- ``det-random``         — ``random.*``/``np.random.*``/``uuid``/``secrets``.
- ``det-hash-builtin``   — builtin ``hash()``: salted per process for str/
  bytes (``hashlib`` is the deterministic spelling and stays silent).

Set-typedness is inferred structurally: set literals/comprehensions,
``set()``/``frozenset()`` calls, unions/intersections of those, locals
assigned from them, and ``self.X`` attributes declared ``: set[...]`` or
initialized to ``set(...)`` in the class body / ``__init__``.
"""

from __future__ import annotations

import ast

from .core import Finding, Module, PackageIndex, resolve

WALLCLOCK = {
    "time.time", "time.time_ns", "time.monotonic", "time.monotonic_ns",
    "time.perf_counter", "time.perf_counter_ns", "time.process_time",
    "datetime.datetime.now", "datetime.datetime.utcnow", "datetime.date.today",
}
RANDOM_HEADS = ("random.", "numpy.random.", "secrets.", "uuid.")


def in_scope(rel: str, scope: list) -> bool:
    return any(rel == s or rel.startswith(s.rstrip("/") + "/") for s in scope)


class _SetTypes(ast.NodeVisitor):
    """Collect set-typed local names per function and set-typed ``self.X``
    attributes per class (from annotations and __init__ assignments)."""

    def __init__(self, mod: Module) -> None:
        self.mod = mod
        self.class_attrs: dict = {}      # class -> set of attr names
        self._stack: list = []

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self._stack.append(node.name)
        self.class_attrs.setdefault(node.name, set())
        self.generic_visit(node)
        self._stack.pop()

    def _note_attr(self, target: ast.AST, value: ast.AST | None,
                   annotation: ast.AST | None) -> None:
        if not self._stack:
            return
        if (isinstance(target, ast.Attribute)
                and isinstance(target.value, ast.Name)
                and target.value.id == "self"):
            name = target.attr
        elif isinstance(target, ast.Name):
            name = target.id
        else:
            return
        if _is_set_annotation(annotation) or (value is not None and _is_set_expr(value, set())):
            self.class_attrs[self._stack[-1]].add(name)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        self._note_attr(node.target, node.value, node.annotation)
        self.generic_visit(node)

    def visit_Assign(self, node: ast.Assign) -> None:
        for t in node.targets:
            self._note_attr(t, node.value, None)
        self.generic_visit(node)


def _is_set_annotation(ann: ast.AST | None) -> bool:
    if ann is None:
        return False
    txt = ast.unparse(ann) if not isinstance(ann, ast.Constant) else str(ann.value)
    head = txt.split("[")[0].strip().strip('"\'')
    return head in ("set", "frozenset", "Set", "FrozenSet", "typing.Set",
                    "typing.FrozenSet", "AbstractSet", "MutableSet")


def _is_set_expr(node: ast.AST, local_sets: set, class_attrs: set = frozenset()) -> bool:
    if isinstance(node, (ast.Set, ast.SetComp)):
        return True
    if isinstance(node, ast.Call):
        fn = node.func
        if isinstance(fn, ast.Name) and fn.id in ("set", "frozenset"):
            return True
        if isinstance(fn, ast.Attribute) and fn.attr in (
                "union", "intersection", "difference", "symmetric_difference",
                "copy") and _is_set_expr(fn.value, local_sets, class_attrs):
            return True
        return False
    if isinstance(node, ast.BinOp) and isinstance(node.op, (ast.BitOr, ast.BitAnd,
                                                            ast.Sub, ast.BitXor)):
        return (_is_set_expr(node.left, local_sets, class_attrs)
                or _is_set_expr(node.right, local_sets, class_attrs))
    if isinstance(node, ast.Name):
        return node.id in local_sets
    if (isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr in class_attrs
    return False


def run(index: PackageIndex, scope: list) -> list[Finding]:
    findings: list[Finding] = []
    for mod in index.modules:
        if not in_scope(mod.rel, scope):
            continue
        aliases = mod.aliases()
        types = _SetTypes(mod)
        types.visit(mod.tree)
        for fn_node, class_name in _functions(mod.tree):
            _scan_function(mod, aliases, fn_node,
                           types.class_attrs.get(class_name, set()), findings)
    return findings


def _functions(tree: ast.Module):
    """Top-level functions and class methods, each exactly once, with the
    owning class name (nested defs scan as part of their parent)."""
    owner: dict = {}
    nested: set = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef):
            for sub in node.body:
                if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    owner[id(sub)] = node.name
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for sub in ast.walk(node):
                if sub is not node and isinstance(
                        sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    nested.add(id(sub))
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and id(node) not in nested:
            yield node, owner.get(id(node))


def _scan_function(mod: Module, aliases: dict, fn: ast.AST,
                   class_attrs: set, findings: list) -> None:
    name = fn.name

    # Per-use flow for local names: a name's set-typedness at line L is the
    # verdict of its LAST assignment before L — so ``docs = set(x); docs =
    # sorted(docs); for d in docs`` is silent (the hint's own fix), while
    # ``for d in s: ...`` before a later ``s = set(...)`` doesn't flag the
    # loop, and ``s = set(x); for d in s`` after it still does.
    assigns: list = []
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            assigns.append((node.lineno, node.col_offset,
                            node.targets[0].id, node.value, None))
        elif isinstance(node, ast.AnnAssign) and isinstance(node.target, ast.Name):
            assigns.append((node.lineno, node.col_offset,
                            node.target.id, node.value, node.annotation))
    assigns.sort(key=lambda a: (a[0], a[1]))

    def typed_at(var: str, line: int, stack: frozenset) -> bool:
        last = None
        for ln, _col, tgt, value, ann in assigns:
            if ln >= line:
                break
            if tgt == var:
                last = (ln, value, ann)
        if last is None:
            return False
        ln, value, ann = last
        if ann is not None and _is_set_annotation(ann):
            return True
        if value is None or (var, ln) in stack:
            return False
        return _expr_is_set(value, ln, stack | {(var, ln)})

    def _expr_is_set(node: ast.AST, line: int, stack: frozenset = frozenset()) -> bool:
        if isinstance(node, ast.Name):
            return typed_at(node.id, line, stack)
        if (isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name)
                and node.value.id == "self"):
            return node.attr in class_attrs
        if isinstance(node, ast.BinOp) and isinstance(
                node.op, (ast.BitOr, ast.BitAnd, ast.Sub, ast.BitXor)):
            return (_expr_is_set(node.left, line, stack)
                    or _expr_is_set(node.right, line, stack))
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute) \
                and node.func.attr in ("union", "intersection", "difference",
                                       "symmetric_difference", "copy"):
            return _expr_is_set(node.func.value, line, stack)
        return _is_set_expr(node, set(), class_attrs)

    def set_typed(e: ast.AST) -> bool:
        return _expr_is_set(e, getattr(e, "lineno", 0))

    def flag(rule: str, node: ast.AST, message: str, hint: str, detail: str) -> None:
        findings.append(Finding(rule=rule, file=mod.rel,
                                line=getattr(node, "lineno", 0),
                                message=f"{name}: {message}", hint=hint,
                                detail=f"{name}: {detail}"))

    for node in ast.walk(fn):
        if isinstance(node, ast.For) and set_typed(node.iter):
            seg = mod.segment(node.iter, limit=40)
            flag("det-set-iteration", node,
                 f"iterates a set (`{seg}`): PYTHONHASHSEED-dependent order",
                 "wrap in sorted(...) so every replica folds the same order",
                 f"set iteration over `{seg}`")
        elif isinstance(node, (ast.ListComp, ast.SetComp, ast.DictComp,
                               ast.GeneratorExp)):
            for gen in node.generators:
                if set_typed(gen.iter):
                    seg = mod.segment(gen.iter, limit=40)
                    flag("det-set-iteration", node,
                         f"comprehension over a set (`{seg}`)",
                         "wrap in sorted(...) so every replica folds the same order",
                         f"set iteration over `{seg}`")
        elif isinstance(node, ast.Call):
            fname = resolve(node.func, aliases)
            bare = fname.split(".")[-1] if fname else None
            if (isinstance(node.func, ast.Name)
                    and node.func.id in ("list", "tuple")
                    and node.args and set_typed(node.args[0])):
                seg = mod.segment(node.args[0], limit=40)
                flag("det-set-iteration", node,
                     f"materializes a set in hash order (`{bare}({seg})`)",
                     "use sorted(...) instead",
                     f"set materialization `{bare}({seg})`")
            elif isinstance(node.func, ast.Name) and node.func.id == "id":
                seg = mod.segment(node, limit=40)
                flag("det-id-ordering", node,
                     f"id() use (`{seg}`): interpreter-run-dependent value",
                     "key by a stable identifier (name, seq, sha) instead",
                     f"id() use `{seg}`")
            elif isinstance(node.func, ast.Name) and node.func.id == "hash":
                seg = mod.segment(node, limit=40)
                flag("det-hash-builtin", node,
                     f"builtin hash() (`{seg}`): salted per process for str/bytes",
                     "use hashlib for content hashes",
                     f"hash() use `{seg}`")
            elif fname in WALLCLOCK:
                flag("det-wallclock", node,
                     f"wall-clock read ({fname}) inside a byte-identity path",
                     "thread the timestamp in from the sequenced op instead",
                     f"wallclock {fname}")
            elif fname and fname.startswith(RANDOM_HEADS):
                flag("det-random", node,
                     f"nondeterministic source ({fname}) inside a byte-identity path",
                     "derive from the op stream (seq, client seed) instead",
                     f"random {fname}")
