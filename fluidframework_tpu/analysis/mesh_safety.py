"""Pass 11 — mesh-safety: shard_map/collective hazards, statically.

The seg-parallel serving path (PR 11) put real collectives on the hot
path: ``psum``/``pmin``/``all_gather`` over a named mesh axis, wrapped in
``jit(shard_map(...))`` programs whose in/out specs and donation flags
are load-bearing.  Three hazard classes are statically checkable and each
has already cost a debugging session:

- ``mesh-axis-unknown`` — a collective whose axis name resolves to a
  string no ``Mesh(...)`` construction in the package ever declares.  A
  typo'd axis traces fine in tests that bind it and explodes (or silently
  no-ops) on the mesh that doesn't.  The resolver follows constants
  through parameter defaults and module/imported constants
  (``SEG_AXIS``-style), so the kernels' ``axis=SEG_AXIS`` idiom checks.
- ``mesh-in-specs-arity`` — a ``shard_map`` whose literal ``in_specs``
  tuple disagrees with the wrapped function's positional arity: today a
  confusing trace-time error, here a finding with both numbers.
- ``mesh-donate-replicated-out`` — donation enabled on a program whose
  ``out_specs`` replicate any output.  This is the live bug class the
  seg-parallel byte-identity fuzz caught: a donated shard_map executable
  with replicated outputs, RELOADED from the persistent XLA compile
  cache, returns permuted garbage (jax 0.4.37 — see
  ``parallel/mesh.py::mesh_seg_program``).  Fires on (a) a statically
  replicated ``out_specs`` (a bare ``P()`` literal in the spec tree)
  jitted with non-empty ``donate_argnums``, and (b) any program declared
  in layers.json ``mesh_scope.replicated_out_programs`` whose donation
  resolves ON (parameter defaults included) — the config carries the
  hand-knowledge that ``mesh_seg_program``'s out specs replicate, so a
  well-meaning "re-enable donation" edit trips this rule, not a fuzz
  flake.  Scope entries that no longer name a real function fail loudly.
"""

from __future__ import annotations

import ast

from .core import (
    Finding,
    Module,
    PackageIndex,
    PackageView,
    build_func_index,
    dotted_name,
    resolve,
    resolve_in,
)
from .jit_safety import JIT_NAMES, unwrap_target

COLLECTIVES = {
    "psum", "pmin", "pmax", "pmean", "all_gather", "all_to_all",
    "ppermute", "axis_index", "psum_scatter", "pshuffle",
}
_SPEC_NAMES = {"jax.sharding.PartitionSpec", "PartitionSpec", "P"}


def _is_collective(fq: str | None) -> bool:
    if not fq:
        return False
    parts = fq.split(".")
    return parts[-1] in COLLECTIVES and ("lax" in parts or parts[0] == "jax")


def _param_defaults(fn: ast.AST) -> dict:
    """param name -> default expression (positional + kw-only)."""
    out: dict = {}
    a = fn.args
    pos = a.posonlyargs + a.args
    for p, d in zip(pos[len(pos) - len(a.defaults):], a.defaults):
        out[p.arg] = d
    for p, d in zip(a.kwonlyargs, a.kw_defaults):
        if d is not None:
            out[p.arg] = d
    return out


class _Resolver:
    """Static constant resolution: parameter defaults, module constants,
    and imported constants (``SEG_AXIS`` through the alias map)."""

    def __init__(self, pv: PackageView, mod: Module, fn: ast.AST | None):
        self.pv = pv
        self.mod = mod
        self.aliases = mod.aliases()
        self.defaults = _param_defaults(fn) if fn is not None else {}

    def const_str(self, expr: ast.AST | None, depth: int = 0) -> str | None:
        if expr is None or depth > 4:
            return None
        if isinstance(expr, ast.Constant):
            return expr.value if isinstance(expr.value, str) else None
        if isinstance(expr, ast.Name):
            if expr.id in self.defaults:
                return self.const_str(self.defaults[expr.id], depth + 1)
            local = self.pv.module_constants(self.mod.modname).get(expr.id)
            if local is not None:
                return local
        fq = resolve(expr, self.aliases)
        if fq and "." in fq:
            modname, _, name = fq.rpartition(".")
            val = self.pv.module_constants(modname).get(name)
            if isinstance(val, str):
                return val
        return None

    def const_truth(self, expr: ast.AST | None, depth: int = 0) -> bool | None:
        if expr is None or depth > 4:
            return None
        if isinstance(expr, ast.Constant):
            return bool(expr.value)
        if isinstance(expr, ast.Name) and expr.id in self.defaults:
            return self.const_truth(self.defaults[expr.id], depth + 1)
        return None

    def donates(self, expr: ast.AST | None, depth: int = 0) -> bool | None:
        """donate_argnums expression -> True (definitely non-empty),
        False (definitely empty), None (unknown)."""
        if expr is None or depth > 4:
            return None
        if isinstance(expr, (ast.Tuple, ast.List)):
            return bool(expr.elts)
        if isinstance(expr, ast.Constant):
            if isinstance(expr.value, int) and not isinstance(expr.value, bool):
                return True
            return None
        if isinstance(expr, ast.IfExp):
            t = self.const_truth(expr.test, depth + 1)
            if t is None:
                return None
            return self.donates(expr.body if t else expr.orelse, depth + 1)
        if isinstance(expr, ast.Name) and expr.id in self.defaults:
            return self.donates(self.defaults[expr.id], depth + 1)
        return None


def _axis_universe(index: PackageIndex, pv: PackageView,
                   calls: dict) -> set:
    """Every axis name any ``Mesh(...)`` construction in the package
    declares (tuple literals, through param defaults/constants)."""
    universe: set = set()
    for mod in index.modules:
        aliases = mod.aliases()
        for fn, call in calls[mod.modname]:
            fq = resolve(call.func, aliases)
            if not fq or fq.split(".")[-1] != "Mesh":
                continue
            names_expr = None
            if len(call.args) >= 2:
                names_expr = call.args[1]
            for kw in call.keywords:
                if kw.arg == "axis_names":
                    names_expr = kw.value
            if names_expr is None:
                continue
            res = _Resolver(pv, mod, fn)
            elts = (names_expr.elts
                    if isinstance(names_expr, (ast.Tuple, ast.List))
                    else [names_expr])
            for e in elts:
                s = res.const_str(e)
                if s is not None:
                    universe.add(s)
    return universe


def _calls_with_owner(mod: Module):
    """(INNERMOST enclosing function def or None, Call) pairs for a
    module.  Innermost matters: the resolver reads parameter defaults off
    the owner, and a kernel closure nested in a factory must resolve its
    own ``axis=SEG_AXIS`` default, never the factory's."""
    out: list = []

    def visit(node, owner):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.Call):
                out.append((owner, child))
            child_owner = (
                child
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef))
                else owner
            )
            visit(child, child_owner)

    visit(mod.tree, None)
    return out


def _axis_findings(index, pv, universe, calls) -> list:
    findings: list = []
    if not universe:
        return findings
    for mod in index.modules:
        aliases = mod.aliases()
        for fn, call in calls[mod.modname]:
            fq = resolve(call.func, aliases)
            if not _is_collective(fq):
                continue
            leaf = fq.split(".")[-1]
            axis_expr = None
            if leaf == "axis_index":
                axis_expr = call.args[0] if call.args else None
            elif len(call.args) >= 2:
                axis_expr = call.args[1]
            for kw in call.keywords:
                if kw.arg in ("axis", "axis_name"):
                    axis_expr = kw.value
            axis = _Resolver(pv, mod, fn).const_str(axis_expr)
            if axis is None or axis in universe:
                continue
            findings.append(Finding(
                rule="mesh-axis-unknown",
                file=mod.rel, line=call.lineno,
                message=(
                    f"`{leaf}` over axis {axis!r}, which no Mesh in the "
                    f"package declares (known axes: "
                    f"{sorted(universe)})"
                ),
                hint=(
                    "bind the collective to a declared mesh axis (a "
                    "typo'd axis no-ops or explodes only on the mesh "
                    "that lacks it)"
                ),
                detail=f"{leaf} over unknown axis {axis!r}",
            ))
    return findings


def _spec_replicates(expr: ast.AST | None, aliases: dict,
                     local_assigns: dict, depth: int = 0) -> bool:
    """True when the out_specs expression statically contains a bare
    ``P()`` / ``PartitionSpec()`` (a replicated output)."""
    if expr is None or depth > 3:
        return False
    if isinstance(expr, ast.Name) and expr.id in local_assigns:
        return _spec_replicates(
            local_assigns[expr.id], aliases, local_assigns, depth + 1)
    for node in ast.walk(expr):
        if isinstance(node, ast.Call) and not node.args and not node.keywords:
            fq = resolve(node.func, aliases)
            dn = dotted_name(node.func)
            if (fq in _SPEC_NAMES or dn in _SPEC_NAMES
                    or (fq or "").endswith(".PartitionSpec")):
                return True
    return False


def _local_assigns(fn: ast.AST | None) -> dict:
    out: dict = {}
    if fn is None:
        return out
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            out[node.targets[0].id] = node.value
    return out


def _shard_map_of(expr: ast.AST | None, local_assigns: dict):
    """Follow ``expr`` (directly or via a local name) to a shard_map call."""
    if isinstance(expr, ast.Name):
        expr = local_assigns.get(expr.id)
    if isinstance(expr, ast.Call):
        dn = dotted_name(expr.func) or ""
        if dn.split(".")[-1] == "shard_map":
            return expr
    return None


def _jit_wrap_findings(index, pv, calls) -> list:
    findings: list = []
    for mod in index.modules:
        aliases = mod.aliases()
        for fn, call in calls[mod.modname]:
            fq = resolve(call.func, aliases)
            if fq not in JIT_NAMES and (fq or "") != "jit":
                continue
            donate_expr = next(
                (k.value for k in call.keywords if k.arg == "donate_argnums"),
                None,
            )
            res = _Resolver(pv, mod, fn)
            if res.donates(donate_expr) is not True:
                continue
            assigns = _local_assigns(fn)
            sm = _shard_map_of(call.args[0] if call.args else None, assigns)
            if sm is None:
                continue
            out_specs = next(
                (k.value for k in sm.keywords if k.arg == "out_specs"), None
            )
            if not _spec_replicates(out_specs, aliases, assigns):
                continue
            findings.append(Finding(
                rule="mesh-donate-replicated-out",
                file=mod.rel, line=call.lineno,
                message=(
                    "donated jit over a shard_map whose out_specs "
                    "replicate an output: a donated replicated-output "
                    "executable reloaded from the persistent XLA compile "
                    "cache mis-aliases its buffers (jax 0.4.37)"
                ),
                hint=(
                    "keep donate_argnums empty for replicated-output "
                    "programs (see parallel/mesh.py::mesh_seg_program)"
                ),
                detail="donated shard_map with replicated out_specs",
            ))
    return findings


def _declared_program_findings(index, pv, mesh_scope: dict,
                               func_index: dict) -> list:
    findings: list = []
    entries = (mesh_scope or {}).get("replicated_out_programs", [])
    for entry in entries:
        try:
            rel, fn_name = entry.split("::")
        except ValueError:
            raise ValueError(
                f"mesh_scope.replicated_out_programs entry {entry!r}: "
                "expected 'path/to/file.py::function'"
            ) from None
        mod = next((m for m in index.modules if m.rel == rel), None)
        if mod is None:
            # Root-name-agnostic fallback: seeded-violation tests (and
            # other repos) analyze COPIES of the tree under a different
            # directory name; the entry's path tail still pins the file.
            tail = rel.split("/", 1)[-1]
            mod = next(
                (m for m in index.modules
                 if m.rel.split("/", 1)[-1] == tail), None,
            )
        fn = None
        if mod is not None:
            info = func_index.get(f"{mod.modname}.{fn_name}")
            fn = info.node if info is not None else None
        if fn is None:
            raise ValueError(
                f"mesh_scope.replicated_out_programs entry {entry!r} "
                "matches no function — fix the entry (a stale scope "
                "silently un-guards the donation bug)"
            )
        res = _Resolver(pv, mod, fn)
        aliases = mod.aliases()
        flagged = False
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            fq = resolve(node.func, aliases)
            if fq not in JIT_NAMES and (fq or "") != "jit":
                continue
            donate_expr = next(
                (k.value for k in node.keywords
                 if k.arg == "donate_argnums"), None,
            )
            if res.donates(donate_expr) is True:
                findings.append(Finding(
                    rule="mesh-donate-replicated-out",
                    file=mod.rel, line=node.lineno,
                    message=(
                        f"{fn_name} is declared replicated-out "
                        "(mesh_scope) but its jit resolves to NON-EMPTY "
                        "donate_argnums: donated replicated-output "
                        "executables corrupt on persistent-cache reload "
                        "(jax 0.4.37, two-process repro)"
                    ),
                    hint=(
                        "keep donation OFF (donate defaults False) until "
                        "the upstream aliasing bug is fixed"
                    ),
                    detail=f"{fn_name}: donation enabled on replicated-out program",
                ))
                flagged = True
        if not flagged:
            donate_default = _param_defaults(fn).get("donate")
            if (isinstance(donate_default, ast.Constant)
                    and donate_default.value is True):
                findings.append(Finding(
                    rule="mesh-donate-replicated-out",
                    file=mod.rel, line=fn.lineno,
                    message=(
                        f"{fn_name} (declared replicated-out) defaults "
                        "donate=True — the cache-reload aliasing bug "
                        "class (jax 0.4.37)"
                    ),
                    hint="default donate=False; see the repro note",
                    detail=f"{fn_name}: donation enabled on replicated-out program",
                ))
    return findings


def _arity_findings(index, pv, calls, func_index: dict) -> list:
    findings: list = []
    for mod in index.modules:
        aliases = mod.aliases()
        for _fn, call in calls[mod.modname]:
            dn = dotted_name(call.func) or ""
            if dn.split(".")[-1] != "shard_map":
                continue
            in_specs = next(
                (k.value for k in call.keywords if k.arg == "in_specs"), None
            )
            if not isinstance(in_specs, (ast.Tuple, ast.List)):
                continue
            target_expr = call.args[0] if call.args else next(
                (k.value for k in call.keywords if k.arg == "f"), None
            )
            n_params = None
            label = None
            t = unwrap_target(mod, aliases, target_expr)
            if t is None and isinstance(target_expr, ast.Lambda):
                t = ("lambda", target_expr)
            if t is not None and t[0] == "name":
                info = func_index.get(t[1])
                if info is not None and not info.node.args.vararg:
                    n_params = len(info.params())
                    if info.class_name and info.params()[:1] == ["self"]:
                        n_params -= 1
                    label = t[1].split(".")[-1]
            elif t is not None and t[0] == "lambda":
                lam = t[1]
                if not lam.args.vararg:
                    n_params = len(lam.args.posonlyargs + lam.args.args)
                    label = "<lambda>"
            if n_params is None or n_params == len(in_specs.elts):
                continue
            findings.append(Finding(
                rule="mesh-in-specs-arity",
                file=mod.rel, line=call.lineno,
                message=(
                    f"shard_map in_specs has {len(in_specs.elts)} specs "
                    f"but `{label}` takes {n_params} positional args"
                ),
                hint="one spec per mapped argument, in order",
                detail=(
                    f"in_specs arity {len(in_specs.elts)} != {n_params} "
                    f"params of {label}"
                ),
            ))
    return findings


def run(index: PackageIndex, mesh_scope: dict | None) -> list[Finding]:
    pv = PackageView.of(index)
    # One AST sweep + one function index, shared by every collector: the
    # gate runs on Docker builds and pre-commit loops, so the pass pays
    # for its (enclosing-function, call) pairs exactly once per module.
    func_index = build_func_index(index)
    calls = {m.modname: list(_calls_with_owner(m)) for m in index.modules}
    universe = _axis_universe(index, pv, calls)
    findings = _axis_findings(index, pv, universe, calls)
    findings += _arity_findings(index, pv, calls, func_index)
    findings += _jit_wrap_findings(index, pv, calls)
    findings += _declared_program_findings(index, pv, mesh_scope or {},
                                           func_index)
    # Dedup (fixture trees can reach a site twice through the walkers).
    seen: set = set()
    out: list = []
    for f in findings:
        k = (f.rule, f.file, f.line, f.detail)
        if k not in seen:
            seen.add(k)
            out.append(f)
    return out
