"""Pass 2 — jit-safety: trace hazards reachable from jit entry points.

The RecompileWatchdog (PR 7) catches trace despecialization at *runtime* —
after the fleet already stalled on a recompile.  This pass is its static
complement: it finds the constructs that despecialize (or outright break) a
trace before anything runs.

Mechanics, pure AST:

1. **Entry discovery** — every function wrapped by ``jax.jit``,
   ``functools.partial(jax.jit, ...)``, ``shard_map`` or
   ``parallel.mesh.mesh_fleet_program`` (decorator or call form, through
   transparent wrappers like ``jax.vmap``).
2. **Reachability + taint** — entry parameters are tracers (minus
   ``static_argnums``/``static_argnames``); taint flows through
   assignments, arithmetic, ``jnp.*`` calls and into callees (package-wide
   worklist, keyword- and position-aware).  ``.shape``/``.dtype``/
   ``len()``/``is None`` results are static under trace and untaint.
3. **Rules** fired inside reachable code:

   - ``jit-branch-on-tracer``  — ``if``/``while``/ternary/``assert`` on a
     traced value (ConcretizationTypeError, or a silent despecialization
     when hidden behind ``int()``)
   - ``jit-np-on-tracer``      — ``np.*`` call on a traced value (host
     round-trip; breaks under jit)
   - ``jit-host-sync``         — ``int()/float()/bool()/.item()/.tolist()``
     on a traced value
   - ``jit-unhashable-static`` — list/dict/set literal passed for a static
     parameter (TypeError at dispatch, every call a cache miss before it)

4. ``jit-host-sync-loop`` — package-wide (host code included): a
   per-element ``x[i].item()`` inside a loop / comprehension; one device
   sync per element where one bulk ``.tolist()`` outside the loop does it
   in a single transfer (the dds/tree/forest.py:191 class).
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field

from .core import (  # noqa: F401  (FuncInfo/build_func_index/resolve_in
    # re-exported: the donation + mesh-safety passes import them from here
    # and from core interchangeably)
    Finding,
    FuncInfo,
    Module,
    PackageIndex,
    build_func_index,
    dotted_name,
    resolve,
    resolve_in,
)

JIT_NAMES = {"jax.jit"}
SHARD_MAP_NAMES = {"jax.experimental.shard_map.shard_map", "shard_map"}
PARTIAL_NAMES = {"functools.partial"}
# Wrappers that pass their first argument through to the trace.
TRANSPARENT = {"jax.vmap", "jax.named_call", "jax.checkpoint", "jax.remat"}
# Calls whose result is static at trace time even on traced inputs.
STATIC_RESULT_CALLS = {
    "len", "isinstance", "type", "hasattr", "getattr", "callable",
    "repr", "str", "format",
}
HOST_SYNC_BUILTINS = {"int", "float", "bool", "complex"}
HOST_SYNC_METHODS = {"item", "tolist", "block_until_ready"}
# Attribute reads that are static metadata on a tracer.
STATIC_ATTRS = {
    "shape", "dtype", "ndim", "size", "weak_type", "sharding", "aval",
    "itemsize", "nbytes",
}


# --------------------------------------------------------------------------
# Jit registration scanning (shared with the donation + mesh-safety passes;
# the function index itself lives in core.build_func_index)
# --------------------------------------------------------------------------

def _const_index_set(node: ast.AST | None) -> set:
    """static_argnums/donate_argnums literal -> set of ints."""
    if node is None:
        return set()
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return {node.value}
    if isinstance(node, (ast.Tuple, ast.List)):
        return {
            e.value for e in node.elts
            if isinstance(e, ast.Constant) and isinstance(e.value, int)
        }
    return set()


def _const_name_set(node: ast.AST | None) -> set:
    if node is None:
        return set()
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return {node.value}
    if isinstance(node, (ast.Tuple, ast.List)):
        return {
            e.value for e in node.elts
            if isinstance(e, ast.Constant) and isinstance(e.value, str)
        }
    return set()


@dataclass
class JitWrap:
    """One ``jax.jit``-like wrapping: what it wraps + how."""

    target: ast.AST | None        # the wrapped function expression
    static_argnums: set = field(default_factory=set)
    static_argnames: set = field(default_factory=set)
    donate_argnums: set = field(default_factory=set)
    kind: str = "jit"             # "jit" | "shard_map" | "mesh_fleet_program"
    call: ast.Call | None = None


def parse_jit_value(mod: Module, aliases: dict, expr: ast.AST) -> JitWrap | None:
    """Recognize jit-wrapping expressions (None if ``expr`` isn't one):

    - ``jax.jit(f, **kw)``
    - ``functools.partial(jax.jit, **kw)(f)``  /  used bare as a decorator
    - ``shard_map(f, ...)``
    - ``mesh_fleet_program(f, ...)`` (donates arg 0 unless donate=False)
    """
    if not isinstance(expr, ast.Call):
        # Bare ``@jax.jit`` decorator.
        if resolve(expr, aliases) in JIT_NAMES:
            return JitWrap(target=None)
        return None
    fn = resolve(expr.func, aliases)
    kw = {k.arg: k.value for k in expr.keywords if k.arg}
    if fn in JIT_NAMES:
        return JitWrap(
            target=expr.args[0] if expr.args else None,
            static_argnums=_const_index_set(kw.get("static_argnums")),
            static_argnames=_const_name_set(kw.get("static_argnames")),
            donate_argnums=_const_index_set(kw.get("donate_argnums")),
            call=expr,
        )
    if fn in SHARD_MAP_NAMES or (fn or "").endswith(".shard_map"):
        return JitWrap(
            target=expr.args[0] if expr.args else kw.get("f"),
            kind="shard_map", call=expr,
        )
    if (fn or "").endswith("mesh_fleet_program"):
        donate: set = {0}
        d = kw.get("donate")
        if isinstance(d, ast.Constant) and d.value is False:
            donate = set()
        return JitWrap(
            target=expr.args[0] if expr.args else None,
            donate_argnums=donate, kind="mesh_fleet_program", call=expr,
        )
    if fn in PARTIAL_NAMES or fn == "partial":
        if expr.args and resolve(expr.args[0], aliases) in JIT_NAMES:
            return JitWrap(
                target=None,
                static_argnums=_const_index_set(kw.get("static_argnums")),
                static_argnames=_const_name_set(kw.get("static_argnames")),
                donate_argnums=_const_index_set(kw.get("donate_argnums")),
                call=expr,
            )
    # ``partial(jax.jit, ...)(f)`` — outer call whose func is the partial.
    if isinstance(expr.func, ast.Call):
        inner = parse_jit_value(mod, aliases, expr.func)
        if inner is not None and inner.target is None:
            inner.target = expr.args[0] if expr.args else None
            inner.call = expr
            return inner
    return None


def unwrap_target(mod: Module, aliases: dict, expr: ast.AST | None,
                  class_name: str | None = None):
    """Follow transparent wrappers down to the wrapped function expression.

    -> ("name", fq_string) | ("lambda", Lambda) | None
    """
    while isinstance(expr, ast.Call):
        fn = resolve(expr.func, aliases)
        if fn in TRANSPARENT or (fn or "").startswith("jax.vmap"):
            expr = expr.args[0] if expr.args else None
        else:
            inner = parse_jit_value(mod, aliases, expr)  # nested jit(...)
            if inner is not None:
                expr = inner.target
            else:
                return None
    if isinstance(expr, ast.Lambda):
        return ("lambda", expr)
    if (isinstance(expr, ast.Attribute) and isinstance(expr.value, ast.Name)
            and expr.value.id == "self" and class_name):
        # Bound method: jax.jit(self._step, ...) inside class C.
        return ("name", f"{mod.modname}.{class_name}.{expr.attr}")
    if expr is not None:
        fq = resolve_in(mod, aliases, expr)
        if fq:
            return ("name", fq)
    return None


@dataclass
class Registration:
    """One jitted callable: where it's bound + what it wraps."""

    wrap: JitWrap
    mod: Module
    target: tuple | None          # unwrap_target result
    bound_to: str | None = None   # "<modname>.<var>" or "self.<attr>" key
    line: int = 0


def _walk_with_class(tree: ast.Module):
    """(node, enclosing_class_name) pairs — registrations inside a class
    body (``self._prog = jax.jit(self._step, ...)``) need the class to
    resolve the bound-method target."""
    for top in tree.body:
        if isinstance(top, ast.ClassDef):
            for sub in ast.walk(top):
                yield sub, top.name
        else:
            for sub in ast.walk(top):
                yield sub, None


def scan_registrations(index: PackageIndex, func_index: dict) -> list[Registration]:
    regs: list[Registration] = []
    for mod in index.modules:
        aliases = mod.aliases()

        def handle_value(expr, bound_to=None, line=0, class_name=None,
                         mod=mod, aliases=aliases):
            w = parse_jit_value(mod, aliases, expr)
            if w is None or w.target is None:
                return
            t = unwrap_target(mod, aliases, w.target, class_name=class_name)
            regs.append(Registration(wrap=w, mod=mod, target=t,
                                     bound_to=bound_to, line=line))

        for node, class_name in _walk_with_class(mod.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    w = parse_jit_value(mod, aliases, dec)
                    if w is not None and w.target is None:
                        qual = (f"{mod.modname}.{class_name}.{node.name}"
                                if class_name and node.name != class_name
                                and f"{mod.modname}.{class_name}.{node.name}" in func_index
                                else f"{mod.modname}.{node.name}")
                        w.target = ast.Name(id=node.name, ctx=ast.Load())
                        regs.append(Registration(
                            wrap=w, mod=mod,
                            target=("name", qual),
                            bound_to=qual,
                            line=node.lineno,
                        ))
            elif isinstance(node, ast.Assign):
                bound = None
                if len(node.targets) == 1:
                    t = node.targets[0]
                    if isinstance(t, ast.Name):
                        bound = f"{mod.modname}.{t.id}"
                    elif (isinstance(t, ast.Attribute)
                          and isinstance(t.value, ast.Name)
                          and t.value.id == "self"):
                        bound = f"self.{t.attr}"
                handle_value(node.value, bound_to=bound, line=node.lineno,
                             class_name=class_name)
            elif isinstance(node, ast.Expr):
                handle_value(node.value, line=node.lineno, class_name=class_name)
            elif isinstance(node, ast.Return) and node.value is not None:
                handle_value(node.value, line=node.lineno, class_name=class_name)
    return regs


# --------------------------------------------------------------------------
# Taint analysis
# --------------------------------------------------------------------------

class _FuncScan:
    """One pass over one function with a given tainted-parameter set."""

    def __init__(self, info: FuncInfo, tainted_params: frozenset,
                 findings: list, edges: list, display: str) -> None:
        self.info = info
        self.mod = info.mod
        self.aliases = info.mod.aliases()
        self.env: set = set(tainted_params)
        self.findings = findings
        self.edges = edges          # (callee_fq, frozenset(tainted params))
        self.display = display

    # ------------------------------------------------------------- helpers
    def _flag(self, rule: str, node: ast.AST, message: str, hint: str,
              detail: str) -> None:
        self.findings.append(Finding(
            rule=rule, file=self.mod.rel, line=getattr(node, "lineno", 0),
            message=message, hint=hint, detail=detail,
        ))

    def _callee_info(self, call: ast.Call):
        """Resolve a call to a package function -> (fq, param_offset)."""
        func = call.func
        # self.method() inside a class
        if (isinstance(func, ast.Attribute) and isinstance(func.value, ast.Name)
                and func.value.id == "self" and self.info.class_name):
            fq = f"{self.mod.modname}.{self.info.class_name}.{func.attr}"
            return fq, 1
        fq = resolve_in(self.mod, self.aliases, func)
        return fq, 0

    # ---------------------------------------------------------------- taint
    def tainted(self, node: ast.AST | None) -> bool:  # noqa: C901
        if node is None or isinstance(node, ast.Constant):
            return False
        if isinstance(node, ast.Name):
            return node.id in self.env
        if isinstance(node, ast.Attribute):
            if node.attr in STATIC_ATTRS:
                self.tainted(node.value)
                return False
            return self.tainted(node.value)
        if isinstance(node, ast.Subscript):
            self.tainted(node.slice)
            return self.tainted(node.value)
        if isinstance(node, ast.Compare):
            t = self.tainted(node.left) or any(self.tainted(c) for c in node.comparators)
            if all(isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops):
                return False  # identity checks are static at trace time
            return t
        if isinstance(node, (ast.BoolOp,)):
            return any(self.tainted(v) for v in node.values)
        if isinstance(node, ast.BinOp):
            return self.tainted(node.left) | self.tainted(node.right)
        if isinstance(node, ast.UnaryOp):
            return self.tainted(node.operand)
        if isinstance(node, ast.IfExp):
            if self.tainted(node.test):
                self._flag(
                    "jit-branch-on-tracer", node,
                    f"{self.display}: ternary on traced value "
                    f"`{self.mod.segment(node.test)}`",
                    "use jnp.where / lax.select (both branches traced)",
                    f"{self.display}: ternary on traced `{self.mod.segment(node.test)}`",
                )
            return self.tainted(node.body) or self.tainted(node.orelse)
        if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
            return any(self.tainted(e) for e in node.elts)
        if isinstance(node, ast.Dict):
            return any(self.tainted(v) for v in list(node.keys) + list(node.values) if v)
        if isinstance(node, ast.Starred):
            return self.tainted(node.value)
        if isinstance(node, ast.NamedExpr):
            t = self.tainted(node.value)
            if t:
                self.env.add(node.target.id)
            return t
        if isinstance(node, (ast.ListComp, ast.SetComp, ast.GeneratorExp, ast.DictComp)):
            # Tainted iff traced data flows in: a generator iterates a
            # tainted iterable (its targets become tainted for the element
            # expressions), or the element expressions touch tainted names
            # themselves.  A fully static comprehension stays branchable.
            bound: set = set()
            iter_taint = False
            for gen in node.generators:
                if self.tainted(gen.iter):
                    iter_taint = True
                    for tn in ast.walk(gen.target):
                        if isinstance(tn, ast.Name):
                            bound.add(tn.id)
            added = bound - self.env
            self.env |= added
            try:
                parts = ([node.key, node.value] if isinstance(node, ast.DictComp)
                         else [node.elt])
                parts += [c for gen in node.generators for c in gen.ifs]
                elt_taint = any(self.tainted(p) for p in parts if p is not None)
            finally:
                self.env -= added
            return iter_taint or elt_taint
        if isinstance(node, ast.Call):
            return self._call(node)
        if isinstance(node, ast.JoinedStr):
            return False
        # Unknown node kinds: visit children, assume untainted.
        for child in ast.iter_child_nodes(node):
            self.tainted(child) if isinstance(child, ast.expr) else None
        return False

    def _call(self, call: ast.Call) -> bool:  # noqa: C901
        arg_taints = [self.tainted(a) for a in call.args]
        kw_taints = {k.arg: self.tainted(k.value) for k in call.keywords}
        any_taint = any(arg_taints) or any(kw_taints.values())
        fn = resolve(call.func, self.aliases)

        # Host-sync builtins / methods on traced values.
        if fn in HOST_SYNC_BUILTINS and any_taint:
            self._flag(
                "jit-host-sync", call,
                f"{self.display}: {fn}() forces a traced value to a host scalar",
                "keep it on device (jnp ops) or pass it as a static arg",
                f"{self.display}: {fn}() on traced value",
            )
            return False
        if (isinstance(call.func, ast.Attribute)
                and call.func.attr in HOST_SYNC_METHODS
                and self.tainted(call.func.value)):
            self._flag(
                "jit-host-sync", call,
                f"{self.display}: .{call.func.attr}() on a traced value",
                "device values cannot concretize under trace; return them instead",
                f"{self.display}: .{call.func.attr}() on traced value",
            )
            return False

        # np.* on tracers.
        if fn and (fn == "numpy" or fn.startswith("numpy.")) and any_taint:
            self._flag(
                "jit-np-on-tracer", call,
                f"{self.display}: {self.mod.segment(call.func)}() called on a "
                "traced value (host numpy inside a traced function)",
                "use the jnp equivalent so the op stays in the trace",
                f"{self.display}: {self.mod.segment(call.func)} on traced value",
            )
            return True

        if fn in STATIC_RESULT_CALLS:
            return False

        # Propagate into package callees (position+keyword aware).
        fq, offset = self._callee_info(call)
        if fq and fq.startswith(self.mod.modname.split(".")[0] + "."):
            self.edges.append((fq, offset, call, arg_taints, kw_taints))
        # Wrapped calls like jax.vmap(f, ...)(args): route taint to f.
        if isinstance(call.func, ast.Call):
            t = unwrap_target(self.mod, self.aliases, call.func)
            if t is not None and t[0] == "name":
                self.edges.append((t[1], 0, call, arg_taints, kw_taints))
        return any_taint

    def _scan_narrowed(self, stmts: list, narrowed: set) -> None:
        removed = narrowed & self.env
        self.env -= removed
        self.scan(stmts)
        self.env |= removed

    # ------------------------------------------------------------ statements
    def bind(self, target: ast.AST, t: bool) -> None:
        if isinstance(target, ast.Name):
            (self.env.add if t else self.env.discard)(target.id)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for e in target.elts:
                self.bind(e, t)
        elif isinstance(target, ast.Starred):
            self.bind(target.value, t)
        # Attribute / Subscript targets: no local binding to track.

    def run(self) -> None:
        self.scan(self.info.node.body if not isinstance(self.info.node, ast.Lambda)
                  else [ast.Expr(value=self.info.node.body)])

    def scan(self, stmts: list) -> None:  # noqa: C901
        for st in stmts:
            if isinstance(st, ast.Assign):
                t = self.tainted(st.value)
                for target in st.targets:
                    self.bind(target, t)
            elif isinstance(st, ast.AugAssign):
                t = self.tainted(st.value) or self.tainted(st.target)
                self.bind(st.target, t)
            elif isinstance(st, ast.AnnAssign):
                if st.value is not None:
                    self.bind(st.target, self.tainted(st.value))
            elif isinstance(st, (ast.If, ast.While)):
                if self.tainted(st.test):
                    kind = "if" if isinstance(st, ast.If) else "while"
                    self._flag(
                        "jit-branch-on-tracer", st,
                        f"{self.display}: Python `{kind}` on traced value "
                        f"`{self.mod.segment(st.test)}`",
                        "trace-time control flow must use lax.cond/lax.while_loop "
                        "(or hoist the value to a static arg)",
                        f"{self.display}: {kind} on traced `{self.mod.segment(st.test)}`",
                    )
                if isinstance(st, ast.If):
                    # `if isinstance(x, bool):` narrows x to a static python
                    # value in that arm — the standard static/traced
                    # dual-mode kernel idiom (the other arm keeps the taint
                    # and must use lax.cond).
                    then_narrow, else_narrow = _isinstance_narrowing(st.test)
                    self._scan_narrowed(st.body, then_narrow)
                    self._scan_narrowed(st.orelse, else_narrow)
                else:
                    self.scan(st.body)
                    self.scan(st.orelse)
            elif isinstance(st, ast.Assert):
                if self.tainted(st.test):
                    self._flag(
                        "jit-branch-on-tracer", st,
                        f"{self.display}: assert on traced value "
                        f"`{self.mod.segment(st.test)}`",
                        "use checkify or debug.check for traced assertions",
                        f"{self.display}: assert on traced `{self.mod.segment(st.test)}`",
                    )
            elif isinstance(st, ast.For):
                self.bind(st.target, self.tainted(st.iter))
                self.scan(st.body)
                self.scan(st.orelse)
            elif isinstance(st, ast.With):
                for item in st.items:
                    self.tainted(item.context_expr)
                self.scan(st.body)
            elif isinstance(st, ast.Try):
                self.scan(st.body)
                for h in st.handlers:
                    self.scan(h.body)
                self.scan(st.orelse)
                self.scan(st.finalbody)
            elif isinstance(st, (ast.Return, ast.Expr)):
                self.tainted(st.value)
            elif isinstance(st, ast.Raise):
                self.tainted(st.exc)
            # Nested defs/classes: separate scopes, skipped.


def _isinstance_narrowing(test: ast.AST) -> tuple:
    """-> (names static in the then-arm, names static in the else-arm) for
    ``isinstance(x, ...)`` / ``not isinstance(x, ...)`` tests (including
    ``isinstance(...) and ...`` conjunctions for the then-arm)."""
    def direct(node: ast.AST) -> set:
        if (isinstance(node, ast.Call) and isinstance(node.func, ast.Name)
                and node.func.id == "isinstance" and node.args
                and isinstance(node.args[0], ast.Name)):
            return {node.args[0].id}
        return set()

    then_narrow = direct(test)
    else_narrow: set = set()
    if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
        else_narrow = direct(test.operand)
    elif isinstance(test, ast.BoolOp) and isinstance(test.op, ast.And):
        for v in test.values:
            then_narrow |= direct(v)
    return then_narrow, else_narrow


def _map_edge_taint(callee: FuncInfo, offset: int, call: ast.Call,
                    arg_taints: list, kw_taints: dict) -> frozenset:
    params = callee.params()
    tainted: set = set()
    for i, t in enumerate(arg_taints):
        j = i + offset
        if t and j < len(params):
            tainted.add(params[j])
        elif t:
            tainted.update(params)  # *args overflow: be conservative
    for name, t in kw_taints.items():
        if t and name and (name in params or name in callee.kwonly()):
            tainted.add(name)
    return frozenset(tainted)


def run(index: PackageIndex) -> list[Finding]:
    findings: list[Finding] = []
    func_index = build_func_index(index)
    regs = scan_registrations(index, func_index)

    # Seed the worklist: entry params taint (minus statics).
    taint_state: dict = {}   # fq -> frozenset of tainted param names
    work: list = []

    def seed(fq: str, wrap: JitWrap) -> None:
        info = func_index.get(fq)
        if info is None:
            return
        params = info.params()
        tainted = set(params)
        if info.class_name and params[:1] == ["self"]:
            tainted.discard("self")
        for i in wrap.static_argnums:
            if i < len(params):
                tainted.discard(params[i])
        tainted -= wrap.static_argnames
        merge(fq, frozenset(tainted))

    def merge(fq: str, tset: frozenset) -> None:
        cur = taint_state.get(fq, frozenset())
        new = cur | tset
        if new != cur or fq not in taint_state:
            taint_state[fq] = new
            work.append(fq)

    lambda_regs = []
    for reg in regs:
        if reg.target is None:
            continue
        kind, tgt = reg.target
        if kind == "name":
            seed(tgt, reg.wrap)
        else:
            lambda_regs.append((reg, tgt))

    # Lambdas wrapped directly in jit: scan once, all params tainted.
    for reg, lam in lambda_regs:
        params = [p.arg for p in lam.args.posonlyargs + lam.args.args]
        tainted = frozenset(
            p for i, p in enumerate(params)
            if i not in reg.wrap.static_argnums and p not in reg.wrap.static_argnames
        )
        info = FuncInfo(reg.mod, lam, f"{reg.mod.modname}.<lambda L{lam.lineno}>")
        edges: list = []
        scan = _FuncScan(info, tainted, findings, edges,
                         display=f"<lambda:{lam.lineno}>")
        scan.run()
        for fq, offset, call, a_t, k_t in edges:
            callee = func_index.get(fq)
            if callee is not None:
                merge(fq, _map_edge_taint(callee, offset, call, a_t, k_t))

    # Worklist to fixpoint.
    processed_with: dict = {}
    guard = 0
    while work and guard < 10000:
        guard += 1
        fq = work.pop()
        tset = taint_state[fq]
        if processed_with.get(fq) == tset:
            continue
        processed_with[fq] = tset
        info = func_index[fq]
        edges: list = []
        scan = _FuncScan(info, tset, findings, edges,
                         display=fq.split(".")[-1])
        scan.run()
        for callee_fq, offset, call, a_t, k_t in edges:
            callee = func_index.get(callee_fq)
            if callee is None:
                continue
            et = _map_edge_taint(callee, offset, call, a_t, k_t)
            if et:
                merge(callee_fq, et)

    # Dedup: fixpoint re-scans can fire the same site repeatedly.
    seen: set = set()
    out: list[Finding] = []
    for f in findings:
        k = (f.rule, f.file, f.line, f.detail)
        if k not in seen:
            seen.add(k)
            out.append(f)

    out.extend(_unhashable_static(index, regs))
    out.extend(_host_sync_loops(index))
    return out


# --------------------------------------------------------------------------
# jit-unhashable-static
# --------------------------------------------------------------------------

_UNHASHABLE = (ast.List, ast.Dict, ast.Set, ast.ListComp, ast.DictComp, ast.SetComp)


def _unhashable_static(index: PackageIndex, regs) -> list[Finding]:
    findings: list[Finding] = []
    # Bound name -> (static nums adjusted, static names) for call-site checks.
    statics: dict = {}
    for reg in regs:
        if reg.bound_to and (reg.wrap.static_argnums or reg.wrap.static_argnames):
            statics[reg.bound_to] = (reg.wrap.static_argnums, reg.wrap.static_argnames)
    if not statics:
        return findings
    for mod in index.modules:
        aliases = mod.aliases()
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            fq = resolve_in(mod, aliases, node.func)
            key = fq if fq in statics else None
            if key is None and isinstance(node.func, ast.Attribute) \
                    and isinstance(node.func.value, ast.Name) \
                    and node.func.value.id == "self":
                key = f"self.{node.func.attr}"
                if key not in statics:
                    key = None
            if key is None:
                continue
            nums, names = statics[key]
            for i, arg in enumerate(node.args):
                if i in nums and isinstance(arg, _UNHASHABLE):
                    findings.append(Finding(
                        rule="jit-unhashable-static", file=mod.rel,
                        line=arg.lineno,
                        message=(
                            f"unhashable literal passed for static arg {i} of "
                            f"jitted `{key.split('.')[-1]}`"
                        ),
                        hint="static args must be hashable: pass a tuple/frozenset",
                        detail=f"unhashable static arg {i} to {key.split('.')[-1]}",
                    ))
            for k in node.keywords:
                if k.arg in names and isinstance(k.value, _UNHASHABLE):
                    findings.append(Finding(
                        rule="jit-unhashable-static", file=mod.rel,
                        line=k.value.lineno,
                        message=(
                            f"unhashable literal passed for static arg "
                            f"{k.arg!r} of jitted `{key.split('.')[-1]}`"
                        ),
                        hint="static args must be hashable: pass a tuple/frozenset",
                        detail=f"unhashable static arg {k.arg} to {key.split('.')[-1]}",
                    ))
    return findings


# --------------------------------------------------------------------------
# jit-host-sync-loop (package-wide, host code included)
# --------------------------------------------------------------------------

def _host_sync_loops(index: PackageIndex) -> list[Finding]:
    findings: list[Finding] = []
    for mod in index.modules:
        loops: list = []
        for node in ast.walk(mod.tree):
            if isinstance(node, (ast.For, ast.While)):
                loops.append((node, node.body + node.orelse))
            elif isinstance(node, (ast.ListComp, ast.SetComp, ast.GeneratorExp)):
                loops.append((node, [node.elt]))
            elif isinstance(node, ast.DictComp):
                loops.append((node, [node.key, node.value]))
        flagged: set = set()
        for loop, body in loops:
            for part in body:
                for call in ast.walk(part):
                    if (isinstance(call, ast.Call)
                            and isinstance(call.func, ast.Attribute)
                            and call.func.attr == "item"
                            and isinstance(call.func.value, ast.Subscript)
                            and not call.args):
                        if call.lineno in flagged:
                            continue
                        flagged.add(call.lineno)
                        seg = mod.segment(call, limit=40)
                        findings.append(Finding(
                            rule="jit-host-sync-loop", file=mod.rel,
                            line=call.lineno,
                            message=(
                                f"per-element `.item()` inside a loop "
                                f"(`{seg}`): one host sync per element"
                            ),
                            hint=(
                                "convert the array once outside the loop "
                                "(np.asarray(...).tolist()) and index the list"
                            ),
                            detail=f"per-element .item() in loop: `{seg}`",
                        ))
    return findings
