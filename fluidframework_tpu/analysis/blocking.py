"""Pass 10 — blocking-under-lock: slow syscalls inside critical sections.

PR 12's law was "durable fsyncs never run under the serving lock"; PR 13's
was "signals never write sockets under the service lock".  Both were won
by hand and live in comments.  This pass turns them into configuration:
``analysis/layers.json`` names the *critical locks* — the ones every
serving thread convoys on — and, per lock, the categories of blocking
call that must never execute while it is held.

Categories:

- ``fsync``       — ``os.fsync``/``fdatasync`` (+ configured package IO
  like ``checkpoint_store.save``: one rotational-disk flush under the
  serving lock stalls every ingest behind ~10ms of platter)
- ``sleep``       — ``time.sleep`` and bare ``.sleep()`` methods
- ``subprocess``  — ``subprocess.*`` spawn/communicate
- ``http``        — ``urllib.request.urlopen`` and friends
- ``socket``      — ``send*/recv*/accept/connect`` on sockets (a peer
  with a full kernel buffer blocks the holder indefinitely)
- ``dispatch``    — jitted-program synchronization: ``block_until_ready``,
  ``jax.device_get``, device→host ``np.asarray``

Reach is package-wide via the shared ``core`` walkers: the held set rides
call edges, so ``step()`` taking ``ckpt_lock`` and calling into
``models/recovery`` carries the lock into every function that sweep
touches.  Config (``concurrency_scope`` in layers.json)::

    "critical_locks": [
      {"lock": "ckpt_lock", "deny": ["fsync", "sleep", ...],
       "exempt": ["Class.method"]},
    ],
    "blocking_calls": {"checkpoint_store.save": "fsync"}

``lock`` matches the ``core.LockNamer`` identity (bare name for
``shared_locks`` entries, ``Class.attr`` otherwise); ``exempt`` names
functions whose interior is sanctioned for that lock (reviewed bounded
operations — e.g. a nonblocking wake-pipe write).  ``blocking_calls``
maps dotted call suffixes to a category: the hand-knowledge of which
package APIs block, applied where static typing cannot see through an
attribute chain.  Unknown categories/locks fail loudly — a config typo
must never silently narrow the pass.
"""

from __future__ import annotations

import ast

from .core import (
    Finding,
    LockFlowScan,
    LockNamer,
    PackageIndex,
    PackageView,
    dotted_name,
    resolve,
    walk_lock_flow,
)

CATEGORIES = ("fsync", "sleep", "subprocess", "http", "socket", "dispatch")

_FQ_CALLS = {
    "time.sleep": "sleep",
    "os.fsync": "fsync",
    "os.fdatasync": "fsync",
    "urllib.request.urlopen": "http",
    "socket.create_connection": "socket",
    "numpy.asarray": "dispatch",
    "jax.device_get": "dispatch",
    "jax.block_until_ready": "dispatch",
}

_ATTR_CALLS = {
    "fsync": "fsync", "fdatasync": "fsync",
    "sleep": "sleep",
    "send": "socket", "sendall": "socket", "sendmsg": "socket",
    "sendto": "socket", "recv": "socket", "recv_into": "socket",
    "recvmsg": "socket", "recvfrom": "socket", "accept": "socket",
    "connect": "socket", "connect_ex": "socket",
    "urlopen": "http", "getresponse": "http",
    "block_until_ready": "dispatch",
}


def _load_cfg(concurrency_scope: dict | None):
    cfg = concurrency_scope or {}
    critical: dict = {}
    exempt: dict = {}
    for entry in cfg.get("critical_locks", []):
        lock = entry.get("lock")
        deny = entry.get("deny", [])
        unknown = set(deny) - set(CATEGORIES)
        if not lock or unknown:
            raise ValueError(
                f"critical_locks entry {entry!r}: "
                + ("missing 'lock'" if not lock
                   else f"unknown deny categories {sorted(unknown)} "
                        f"(know {CATEGORIES})")
            )
        critical[lock] = frozenset(deny)
        exempt[lock] = frozenset(entry.get("exempt", []))
    patterns = dict(cfg.get("blocking_calls", {}))
    bad = {p: c for p, c in patterns.items() if c not in CATEGORIES}
    if bad:
        raise ValueError(
            f"blocking_calls with unknown categories: {bad} "
            f"(know {CATEGORIES})"
        )
    return critical, exempt, patterns


def _classify(call: ast.Call, aliases: dict, patterns: dict,
              resolved_pkg: bool) -> str | None:
    dn = dotted_name(call.func)
    if dn is not None:
        for pat, cat in patterns.items():
            if dn == pat or dn.endswith("." + pat):
                return cat
    fq = resolve(call.func, aliases)
    if fq is not None:
        if fq in _FQ_CALLS:
            return _FQ_CALLS[fq]
        if fq.startswith("subprocess."):
            return "subprocess"
        if fq.startswith("http.client."):
            return "http"
    if resolved_pkg:
        return None  # package function: the call edge carries the lock in
    if isinstance(call.func, ast.Attribute):
        return _ATTR_CALLS.get(call.func.attr)
    return None


def run(index: PackageIndex,
        concurrency_scope: dict | None) -> list[Finding]:
    critical, exempt, patterns = _load_cfg(concurrency_scope)
    if not critical:
        return []
    cfg = concurrency_scope or {}
    pv = PackageView.of(index)
    namer = LockNamer(frozenset(cfg.get("shared_locks", [])))
    crit_ids = frozenset(critical)

    def make_scan(key, held):
        fn = pv.function(key)
        if fn is None:
            return None
        types = pv.fn_local_types(key)
        resolved: set = set()

        def resolver(call, t=types, k=key, rc=resolved):
            out = pv.resolve_call(k, t, call)
            if out is not None:
                rc.add(id(call))
            return out

        scan = LockFlowScan(
            fn, held, namer, modname=key.modname,
            class_name=key.class_name, types=types, resolver=resolver,
        ).run()
        scan.resolved_pkg_calls = resolved
        return scan

    # The shared worklist engine; held sets project onto the critical
    # locks at every edge, bounding the context count to subsets of the
    # configured locks.
    scans = walk_lock_flow(
        [(k, frozenset()) for k in pv.all_functions()],
        make_scan,
        canonical=lambda held: frozenset(held) & crit_ids,
    )

    findings: list[Finding] = []
    seen: set = set()
    for key, ctxs in scans.items():
        view = pv.views[key.modname]
        label = key.label()
        rel = view.mod.rel
        for scan in ctxs.values():
            if scan is None:
                continue
            for call, held in scan.calls:
                crit_held = held & crit_ids
                if not crit_held:
                    continue
                cat = _classify(
                    call, view.aliases, patterns,
                    id(call) in scan.resolved_pkg_calls,
                )
                if cat is None:
                    continue
                for lock in sorted(crit_held):
                    if cat not in critical[lock]:
                        continue
                    if label in exempt[lock] or key.name in exempt[lock]:
                        continue
                    sig = (rel, call.lineno, lock, cat)
                    if sig in seen:
                        continue
                    seen.add(sig)
                    seg = view.mod.segment(call, limit=48)
                    findings.append(Finding(
                        rule="blocking-under-lock",
                        file=rel, line=call.lineno,
                        message=(
                            f"{label}: {cat} call `{seg}` reachable while "
                            f"`{lock}` is held — every thread waiting on "
                            "the lock waits on this syscall too"
                        ),
                        hint=(
                            "move the blocking call outside the critical "
                            "section (build under the lock, flush after "
                            "release), or exempt/baseline with a rationale"
                        ),
                        detail=f"{label}: {cat} under {lock} (`{seg}`)",
                    ))
    findings.sort(key=lambda f: (f.file, f.line))
    return findings
