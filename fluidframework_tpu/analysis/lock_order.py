"""Pass 8 — lock-order: static deadlock detection over the lock graph.

PRs 10–14 grew a real concurrency plane: the engines' ``ckpt_lock`` /
``_ckpt_io_lock`` pair, ``netserver``'s service lock feeding the fanout
plane lock, the fanout writer's own lock, the launcher supervisor's
``Deployment._lock``, the failover heartbeat lock.  Every one of those has
a documented acquisition order ("service-lock → plane-lock", "ckpt before
io") that today lives only in comments — one refactor away from an
AB/BA deadlock that presents as a once-a-month wedged fleet.

This pass makes the order machine-checked:

1. **Graph** — package-wide, via the shared ``core`` walkers: an edge
   ``L1 → L2`` exists when lock ``L2`` is acquired (``with``-statement)
   while ``L1`` is held — through ``with``-nesting, self-calls, module
   functions, typed attributes, and cross-module call edges (the held set
   rides the edges, so ``step()`` holding ``ckpt_lock`` and calling into
   ``models/recovery`` which takes ``_ckpt_io_lock`` contributes
   ``ckpt_lock → _ckpt_io_lock``).
2. **Identity** — ``core.LockNamer``: attributes named in layers.json
   ``concurrency_scope.shared_locks`` unify package-wide on their bare
   name (``self.ckpt_lock`` in the engine ≡ ``engine.ckpt_lock`` in the
   recovery plane); everything else is class-qualified so unrelated
   ``_lock`` attributes never collapse into false cycles.
3. **Finding** — ``lock-order-cycle``: any strongly-connected component
   of the graph with ≥2 locks (re-entrant self-acquisition — legal for
   the RLocks this codebase uses — is exempt).  The finding names the
   cycle and one witness acquisition site per edge.

Not modeled (documented limits): ``lock.acquire()`` method calls (the
launcher heartbeat's bounded try-acquire is deliberately not a ``with``),
and the failover lease's O_EXCL *sidecar file* mutex — a cross-process
file, not a ``threading`` lock.
"""

from __future__ import annotations

from .core import (
    Finding,
    LockFlowScan,
    LockNamer,
    PackageIndex,
    PackageView,
    walk_lock_flow,
)


def build_lock_graph(index: PackageIndex, shared_locks) -> dict:
    """-> {(L1, L2): (file, line, func_label)} — one witness per edge."""
    pv = PackageView.of(index)
    namer = LockNamer(frozenset(shared_locks))

    def make_scan(key, held):
        fn = pv.function(key)
        if fn is None:
            return None
        types = pv.fn_local_types(key)
        return LockFlowScan(
            fn, held, namer, modname=key.modname,
            class_name=key.class_name, types=types,
            resolver=lambda call, t=types, k=key: pv.resolve_call(k, t, call),
        ).run()

    entries = [(k, frozenset()) for k in pv.all_functions()]
    scans = walk_lock_flow(entries, make_scan)

    edges: dict = {}
    for key, ctxs in scans.items():
        for scan in ctxs.values():
            if scan is None:
                continue
            rel = pv.views[key.modname].mod.rel
            for lock_id, line, held in scan.acquires:
                for h in held:
                    if h == lock_id:
                        continue  # re-entrant (RLock) self-acquisition
                    edges.setdefault((h, lock_id), (rel, line, key.label()))
    return edges


def _cycles(edges: dict) -> list:
    """Strongly-connected components with >= 2 nodes (Tarjan, iterative)."""
    adj: dict = {}
    for (a, b) in edges:
        adj.setdefault(a, set()).add(b)
        adj.setdefault(b, set())
    index_of: dict = {}
    low: dict = {}
    on_stack: set = set()
    stack: list = []
    sccs: list = []
    counter = [0]

    for root in sorted(adj):
        if root in index_of:
            continue
        work = [(root, iter(sorted(adj[root])))]
        index_of[root] = low[root] = counter[0]
        counter[0] += 1
        stack.append(root)
        on_stack.add(root)
        while work:
            node, it = work[-1]
            advanced = False
            for nxt in it:
                if nxt not in index_of:
                    index_of[nxt] = low[nxt] = counter[0]
                    counter[0] += 1
                    stack.append(nxt)
                    on_stack.add(nxt)
                    work.append((nxt, iter(sorted(adj[nxt]))))
                    advanced = True
                    break
                if nxt in on_stack:
                    low[node] = min(low[node], index_of[nxt])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index_of[node]:
                comp = []
                while True:
                    v = stack.pop()
                    on_stack.discard(v)
                    comp.append(v)
                    if v == node:
                        break
                if len(comp) >= 2:
                    sccs.append(sorted(comp))
    return sccs


def run(index: PackageIndex, concurrency_scope: dict | None) -> list[Finding]:
    cfg = concurrency_scope or {}
    shared = cfg.get("shared_locks", [])
    edges = build_lock_graph(index, shared)
    findings: list[Finding] = []
    for comp in _cycles(edges):
        members = set(comp)
        witnesses = sorted(
            (file, line, f"{a} -> {b} in {label}")
            for (a, b), (file, line, label) in edges.items()
            if a in members and b in members
        )
        file, line, _ = witnesses[0]
        cycle = " -> ".join(comp + comp[:1])
        findings.append(Finding(
            rule="lock-order-cycle",
            file=file, line=line,
            message=(
                f"lock acquisition cycle {cycle}: "
                + "; ".join(f"{w[2]} ({w[0]}:{w[1]})" for w in witnesses[:4])
            ),
            hint=(
                "pick ONE global order for these locks and acquire them "
                "in it everywhere (or release the outer lock before "
                "taking the inner one)"
            ),
            detail=f"lock cycle: {cycle}",
        ))
    findings.sort(key=lambda f: (f.file, f.line))
    return findings
