"""Chaos controller + soak harness over the full serving stack.

The server-side counterpart of ``driver/fault_injection.py`` (which mirrors
test-service-load's client-side FaultInjectionDocumentServiceFactory): a
SEEDED, DETERMINISTIC fault schedule applied to the real composed stack —
netserver ``ServicePlane`` (admission-controlled TCP/HTTP fronts over real
sockets), a durable op topic + ``ScribePool``, and a MIXED device fleet:
a ``FleetConsumer`` feeding a checkpointed ``DocBatchEngine`` (string docs)
plus, when ``tree_doc_ids`` are given, a second consumer feeding a
``TreeBatchEngine`` (tree docs) — both families restored from the same
durable-checkpoint contract.  SharedString AND SharedTree writers drive
one Zipf document popularity ranking with connect/disconnect churn through
the driver-layer nack/backoff contract.

Fault kinds (``ChaosSchedule`` events; the schedule JSON round-trips so a
failing run's schedule can be committed as a regression):

- ``fleet_kill``      — crash the device-fleet tier (BOTH families when a
                        tree tier runs): consumers + engines are discarded,
                        successors restore from durable checkpoints — or a
                        warm standby promotes — and re-consume the firehose
                        (seq-floor dedupe makes the replay idempotent).
- ``torn_socket``     — hard-close one writer's TCP stream mid-session, no
                        leave handshake; a replacement client rejoins and
                        catches up from delta storage.
- ``nack_storm``      — the front sheds the next N submits for a document
                        (``AdmissionController.force_overload``); writers
                        back off per the jittered retry_after contract and
                        resubmit in place.
- ``scribe_kill``     — crash a ScribePool member (no flush, no goodbye).
- ``scribe_crash``    — crash a member MID-FOLD (``ScribeLambda.
                        chaos_abort_after_folds``): folded-but-uncommitted
                        state dies between the fold and its offset commit.
- ``fsync_delay`` /   — stall (then restore) every durable topic
  ``fsync_clear``       partition's appends, the slow-disk schedule.
- ``migrate``         — live mid-stream placement move: the target doc's
                        engine migrates it to another mesh shard while
                        writers keep submitting (skip-counted when the
                        engine runs unsharded or the doc sits in a
                        host-only lane the placement plane refuses).

Invariants checked (the run FAILS loudly, not statistically):

- **byte identity**: after quiescing, every document's device-fleet state ==
  a fault-free oracle replay of the server's sequenced log == every
  surviving writer's replica — ``RefMergeTree`` text for string docs, an
  EditManager + Forest replay (root-field node JSON) for tree docs.
- **no double-acks**: the scribe plane never externalizes two summaryAck
  records for the same (doc, seq).
- **bounded ingest**: no doc's staged queue ever exceeds the engine's high
  watermark plus one pump's slack (credit-based flow control holds under
  fault).

``run_chaos`` is the short seeded harness (tier-1 smoke); ``run_soak``
drives it at length with latency SLOs (p50/p99 under fault via the engine's
op-latency histograms), shed/pause/backoff counters, and an RSS bound —
the ``bench.py --config soak`` artifact (SOAK_r10.json).
"""

from __future__ import annotations

import contextlib
import json
import random
import time
from dataclasses import asdict, dataclass, field

from ..dds.mergetree_ref import RefMergeTree
from ..dds.shared_string import SharedString
from ..dds.tree.changeset import (
    apply_commit,
    commit_from_json,
    make_insert,
    make_move,
    make_remove,
    make_set_value,
)
from ..dds.tree.editmanager import EditManager
from ..dds.tree.forest import Forest
from ..dds.tree.schema import leaf
from ..dds.tree.shared_tree import SharedTreeChannel
from ..driver.definitions import DriverError
from ..driver.network_driver import HttpDeltaStorageService, NetworkDeltaConnection, _Http
from ..loader.connection_manager import BackoffPolicy
from ..protocol.channel import (
    ChannelDeltaConnection,
    ChannelMessage,
    MessageCollection,
    MessageEnvelope,
)
from ..protocol.messages import (
    DeltaType,
    MessageType,
    SequencedMessage,
    UnsequencedMessage,
)
from ..runtime.summary import parse_scribe_ack
from ..server.admission import AdmissionConfig, AdmissionController
from ..server.netserver import ServicePlane

# "migrate" is deliberately LAST: make_schedule draws per kind in tuple
# order, so appending keeps every pre-existing seeded schedule's events
# bit-identical (the committed-schedule regression contract).
EVENT_KINDS = (
    "fleet_kill", "torn_socket", "nack_storm",
    "scribe_kill", "scribe_crash", "fsync_delay", "migrate",
)


@dataclass
class ChaosEvent:
    tick: int
    kind: str
    target: str = ""   # doc id / member id ("" = schedule picks at runtime)
    param: float = 0.0  # kind-specific (storm length, fold count, delay s)


@dataclass
class ChaosSchedule:
    """A seeded fault schedule: same seed -> same events, committed as JSON
    (the schedule format documented in README "Overload & chaos")."""

    seed: int
    events: list = field(default_factory=list)

    def at(self, tick: int) -> list:
        return [e for e in self.events if e.tick == tick]

    def to_json(self) -> str:
        return json.dumps(
            {"seed": self.seed, "events": [asdict(e) for e in self.events]},
            indent=2,
        )

    @staticmethod
    def from_json(raw: str) -> "ChaosSchedule":
        d = json.loads(raw)
        return ChaosSchedule(
            seed=d["seed"], events=[ChaosEvent(**e) for e in d["events"]]
        )


def make_schedule(
    seed: int,
    ticks: int,
    doc_ids: list,
    kinds=EVENT_KINDS,
    events_per_kind: int = 1,
) -> ChaosSchedule:
    """Deterministic schedule from a seed: ``events_per_kind`` events of
    each kind, spread over the middle 80% of the run (faults at tick 0
    would race setup; faults at the very end test nothing — the quiesce
    phase would mask them).  ``fsync_delay`` events auto-pair with an
    ``fsync_clear`` a few ticks later."""
    rng = random.Random(seed)
    lo, hi = max(1, ticks // 10), max(2, ticks - ticks // 10)
    events: list = []
    for kind in kinds:
        for _ in range(events_per_kind):
            tick = rng.randrange(lo, hi)
            doc = rng.choice(doc_ids)
            if kind == "nack_storm":
                events.append(ChaosEvent(tick, kind, doc, rng.randrange(3, 9)))
            elif kind == "scribe_crash":
                events.append(ChaosEvent(tick, kind, "", rng.randrange(2, 6)))
            elif kind == "fsync_delay":
                events.append(ChaosEvent(tick, kind, "", 0.002))
                events.append(ChaosEvent(
                    min(tick + max(2, ticks // 10), ticks - 1), "fsync_clear"
                ))
            else:
                events.append(ChaosEvent(tick, kind, doc))
    events.sort(key=lambda e: (e.tick, e.kind, e.target))
    return ChaosSchedule(seed=seed, events=events)


class TornConnection(Exception):
    """The writer's connection died (torn socket / fatal nack): the harness
    replaces the writer with a fresh identity that catches up from storage."""


class _ChaosWireClient:
    """Shared raw-wire client machinery for both writer families.

    Implements the client half of the flow-control contract at the wire
    level (the loader's Container does the same through its layers): a
    retryable admission nack leaves the connection and clientSeq stream
    intact, so the writer waits the jittered, retry_after-floored delay and
    resubmits THE SAME op in place; a protocol nack or torn socket raises
    ``TornConnection`` and the harness re-enters with a fresh identity,
    catching up from delta storage.  Stop-and-wait submission (one op per
    server round-trip, ``sync`` as the settle barrier) keeps the clientSeq
    stream gap-free under interleaved shedding.

    Subclasses bind the replica family via ``_init_replica`` (build the
    replica before the connection exists — the live listener fires during
    connect), ``_apply`` (one sequenced message), and ``_assert_joined``.
    """

    MAX_RESUBMITS = 64

    def __init__(
        self,
        host: str,
        port: int,
        http_port: int,
        doc_id: str,
        base_id: str,
        rng: random.Random,
        sleep_cap_s: float = 0.05,
        backoff: BackoffPolicy | None = None,
    ) -> None:
        self.doc_id = doc_id
        self._host, self._port = host, port
        self._storage = HttpDeltaStorageService(
            _Http(host, http_port), doc_id
        )
        self.client_id = base_id
        self._rng = rng
        self._sleep_cap_s = sleep_cap_s
        self.backoff = backoff if backoff is not None else BackoffPolicy(
            rng=random.Random(rng.getrandbits(32)),
            initial_s=0.005, max_s=0.05, deadline_s=30.0,
        )
        self.nack_backoffs = 0
        self.ops_submitted = 0
        self.last_seq = 0
        self._nacked = None
        self._init_replica()
        self.conn = NetworkDeltaConnection(
            host, port, doc_id, base_id, "write",
            listener=self._on_msg, nack_listener=self._on_nack,
            signal_listener=None,
        )
        # Catch-up: the delivered prefix from delta storage (the driver's
        # snapshot->stream gap repair), then pump until our join lands.
        if self.conn.checkpoint_seq > 0:
            for m in self._storage.get_deltas(1, self.conn.checkpoint_seq):
                self._apply(m)
        self.conn.sync()
        self._assert_joined()

    # ------------------------------------------------------- family hooks
    def _init_replica(self) -> None:
        raise NotImplementedError

    def _apply(self, msg: SequencedMessage) -> None:
        raise NotImplementedError

    def _assert_joined(self) -> None:
        raise NotImplementedError

    # ---------------------------------------------------------------- inbound
    def _on_msg(self, msg: SequencedMessage) -> None:
        self._apply(msg)

    def _on_nack(self, nack) -> None:
        self._nacked = nack

    # --------------------------------------------------------------- outbound
    def _submit_one(self, m) -> None:
        for _attempt in range(self.MAX_RESUBMITS):
            if not self.conn.connected:
                raise TornConnection(self.client_id)
            self._nacked = None
            try:
                self.conn.submit(m)
                self.conn.sync()
            except (DriverError, OSError) as e:
                raise TornConnection(f"{self.client_id}: {e}") from e
            if self._nacked is None:
                self.ops_submitted += 1
                self.backoff.reset()
                return
            if not self.conn.connected:
                raise TornConnection(
                    f"{self.client_id}: fatal nack {self._nacked.reason}"
                )
            # Retryable admission shed: same op, same clientSeq, after the
            # jittered retry_after-floored delay (capped in harness time;
            # only the capped sleep actually taken counts as spent).
            self.nack_backoffs += 1
            delay = min(
                self.backoff.next_delay(self._nacked.retry_after),
                self._sleep_cap_s,
            )
            time.sleep(delay)
            self.backoff.consume(delay)
        raise TornConnection(
            f"{self.client_id}: op never admitted after "
            f"{self.MAX_RESUBMITS} resubmits"
        )

    def settle(self) -> None:
        """Dispatch everything the server already broadcast to us; raises
        ``TornConnection`` on a dead stream (a frozen replica must be
        REPLACED, never silently compared against live state)."""
        if not self.conn.connected:
            raise TornConnection(self.client_id)
        self.conn.sync()

    # ------------------------------------------------------------------ fault
    def tear(self) -> None:
        """Hard socket kill: no disconnect handshake (the torn-socket
        fault).  ``shutdown`` (not ``close``) actually severs the TCP
        stream — a plain close defers while the reader's makefile holds a
        reference.  The server discovers EOF and broadcasts our leave."""
        import socket as _socket

        with contextlib.suppress(OSError):
            self.conn._sock.shutdown(_socket.SHUT_RDWR)

    def close(self) -> None:
        if self.conn.connected:
            with contextlib.suppress(DriverError, OSError):
                self.conn.disconnect()


class ChaosWriter(_ChaosWireClient):
    """One raw-wire SharedString client over a real TCP delta connection
    (see ``_ChaosWireClient`` for the flow-control contract it rides)."""

    def _init_replica(self) -> None:
        self.replica = SharedString(client_id=self.client_id)

    def _assert_joined(self) -> None:
        assert self.replica.short_client >= 0, "join not delivered"

    def _apply(self, msg: SequencedMessage) -> None:
        if msg.seq <= self.last_seq:
            return  # catch-up / live-stream overlap
        self.last_seq = msg.seq
        self.replica.process(msg)

    def edit(self) -> None:
        """One rng-driven edit staged on the replica (not yet submitted)."""
        text = self.replica.text
        n = len(text)
        if self._rng.random() < 0.7 or n < 4:
            self.replica.insert_text(
                self._rng.randint(0, n),
                "".join(self._rng.choice("abcdefgh")
                        for _ in range(self._rng.randint(1, 6))),
            )
        else:
            p = self._rng.randint(0, n - 2)
            self.replica.remove_range(p, p + 1)

    def flush(self) -> int:
        """Submit the staged outbox stop-and-wait; returns ops sequenced.
        Honors retryable admission nacks with jittered backoff in place;
        raises TornConnection on teardown."""
        sent = 0
        for m in self.replica.take_outbox():
            self._submit_one(m)
            sent += 1
        return sent


class ChaosTreeWriter(_ChaosWireClient):
    """One raw-wire SharedTree client over a real TCP delta connection.

    The tree-family counterpart of ``ChaosWriter``: a full
    ``SharedTreeChannel`` replica (EditManager + forest with the
    optimistic local branch) attached through a ``ChannelDeltaConnection``
    shim whose submit path stages wire contents into an outbox; ``flush``
    mints the same stop-and-wait ``UnsequencedMessage`` stream the string
    writer uses, so admission nacks, torn sockets, and delta-storage
    catch-up ride the identical driver contract.  Inbound sequenced
    messages bridge back as single-message collections; our own ops come
    back flagged local (the channel's pending-FIFO ack)."""

    def _init_replica(self) -> None:
        self.tree = SharedTreeChannel("t")
        self._outbox: list = []
        self._client_seq = 0
        self._joined = False
        shim = ChannelDeltaConnection(
            submit_fn=lambda contents, md=None, internal=False: (
                self._outbox.append(contents)
            ),
            quorum_fn=lambda cid: 0,
            client_id_fn=lambda: self.client_id,
        )
        shim.connected = True
        self.tree.connect(shim)

    def _assert_joined(self) -> None:
        assert self._joined, "join not delivered"

    def _apply(self, msg: SequencedMessage) -> None:
        if msg.seq <= self.last_seq:
            return  # catch-up / live-stream overlap
        self.last_seq = msg.seq
        if msg.type == MessageType.JOIN:
            if msg.contents.get("clientId") == self.client_id:
                self._joined = True
            return
        if msg.type != MessageType.OP:
            return
        self.tree.process_messages(MessageCollection(
            envelope=MessageEnvelope(
                client_id=msg.client_id, seq=msg.seq,
                min_seq=msg.min_seq, ref_seq=msg.ref_seq,
            ),
            messages=[ChannelMessage(
                contents=msg.contents,
                local=(msg.client_id == self.client_id),
            )],
        ))

    def root_json(self) -> list:
        """The replica's root field as node JSON (the identity surface)."""
        return [n.to_json() for n in self.tree.forest.root_field]

    def edit(self) -> None:
        """One rng-driven tree edit staged on the channel outbox (same op
        mix as the differential engine tests, nested edits included)."""
        t, rng = self.tree, self._rng
        n = len(t.forest.root_field)
        kind = rng.choices(
            ["ins", "rm", "set", "move", "nested"], [5, 3, 3, 3, 1]
        )[0]
        if kind == "ins" or n == 0:
            t.submit_change(make_insert(
                [], "", rng.randint(0, n), [leaf(rng.randrange(1000))]
            ))
        elif kind == "rm":
            i = rng.randrange(n)
            t.submit_change(
                make_remove([], "", i, rng.randint(1, min(2, n - i)))
            )
        elif kind == "set":
            t.submit_change(
                make_set_value([("", rng.randrange(n))], rng.randrange(1000))
            )
        elif kind == "move":
            s = rng.randrange(n)
            c = rng.randint(1, min(2, n - s))
            t.submit_change(make_move([], "", s, c, rng.randint(0, n)))
        else:
            t.submit_change(
                make_insert([("", rng.randrange(n))], "sub", 0, [leaf(7)])
            )

    def flush(self) -> int:
        """Wire the staged channel outbox stop-and-wait (one
        ``UnsequencedMessage`` per edit, gap-free clientSeq stream)."""
        sent = 0
        out, self._outbox = self._outbox, []
        for contents in out:
            self._client_seq += 1
            self._submit_one(UnsequencedMessage(
                client_id=self.client_id, client_seq=self._client_seq,
                ref_seq=self.last_seq, type=MessageType.OP,
                contents=contents,
            ))
            sent += 1
        return sent


class ChaosStack:
    """The composed stack under test + the fault controller driving it."""

    def __init__(
        self,
        seed: int,
        doc_ids: list,
        workdir: str,
        writers_per_doc: int = 2,
        zipf_a: float = 1.1,
        churn_rate: float = 0.05,
        ops_per_tick: int = 6,
        step_every: int = 2,
        checkpoint_every: int = 32,
        megastep_k: int = 2,
        ops_per_step: int = 8,
        admission: AdmissionConfig | None = None,
        scribe_members: int = 2,
        standby: bool = False,
        ckpt_stale_seconds: float = 0.0,
        recovery_bound_s: float = 30.0,
        tree_doc_ids: list | None = None,
    ) -> None:
        self.rng = random.Random(seed)
        self.doc_ids = list(doc_ids)
        # Tree tier (ISSUE 16 mixed fleets): ``tree_doc_ids`` adds a second
        # engine family — its own FleetConsumer + TreeBatchEngine +
        # checkpoint store + warm standby — sharing the service plane, the
        # scribe pool, and one Zipf popularity ranking with the string
        # docs.  Empty keeps the string-only stack byte-for-byte unchanged.
        self.tree_doc_ids = list(tree_doc_ids or [])
        self.all_doc_ids = self.doc_ids + self.tree_doc_ids
        self._family = {d: "tree" for d in self.tree_doc_ids}
        self.workdir = workdir
        self.churn_rate = churn_rate
        self.ops_per_tick = ops_per_tick
        self.step_every = max(1, step_every)
        # Fast-recovery plane knobs (ISSUE 12): ``standby`` keeps a warm
        # pre-compiled, checkpoint-trailing engine ready so fleet_kill
        # promotes instead of cold-booting; ``ckpt_stale_seconds`` runs
        # the bounded-staleness background checkpoint writer so the
        # replay tail stays small; ``recovery_bound_s`` is the hard
        # per-incident invariant bound (kill -> first post-restore op).
        self.standby_enabled = standby
        self.ckpt_stale_seconds = ckpt_stale_seconds
        self.recovery_bound_s = recovery_bound_s
        self.standby = None
        self.tree_standby = None
        self._ckpt_writer = None
        self._tree_ckpt_writer = None
        self._recovery_ms: list = []  # per-incident, authoritative
        self._tree_recovery_ms: list = []
        self._engine_incidents_seen = 0
        self._tree_incidents_seen = 0
        # Kills that landed while the previous incident was still open
        # fold into it (earliest start wins), so N kills can resolve into
        # N - merged measured incidents; the invariant accounts for this.
        self._merged_kills = 0
        self._tree_merged_kills = 0
        self.counters = {
            "ticks": 0, "ops_sequenced": 0, "torn_sockets": 0,
            "fleet_restarts": 0, "scribe_kills": 0, "scribe_crashes": 0,
            "writer_replacements": 0, "churn_disconnects": 0,
            "churn_joins": 0, "nack_backoffs": 0, "standby_promotions": 0,
            "doc_migrations": 0, "migrations_skipped": 0,
        }
        self.max_queue_depth = 0
        self.max_tree_queue_depth = 0
        self._writer_serial = 0
        self._retired_nack_backoffs = 0  # counts from replaced/closed writers

        # Zipf popularity over BOTH families' docs as one ranking (rank 0
        # hottest; string docs first, so string-only stacks are unchanged).
        weights = [
            1.0 / (i + 1) ** zipf_a for i in range(len(self.all_doc_ids))
        ]
        self._weights = weights

        # ---- service plane (admission-controlled fronts over real sockets)
        self.admission = AdmissionController(
            admission if admission is not None else AdmissionConfig(
                max_pending=2048, max_consumer_backlog=256,
                base_retry_after_s=0.005, max_retry_after_s=0.05,
            )
        )
        self.plane = ServicePlane(admission=self.admission).start()
        try:
            self._build(doc_ids, workdir, writers_per_doc, checkpoint_every,
                        megastep_k, ops_per_step, scribe_members)
        except BaseException:
            self.close()  # a failed setup must not leak the stack
            raise

    def _build(self, doc_ids, workdir, writers_per_doc, checkpoint_every,
               megastep_k, ops_per_step, scribe_members) -> None:
        import os

        from ..models.doc_batch_engine import DocBatchEngine
        from ..server.fleet_consumer import FleetConsumer
        from ..server.ordered_log import CheckpointStore, DurableTopic
        from ..server.partition_manager import ScribePool
        from ..server.scribe import ScribeConfig

        # ---- device fleet tier (checkpointed; tight watermarks)
        self._engine_cls = DocBatchEngine
        self._consumer_cls = FleetConsumer
        self.checkpoint_store = CheckpointStore(
            os.path.join(workdir, "checkpoints")
        )
        self._engine_kw = dict(
            max_segments=512, text_capacity=8192, max_insert_len=8,
            ops_per_step=ops_per_step, megastep_k=megastep_k, use_mesh=False,
            checkpoint_store=self.checkpoint_store,
            checkpoint_every=checkpoint_every, doc_keys=list(doc_ids),
            latency_sample_every=4,
        )
        self.engine = None
        self.consumer = None
        self._boot_fleet()
        if self.standby_enabled:
            self._make_standby()
        self._start_ckpt_writer()

        # ---- tree device fleet tier (second family, own durable store)
        self.tree_engine = None
        self.tree_consumer = None
        if self.tree_doc_ids:
            import jax

            from ..parallel.mesh import doc_mesh

            self.tree_checkpoint_store = CheckpointStore(
                os.path.join(workdir, "tree-checkpoints")
            )
            # A real mesh (when the platform has devices) gives the tree
            # engine >1 shard, making the ``migrate`` fault a LIVE
            # mid-stream placement move; single-device runs degrade to
            # skip-counted migrations, everything else identical.
            mesh = doc_mesh() if jax.device_count() > 1 else None
            self._tree_engine_kw = dict(
                capacity=256, pool_capacity=1024, max_insert_len=4,
                ops_per_step=ops_per_step, megastep_k=megastep_k,
                mesh=mesh,
                spare_slots=2 * jax.device_count() if mesh else 1,
                checkpoint_store=self.tree_checkpoint_store,
                checkpoint_every=checkpoint_every,
                doc_keys=list(self.tree_doc_ids),
            )
            self._boot_tree_fleet()
            if self.standby_enabled:
                self._make_tree_standby()
            self._start_tree_ckpt_writer()

        # ---- scribe plane (durable topic mirror + member pool)
        self.topic = DurableTopic(
            "deltas", 2, os.path.join(workdir, "topic"),
            encode=lambda m: m.to_json(),
            decode=SequencedMessage.from_json,
        )
        self.pool = ScribePool(
            self.topic, os.path.join(workdir, "scribe"),
            config=ScribeConfig(max_ops=16),
        )
        self._scribe_serial = 0
        for _ in range(scribe_members):
            self._add_scribe_member()
        self._mirror_cursor = {d: 0 for d in self.all_doc_ids}

        # ---- writers (both families; _add_writer picks the class)
        self.writers: dict[str, list] = {d: [] for d in self.all_doc_ids}
        for d in self.all_doc_ids:
            for _ in range(writers_per_doc):
                self._add_writer(d)

    # ----------------------------------------------------------------- boot
    def _boot_fleet(self) -> None:
        """(Re)build the fleet tier: engine restored from durable
        checkpoints, consumer re-reading the firehose (seq-floor dedupe
        skips everything the checkpoints cover)."""
        eng = self._engine_cls(len(self.doc_ids), **self._engine_kw)
        eng.restore_from_checkpoints()
        self.engine = eng
        self._engine_incidents_seen = 0
        self.consumer = self._consumer_cls(
            "127.0.0.1", self.plane.nexus.port, eng, self.doc_ids
        )

    def _boot_tree_fleet(self) -> None:
        """(Re)build the tree tier the same way: engine restored from ITS
        durable checkpoints, consumer re-reading the firehose."""
        from ..models.tree_batch_engine import TreeBatchEngine

        eng = TreeBatchEngine(len(self.tree_doc_ids), **self._tree_engine_kw)
        eng.restore_from_checkpoints()
        self.tree_engine = eng
        self._tree_incidents_seen = 0
        self.tree_consumer = self._consumer_cls(
            "127.0.0.1", self.plane.nexus.port, eng, self.tree_doc_ids
        )

    # ------------------------------------------------------ recovery plane
    def _make_standby(self) -> None:
        """Spin up the NEXT warm standby: a fresh engine with its serving
        programs pre-compiled and the current checkpoints adopted, kept
        trailing by ``tick`` until a fleet_kill promotes it."""
        from ..server.failover import WarmStandby

        eng = self._engine_cls(len(self.doc_ids), **self._engine_kw)
        self.standby = WarmStandby(
            eng, self.checkpoint_store, lease=None
        ).prepare()

    def _make_tree_standby(self) -> None:
        """The tree family's warm standby: same WarmStandby machinery over
        a TreeBatchEngine (in-place pooled-column re-seed on trail)."""
        from ..models.tree_batch_engine import TreeBatchEngine
        from ..server.failover import WarmStandby

        eng = TreeBatchEngine(len(self.tree_doc_ids), **self._tree_engine_kw)
        self.tree_standby = WarmStandby(
            eng, self.tree_checkpoint_store, lease=None
        ).prepare()

    def _start_ckpt_writer(self) -> None:
        """(Re)arm the bounded-staleness background checkpoint writer on
        the CURRENT engine (a killed engine's writer is stopped with it)."""
        if self._ckpt_writer is not None:
            self._ckpt_writer.stop()
            self._ckpt_writer = None
        if self.ckpt_stale_seconds:
            from ..models.recovery import BackgroundCheckpointWriter

            self._ckpt_writer = BackgroundCheckpointWriter(
                self.engine,
                max_seconds_behind=self.ckpt_stale_seconds,
                interval_s=max(0.02, self.ckpt_stale_seconds / 2),
            ).start()

    def _start_tree_ckpt_writer(self) -> None:
        if self._tree_ckpt_writer is not None:
            self._tree_ckpt_writer.stop()
            self._tree_ckpt_writer = None
        if self.ckpt_stale_seconds:
            from ..models.recovery import BackgroundCheckpointWriter

            self._tree_ckpt_writer = BackgroundCheckpointWriter(
                self.tree_engine,
                max_seconds_behind=self.ckpt_stale_seconds,
                interval_s=max(0.02, self.ckpt_stale_seconds / 2),
            ).start()

    def _poll_recovery(self) -> None:
        """Harvest newly completed recovery incidents off the current
        engines into the per-FAMILY incident lists (incidents complete
        one at a time — a new one only begins at the next kill)."""
        tr = self.engine.recovery_tracker
        while self._engine_incidents_seen < tr.incidents:
            self._engine_incidents_seen += 1
            self._recovery_ms.append(tr.last_ms)
        if self.tree_engine is not None:
            tr = self.tree_engine.recovery_tracker
            while self._tree_incidents_seen < tr.incidents:
                self._tree_incidents_seen += 1
                self._tree_recovery_ms.append(tr.last_ms)

    @staticmethod
    def _pct(ms: list, q: float):
        if not ms:
            return None
        import math

        return ms[max(1, math.ceil(q * len(ms))) - 1]

    def recovery_report(self) -> dict:
        """The per-incident recovery surface (report + invariants):
        exact per-FAMILY percentiles over the measured kill ->
        first-applied-op intervals."""
        self._poll_recovery()
        ms = sorted(self._recovery_ms)
        rep = {
            "incidents": len(ms),
            "open": int(self.engine.recovery_tracker.active),
            "standby": self.standby_enabled,
            "recovery_p50_ms": self._pct(ms, 0.5),
            "recovery_p99_ms": self._pct(ms, 0.99),
            "recovery_max_ms": ms[-1] if ms else None,
            "intervals_ms": list(self._recovery_ms),
            "merged_kills": self._merged_kills,
        }
        if self.tree_engine is not None:
            tms = sorted(self._tree_recovery_ms)
            rep.update({
                "tree_incidents": len(tms),
                "tree_open": int(self.tree_engine.recovery_tracker.active),
                "tree_recovery_p50_ms": self._pct(tms, 0.5),
                "tree_recovery_p99_ms": self._pct(tms, 0.99),
                "tree_recovery_max_ms": tms[-1] if tms else None,
                "tree_intervals_ms": list(self._tree_recovery_ms),
                "tree_merged_kills": self._tree_merged_kills,
            })
        return rep

    def _add_writer(self, doc_id: str) -> _ChaosWireClient:
        self._writer_serial += 1
        cls = (
            ChaosTreeWriter if self._family.get(doc_id) == "tree"
            else ChaosWriter
        )
        w = cls(
            "127.0.0.1", self.plane.nexus.port, self.plane.http.port,
            doc_id, f"{doc_id}-w{self._writer_serial}",
            random.Random(self.rng.getrandbits(32)),
        )
        self.writers[doc_id].append(w)
        return w

    def _add_scribe_member(self):
        self._scribe_serial += 1
        return self.pool.add_member(f"scribe{self._scribe_serial}")

    # ----------------------------------------------------------------- tick
    def tick(self, t: int, schedule: ChaosSchedule) -> None:
        from ..server.scribe import ChaosCrash

        self.counters["ticks"] += 1
        for ev in schedule.at(t):
            self._fire(ev)

        # Connect/disconnect churn: a writer leaves gracefully, a fresh
        # identity joins elsewhere (both rng-driven, both Zipf-weighted).
        if self.rng.random() < self.churn_rate:
            doc = self._pick_doc()
            if len(self.writers[doc]) > 1:
                w = self.writers[doc].pop(self.rng.randrange(len(self.writers[doc])))
                self._retired_nack_backoffs += w.nack_backoffs
                w.close()
                self.counters["churn_disconnects"] += 1
        if self.rng.random() < self.churn_rate:
            self._add_writer(self._pick_doc())
            self.counters["churn_joins"] += 1

        # Zipf-popular traffic through the admission-controlled front.
        for _ in range(self.ops_per_tick):
            doc = self._pick_doc()
            if not self.writers[doc]:
                self._add_writer(doc)
            w = self.rng.choice(self.writers[doc])
            try:
                w.edit()
                self.counters["ops_sequenced"] += w.flush()
            except TornConnection:
                self._replace_writer(w)
        self.counters["nack_backoffs"] = self._retired_nack_backoffs + sum(
            x.nack_backoffs for ws in self.writers.values() for x in ws
        )

        # Every writer drains its broadcast backlog (replicas stay live; a
        # torn one is replaced so a frozen replica never masquerades).
        for ws in self.writers.values():
            for w in list(ws):
                try:
                    w.settle()
                except (TornConnection, DriverError, OSError):
                    self._replace_writer(w)

        # Fleet tiers: pump (flow-control-gated), step on cadence.
        self.consumer.pump(wait_s=0.005)
        if t % self.step_every == 0:
            self.consumer.step()
        if self.tree_consumer is not None:
            self.tree_consumer.pump(wait_s=0.005)
            if t % self.step_every == 0:
                self.tree_consumer.step()
        # Recovery plane: the warm standby trails the checkpoint dir so
        # promotion is O(dirty tail); completed incidents harvest into
        # the per-incident list the invariants assert over.  The NEXT
        # standby after a promotion builds here, and only once the open
        # incident closed — building it inside the kill handler while the
        # incident is still open (empty post-kill tail) would fold its
        # warmup compiles into the measured recovery interval.
        if self.standby is not None:
            self.standby.trail()
        elif (
            self.standby_enabled
            and not self.engine.recovery_tracker.active
        ):
            self._make_standby()
        if self.tree_engine is not None:
            if self.tree_standby is not None:
                self.tree_standby.trail()
            elif (
                self.standby_enabled
                and not self.tree_engine.recovery_tracker.active
            ):
                self._make_tree_standby()
        self._poll_recovery()

        # Scribe plane: mirror the new sequenced records into the durable
        # topic, pump the pool (a ChaosCrash kills the member mid-fold and
        # a successor joins — the at-least-once re-read path).
        self._mirror_log()
        try:
            self.pool.pump()
        except ChaosCrash:
            crashed = [
                mid for mid, m in self.pool.members.items()
                if m.chaos_abort_after_folds == 0 and getattr(
                    m, "_chaos_armed", False
                )
            ]
            for mid in crashed or list(self.pool.members)[:1]:
                self.pool.kill_member(mid)
                self.counters["scribe_crashes"] += 1
            self._add_scribe_member()

        # Bounded-ingest invariant: the watermark gate must hold the line
        # even while faults fire (checked EVERY tick, not at the end).
        depth = max(
            (len(h.queue) for h in self.engine.hosts), default=0
        )
        self.max_queue_depth = max(self.max_queue_depth, depth)
        bound = self._depth_bound()
        if depth > bound:
            raise AssertionError(
                f"tick {t}: staged queue depth {depth} exceeded bound "
                f"{bound} (high watermark {self.engine.overload_gate.high})"
            )
        if self.tree_engine is not None:
            depth = max(
                (len(h.queue) for h in self.tree_engine.hosts), default=0
            )
            self.max_tree_queue_depth = max(self.max_tree_queue_depth, depth)
            bound = self._tree_depth_bound()
            if depth > bound:
                raise AssertionError(
                    f"tick {t}: tree staged queue depth {depth} exceeded "
                    f"bound {bound} (high watermark "
                    f"{self.tree_engine.overload_gate.high})"
                )

    def _depth_bound(self) -> int:
        # One pump can stage at most the post-checkpoint catch-up tail on
        # top of the high watermark before the gate pauses the partition.
        return (
            self.engine.overload_gate.high
            + self._engine_kw["checkpoint_every"]
            + 4 * self.ops_per_tick
        )

    def _tree_depth_bound(self) -> int:
        # Same shape as _depth_bound with one twist: a tree wire op can
        # flatten into a couple of staged rows, so the catch-up tail and
        # per-tick slack carry a 2x row-expansion factor.
        return (
            self.tree_engine.overload_gate.high
            + 2 * self._tree_engine_kw["checkpoint_every"]
            + 8 * self.ops_per_tick
        )

    def _pick_doc(self) -> str:
        return self.rng.choices(
            self.all_doc_ids, weights=self._weights, k=1
        )[0]

    def _replace_writer(self, w: _ChaosWireClient) -> None:
        ws = self.writers[w.doc_id]
        if w in ws:
            ws.remove(w)
        self._retired_nack_backoffs += w.nack_backoffs
        w.close()
        self._add_writer(w.doc_id)
        self.counters["writer_replacements"] += 1

    # ---------------------------------------------------------------- faults
    def _fire(self, ev: ChaosEvent) -> None:
        if ev.kind == "fleet_kill":
            t0 = time.monotonic()
            self.consumer.close()
            if self._ckpt_writer is not None:
                self._ckpt_writer.stop()
                self._ckpt_writer = None
            self._poll_recovery()  # harvest the dying engine's incidents
            # A kill landing while the PREVIOUS incident is still open
            # (no op applied between two kills) must not drop it: the
            # unresolved window carries onto the successor — earliest
            # start wins, so the measured interval spans the first kill.
            open_t0 = self.engine.recovery_tracker.started_at
            if open_t0 is not None:
                t0 = min(t0, open_t0)
                self._merged_kills += 1
            if self.standby is not None:
                # Warm failover: the trailing standby promotes — final
                # checkpoint adoption only, programs already compiled.
                eng = self.standby.promote(incident_started_at=t0)
                self.standby = None
                self.engine = eng
                self._engine_incidents_seen = 0
                self.consumer = self._consumer_cls(
                    "127.0.0.1", self.plane.nexus.port, eng, self.doc_ids
                )
                self.counters["standby_promotions"] += 1
            else:
                self._boot_fleet()
                self.engine.note_incident(t0)
            self.counters["fleet_restarts"] += 1
            # Catch up NOW — a real failover pumps the moment it owns the
            # firehose; the incident closes at the first op actually
            # applied post-restore (kill -> first post-restore ack).
            self.consumer.pump(wait_s=0.005)
            self.consumer.step()
            self._start_ckpt_writer()
            if self.standby_enabled and not self.engine.recovery_tracker.active:
                # The NEXT standby spins up after the measured promote
                # (its boot cost is standby-build time, not recovery).
                # With the incident still open (empty post-kill tail) the
                # tick hook builds it once the incident closes instead —
                # warmup compiles must not inflate the measured window.
                self._make_standby()
            # The tree tier dies with the same fleet process: promote its
            # standby (in-place pooled-column re-seed already done by
            # trail) or cold-boot from its durable checkpoint store.
            if self.tree_engine is not None:
                t0t = time.monotonic()
                self.tree_consumer.close()
                if self._tree_ckpt_writer is not None:
                    self._tree_ckpt_writer.stop()
                    self._tree_ckpt_writer = None
                open_t0 = self.tree_engine.recovery_tracker.started_at
                if open_t0 is not None:
                    t0t = min(t0t, open_t0)
                    self._tree_merged_kills += 1
                if self.tree_standby is not None:
                    eng = self.tree_standby.promote(incident_started_at=t0t)
                    self.tree_standby = None
                    self.tree_engine = eng
                    self._tree_incidents_seen = 0
                    self.tree_consumer = self._consumer_cls(
                        "127.0.0.1", self.plane.nexus.port, eng,
                        self.tree_doc_ids,
                    )
                    self.counters["standby_promotions"] += 1
                else:
                    self._boot_tree_fleet()
                    self.tree_engine.note_incident(t0t)
                self.tree_consumer.pump(wait_s=0.005)
                self.tree_consumer.step()
                self._start_tree_ckpt_writer()
                if (
                    self.standby_enabled
                    and not self.tree_engine.recovery_tracker.active
                ):
                    self._make_tree_standby()
        elif ev.kind == "migrate":
            # Live mid-stream placement move: writers keep submitting while
            # the engine folds + re-materializes the doc on another shard.
            # Unsharded engines and host-lane docs (seg-lane/overflow/
            # fallback — the placement plane refuses those loudly) count as
            # skips, so the fault degrades gracefully off-mesh.
            from ..models.placement import PlacementError

            doc = ev.target or self._pick_doc()
            if self._family.get(doc) == "tree":
                eng, i = self.tree_engine, self.tree_doc_ids.index(doc)
            else:
                eng, i = self.engine, self.doc_ids.index(doc)
            moved = False
            if eng is not None and eng.n_shards > 1:
                for dst in range(eng.n_shards):
                    try:
                        moved = eng.migrate_doc(i, dst)
                    except PlacementError:
                        break
                    if moved:
                        break
            self.counters["doc_migrations" if moved else
                          "migrations_skipped"] += 1
        elif ev.kind == "torn_socket":
            doc = ev.target or self._pick_doc()
            if self.writers[doc]:
                w = self.rng.choice(self.writers[doc])
                w.tear()
                self.counters["torn_sockets"] += 1
        elif ev.kind == "nack_storm":
            doc = ev.target or self._pick_doc()
            self.admission.force_overload(doc, int(ev.param) or 4)
        elif ev.kind == "scribe_kill":
            if self.pool.members:
                mid = self.rng.choice(sorted(self.pool.members))
                self.pool.kill_member(mid)
                self.counters["scribe_kills"] += 1
                self._add_scribe_member()
        elif ev.kind == "scribe_crash":
            if self.pool.members:
                mid = self.rng.choice(sorted(self.pool.members))
                m = self.pool.members[mid]
                m.chaos_abort_after_folds = int(ev.param) or 2
                m._chaos_armed = True
        elif ev.kind == "fsync_delay":
            self.topic.set_fault_flush_delay(ev.param or 0.002)
        elif ev.kind == "fsync_clear":
            self.topic.set_fault_flush_delay(0.0)
        else:
            raise ValueError(f"unknown chaos event kind {ev.kind!r}")

    def _mirror_log(self) -> None:
        """Feed the scribe plane the same total order the firehose carries
        (the deltas-topic produce seam, in-process)."""
        with self.plane.nexus.lock:
            for d in self.all_doc_ids:
                doc = self.plane.service.document(d)
                log = doc.sequencer.log
                cur = self._mirror_cursor[d]
                for msg in log[cur:]:
                    self.topic.produce(d, msg)
                self._mirror_cursor[d] = len(log)

    # -------------------------------------------------------------- quiesce
    def quiesce(self, max_rounds: int = 400) -> None:
        """Drain everything: writers settle, the fleet consumes every
        sequenced op (engine seq floor reaches the log head per doc), the
        scribe pool folds the mirrored tail."""
        for ws in self.writers.values():
            for w in list(ws):
                try:
                    w.settle()
                except (TornConnection, DriverError, OSError):
                    self._retired_nack_backoffs += w.nack_backoffs
                    ws.remove(w)
                    w.close()
        with self.plane.nexus.lock:
            want = {
                d: max(
                    (m.seq for m in
                     self.plane.service.document(d).sequencer.log
                     if m.type == MessageType.OP),
                    default=0,
                )
                for d in self.all_doc_ids
            }
        for _ in range(max_rounds):
            self.consumer.pump(wait_s=0.01)
            self.consumer.step()
            if self.tree_consumer is not None:
                self.tree_consumer.pump(wait_s=0.01)
                self.tree_consumer.step()
            if all(
                self.engine.hosts[i].last_seq >= want[d]
                for i, d in enumerate(self.doc_ids)
            ) and (
                self.tree_engine is None
                or all(
                    self.tree_engine.hosts[i].last_seq >= want[d]
                    for i, d in enumerate(self.tree_doc_ids)
                )
            ):
                break
        else:
            raise TimeoutError(
                f"fleet never caught up: "
                f"{[(d, self.engine.hosts[i].last_seq, want[d]) for i, d in enumerate(self.doc_ids)]}"
                + (
                    f" tree: {[(d, self.tree_engine.hosts[i].last_seq, want[d]) for i, d in enumerate(self.tree_doc_ids)]}"
                    if self.tree_engine is not None else ""
                )
            )
        self._mirror_log()
        self.pool.pump()

    # ----------------------------------------------------------- invariants
    def oracle_text(self, doc_id: str) -> str:
        """Fault-free replay of the server's sequenced log through the host
        reference merge tree — the byte-identity oracle."""
        with self.plane.nexus.lock:
            log = list(self.plane.service.document(doc_id).sequencer.log)
        tree = RefMergeTree()
        quorum: dict[str, int] = {}
        for msg in log:
            if msg.type == MessageType.JOIN:
                quorum[msg.contents["clientId"]] = msg.contents["short"]
            elif msg.type == MessageType.OP:
                c = msg.contents
                kind = c["type"]
                client = quorum[msg.client_id]
                if kind == DeltaType.INSERT:
                    tree.apply_insert(
                        c["pos1"], c["seg"], msg.seq, client, msg.ref_seq
                    )
                elif kind == DeltaType.REMOVE:
                    tree.apply_remove(
                        c["pos1"], c["pos2"], msg.seq, client, msg.ref_seq
                    )
                elif kind == DeltaType.ANNOTATE:
                    for prop, value in c["props"].items():
                        tree.apply_annotate(
                            c["pos1"], c["pos2"], int(prop), value,
                            msg.seq, client, msg.ref_seq,
                        )
        return tree.visible_text()

    def oracle_tree_json(self, doc_id: str) -> list:
        """Fault-free replay of the server's sequenced log through a host
        EditManager + Forest (the scribe's tree replica idiom) — the tree
        family's byte-identity oracle (root-field node JSON)."""
        with self.plane.nexus.lock:
            log = list(self.plane.service.document(doc_id).sequencer.log)
        em, forest = EditManager(), Forest()
        for msg in log:
            if msg.type != MessageType.OP:
                continue
            c = msg.contents
            trunk = em.add_sequenced(
                client_id=msg.client_id,
                revision=(c["sid"], c["rev"]),
                change=commit_from_json(c["changes"]),
                ref_seq=msg.ref_seq,
                seq=msg.seq,
            )
            em.advance_min_seq(msg.min_seq)
            apply_commit(forest.root, trunk)
        return [n.to_json() for n in forest.root_field]

    def check_invariants(self) -> dict:
        """Byte identity + no double-acks; raises AssertionError on any
        violation, returns the report fragment on success."""
        texts = {}
        for i, d in enumerate(self.doc_ids):
            oracle = self.oracle_text(d)
            fleet = self.engine.text(i)
            assert fleet == oracle, (
                f"{d}: fleet diverged from fault-free oracle replay\n"
                f"  fleet : {fleet!r}\n  oracle: {oracle!r}"
            )
            for w in self.writers[d]:
                assert w.replica.text == oracle, (
                    f"{d}: writer {w.client_id} diverged\n"
                    f"  writer: {w.replica.text!r}\n  oracle: {oracle!r}"
                )
            texts[d] = oracle
        assert not self.engine.errors().any(), "engine error bits latched"

        # Tree family: same HARD identity, against the EditManager+Forest
        # oracle — the device fleet's root-field JSON and every surviving
        # tree writer's replica must match byte-for-byte (device-lane and
        # host-fallback docs alike, across kills/promotes/migrations).
        tree_nodes = 0
        for i, d in enumerate(self.tree_doc_ids):
            oracle = self.oracle_tree_json(d)
            fleet = self.tree_engine.tree_json(i)
            assert fleet == oracle, (
                f"{d}: tree fleet diverged from fault-free oracle replay\n"
                f"  fleet : {fleet!r}\n  oracle: {oracle!r}"
            )
            for w in self.writers[d]:
                got = w.root_json()
                assert got == oracle, (
                    f"{d}: tree writer {w.client_id} diverged\n"
                    f"  writer: {got!r}\n  oracle: {oracle!r}"
                )
            tree_nodes += len(oracle)
        if self.tree_engine is not None:
            assert not self.tree_engine.errors().any(), (
                "tree engine error bits latched"
            )

        # No double-acks: one summaryAck per (doc, seq) across the topic.
        seen: set = set()
        doubles = []
        for p in range(self.topic.n_partitions):
            part = self.topic.partition(p)
            for rec in part.read(part.base):
                ack = parse_scribe_ack(rec.payload)
                if ack is not None:
                    key = (ack[0], ack[1])
                    if key in seen:
                        doubles.append(key)
                    seen.add(key)
        assert not doubles, f"double-acked summaries: {doubles}"

        # No scribe replica may have failed folding: chaos generates only
        # well-formed traffic, so a failed doc means the pool machinery
        # (adoption, rebalance, crash re-read) gapped a replica — the
        # stale-replica class the r10 soak caught.
        failed = {
            (mid, doc): ad.failed
            for mid, m in self.pool.members.items()
            for doc, ad in m.docs.items()
            if ad.failed is not None
        }
        assert not failed, f"scribe replicas failed folding: {failed}"

        # Bounded recovery (first-class, not just bounded queues): every
        # fleet_kill resolved into a measured incident (none still open
        # after quiesce) — kills that folded into a still-open incident
        # (back-to-back kills with an empty tail between) merge into ONE
        # measured window, so the floor is kills minus merges — and every
        # interval sits under the bound.
        rec = self.recovery_report()
        assert rec["open"] == 0, "unresolved recovery incident after quiesce"
        expected = self.counters["fleet_restarts"] - self._merged_kills
        assert rec["incidents"] >= expected, (
            f"{self.counters['fleet_restarts']} fleet kills "
            f"({self._merged_kills} merged) but only "
            f"{rec['incidents']} measured recovery incidents"
        )
        bound_ms = self.recovery_bound_s * 1e3
        slow = [ms for ms in rec["intervals_ms"] if ms > bound_ms]
        assert not slow, (
            f"recovery intervals exceeded the {bound_ms:.0f} ms bound: {slow}"
        )
        if self.tree_engine is not None:
            # The tree tier dies with the same kills: its per-family
            # incidents must resolve under the same bound.
            assert rec["tree_open"] == 0, (
                "unresolved tree recovery incident after quiesce"
            )
            expected = (
                self.counters["fleet_restarts"] - self._tree_merged_kills
            )
            assert rec["tree_incidents"] >= expected, (
                f"{self.counters['fleet_restarts']} fleet kills "
                f"({self._tree_merged_kills} merged) but only "
                f"{rec['tree_incidents']} measured tree recovery incidents"
            )
            slow = [
                ms for ms in rec["tree_intervals_ms"] if ms > bound_ms
            ]
            assert not slow, (
                f"tree recovery intervals exceeded the {bound_ms:.0f} ms "
                f"bound: {slow}"
            )
        out = {
            "converged_docs": len(texts),
            "text_bytes": sum(len(t) for t in texts.values()),
            "summary_acks": len(seen),
            "double_acks": 0,
            "max_queue_depth": self.max_queue_depth,
            "queue_depth_bound": self._depth_bound(),
            "recovery_incidents": rec["incidents"],
            "recovery_max_ms": rec["recovery_max_ms"],
            "recovery_bound_ms": bound_ms,
        }
        if self.tree_engine is not None:
            out.update({
                "tree_converged_docs": len(self.tree_doc_ids),
                "tree_nodes": tree_nodes,
                "max_tree_queue_depth": self.max_tree_queue_depth,
                "tree_queue_depth_bound": self._tree_depth_bound(),
                "tree_recovery_incidents": rec["tree_incidents"],
                "tree_recovery_max_ms": rec["tree_recovery_max_ms"],
            })
        return out

    def close(self) -> None:
        # Defensive getattr walk: close() also runs when __init__ failed
        # partway (a writer join assert, a bind error), where later
        # attributes never came to exist — a failed setup must not leak
        # server threads/sockets into the caller's process.
        for ws in getattr(self, "writers", {}).values():
            for w in ws:
                w.close()
        if getattr(self, "_ckpt_writer", None) is not None:
            self._ckpt_writer.stop()
        if getattr(self, "_tree_ckpt_writer", None) is not None:
            self._tree_ckpt_writer.stop()
        if getattr(self, "consumer", None) is not None:
            self.consumer.close()
        if getattr(self, "tree_consumer", None) is not None:
            self.tree_consumer.close()
        if getattr(self, "pool", None) is not None:
            self.pool.close()
        if getattr(self, "topic", None) is not None:
            self.topic.close()
        if getattr(self, "plane", None) is not None:
            self.plane.stop()


# ---------------------------------------------------------------------------
# Entry points: chaos smoke + soak
# ---------------------------------------------------------------------------

def run_chaos(
    seed: int = 7,
    ticks: int = 40,
    n_docs: int = 3,
    n_tree_docs: int = 0,
    schedule: ChaosSchedule | None = None,
    workdir: str | None = None,
    **stack_kw,
) -> dict:
    """One seeded chaos run over the full stack; returns the report dict
    (raises on any invariant violation).  ``n_tree_docs > 0`` runs the
    MIXED fleet: tree docs join the Zipf ranking, the fault schedule, and
    the byte-identity invariants alongside the string docs."""
    import tempfile

    doc_ids = [f"cd{i}" for i in range(n_docs)]
    tree_ids = [f"td{i}" for i in range(n_tree_docs)]
    if schedule is None:
        schedule = make_schedule(seed, ticks, doc_ids + tree_ids)
    owndir = None
    if workdir is None:
        owndir = tempfile.TemporaryDirectory(prefix="fftpu-chaos-")
        workdir = owndir.name
    stack = None
    t0 = time.perf_counter()
    try:
        # ChaosStack.__init__ self-cleans on failure; constructing inside
        # the try keeps the tempdir cleanup on that path too.
        stack = ChaosStack(
            seed, doc_ids, workdir, tree_doc_ids=tree_ids, **stack_kw
        )
        for t in range(ticks):
            stack.tick(t, schedule)
        stack.quiesce()
        invariants = stack.check_invariants()
        health = stack.engine.health()
        report = {
            "seed": seed,
            "ticks": ticks,
            "duration_s": round(time.perf_counter() - t0, 3),
            "schedule_events": len(schedule.events),
            "events_by_kind": {
                k: sum(1 for e in schedule.events if e.kind == k)
                for k in sorted({e.kind for e in schedule.events})
            },
            "invariants": invariants,
            "counters": dict(stack.counters),
            "recovery": stack.recovery_report(),
            "admission": stack.admission.stats(),
            "flow_control": {
                **stack.engine.ingest_watermarks(),
                "pump_pauses": stack.consumer.pump_pauses,
                "pump_resumes": stack.consumer.pump_resumes,
                "overload_events": health.get("overload_events", 0),
            },
            "scribe": stack.pool.health(),
        }
        if health.get("latency_samples"):
            report["latency_p50_ms"] = health.get("latency_p50_ms")
            report["latency_p99_ms"] = health.get("latency_p99_ms")
        if stack.tree_engine is not None:
            report["tree"] = {
                "n_docs": len(stack.tree_doc_ids),
                "n_shards": stack.tree_engine.n_shards,
                "health": {
                    k: v for k, v in stack.tree_engine.health().items()
                    if isinstance(v, (int, float, str, bool))
                },
            }
        return report
    finally:
        if stack is not None:
            stack.close()
        if owndir is not None:
            owndir.cleanup()


def run_soak(
    seed: int = 10,
    ticks: int = 240,
    n_docs: int = 6,
    n_tree_docs: int = 0,
    events_per_kind: int = 2,
    rss_bound_mb: float = 4096.0,
    **stack_kw,
) -> dict:
    """The soak runner (``bench.py --config soak``): Zipf traffic with
    churn through a longer chaos schedule, continuous invariant checks,
    and an SLO artifact row — p50/p99 op latency UNDER FAULT from the
    engine's e2e histograms, plus shed/pause/backoff counters and an RSS
    ceiling."""
    import resource

    doc_ids = [f"cd{i}" for i in range(n_docs)]
    doc_ids += [f"td{i}" for i in range(n_tree_docs)]
    schedule = make_schedule(
        seed, ticks, doc_ids, events_per_kind=events_per_kind
    )
    stack_kw.setdefault("churn_rate", 0.08)
    report = run_chaos(
        seed=seed, ticks=ticks, n_docs=n_docs, n_tree_docs=n_tree_docs,
        schedule=schedule, **stack_kw
    )
    max_rss_mb = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024.0
    assert max_rss_mb < rss_bound_mb, (
        f"soak RSS {max_rss_mb:.0f} MB exceeded bound {rss_bound_mb:.0f} MB"
    )
    ops = report["counters"]["ops_sequenced"]
    recovery = report.get("recovery", {})
    return {
        "metric": "soak_p99_latency_ms_under_fault",
        "value": report.get("latency_p99_ms"),
        "unit": "ms",
        "p50_ms": report.get("latency_p50_ms"),
        "p99_ms": report.get("latency_p99_ms"),
        # The r12 availability columns: per-incident recovery time
        # (fleet kill -> first post-restore op applied), r16 adds the
        # tree family's own columns (None when no tree tier ran).
        "recovery_p50_ms": recovery.get("recovery_p50_ms"),
        "recovery_p99_ms": recovery.get("recovery_p99_ms"),
        "tree_recovery_p50_ms": recovery.get("tree_recovery_p50_ms"),
        "tree_recovery_p99_ms": recovery.get("tree_recovery_p99_ms"),
        "standby": recovery.get("standby", False),
        "ops_sequenced": ops,
        "ops_per_sec": round(ops / report["duration_s"], 1)
        if report["duration_s"] else None,
        "max_rss_mb": round(max_rss_mb, 1),
        "rss_bound_mb": rss_bound_mb,
        **{k: report[k] for k in (
            "seed", "ticks", "duration_s", "events_by_kind", "invariants",
            "counters", "recovery", "admission", "flow_control", "scribe",
        )},
    }
