"""Replay of the reference's OWN recorded conflict-farm traces.

The reference repo ships 60 replay files under
``packages/dds/merge-tree/src/test/results/`` — ReplayGroup arrays
(``mergeTreeOperationRunner.ts:276``) recorded from its conflict-farm runs,
each carrying the sequenced message stream (ISequencedDocumentMessage JSON)
plus the reference-computed ``initialText``/``resultText`` per group.  Its
``client.replay.spec.ts`` replays them through TestClient and asserts
convergence to ``resultText``.

This module is our side of that contract (VERDICT r3 missing #1): the same
files drive our stack — issuer-faithfully (each trace client re-issues its
op locally at its recorded refSeq, then the sequenced message acks it) and
as a pure remote observer — and every group must converge to the
reference-recorded text.  Nothing here is self-written oracle output; the
expected strings come from the reference implementation.

Annotate props in the traces are string key/value (``{"client": "B"}``);
device kernels need integer prop ids, so ``intern_trace`` rewrites them to
interned ints by first appearance in sequenced order — deterministic from
the trace alone, hence identical on every replica.  Text, positions, op
types, seq/refSeq/MSN are untouched.
"""

from __future__ import annotations

import json
import os
from typing import Any, Callable

from ..dds.mergetree_ref import RefMergeTree, Segment
from ..dds.shared_string import SharedString
from ..protocol.messages import DeltaType, MessageType, SequencedMessage
from ..protocol.stamps import ALL_ACKED, NON_COLLAB_CLIENT, UNIVERSAL_SEQ

REFERENCE_RESULTS_DIR = (
    "/root/reference/packages/dds/merge-tree/src/test/results"
)


def reference_trace_files() -> list[str]:
    """Sorted paths of the reference's replay result files (empty when the
    reference checkout is absent — callers should skip)."""
    if not os.path.isdir(REFERENCE_RESULTS_DIR):
        return []
    return sorted(
        os.path.join(REFERENCE_RESULTS_DIR, f)
        for f in os.listdir(REFERENCE_RESULTS_DIR)
        if f.endswith(".json")
    )


def load_trace(path: str) -> list[dict[str, Any]]:
    with open(path) as f:
        return json.load(f)


def intern_trace(groups: list[dict[str, Any]]) -> dict[str, dict[str, int]]:
    """Rewrite annotate props to interned int ids/values, in place.

    Returns the interning tables {"props": {...}, "values": {...}} so a test
    can decode results if it needs to.
    """
    props: dict[str, int] = {}
    values: dict[str, int] = {}
    for group in groups:
        for msg in group["msgs"]:
            c = msg["contents"]
            if c["type"] == int(DeltaType.ANNOTATE):
                c["props"] = {
                    str(props.setdefault(k, len(props))): (
                        v if isinstance(v, int) and not isinstance(v, bool)
                        else values.setdefault(str(v), len(values))
                    )
                    for k, v in c["props"].items()
                }
    return {"props": props, "values": values}


def trace_clients(groups: list[dict[str, Any]]) -> list[str]:
    """Authoring client ids in order of first appearance across the file
    (the replay spec pre-creates them the same way, client.replay.spec.ts)."""
    seen: list[str] = []
    for group in groups:
        for msg in group["msgs"]:
            if msg["clientId"] not in seen:
                seen.append(msg["clientId"])
    return seen


def bootstrap_text(backend: Any, text: str) -> None:
    """Pre-collaboration initial text: one NonCollab universal segment, the
    state TestClient.createFromClientSnapshot hands every joining client
    (snapshotLoader.ts specToSegment: UniversalSequenceNumber +
    NonCollabClient for merge-info-free specs).  Works on any backend with
    ``import_summary`` (oracle and kernel)."""
    if text:
        backend.import_summary({
            "segments": [{
                "text": text,
                "ins": [UNIVERSAL_SEQ, NON_COLLAB_CLIENT],
                "removes": [], "props": {},
            }],
            "obliterates": [],
            "minSeq": 0,
        })


def _join_msgs(names: list[str]) -> list[SequencedMessage]:
    return [
        SequencedMessage(
            client_id=name, client_seq=0, ref_seq=0, seq=0, min_seq=0,
            type=MessageType.JOIN,
            contents={"clientId": name, "short": i},
        )
        for i, name in enumerate(names)
    ]


def _issue(client: SharedString, contents: dict[str, Any]) -> None:
    """Re-issue a trace op locally (reference localTransaction)."""
    kind = contents["type"]
    if kind == int(DeltaType.INSERT):
        client.insert_text(contents["pos1"], contents["seg"])
    elif kind == int(DeltaType.REMOVE):
        client.remove_range(contents["pos1"], contents["pos2"])
    elif kind == int(DeltaType.ANNOTATE):
        for prop, value in contents["props"].items():
            client.annotate_range(
                contents["pos1"], contents["pos2"], int(prop), value
            )
    elif kind == int(DeltaType.OBLITERATE):
        client.obliterate_range(contents["pos1"], contents["pos2"])
    else:
        raise ValueError(f"unsupported trace op type {kind}")
    client.take_outbox()  # the trace already carries the sequenced form


def replay_trace(
    groups: list[dict[str, Any]],
    max_groups: int | None = None,
    observer_backend: Callable[[], Any] | None = None,
    on_group: Callable[[int, list[SharedString], SharedString], None] | None = None,
) -> tuple[list[SharedString], SharedString]:
    """Issuer-faithful replay of a reference trace file.

    Mirrors client.replay.spec.ts: every authoring client catches up to the
    op's recorded refSeq, re-issues the op locally (minting a pending local
    stamp), and the sequenced trace message later acks it; all other
    replicas apply it remotely.  A pure-observer replica (optionally on a
    different backend, e.g. the TPU kernel) applies everything remotely.
    After each group drains, every replica must equal the
    reference-recorded ``resultText``.

    Returns (clients, observer) after the final replayed group.
    """
    intern_trace(groups)
    names = trace_clients(groups)
    clients = {n: SharedString(client_id=n) for n in names}
    observer = SharedString(
        client_id="__observer__",
        backend=observer_backend() if observer_backend else None,
    )
    replicas: list[SharedString] = [*clients.values(), observer]

    initial = groups[0]["initialText"]
    for rep in replicas:
        bootstrap_text(rep.backend, initial)
    for join in _join_msgs(names):
        for rep in replicas:
            rep.process(join)

    queues: dict[str, list[SequencedMessage]] = {n: [] for n in names}
    observer_queue: list[SequencedMessage] = []

    for gi, group in enumerate(groups):
        if max_groups is not None and gi >= max_groups:
            break
        for rep in replicas:
            assert rep.text == group["initialText"], (
                f"group {gi} initial text mismatch on {rep.client_id!r}"
            )
        for raw in group["msgs"]:
            msg = SequencedMessage.from_json(json.dumps(raw))
            issuer = clients[msg.client_id]
            # Catch up until the issuer's applied seq reaches the op's
            # recorded refSeq (client.replay.spec.ts catch-up loop).
            q = queues[msg.client_id]
            while q and msg.ref_seq > issuer.current_seq:
                issuer.process(q.pop(0))
            _issue(issuer, msg.contents)
            for name in names:
                queues[name].append(msg)
            observer_queue.append(msg)
        for name in names:
            while queues[name]:
                clients[name].process(queues[name].pop(0))
        while observer_queue:
            observer.process(observer_queue.pop(0))
        expect = group["resultText"]
        for rep in replicas:
            got = rep.text
            assert got == expect, (
                f"group {gi}: {rep.client_id!r} diverged from reference "
                f"result ({got!r:.60} != {expect!r:.60})"
            )
        if on_group is not None:
            on_group(gi, list(clients.values()), observer)
    return list(clients.values()), observer


def replay_observer_only(
    groups: list[dict[str, Any]],
    backend_factory: Callable[[], Any] | None = None,
    max_groups: int | None = None,
) -> SharedString:
    """Cheap variant: a single remote-only replica applies the sequenced
    stream and must converge to every group's reference resultText."""
    intern_trace(groups)
    names = trace_clients(groups)
    observer = SharedString(
        client_id="__observer__",
        backend=backend_factory() if backend_factory else None,
    )
    bootstrap_text(observer.backend, groups[0]["initialText"])
    for join in _join_msgs(names):
        observer.process(join)
    for gi, group in enumerate(groups):
        if max_groups is not None and gi >= max_groups:
            break
        for raw in group["msgs"]:
            observer.process(SequencedMessage.from_json(json.dumps(raw)))
        got = observer.backend.visible_text(ALL_ACKED, observer.short_client)
        assert got == group["resultText"], (
            f"group {gi}: observer diverged "
            f"({got!r:.60} != {group['resultText']!r:.60})"
        )
    return observer
