"""Loaders for the reference's committed SEQUENCE snapshot artifacts.

The reference repo ships real summaries its own tests load
(`packages/dds/sequence/src/test/snapshots/v1/*.json`, written by
sharedString summarize and checked in so format drift is caught).  Loading
those files here is the strongest available proof of sequence-format
fidelity (VERDICT r4 next #3): the artifacts were produced by the
TypeScript implementation, not by this repo.

Each artifact is an ITree JSON (`{entries: [{path, type, value}...]}`):
merge-tree blobs (``header``, ``body_0``...) under the ``content`` subtree
(sequence/src/sequenceFactory.ts load path), and — for SharedString
documents with interval collections — a top-level ``header`` blob holding
each collection's serialized intervals
(intervalCollection.ts serializeInternal: ``[start, end, seq, type,
props]`` rows, props carrying ``intervalId``).
"""

from __future__ import annotations

import json
import os
from typing import Any, Callable

from ..dds.sequence_intervals import SequenceInterval
from ..dds.snapshot_v1 import decode_snapshot_v1

V1_SNAPSHOT_DIR = os.path.join(
    os.environ.get("FFTPU_REFERENCE_DIR", "/root/reference"),
    "packages/dds/sequence/src/test/snapshots/v1",
)


def _json_files(directory: str) -> list[str]:
    if not os.path.isdir(directory):
        return []
    return sorted(
        os.path.join(directory, f)
        for f in os.listdir(directory)
        if f.endswith(".json")
    )


def v1_artifact_files() -> list[str]:
    return _json_files(V1_SNAPSHOT_DIR)


def artifact_blobs(path: str) -> tuple[dict[str, str], dict[str, str]]:
    """Flatten an artifact ITree into ({merge-tree blob name: contents},
    {other blob path: contents}).  Merge-tree blobs are the ones under a
    ``content`` subtree; everything else (the interval-collection header)
    lands in the second map."""
    data = json.load(open(path, encoding="utf-8"))
    blobs: dict[str, str] = {}
    extra: dict[str, str] = {}

    def walk(tree: dict, under_content: bool) -> None:
        for e in tree.get("entries", []):
            if e["type"] == "Tree":
                walk(e["value"], under_content or e["path"] == "content")
            elif e["type"] == "Blob":
                (blobs if under_content else extra)[e["path"]] = (
                    e["value"]["contents"]
                )

    walk(data, False)
    return blobs, extra


def import_reference_intervals(
    header_json: str,
) -> dict[str, list[SequenceInterval]]:
    """Parse the sequence-level header blob: {collection key:
    {type: "sharedStringIntervalCollection", value: {label, intervals,
    version}}} -> label -> [SequenceInterval].  Serialized rows are
    ``[start, end, sequenceNumber, intervalType, props]``."""
    out: dict[str, list[SequenceInterval]] = {}
    for _key, entry in json.loads(header_json).items():
        if entry.get("type") != "sharedStringIntervalCollection":
            continue
        value = entry["value"]
        ivs = []
        for row in value["intervals"]:
            start, end, _seq, _itype, props = row
            props = dict(props or {})
            interval_id = props.pop("intervalId")
            ivs.append(SequenceInterval(
                interval_id=interval_id, start=start, end=end, props=props,
            ))
        out[value["label"]] = ivs
    return out


def legacy_artifact_files() -> list[str]:
    """The reference's LEGACY-format committed snapshots (snapshotlegacy.ts
    MergeTreeChunkLegacy): snapshots/legacy and legacyWithCatchUp."""
    root = os.path.dirname(V1_SNAPSHOT_DIR)
    out = []
    for d in ("legacy", "legacyWithCatchUp"):
        out.extend(_json_files(os.path.join(root, d)))
    return out


def load_legacy_sequence_artifact(path: str):
    """Load a LEGACY-format artifact (header + optional body chunk of
    ``segmentTexts`` IJSONSegment specs, snapshotlegacy.ts) into a fresh
    oracle.  Returns (RefMergeTree, sequenceNumber, {label: intervals})."""
    from ..dds.snapshot_v1 import _spec_text_props
    from ..dds.mergetree_ref import RefMergeTree, Segment
    from ..protocol.stamps import NON_COLLAB_CLIENT, UNIVERSAL_SEQ

    blobs, extra = artifact_blobs(path)
    header = json.loads(blobs["header"])
    meta = header["headerMetadata"]
    chunks = [header]
    for entry in meta["orderedChunkMetadata"]:
        if entry["id"] != "header":
            chunks.append(json.loads(blobs[entry["id"]]))
    tree = RefMergeTree()
    for chunk in chunks:
        assert chunk["chunkSegmentCount"] == len(chunk["segmentTexts"])
        for spec in chunk["segmentTexts"]:
            text, props = _spec_text_props(spec)
            tree.segments.append(Segment(
                text=text,
                ins_key=UNIVERSAL_SEQ,
                ins_client=NON_COLLAB_CLIENT,
                props={p: (v, UNIVERSAL_SEQ) for p, v in (props or {}).items()},
            ))
    assert len(tree.segments) == meta["totalSegmentCount"]
    intervals = (
        import_reference_intervals(extra["header"]) if "header" in extra else {}
    )
    return tree, meta["sequenceNumber"], intervals


def load_sequence_artifact(
    path: str,
    get_short_client_id: Callable[[str], int] | None = None,
) -> tuple[Any, int, int, dict[str, list[SequenceInterval]]]:
    """Load one reference artifact: returns (RefMergeTree, seq, min_seq,
    {label: intervals}).  Property keys stay raw strings (the artifacts
    carry rich props: markerId, referenceTileLabels, nested objects)."""
    blobs, extra = artifact_blobs(path)
    names: list[str] = []

    def default_short(long_id: str) -> int:
        if long_id not in names:
            names.append(long_id)
        return names.index(long_id)

    tree, seq, min_seq = decode_snapshot_v1(
        blobs, get_short_client_id or default_short, prop_decoder=str
    )
    intervals = (
        import_reference_intervals(extra["header"]) if "header" in extra else {}
    )
    return tree, seq, min_seq, intervals
