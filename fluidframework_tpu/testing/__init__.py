"""Test infrastructure (SURVEY §4): the DDS fuzz harness and stochastic
utilities — the reference's @fluid-private/test-dds-utils +
stochastic-test-utils, the central convergence-correctness tooling.
"""

from .fuzz import (
    DDSFuzzModel,
    FuzzClient,
    FuzzFailure,
    run_fuzz_seed,
    run_fuzz_suite,
)

__all__ = [
    "DDSFuzzModel",
    "FuzzClient",
    "FuzzFailure",
    "run_fuzz_seed",
    "run_fuzz_suite",
]
