"""Test-facing wrapper running the REAL network plane in-process.

``NetworkTestService`` exposes the same surface tests use on
``LocalService`` (``document()`` for introspection, ``process_all()`` for
deterministic delivery), but every byte actually crosses TCP/HTTP sockets
through the nexus/alfred fronts (server/netserver.py) and the network
driver (driver/network_driver.py).  ``process_all`` maps to driver
``sync_all`` — repeated server-echoed sync markers, no sleeps.
"""

from __future__ import annotations

from ..driver.network_driver import NetworkDocumentServiceFactory
from ..server.netserver import ServicePlane


class NetworkTestService:
    def __init__(self, token_provider=None) -> None:
        self.plane = ServicePlane().start()
        self.factory = NetworkDocumentServiceFactory(
            "127.0.0.1",
            self.plane.nexus.port,
            self.plane.http.port,
            token_provider=token_provider,
        )

    # ------------------------------------------------- LocalService surface
    def document(self, doc_id: str):
        """Server-side introspection (safe once process_all has quiesced)."""
        return self.plane.service.document(doc_id)

    def process_all(self) -> int:
        return self.factory.sync_all()

    def enable_auth(self, *a, **kw):
        return self.plane.service.enable_auth(*a, **kw)

    def close(self) -> None:
        for conn in self.factory.live_connections:
            conn.disconnect()
        self.plane.stop()
