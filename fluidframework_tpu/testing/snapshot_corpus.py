"""Golden snapshot corpus: scripted documents + their pinned summaries.

Reference parity: packages/test/snapshots — a committed corpus of real
snapshot files regenerated only deliberately, so any change to a DDS's
summary layout shows up as a reviewed diff, and every supported read
format keeps loading forever.

``build_documents()`` scripts one deterministic document per DDS family;
``python -m fluidframework_tpu.testing.snapshot_corpus`` regenerates
``tests/snapshots/*.json``. The test suite asserts both directions:
1. every committed file LOADS and reproduces the recorded user state
   (backward compatibility for every committed format version), and
2. re-running the scripts yields summaries byte-identical to the current-
   format files (no accidental format drift).
"""

from __future__ import annotations

import json
import os
from typing import Any, Callable

from ..dds.channels import default_registry
from ..dds.sequence_intervals import Side
from ..runtime import ContainerRuntime
from ..runtime.snapshot_formats import current_format
from ..server.local_service import LocalService

SNAPSHOT_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "tests", "snapshots",
)


def _host(channel_type: str, name: str):
    svc = LocalService()
    doc = svc.document("corpus")
    c = ContainerRuntime(default_registry(), container_id="writer")
    ds = c.create_datastore("root")
    ch = ds.create_channel(channel_type, name)
    c.connect(doc, "writer")
    doc.process_all()
    return svc, doc, c, ch


def _settle(doc, c):
    c.flush()
    doc.process_all()


# --------------------------------------------------------------- the scripts

def _string():
    svc, doc, c, ch = _host("sharedString", "text")
    ch.insert_text(0, "hello world")
    ch.annotate_range(0, 5, "style", {"bold": True})
    coll = ch.get_interval_collection("marks")
    coll.add(0, 4, {"kind": "word"})
    coll.add((5, Side.AFTER), "end", {"kind": "sticky"})
    ch.remove_range(5, 6)
    ch.obliterate_range(0, 2)
    _settle(doc, c)
    return ch


def _map():
    svc, doc, c, ch = _host("sharedMap", "kv")
    ch.set("alpha", 1)
    ch.set("beta", {"nested": [1, 2, 3]})
    ch.set("gamma", "to-delete")
    ch.delete("gamma")
    _settle(doc, c)
    return ch


def _matrix():
    svc, doc, c, ch = _host("sharedMatrix", "grid")
    ch.insert_rows(0, 3)
    ch.insert_cols(0, 2)
    ch.set_cell(0, 0, "a")
    ch.set_cell(2, 1, 42)
    ch.remove_rows(1, 1)
    _settle(doc, c)
    return ch


def _tree():
    from ..dds.tree.changeset import make_insert, make_set_value
    from ..dds.tree.schema import leaf
    from ..utils.id_compressor import IdCompressor

    svc, doc, c, ch = _host("sharedTree", "tree")
    # Pin the compressor session so revision UUIDs (and thus the summary
    # bytes) are reproducible across regenerations.
    ch.idc = IdCompressor(session_id="00000000-0000-4000-8000-00000000c0de")
    for i, v in enumerate([10, 20, 30]):
        ch.submit_change(make_insert([], "", i, [leaf(v)]))
    ch.submit_change(make_set_value([("", 1)], 99))
    with ch.transaction():
        ch.submit_change(make_insert([], "", 3, [leaf(40)]))
    _settle(doc, c)
    return ch


def _cell():
    svc, doc, c, ch = _host("sharedCell", "cell")
    ch.set({"payload": True})
    _settle(doc, c)
    return ch


def _counter():
    svc, doc, c, ch = _host("sharedCounter", "n")
    ch.increment(5)
    ch.increment(-2)
    _settle(doc, c)
    return ch


def _directory():
    svc, doc, c, ch = _host("sharedDirectory", "dir")
    ch.set("", "topKey", 1)
    ch.create_subdirectory("sub")
    ch.set("sub", "inner", "x")
    _settle(doc, c)
    return ch


def _json_ot():
    svc, doc, c, ch = _host("sharedJsonOT", "jdoc")
    ch.replace([], {"items": [1, 2, 3], "meta": {"title": "pinned"}})
    ch.insert(["items", 1], 99)
    ch.remove(["items", 3])
    ch.replace(["meta", "title"], "golden")
    _settle(doc, c)
    return ch


SCRIPTS: dict[str, Callable[[], Any]] = {
    "sharedString": _string,
    "sharedMap": _map,
    "sharedMatrix": _matrix,
    "sharedTree": _tree,
    "sharedCell": _cell,
    "sharedCounter": _counter,
    "sharedDirectory": _directory,
    "sharedJsonOT": _json_ot,
}


# State extractors run on BOTH the scripted channel and a channel freshly
# loaded from a committed summary — the equality the corpus pins.

def extract_state(name: str, ch) -> dict:
    if name == "sharedString":
        return {
            "text": ch.text,
            "annotations": ch.annotations(),
            "intervals": sorted(
                (iv.to_json() for iv in ch.get_interval_collection("marks")),
                key=lambda d: d["id"],
            ),
        }
    if name == "sharedMap":
        return {"entries": {k: ch.get(k) for k in sorted(ch.keys())}}
    if name == "sharedMatrix":
        return {
            "rows": ch.row_count,
            "cols": ch.col_count,
            "cells": [
                [ch.get_cell(r, col) for col in range(ch.col_count)]
                for r in range(ch.row_count)
            ],
        }
    if name == "sharedTree":
        return {"forest": ch.forest.to_json()}
    if name == "sharedCell":
        return {"value": ch.get()}
    if name == "sharedCounter":
        return {"value": ch.value}
    if name == "sharedDirectory":
        return {
            "top": {k: ch.get("", k) for k in sorted(ch.keys(""))},
            "sub": {k: ch.get("sub", k) for k in sorted(ch.keys("sub"))},
        }
    if name == "sharedJsonOT":
        return {"doc": ch.get()}
    raise KeyError(name)


def build_entry(name: str) -> dict:
    ch = SCRIPTS[name]()
    return {
        "type": name,
        "format": current_format(name),
        "summary": ch.summarize(),
        "state": extract_state(name, ch),
    }


def canonical(obj) -> str:
    return json.dumps(obj, sort_keys=True, indent=1)


def regenerate() -> list[str]:
    os.makedirs(SNAPSHOT_DIR, exist_ok=True)
    written = []
    for name in SCRIPTS:
        entry = build_entry(name)
        path = os.path.join(SNAPSHOT_DIR, f"{name}.v{entry['format']}.json")
        with open(path, "w") as f:
            f.write(canonical(entry) + "\n")
        written.append(path)
    return written


if __name__ == "__main__":
    for path in regenerate():
        print(path)
