"""Model-based DDS fuzz harness with an eventual-consistency oracle.

Reference parity: @fluid-private/test-dds-utils ``DDSFuzzModel`` /
``createDDSFuzzSuite`` (packages/dds/test-dds-utils/src/ddsFuzzHarness.ts:233)
+ @fluid-private/stochastic-test-utils: a weighted generator of operations,
a reducer applying them to one of N simulated clients, built-in meta-ops
(synchronize, client add, reconnect, offline stash/rehydrate, rollback of
staged ops), convergence validation after every synchronize, seed
minification on failure, and deterministic failure replay.

A model plugs in exactly three things (ddsFuzzHarness.ts's shape):
  - ``channel_type``: which DDS to host,
  - ``generate(rng, channel)``: one weighted random op description,
  - ``reduce(channel, op)``: apply it through the channel's public API,
plus optional ``check_consistent(a, b)`` (defaults to summary equality
after synchronize).
"""

from __future__ import annotations

import random
from dataclasses import dataclass, field
from typing import Any, Callable

from ..dds.channels import default_registry
from ..runtime.container_runtime import ContainerRuntime
from ..server.local_service import LocalService


@dataclass
class DDSFuzzModel:
    name: str
    channel_type: str
    generate: Callable[[random.Random, Any], dict | None]
    reduce: Callable[[Any, dict], None]
    check_consistent: Callable[[Any, Any], None] | None = None
    # meta-op weights (ddsFuzzHarness.ts:155 defaults, simplified)
    weights: dict[str, float] = field(
        default_factory=lambda: {
            "edit": 12.0,
            "flush": 4.0,
            "synchronize": 2.0,
            "reconnect": 0.5,
            "stash": 0.25,
            "add_client": 0.25,
            "rollback": 0.25,
        }
    )


class FuzzClient:
    """One simulated client: container + the single channel under test."""

    def __init__(self, doc, name: str, channel_type: str, stash: str | None = None):
        self.name = name
        self.epoch = 0  # reconnect counter (deterministic identity minting)
        self.container = ContainerRuntime(default_registry(), container_id=name)
        ds = self.container.create_datastore("root")
        ds.create_channel(channel_type, "target")
        self.container.connect(doc, name, stash=stash)

    @property
    def channel(self):
        return self.container.datastore("root").get_channel("target")


class FuzzFailure(AssertionError):
    def __init__(self, seed: int, step: int, trace: list, cause: BaseException):
        super().__init__(
            f"fuzz seed {seed} failed at step {step}: {cause!r}\n"
            f"trace ({len(trace)} actions): {trace}"
        )
        self.seed = seed
        self.step = step
        self.trace = trace
        self.cause = cause


def _default_check(a, b) -> None:
    sa, sb = a.summarize(), b.summarize()
    assert sa == sb, f"divergence:\n  {sa}\n  {sb}"


def run_fuzz_seed(
    model: DDSFuzzModel,
    seed: int,
    steps: int = 120,
    n_clients: int = 3,
    trace: list | None = None,
    replay: bool = False,
) -> None:
    """Run one randomized schedule; raises FuzzFailure on any defect.

    When ``trace`` is given it records the executed action list (for
    minification); with ``replay=True`` the given trace is executed verbatim
    instead (deterministic failure replay, ddsFuzzHarness replay files).
    """
    rng = random.Random(seed)
    svc = LocalService()
    doc = svc.document(f"fuzz-{model.name}-{seed}")
    clients = [FuzzClient(doc, f"C{i}", model.channel_type) for i in range(n_clients)]
    doc.process_all()

    recorded: list = trace if trace is not None else []

    def pick_action(step_rng):
        kinds = list(model.weights)
        weights = [model.weights[k] for k in kinds]
        return step_rng.choices(kinds, weights=weights)[0]

    step = -1
    try:
        schedule = range(len(recorded)) if replay else range(steps)
        for step in schedule:
            if replay:
                action = recorded[step]
            else:
                kind = pick_action(rng)
                ci = rng.randrange(len(clients))
                action = {"kind": kind, "client": ci}
                if kind == "edit":
                    c = clients[ci]
                    if not c.container.has_document:
                        action = {"kind": "noop"}
                    else:
                        op = model.generate(rng, c.channel)
                        if op is None:
                            action = {"kind": "noop"}
                        else:
                            action["op"] = op
                recorded.append(action)
            _apply_action(model, action, clients, doc, rng)
        step += 1
        if not replay:
            # Epilogue: one final convergence check (a replayed trace already
            # carries its own recorded epilogue).
            recorded.append({"kind": "synchronize", "client": 0})
            _apply_action(model, recorded[-1], clients, doc, rng)
    except FuzzFailure:
        raise
    except BaseException as e:
        raise FuzzFailure(seed, step, list(recorded), e) from e


def _apply_action(model: DDSFuzzModel, action: dict, clients, doc, rng) -> None:
    kind = action["kind"]
    if kind == "noop":
        return
    c = clients[action.get("client", 0) % len(clients)]
    if kind == "edit":
        if c.container.has_document:
            model.reduce(c.channel, action["op"])
    elif kind == "flush":
        if c.container.has_document:
            c.container.flush()
    elif kind == "synchronize":
        for cl in clients:
            if cl.container.has_document:
                cl.container.flush()
        doc.process_all()
        live = [cl for cl in clients if cl.container.has_document and cl.container.joined]
        check = model.check_consistent or _default_check
        for other in live[1:]:
            check(live[0].channel, other.channel)
    elif kind == "reconnect":
        if c.container.has_document:
            c.container.disconnect()
            c.epoch += 1
            c.container.connect(doc, f"{c.name}.r{c.epoch}")
            doc.process_all()
    elif kind == "stash":
        if c.container.has_document and not c.container.closed:
            c.container.disconnect()
            stash = c.container.get_pending_local_state()
            c.container.close()
            idx = clients.index(c)
            clients[idx] = FuzzClient(
                doc, f"{c.name}.s", model.channel_type, stash=stash
            )
            doc.process_all()
    elif kind == "add_client":
        clients.append(FuzzClient(doc, f"X{len(clients)}", model.channel_type))
        doc.process_all()
    elif kind == "rollback":
        if c.container.has_document:
            try:
                c.container.rollback_staged()
            except NotImplementedError:
                pass
    else:
        raise ValueError(f"unknown fuzz action {kind!r}")


def minimize(model: DDSFuzzModel, failure: FuzzFailure) -> list:
    """Greedy trace minification (ddsFuzzHarness minification): repeatedly
    drop actions while the failure reproduces."""
    trace = list(failure.trace)

    def still_fails(candidate: list) -> bool:
        t = list(candidate)
        try:
            run_fuzz_seed(model, failure.seed, trace=t, replay=True)
            return False
        except FuzzFailure:
            return True
        except BaseException:
            return True

    changed = True
    while changed:
        changed = False
        i = 0
        while i < len(trace):
            candidate = trace[:i] + trace[i + 1 :]
            if still_fails(candidate):
                trace = candidate
                changed = True
            else:
                i += 1
    return trace


def run_fuzz_suite(
    model: DDSFuzzModel,
    seeds: range | list[int],
    steps: int = 120,
    n_clients: int = 3,
    minify: bool = True,
) -> None:
    """Run many seeds; on the first failure, minify and raise with the
    reduced trace (the suite entry point tests call)."""
    for seed in seeds:
        try:
            run_fuzz_seed(model, seed, steps=steps, n_clients=n_clients)
        except FuzzFailure as f:
            if minify:
                reduced = minimize(model, f)
                raise FuzzFailure(f.seed, f.step, reduced, f.cause) from f.cause
            raise
