"""Merge-tree snapshotV1: the REFERENCE wire format, encode and decode.

This is the interop boundary SURVEY.md §7 demands ("protocol-compatible with
the reference's wire formats ... so the reference's fuzz/replay oracles can
verify bit-identical semantics"): a summary emitted here is shaped exactly
like the TypeScript reference's merge-tree V1 snapshot
(merge-tree/src/snapshotV1.ts:42, chunk format snapshotChunks.ts:49), so a
reference client could load it, and a reference-produced V1 snapshot loads
into our oracle (mirroring snapshotLoader.ts specToSegment).

Format recap (all blob values are JSON strings):

- blob ``header``: MergeTreeChunkV1 ``{version:"1", segmentCount, length,
  segments, startIndex, headerMetadata}`` where headerMetadata =
  ``{minSequenceNumber, sequenceNumber, orderedChunkMetadata:[{id}...],
  totalLength, totalSegmentCount}`` (snapshotV1.ts:69, emit :134-189).
- blobs ``body_0``, ``body_1``, ...: same chunk shape, headerMetadata
  absent (TS ``undefined`` is dropped by JSON.stringify).
- each chunk holds segments until accumulated char length >= chunkSize
  (default 10000 chars, snapshotV1.ts:49, getSeqLengthSegs :82).
- a segment spec is either a bare IJSONSegment — a string, or
  ``{text, props}`` for annotated text (textSegment.ts toJSONObject:63) —
  or ``{json, seq?, client?, removedSeq?, removedClient?, removedClientIds?,
  movedSeq?, movedSeqs?, movedClientIds?}`` when merge info above the MSN
  must survive (snapshotChunks.ts IJSONSegmentWithMergeInfo:65).

Elision/coalescing rules mirrored from snapshotV1.ts extractSync:192:

- unacked (local) inserts are elided — a pending op will redeliver them;
- segments whose winning remove is acked at/below the MSN are elided;
- fully-below-MSN live segments drop their merge info and coalesce with a
  compatible neighbour (canAppend: no newline at the join, one side within
  the 256-char granularity — textSegment.ts:77; matching props);
- everything else records merge info: insert stamp only when above the MSN,
  set-removes as removedSeq (FIRST remove's seq for every remover — the
  reference records only that, snapshotLoader.ts:133 fakes the rest),
  slice-removes (obliterates) as movedSeqs/movedClientIds.

Like the reference, the V1 format does NOT carry the in-window obliterate
anchor table or annotate LWW stamps: a replica loaded from V1 can converge
forward from the snapshot seq but cannot re-arbitrate races older than it
(reference TODO AB#32299 documents the same loss).
"""

from __future__ import annotations

import json
from typing import Any, Callable

from ..protocol.stamps import NON_COLLAB_CLIENT, NO_REMOVE, UNIVERSAL_SEQ, acked
from .markers import (
    assert_no_marker_plane,
    is_marker_text,
    marker_char,
    marker_ref_type,
)
from .mergetree_ref import RefMergeTree, Segment

CHUNK_SIZE = 10000          # chars per chunk (snapshotV1.ts:49)
TEXT_GRANULARITY = 256      # coalescing size gate (textSegment.ts:21)
HEADER_BLOB = "header"      # snapshotlegacy.ts:45
BODY_BLOB = "body"          # snapshotlegacy.ts:46


def _can_append(a_text: str, b_text: str) -> bool:
    """textSegment.ts canAppend:77 — no newline at the join point, and at
    least one side within the granularity.  Markers NEVER coalesce
    (Marker.canAppend is constant false, mergeTreeNodes.ts:495)."""
    if is_marker_text(a_text) or is_marker_text(b_text):
        return False
    return not a_text.endswith("\n") and (
        len(a_text) <= TEXT_GRANULARITY or len(b_text) <= TEXT_GRANULARITY
    )


def _props_json(seg: Segment) -> dict[str, Any] | None:
    """Segment props as reference PropertySet JSON (values only — V1 drops
    the LWW stamps, matching toJSONObject)."""
    if not seg.props:
        return None
    return {str(p): v for p, (v, _key) in sorted(seg.props.items())}


def _json_segment(text: str, props: dict[str, Any] | None) -> Any:
    """IJSONSegment: bare string, {text, props} when annotated, or
    {marker: {refType}, props} for a marker segment (marker/textSegment
    toJSONObject)."""
    if is_marker_text(text):
        out: dict[str, Any] = {"marker": {"refType": marker_ref_type(text)}}
        if props:
            out["props"] = props
        return out
    return {"text": text, "props": props} if props else text


def _spec_text_props(j: Any) -> tuple[str, dict[str, Any] | None]:
    """Inverse of _json_segment (snapshotLoader.ts specToSegment:107).
    Decode boundary for the reserved marker plane: only marker specs may
    produce U+E000..U+F8FF codepoints — a snapshot artifact smuggling them
    as 'text' is rejected, matching the op-apply boundary."""
    if isinstance(j, str):
        assert_no_marker_plane(j)
        return j, None
    if "marker" in j:
        return marker_char(j["marker"]["refType"]), j.get("props")
    assert_no_marker_plane(j["text"])
    return j["text"], j.get("props")


def encode_snapshot_v1(
    tree: RefMergeTree,
    seq: int,
    get_long_client_id: Callable[[int], str],
    chunk_size: int = CHUNK_SIZE,
    attribution: bool = False,
) -> dict[str, str]:
    """Emit the reference V1 snapshot blobs for a merge-tree replica.

    ``seq`` is the collab window's current sequence number (the reference
    reads it off mergeTree.collabWindow, snapshotV1.ts:68).  Returns
    {blob name: JSON string} exactly as SnapshotV1.emit writes them.

    With ``attribution`` on, every chunk carries the reference's
    SerializedAttributionCollection (``{seqs, posBreakpoints, length}``,
    attributionCollection.ts:465): run-length insert attribution across the
    chunk's segments, so who-wrote-what survives the below-MSN coalescing
    that strips insert stamps.
    """
    min_seq = tree.min_seq
    slice_keys = tree.slice_keys | {ob.key for ob in tree.obliterates}

    # ---- extractSync: elide / coalesce / record merge info ----------------
    specs: list[Any] = []
    lengths: list[int] = []
    attrs: list[list[tuple[int, Any]]] = []  # per-spec attribution runs

    def push(spec: Any, length: int, runs: list[tuple[int, Any]]) -> None:
        specs.append(spec)
        lengths.append(length)
        attrs.append(runs)

    prev: Segment | None = None  # coalescing candidate (below-MSN run)
    prev_attr: list[tuple[int, Any]] = []

    def flush_prev() -> None:
        nonlocal prev
        if prev is not None:
            push(
                _json_segment(prev.text, _props_json(prev)),
                len(prev.text),
                list(prev_attr),
            )
            prev = None

    for seg in tree.segments:
        if not acked(seg.ins_key):
            continue  # (a) pending insert redelivers on reconnect
        win_rem = seg.removes[0][0] if seg.removes else NO_REMOVE
        if seg.removes and acked(win_rem) and win_rem <= min_seq:
            continue  # (b) removed at/below MSN: unreferenceable

        below_msn = seg.ins_key <= min_seq and (
            not seg.removes or not acked(seg.removes[0][0])
        )
        if below_msn:
            # Coalesce with the previous below-MSN segment when compatible;
            # attribution runs concatenate across the join so the merged
            # spec keeps exact per-char provenance.
            if prev is None:
                prev, prev_attr = seg, list(seg.attr_runs())
            elif _can_append(prev.text, seg.text) and _props_json(prev) == _props_json(seg):
                base = len(prev.text)
                for off, key in seg.attr_runs():
                    if not prev_attr or prev_attr[-1][1] != key:
                        prev_attr.append((base + off, key))
                prev = Segment(
                    text=prev.text + seg.text,
                    ins_key=prev.ins_key,
                    ins_client=prev.ins_client,
                    props=dict(prev.props),
                )
            else:
                flush_prev()
                prev, prev_attr = seg, list(seg.attr_runs())
            continue

        flush_prev()
        raw: dict[str, Any] = {
            "json": _json_segment(seg.text, _props_json(seg))
        }
        if seg.ins_key > min_seq:
            raw["seq"] = seg.ins_key
            raw["client"] = get_long_client_id(seg.ins_client)
        set_removes = [
            (k, c) for k, c in seg.removes
            if acked(k) and k not in slice_keys
        ]
        if set_removes:
            raw["removedSeq"] = set_removes[0][0]
            # Vestigial singular field kept for <=0.58 loaders
            # (snapshotV1.ts:308-311).
            raw["removedClient"] = get_long_client_id(set_removes[0][1])
            raw["removedClientIds"] = [
                get_long_client_id(c) for _k, c in set_removes
            ]
        slice_removes = [
            (k, c) for k, c in seg.removes if acked(k) and k in slice_keys
        ]
        if slice_removes:
            raw["movedSeq"] = slice_removes[0][0]
            raw["movedSeqs"] = [k for k, _c in slice_removes]
            raw["movedClientIds"] = [
                get_long_client_id(c) for _k, c in slice_removes
            ]
        assert (
            "seq" in raw or "removedSeq" in raw or "movedSeq" in raw
        ), "corrupted preservation of segment metadata (ref assert 0x066)"
        push(raw, len(seg.text), list(seg.attr_runs()))
    flush_prev()

    # ---- chunking + blob emission (emit :134) -----------------------------
    chunks: list[dict[str, Any]] = []
    start = 0
    while start < len(specs) or not chunks:
        count = 0
        length = 0
        while length < chunk_size and start + count < len(specs):
            length += lengths[start + count]
            count += 1
        chunk: dict[str, Any] = {
            "version": "1",
            "segmentCount": count,
            "length": length,
            "segments": specs[start : start + count],
            "startIndex": start,
        }
        if attribution:
            chunk["attribution"] = _serialize_attribution(
                attrs[start : start + count], lengths[start : start + count]
            )
        chunks.append(chunk)
        start += count

    header = chunks[0]
    ordered = [{"id": HEADER_BLOB}] + [
        {"id": f"{BODY_BLOB}_{i}"} for i in range(len(chunks) - 1)
    ]
    header["headerMetadata"] = {
        "minSequenceNumber": min_seq,
        "sequenceNumber": seq,
        "orderedChunkMetadata": ordered,
        "totalLength": sum(lengths),
        "totalSegmentCount": len(specs),
    }
    blobs = {HEADER_BLOB: json.dumps(header, separators=(",", ":"))}
    for i, chunk in enumerate(chunks[1:]):
        blobs[f"{BODY_BLOB}_{i}"] = json.dumps(chunk, separators=(",", ":"))
    return blobs


def _serialize_attribution(
    attrs: list[list[tuple[int, Any]]], lengths: list[int]
) -> dict[str, Any]:
    """Reference extractSequenceOffsets (attributionCollection.ts:465):
    collapse per-segment runs into chunk-wide parallel arrays, merging
    consecutive equal keys across segment boundaries.  Local keys never
    reach a summary (ref assert 0x5c1)."""
    pos_breakpoints: list[int] = []
    seqs: list[Any] = []
    _SENTINEL = object()
    last: Any = _SENTINEL
    cum = 0
    for runs, length in zip(attrs, lengths):
        for off, key in runs:
            assert not (isinstance(key, dict) and key.get("type") == "local"), (
                "local attribution keys should never be put in summaries"
            )
            if last is _SENTINEL or key != last:
                pos_breakpoints.append(cum + off)
                seqs.append(key)
            last = key
        cum += length
    return {"seqs": seqs, "posBreakpoints": pos_breakpoints, "length": cum}


def _populate_attribution(
    segments: list[Segment], serialized: dict[str, Any], lengths: list[int]
) -> None:
    """Reference populateAttributionCollections (attributionCollection.ts:389):
    slice the chunk-wide runs back onto each segment as override runs."""
    bps = serialized["posBreakpoints"]
    seqs = serialized["seqs"]
    cum = 0
    i = 0
    for seg, length in zip(segments, lengths):
        runs: list[tuple[int, Any]] = []
        # Run in effect at the segment's start.
        while i + 1 < len(bps) and bps[i + 1] <= cum:
            i += 1
        j = i
        while j < len(bps) and bps[j] < cum + length:
            runs.append((max(bps[j] - cum, 0), seqs[j]))
            j += 1
        seg.attr = runs
        cum += length
    assert cum == serialized["length"], "attribution length mismatch"


def decode_snapshot_v1(
    blobs: dict[str, str],
    get_short_client_id: Callable[[str], int],
    prop_decoder: Callable[[str], int] = int,
) -> tuple[RefMergeTree, int, int]:
    """Load V1 snapshot blobs into a fresh oracle replica.

    Mirrors snapshotLoader.ts specToSegment:107: merge-info-free specs get
    the universal insert stamp (NonCollabClient), set-removes all share the
    recorded first removedSeq (the reference's own data loss, loader :133),
    slice-removes restore their individual seqs.  Returns
    (tree, sequenceNumber, minSequenceNumber).
    """
    header = json.loads(blobs[HEADER_BLOB])
    meta = header["headerMetadata"]
    chunks = [header]
    for entry in meta["orderedChunkMetadata"]:
        if entry["id"] != HEADER_BLOB:
            chunks.append(json.loads(blobs[entry["id"]]))

    tree = RefMergeTree()
    tree.min_seq = meta["minSequenceNumber"]
    slice_keys: set[int] = set()
    for chunk in chunks:
        chunk_segs: list[Segment] = []
        for spec in chunk["segments"]:
            if isinstance(spec, dict) and "json" in spec:
                text, props = _spec_text_props(spec["json"])
                ins_seq = spec.get("seq", UNIVERSAL_SEQ)
                client = (
                    get_short_client_id(spec["client"])
                    if "client" in spec
                    else NON_COLLAB_CLIENT
                )
                removes: list[tuple[int, int]] = []
                if "removedSeq" in spec:
                    ids = spec.get("removedClientIds")
                    if ids is None:  # pre-split singular form (loader :128)
                        ids = [spec["removedClient"]]
                    removes += [
                        (spec["removedSeq"], get_short_client_id(i))
                        for i in ids
                    ]
                if "movedSeq" in spec:
                    for k, c in zip(spec["movedSeqs"], spec["movedClientIds"]):
                        removes.append((k, get_short_client_id(c)))
                        slice_keys.add(k)
                removes.sort()
            else:
                text, props = _spec_text_props(spec)
                ins_seq, client, removes = UNIVERSAL_SEQ, NON_COLLAB_CLIENT, []
            chunk_segs.append(Segment(
                text=text,
                ins_key=ins_seq,
                ins_client=client,
                removes=removes,
                props={
                    prop_decoder(p): (v, UNIVERSAL_SEQ)
                    for p, v in (props or {}).items()
                },
            ))
        if "attribution" in chunk:
            _populate_attribution(
                chunk_segs, chunk["attribution"],
                [len(s.text) for s in chunk_segs],
            )
        tree.segments.extend(chunk_segs)
    # Like the reference loader, the obliterates collection itself is NOT
    # rebuilt (snapshotLoader.ts creates only the removes stamps): the
    # slice stamps keep visibility exact, but the swallow window for
    # not-yet-seen concurrent inserts is lost with the anchors — the
    # documented V1 limitation (TODO AB#32299).
    tree.slice_keys = slice_keys
    return tree, meta["sequenceNumber"], meta["minSequenceNumber"]
