"""Operational-transformation DDSes: SharedOT base + a JSON OT type.

Reference parity: the experimental OT family —
``SharedOT`` (experimental/dds/ot/ot/src/ot.ts) keeps a window of sequenced
ops above the MSN and integrates each arrival by TRANSFORMING it over every
sequenced op its sender hadn't seen, then transforms the local pending
queue over it; ``SharedJson1`` (experimental/dds/ot/sharejs/json1/src/
json1.ts) instantiates it with the ot-json1 type.  This is the OTHER merge
model the reference ships beside its CRDTs: state is a plain value, ops
carry intentions, and convergence comes from the transform function's TP1
property rather than from commutative stamps.

``SharedJsonOTChannel`` implements a from-scratch JSON OT type (not a port
of ot-json1): ops are path-addressed ``insert``/``remove``/``replace`` with
list-index transformation (earlier-sequenced sibling inserts/removes shift
later indices; "left" priority for same-index insert ties), subtree-drop
semantics (an op into a concurrently removed or replaced subtree becomes a
no-op), and last-writer-wins for same-path replaces.
"""

from __future__ import annotations

import json
from collections import deque
from typing import Any

from ..protocol.channel import Channel, MessageCollection


class SharedOTChannel(Channel):
    """Generic OT channel (ref ot.ts SharedOT): subclasses define
    ``apply_core(state, op) -> state`` and ``transform(input, earlier)``.

    ``None`` is the universal no-op (a transform may annihilate an op)."""

    def __init__(self, channel_id: str, initial: Any = None) -> None:
        super().__init__(channel_id)
        self._global = initial       # result of all sequenced ops
        # Sequenced ops above the MSN: (seq, client, op) — the transform
        # window (ot.ts sequencedOps).
        self._sequenced: deque[tuple[int, str, Any]] = deque()
        # Local pending ops, continuously transformed over arrivals:
        # (local id, current op form).
        self._pending: list[tuple[int, Any]] = []
        self._next_lid = 0

    # ------------------------------------------------------------- OT type
    def apply_core(self, state: Any, op: Any) -> Any:
        raise NotImplementedError

    def transform(self, input_op: Any, earlier: Any) -> Any:
        raise NotImplementedError

    # --------------------------------------------------------------- local
    @property
    def state(self) -> Any:
        """The optimistic local view: global + pending (ot.ts this.local)."""
        s = self._global
        for _lid, op in self._pending:
            if op is not None:
                s = self.apply_core(s, op)
        return s

    def apply(self, op: Any) -> None:
        lid = self._next_lid
        self._next_lid += 1
        self._pending.append((lid, op))
        self.submit_local_message({"op": op}, {"lid": lid})

    # --------------------------------------------------------------- inbound
    def process_messages(self, collection: MessageCollection) -> None:
        env = collection.envelope
        while self._sequenced and self._sequenced[0][0] < env.min_seq:
            self._sequenced.popleft()
        for m in collection.messages:
            op = m.contents["op"]
            # Adjust for sequenced ops the sender hadn't seen (ot.ts:134).
            for seq, client, prior in self._sequenced:
                if env.ref_seq < seq and client != env.client_id:
                    op = self.transform(op, prior)
            self._sequenced.append((env.seq, env.client_id, op))
            if op is not None:
                self._global = self.apply_core(self._global, op)
            if m.local:
                self._pending.pop(0)
            else:
                self._pending = [
                    (lid, self.transform(p, op) if p is not None else None)
                    for lid, p in self._pending
                ]

    # ---------------------------------------------------- reconnect / stash
    def resubmit(self, contents: Any, local_metadata: Any, squash: bool = False) -> None:
        """Re-stage the CURRENT (continuously transformed) form of the
        pending op — the OT analog of regeneratePendingOp."""
        lid = local_metadata["lid"]
        for got_lid, op in self._pending:
            if got_lid == lid:
                self.submit_local_message({"op": op}, {"lid": lid})
                return
        raise KeyError(f"resubmit for unknown pending op lid={lid}")

    def apply_stashed(self, contents: Any) -> Any:
        lid = self._next_lid
        self._next_lid += 1
        self._pending.append((lid, contents["op"]))
        return {"lid": lid}

    def on_min_seq(self, min_seq: int) -> None:
        while self._sequenced and self._sequenced[0][0] < min_seq:
            self._sequenced.popleft()

    # ------------------------------------------------------------ checkpoint
    def summarize(self) -> dict[str, Any]:
        if self._pending:
            raise RuntimeError("summarize with pending OT ops")
        return {
            "state": self._global,
            "window": [[s, c, op] for s, c, op in self._sequenced],
        }

    def load(self, summary: dict[str, Any]) -> None:
        self._global = summary["state"]
        self._sequenced = deque(
            (s, c, op) for s, c, op in summary.get("window", [])
        )


# ---------------------------------------------------------------------------
# JSON OT type
# ---------------------------------------------------------------------------


def _apply_json(state: Any, op: dict) -> Any:
    """Functional apply: fresh containers along the op's path only."""
    t, path = op["t"], op["p"]

    def walk(node: Any, depth: int) -> Any:
        if depth == len(path) - 1:
            key = path[depth]
            if isinstance(node, list):
                out = list(node)
                if t == "insert":
                    out.insert(key, op["v"])
                elif t == "remove":
                    del out[key]
                else:
                    out[key] = op["v"]
                return out
            out = dict(node)
            if t == "insert" or t == "replace":
                out[key] = op["v"]
            else:
                del out[key]
            return out
        key = path[depth]
        if isinstance(node, list):
            out = list(node)
        else:
            out = dict(node)
        out[key] = walk(out[key], depth + 1)
        return out

    if not path:  # whole-document replace
        return op["v"] if t != "remove" else None
    return walk(state, 0)


def _transform_json(input_op: dict | None, earlier: dict | None) -> dict | None:
    """Transform ``input_op`` to account for ``earlier`` (applied first).

    - earlier REMOVE/REPLACE of a subtree annihilates ops into it (an
      insert at exactly a removed list slot survives — it names a gap, not
      the removed element);
    - earlier list insert/remove at a shared parent shifts later sibling
      indices, with "left" priority for same-index insert ties;
    - same-path replaces: the later-sequenced op wins by applying after.
    """
    if input_op is None or earlier is None:
        return input_op
    ip = list(input_op["p"])
    ep = earlier["p"]
    et, it = earlier["t"], input_op["t"]

    # Subtree annihilation.
    if len(ep) <= len(ip) and ip[: len(ep)] == ep:
        into_subtree = len(ip) > len(ep)
        same_target = len(ip) == len(ep)
        if et == "remove":
            if into_subtree or (same_target and it != "insert"):
                return None
        elif et == "replace" and into_subtree:
            return None
        # (Object-key insert vs a same-key target needs no adjustment: the
        # later-sequenced op simply applies after — LWW by order.)

    # List-index shifts at earlier's parent level.
    if ep and isinstance(ep[-1], int):
        k = len(ep) - 1
        if len(ip) > k and ip[:k] == ep[:k] and isinstance(ip[k], int):
            if et == "insert":
                # Earlier insert at/below the index shifts input right —
                # including the insert-insert tie, where the earlier op
                # keeps "left" and input lands after it.
                if ep[k] <= ip[k]:
                    ip[k] += 1
            elif et == "remove":
                if ep[k] < ip[k]:
                    ip[k] -= 1
    out = dict(input_op)
    out["p"] = ip
    return out


class SharedJsonOTChannel(SharedOTChannel):
    """JSON document over OT (ref SharedJson1 over ot-json1)."""

    channel_type = "sharedJsonOT"

    def __init__(self, channel_id: str) -> None:
        super().__init__(channel_id, initial=None)

    # ------------------------------------------------------------- OT type
    def apply_core(self, state: Any, op: dict) -> Any:
        return _apply_json(state, op)

    def transform(self, input_op, earlier):
        return _transform_json(input_op, earlier)

    # ----------------------------------------------------------- public API
    def get(self) -> Any:
        return self.state

    def at(self, path: list) -> Any:
        node = self.state
        for part in path:
            node = node[part]
        return node

    def insert(self, path: list, value: Any) -> None:
        json.dumps(value)  # wire-serializable guard
        self.apply({"t": "insert", "p": list(path), "v": value})

    def remove(self, path: list) -> None:
        self.apply({"t": "remove", "p": list(path)})

    def replace(self, path: list, value: Any) -> None:
        json.dumps(value)
        self.apply({"t": "replace", "p": list(path), "v": value})


class _JsonOTFactory:
    channel_type = SharedJsonOTChannel.channel_type

    def create(self, channel_id: str) -> SharedJsonOTChannel:
        return SharedJsonOTChannel(channel_id)


SharedJsonOTFactory = _JsonOTFactory()
