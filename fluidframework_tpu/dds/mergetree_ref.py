"""Pure-Python merge-tree oracle with reference-exact convergence semantics.

This is the differential-testing contract for the TPU kernel
(``fluidframework_tpu.ops.mergetree_kernel``): a flat list-of-segments
implementation of the reference's merge-tree CRDT, behaviorally equivalent to
merge-tree/src/mergeTree.ts on the op-application path but with none of the
B-tree machinery (the B-tree + PartialSequenceLengths exist only to make CPU
queries O(log n); a flat walk is the clearest statement of the semantics).

Semantics captured (studied from the reference, re-implemented):

- **Visibility** (perspective.ts ``PriorPerspective``): a segment is present
  from perspective ``(refSeq, viewClient)`` iff its insert has occurred
  (acked with seq <= refSeq, or issued by viewClient) and no remove on it has
  occurred.

- **Insert walk + tie-break** (mergeTree.ts ``insertRecursive`` /
  ``breakTie:1811``): an insert at position P walks segments left-to-right
  consuming perspective-visible length.  Landing mid-segment splits it.
  Landing on a boundary, the insert skips past invisible segments UNLESS the
  incoming stamp is greater than the segment's insert stamp (so among
  concurrent inserts at one position, later-sequenced ops sit closer to the
  front, and local unacked segments — which outrank every acked stamp — stay
  in front of incoming remote inserts), or the segment was removed by an
  acked remove stamped after the incoming insert (reconnect rebase case).

- **Set-remove** (mergeTree.ts ``markRangeRemoved:2292``): removes exactly
  the perspective-visible segments in [P1, P2), splitting boundary segments;
  overlapping removes keep the earliest stamp as the winner (removes[0]).

- **Annotate** (mergeTree.ts ``annotateRange:2009`` + PropertiesManager):
  per-(segment, key) last-writer-wins by stamp order; a pending local
  annotate outranks (masks) every acked one until acked itself.

- **Ack** (client.ts ``ackPendingSegment``): the originating client converts
  pending stamps (localSeq) to acked stamps (seq) when its own op returns.

- **Zamboni** (zamboni.ts:33): segments whose winning remove is acked at or
  below the MSN are unreferenceable from every legal perspective and are
  evicted.

Overlapping removes: the FULL list of remove stamps is retained per segment
(reference ``seg.removes``, kept stamp-sorted).  This is required for
correctness, not just attribution: a segment must be invisible to any
perspective whose client is among the removers, even when the *winning*
(earliest) remove is outside that perspective's refSeq
(perspective.ts ``isSegmentPresent``: ``removes.some(hasOccurred)``).
The TPU kernel carries a fixed number of remover slots per segment with
overflow detection for the same reason.
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace
from typing import Any

from ..protocol.stamps import (
    ALL_ACKED,
    NO_REMOVE,
    acked,
    encode_stamp,
    has_occurred,
)

# Endpoint sidedness for obliterate ranges (ref sequencePlace.ts Side).
SIDE_BEFORE = 0
SIDE_AFTER = 1


def attribution_key_at(runs: list, pos: int) -> Any:
    """The run key in effect at ``pos`` (shared by both backends — the walk
    of reference attributionCollection.ts findIndex:258)."""
    key = runs[0][1]
    for start, k in runs:
        if start > pos:
            break
        key = k
    return key


@dataclass
class Obliterate:
    """One obliterate in the collab window (ref mergeTreeNodes.ts
    ObliterateInfo): stamp + boundary anchors.  Anchors are the segments
    CONTAINING the endpoint characters (the reference's StayOnRemove local
    references, mergeTree.ts:2100-2126); on split an anchor follows the half
    holding its character — first char for Before sides, last char for After
    sides — which makes the reference's ordinal-window overlap test
    (Obliterates.findOverlapping, mergeTree.ts:566) a plain index-window
    test over the flat segment list."""

    key: int          # stamp key (acked seq, or LOCAL_BASE+localSeq pending)
    client: int
    start_seg: "Segment | None"   # None = boundary past the end of content
    start_side: int
    end_seg: "Segment | None"
    end_side: int
    ref_seq: int


@dataclass
class Segment:
    """One run of text plus its operation stamps (columnar tuple on TPU)."""

    text: str
    ins_key: int
    ins_client: int
    # Overlapping remove stamps as (key, client), sorted by key; the first
    # entry is the winning (earliest) remove — reference seg.removes[0].
    # Obliterate stamps live in the same list (visibility is identical);
    # which stamps are slice-removes is recoverable from the Obliterates set.
    removes: list[tuple[int, int]] = field(default_factory=list)
    # prop id -> (value, stamp key of the write that set it)
    props: dict[int, tuple[int, int]] = field(default_factory=dict)
    # Newest concurrent obliterate overlapping this segment's insertion point
    # at insert time (ref ISegmentInsideObliterateInfo
    # .obliteratePrecedingInsertion) — drives the last-obliterater-wins
    # tiebreak when later obliterates consider marking this segment.
    ob_preceding: "Obliterate | None" = None
    # Attribution override runs [(start offset, key)] — set only when the
    # segment was loaded from a snapshot that universalized its insert stamp
    # (ref attributionCollection.ts:63: per-segment AttributionCollection
    # populated from the summary's SequenceOffsets).  Keys: int = op seq,
    # dict = detached key; None = unattributed.  When absent, attribution
    # derives from the live insert stamp (attr_runs below).
    attr: "list[tuple[int, Any]] | None" = None

    @property
    def rem_key(self) -> int:
        return self.removes[0][0] if self.removes else NO_REMOVE

    def attr_runs(self) -> list[tuple[int, Any]]:
        """Attribution runs [(start offset, key)] for this segment's chars.

        Live segments attribute to their insert stamp (int seq when acked,
        the ``{"type": "local"}`` key while pending — reference
        attributionCollection local keys); snapshot-loaded segments use the
        recorded override runs."""
        if self.attr is not None:
            return self.attr
        if acked(self.ins_key):
            return [(0, self.ins_key)]
        return [(0, {"type": "local"})]

    def visible(self, ref_seq: int, view_client: int) -> bool:
        if not has_occurred(self.ins_key, self.ins_client, ref_seq, view_client):
            return False
        return not any(
            has_occurred(key, client, ref_seq, view_client)
            for key, client in self.removes
        )


class RefMergeTree:
    """Flat-array merge-tree replica for one document."""

    def __init__(self, local_client: int = -3) -> None:
        self.segments: list[Segment] = []
        self.local_client = local_client
        self.min_seq = 0
        # Obliterates inside the collab window (ref MergeTree.obliterates).
        self.obliterates: list[Obliterate] = []
        # Every stamp key ever applied by an obliterate — outlives the
        # window record so snapshotV1 encode can tell slice-removes from
        # set-removes (the reference keeps the type on the stamp itself,
        # stamps.ts RemoveOperationStamp.type).
        self.slice_keys: set[int] = set()
        # Stamp keys minted by regenerate_pending during a reconnect replay.
        # When regenerating a LATER pending op, segments carrying these keys
        # must count as "will be sequenced before it" even though the fresh
        # keys are numerically larger than the op's own old key (replay
        # re-stamps in pending order, so fresh keys of earlier ops exceed
        # every original pending key).
        self._regenerated_keys: set[int] = set()

    # ------------------------------------------------------------------ views
    def visible_text(
        self,
        ref_seq: int = ALL_ACKED,
        view_client: int | None = None,
        raw: bool = False,
    ) -> str:
        """Perspective text — EXCLUDES markers (ref getText gathers only
        TextSegments); they still occupy positions (visible_length).
        ``raw=True`` keeps marker codepoints, yielding a string whose
        indices ARE positions (len == visible_length) for position-space
        slicing (undo capture)."""
        from .markers import strip_markers

        vc = self.local_client if view_client is None else view_client
        if raw:
            return "".join(
                s.text for s in self.segments if s.visible(ref_seq, vc)
            )
        return "".join(
            strip_markers(s.text) for s in self.segments if s.visible(ref_seq, vc)
        )

    def marker_scan(
        self, ref_seq: int = ALL_ACKED, view_client: int | None = None
    ) -> list[tuple[int, int, dict]]:
        """Visible markers as (position, refType, {prop_id: value_id}) —
        the host query surface behind getMarkerFromId / searchForMarker
        (ref mergeTreeNodes.ts Marker, sharedString.ts:42)."""
        from .markers import is_marker_text, marker_ref_type

        vc = self.local_client if view_client is None else view_client
        out: list[tuple[int, int, dict]] = []
        pos = 0
        for s in self.segments:
            if not s.visible(ref_seq, vc):
                continue
            if is_marker_text(s.text):
                out.append((
                    pos,
                    marker_ref_type(s.text),
                    {p: v for p, (v, _k) in s.props.items()},
                ))
            pos += len(s.text)
        return out

    def visible_length(self, ref_seq: int = ALL_ACKED, view_client: int | None = None) -> int:
        vc = self.local_client if view_client is None else view_client
        return sum(len(s.text) for s in self.segments if s.visible(ref_seq, vc))

    def annotations(self, ref_seq: int = ALL_ACKED, view_client: int | None = None) -> list[dict[int, int]]:
        """Per visible character: {prop_id: value} (for differential tests)."""
        vc = self.local_client if view_client is None else view_client
        out: list[dict[int, int]] = []
        for s in self.segments:
            if s.visible(ref_seq, vc):
                props = {k: v for k, (v, _key) in sorted(s.props.items())}
                out.extend(props for _ in s.text)
        return out

    def attribution_runs(
        self, ref_seq: int = ALL_ACKED, view_client: int | None = None
    ) -> list[tuple[int, Any]]:
        """Run-length attribution over the visible text: [(start, key)].

        Keys are int op seqs, ``{"type": "local"}`` for pending content, or
        snapshot-recorded override keys (ref attributionCollection.ts
        getKeysInOffsetRange; the merged-run collapse matches its
        serializer, attributionCollection.ts:465)."""
        vc = self.local_client if view_client is None else view_client
        runs: list[tuple[int, Any]] = []
        pos = 0
        for seg in self.segments:
            if not seg.visible(ref_seq, vc):
                continue
            for off, key in seg.attr_runs():
                if not runs or runs[-1][1] != key:
                    runs.append((pos + off, key))
            pos += len(seg.text)
        return runs

    def attribution_at(
        self, pos: int, ref_seq: int = ALL_ACKED, view_client: int | None = None
    ) -> Any:
        """Attribution key for the visible character at ``pos``
        (ref attributionCollection.ts getAtOffset)."""
        vc = self.local_client if view_client is None else view_client
        if not 0 <= pos < self.visible_length(ref_seq, vc):
            raise ValueError(f"attribution offset {pos} out of range")
        return attribution_key_at(self.attribution_runs(ref_seq, vc), pos)

    # ------------------------------------------------------------- primitives
    def _split(self, i: int, offset: int) -> None:
        """Split segment i at text offset, preserving all stamps (ref split)."""
        seg = self.segments[i]
        assert 0 < offset < len(seg.text)
        attr_l = attr_r = None
        if seg.attr is not None:
            attr_l = [(o, k) for o, k in seg.attr if o < offset]
            attr_r = [(o - offset, k) for o, k in seg.attr if o >= offset]
            if not attr_r or attr_r[0][0] > 0:
                # The run containing the split point continues into the
                # right half (reference AttributionCollection.splitAt).
                attr_r.insert(0, (0, attr_l[-1][1]))
        left = replace(
            seg, text=seg.text[:offset], removes=list(seg.removes),
            props=dict(seg.props), attr=attr_l,
        )
        right = replace(
            seg, text=seg.text[offset:], removes=list(seg.removes),
            props=dict(seg.props), attr=attr_r,
        )
        self.segments[i : i + 1] = [left, right]
        # Obliterate anchors follow the half holding their endpoint char:
        # Before sides sit on the segment's first char (left half), After
        # sides on its last char (right half).
        for ob in self.obliterates:
            if ob.start_seg is seg:
                ob.start_seg = left if ob.start_side == SIDE_BEFORE else right
            if ob.end_seg is seg:
                ob.end_seg = left if ob.end_side == SIDE_BEFORE else right

    def _tiebreak(self, seg: Segment, op_key: int) -> bool:
        """mergeTree.ts breakTie leaf case (pos == 0, invisible segment).

        Equal keys (>=) win the tie: they arise only from ops grouped in one
        batch, where the issuer already placed the later op's segment in
        front under its (strictly larger) localSeq stamp — remotes must
        agree after ack collapses the batch onto one sequence number."""
        if op_key >= seg.ins_key:
            return True
        return (
            bool(seg.removes)
            and acked(seg.removes[0][0])
            and seg.removes[0][0] > op_key
        )

    def _find_insert_index(
        self, pos: int, op_key: int, ref_seq: int, view_client: int
    ) -> int:
        """Replicates the inserting walk; may split a segment. Returns index
        at which to insert the new segment into ``self.segments``."""
        rem = pos
        i = 0
        while i < len(self.segments):
            seg = self.segments[i]
            vlen = len(seg.text) if seg.visible(ref_seq, view_client) else 0
            if rem < vlen:
                if rem == 0:
                    return i
                self._split(i, rem)
                return i + 1
            if rem == 0 and vlen == 0 and self._tiebreak(seg, op_key):
                return i
            rem -= vlen
            i += 1
        if rem != 0:
            raise ValueError(f"insert position {pos} beyond visible length")
        return len(self.segments)

    def _range_indices(
        self, pos1: int, pos2: int, ref_seq: int, view_client: int
    ) -> list[int]:
        """Split boundaries and return indices of perspective-visible segments
        wholly inside [pos1, pos2)."""
        assert pos1 <= pos2
        out: list[int] = []
        covered = 0
        i = 0
        while i < len(self.segments) and covered < pos2:
            seg = self.segments[i]
            if not seg.visible(ref_seq, view_client):
                i += 1
                continue
            seg_end = covered + len(seg.text)
            if seg_end <= pos1:
                covered = seg_end
                i += 1
                continue
            if covered < pos1:
                # Split off the prefix before the range.
                self._split(i, pos1 - covered)
                covered = pos1
                i += 1
                continue
            if seg_end > pos2:
                # Split off the suffix after the range.
                self._split(i, pos2 - covered)
                seg_end = pos2
            out.append(i)
            covered = seg_end
            i += 1
        if covered < pos2:
            raise ValueError(f"range [{pos1},{pos2}) beyond visible length")
        return out

    # -------------------------------------------------------------------- ops
    def apply_insert(
        self,
        pos: int,
        text: str,
        op_key: int,
        op_client: int,
        ref_seq: int,
    ) -> Segment:
        idx = self._find_insert_index(pos, op_key, ref_seq, op_client)
        seg = Segment(text=text, ins_key=op_key, ins_client=op_client)
        if self.obliterates:
            self._obliterate_on_insert(seg, idx, op_key, op_client, ref_seq)
        self.segments.insert(idx, seg)
        return seg

    def _obliterate_on_insert(
        self, seg: Segment, idx: int, op_key: int, op_client: int, ref_seq: int
    ) -> None:
        """Mark a just-placed segment removed when it lands inside an
        obliterated range the inserter had not seen (ref mergeTree.ts
        blockInsert obliterate handling, :1647-1745, incl. the
        last-obliterater-gets-to-insert tiebreak)."""
        index_of = {id(s): i for i, s in enumerate(self.segments)}
        concurrent: list[Obliterate] = []
        for ob in self.obliterates:
            if ob.start_seg is None or ob.end_seg is None:
                continue
            s_i = index_of[id(ob.start_seg)]
            e_i = index_of[id(ob.end_seg)]
            # New segment will sit at idx: inside the anchor window iff it
            # lands strictly after the start anchor and at/before the end
            # anchor (ordinal test, findOverlapping).
            if s_i < idx <= e_i and ob.key > ref_seq:
                concurrent.append(ob)
        if not concurrent:
            return
        newest = max(concurrent, key=lambda o: o.key)
        seg.ob_preceding = newest
        others = [o for o in concurrent if o.client != op_client]
        if not others or newest.client == op_client:
            # Inserter performed (or wins with) the newest overlapping
            # obliterate: their insert survives.
            return
        acked_concurrent = [o for o in concurrent if acked(o.key)]
        newest_acked = max(acked_concurrent, key=lambda o: o.key, default=None)
        removes: list[tuple[int, int]] = []
        if newest_acked is None or newest_acked is newest or newest_acked.client != op_client:
            removes = [(o.key, o.client) for o in others if acked(o.key)]
        unacked = [o for o in concurrent if not acked(o.key)]
        if unacked:
            oldest_unacked = min(unacked, key=lambda o: o.key)
            removes.append((oldest_unacked.key, oldest_unacked.client))
        seg.removes = sorted(removes)

    def _split_at(self, pos: int, ref_seq: int, view_client: int) -> None:
        """Split so perspective-position ``pos`` falls on a segment boundary
        (ref ensureIntervalBoundary)."""
        covered = 0
        for i, seg in enumerate(self.segments):
            if not seg.visible(ref_seq, view_client):
                continue
            seg_end = covered + len(seg.text)
            if covered < pos < seg_end:
                self._split(i, pos - covered)
                return
            if seg_end >= pos:
                return
            covered = seg_end

    def _seg_containing(self, p: int, ref_seq: int, view_client: int) -> Segment | None:
        """The perspective-visible segment containing char position ``p``."""
        covered = 0
        for seg in self.segments:
            if not seg.visible(ref_seq, view_client):
                continue
            if covered <= p < covered + len(seg.text):
                return seg
            covered += len(seg.text)
        return None

    def apply_obliterate(
        self,
        pos1: int,
        side1: int,
        pos2: int,
        side2: int,
        op_key: int,
        op_client: int,
        ref_seq: int,
    ) -> list[Segment]:
        """Obliterate the sided range — a slice-remove that also swallows
        concurrent inserts (ref mergeTree.ts obliterateRange:2262 /
        obliterateRangeSided:2083).  ``(pos1, side1)``/``(pos2, side2)`` name
        endpoint CHARACTERS in the op's perspective; the non-sided wire op
        {pos1, pos2} maps to (pos1, Before) .. (pos2-1, After).

        Returns the segments marked removed by this op (for channel events).
        Already-obliterated/removed segments are not re-marked (the marking
        perspective is "everything inserted, nothing removed" — the
        RemoteObliteratePerspective of the reference's design doc)."""
        vis_len = self.visible_length(ref_seq, op_client)
        start_pos = pos1 + (1 if side1 == SIDE_AFTER else 0)
        end_pos = pos2 + (1 if side2 == SIDE_AFTER else 0)
        if not (0 <= pos1 <= pos2 < vis_len and start_pos <= end_pos):
            raise ValueError(
                f"obliterate places ({pos1},{side1})..({pos2},{side2}) invalid "
                f"for visible length {vis_len}"
            )
        self._split_at(start_pos, ref_seq, op_client)
        self._split_at(end_pos, ref_seq, op_client)
        start_seg = self._seg_containing(pos1, ref_seq, op_client)
        end_seg = self._seg_containing(pos2, ref_seq, op_client)
        assert start_seg is not None and end_seg is not None
        ob = Obliterate(
            key=op_key, client=op_client,
            start_seg=start_seg, start_side=side1,
            end_seg=end_seg, end_side=side2,
            ref_seq=ref_seq,
        )
        index_of = {id(s): i for i, s in enumerate(self.segments)}
        lo = index_of[id(start_seg)] + (1 if side1 == SIDE_AFTER else 0)
        hi = index_of[id(end_seg)] - (1 if side2 == SIDE_BEFORE else 0)
        marked: list[Segment] = []
        for i in range(lo, hi + 1):
            seg = self.segments[i]
            # Marking visit rule (ref nodeMap mergeTree.ts:2990-3001 +
            # markRemoved:2144, walking RemoteObliteratePerspective for
            # remote ops, perspective.ts:201): a REMOTE obliterate visits —
            # and splices its stamp into — every window segment EXCEPT those
            # dead in both views: hidden by an acked remove AND not visible
            # at the op's refSeq AND not a local pending insert.  So it
            # still stamps (a) segments covered only by unacked local
            # removes, (b) segments whose acked removes are concurrent with
            # the obliterate (visible at its refSeq), and (c) local pending
            # inserts; skipping any of those diverges the replicas' remove
            # sets.  A LOCAL obliterate walks the local perspective: any
            # remove present locally hides the segment.
            has_acked_rem = any(acked(k) for k, _c in seg.removes)
            if acked(op_key):
                # A concurrent-inserted segment (insert not visible at the
                # op's perspective) is spliced even when acked-removed: the
                # obliterater's replica swallowed it at INSERT time (it held
                # the pending obliterate when the insert arrived, ref
                # blockInsert oldestUnacked, mergeTree.ts:1730-1740), so the
                # walk on every other replica must add the same stamp — the
                # exception being a pre-existing remove stamp from the same
                # client (then the issuer's insert-time rule added only that
                # older one, and the extra stamp would be unobservable).
                ins_concurrent = not has_occurred(
                    seg.ins_key, seg.ins_client, ref_seq, op_client
                )
                # The issuer swallowed this concurrent insert at INSERT time
                # by appending its OLDEST covering pending obliterate (plus
                # all acked stamps).  Our stamp therefore already exists on
                # the issuer iff some same-client stamp came from an
                # obliterate that was pending there when the insert arrived:
                # sequenced after the insert, at or before this op
                # (ins_seq < k <= op_key; == op_key is an earlier op of the
                # same grouped batch, which shares our sequence number).
                same_client_stamp = any(
                    c == op_client and seg.ins_key < k <= op_key
                    for k, c in seg.removes
                )
                if (
                    has_acked_rem
                    and not seg.visible(ref_seq, op_client)
                    and acked(seg.ins_key)
                    and not (ins_concurrent and not same_client_stamp)
                ):
                    continue
            elif seg.removes:
                continue
            if (
                not acked(seg.ins_key)
                and seg.ob_preceding is not None
                and not acked(seg.ob_preceding.key)
                and acked(op_key)
            ):
                # A local pending obliterate is newer than this incoming
                # acked one: last-obliterater-wins lets our insert live.
                continue
            seg.removes.append((op_key, op_client))
            seg.removes.sort()
            # Event list: only segments this op removes from the ACKED view
            # (ref removedSegments vs the splice path, mergeTree.ts:2177).
            if not has_acked_rem:
                marked.append(seg)
        self.obliterates.append(ob)
        self.slice_keys.add(op_key)
        return marked

    def apply_remove(
        self, pos1: int, pos2: int, op_key: int, op_client: int, ref_seq: int
    ) -> list[Segment]:
        out = []
        for i in self._range_indices(pos1, pos2, ref_seq, op_client):
            seg = self.segments[i]
            # Overlapping removes accumulate, stamp-sorted (ref seg.removes).
            seg.removes.append((op_key, op_client))
            seg.removes.sort()
            out.append(seg)
        return out

    def apply_annotate(
        self,
        pos1: int,
        pos2: int,
        prop: int,
        value: int,
        op_key: int,
        op_client: int,
        ref_seq: int,
    ) -> None:
        for i in self._range_indices(pos1, pos2, ref_seq, op_client):
            seg = self.segments[i]
            prev = seg.props.get(prop)
            # LWW by stamp order; pending local writes outrank acked remotes.
            # Ties (>=) go to the later-APPLIED op: ops grouped in one batch
            # share a sequence number, and the issuer resolved them by
            # localSeq order before ack — remotes must agree.
            if prev is None or op_key >= prev[1]:
                seg.props[prop] = (value, op_key)

    # -------------------------------------------------------------------- ack
    def ack(
        self,
        local_seq: int,
        seq: int,
        client: int | None = None,
        ref_seq: int | None = None,
    ) -> None:
        """Convert pending stamps with this localSeq to the acked seq.

        ``client`` (when given) re-stamps the client id to the identity the
        op was sequenced under — channel-hosted replicas stamp local pending
        ops with ``local_client`` and learn their short id only at ack, which
        keeps views stable across reconnection identity changes.
        ``ref_seq`` (when given) rewrites an acked obliterate's recorded
        refSeq to the wire value every remote replica stored (the issuer
        created the record under the ALL_ACKED sentinel; summaries must be
        replica-identical).
        """
        local_key = encode_stamp(-1, local_seq)
        self._regenerated_keys.discard(local_key)
        if local_key in self.slice_keys:
            self.slice_keys.discard(local_key)
            self.slice_keys.add(seq)
        inserted: list[Segment] = []
        removed: list[Segment] = []
        for seg in self.segments:
            if seg.ins_key == local_key:
                seg.ins_key = seq
                if client is not None:
                    seg.ins_client = client
                inserted.append(seg)
            if any(key == local_key for key, _ in seg.removes):
                seg.removes = sorted(
                    (seq if key == local_key else key,
                     client if client is not None and key == local_key else c)
                    for key, c in seg.removes
                )
                removed.append(seg)
            for prop, (value, key) in list(seg.props.items()):
                if key == local_key:
                    seg.props[prop] = (value, seq)
        for ob in self.obliterates:
            if ob.key == local_key:
                # In-place stamp rewrite keeps every seg.ob_preceding
                # reference consistent (the reference mutates ObliterateInfo
                # .stamp the same way on ack).
                ob.key = seq
                if client is not None:
                    ob.client = client
                if ref_seq is not None:
                    ob.ref_seq = ref_seq
        return inserted, removed

    # ----------------------------------------------------- converged queries
    # The "converged view" is the perspective every replica agrees on after
    # full delivery: acked stamps only (refSeq=ALL_ACKED, a client id that
    # matches no pending op). Interval-collection endpoints live in these
    # coordinates (channels.py), so the channel asks, after each sequenced
    # apply, exactly which converged ranges the op touched.

    def converged_position(self, pos: int, ref_seq: int, view_client: int) -> int:
        """Translate a position under perspective (ref_seq, view_client)
        into converged coordinates — the exact slide semantics a merge-tree
        reference would give: landing inside a segment invisible to the
        converged view slides to that segment's converged start."""
        from ..protocol.stamps import NON_COLLAB_CLIENT

        rem = pos
        conv = 0
        for seg in self.segments:
            p_len = len(seg.text) if seg.visible(ref_seq, view_client) else 0
            c_vis = seg.visible(ALL_ACKED, NON_COLLAB_CLIENT)
            if rem < p_len:
                return conv + (rem if c_vis else 0)
            rem -= p_len
            if c_vis:
                conv += len(seg.text)
        if rem == 0:
            return conv
        raise ValueError(f"position {pos} beyond perspective-visible length")

    def converged_insert_ranges(self, segs: list[Segment]) -> list[tuple[int, int]]:
        """(pos, len) of exactly these just-sequenced segments, in post-apply
        converged coordinates, ascending. Identity-based so two ops sharing
        one sequence number (grouped batches) never claim each other's
        segments."""
        from ..protocol.stamps import NON_COLLAB_CLIENT

        wanted = {id(s) for s in segs}
        out: list[tuple[int, int]] = []
        pos = 0
        for seg in self.segments:
            if seg.visible(ALL_ACKED, NON_COLLAB_CLIENT):
                if id(seg) in wanted:
                    out.append((pos, len(seg.text)))
                pos += len(seg.text)
        return out

    def converged_removed_ranges(
        self, segs: list[Segment], op_key: int
    ) -> list[tuple[int, int]]:
        """(pos, len) of what this remove op (stamp ``op_key``, applied to
        exactly ``segs``) deleted from the converged view, in PRE-removal
        converged coordinates, ascending. Segments already dead to the
        converged view (another acked remove also stamped them) are not
        re-reported."""
        wanted = {id(s) for s in segs}
        out: list[tuple[int, int]] = []
        pos = 0
        for seg in self.segments:
            if not acked(seg.ins_key):
                continue
            acked_removes = [k for k, _c in seg.removes if acked(k)]
            newly = id(seg) in wanted and all(k == op_key for k in acked_removes)
            alive = not acked_removes
            if newly:
                out.append((pos, len(seg.text)))
            if newly or alive:
                pos += len(seg.text)
        return out

    def converged_to_local(self, pos: int) -> int:
        """Translate a converged-coordinate position into the LOCAL view
        (acked state plus own pending ops). Landing inside a segment the
        local view cannot see (covered by a pending local remove) slides to
        that segment's local start."""
        from ..protocol.stamps import NON_COLLAB_CLIENT

        conv = 0
        loc = 0
        for seg in self.segments:
            c_vis = seg.visible(ALL_ACKED, NON_COLLAB_CLIENT)
            l_vis = seg.visible(ALL_ACKED, self.local_client)
            n = len(seg.text)
            if c_vis and pos < conv + n:
                return loc + (pos - conv) if l_vis else loc
            if c_vis:
                conv += n
            if l_vis:
                loc += n
        return loc

    def converged_spans_to_local(self, start: int, end: int) -> list[tuple[int, int]]:
        """Map the converged range [start, end) into local-view sub-ranges,
        ascending. Content invisible to the converged view (own pending
        inserts inside the range) produces holes — the caller operating on
        the local view leaves it untouched; content locally hidden by a
        pending remove is skipped (already gone from the local view)."""
        from ..protocol.stamps import NON_COLLAB_CLIENT

        spans: list[list[int]] = []
        conv = 0
        loc = 0
        for seg in self.segments:
            c_vis = seg.visible(ALL_ACKED, NON_COLLAB_CLIENT)
            l_vis = seg.visible(ALL_ACKED, self.local_client)
            n = len(seg.text)
            if c_vis:
                o1 = max(start, conv)
                o2 = min(end, conv + n)
                if o1 < o2 and l_vis:
                    s0 = loc + (o1 - conv)
                    e0 = loc + (o2 - conv)
                    if spans and spans[-1][1] == s0:
                        spans[-1][1] = e0
                    else:
                        spans.append([s0, e0])
                conv += n
            if l_vis:
                loc += n
        return [(s, e) for s, e in spans]

    # --------------------------------------------------------------- reconnect
    def _squashed(self, seg: Segment) -> bool:
        """A pending insert later covered by a pending remove: under squash
        resubmission the pair cancels and the segment never materializes
        remotely (ref reSubmitCore(squash), channel.ts:160)."""
        return not acked(seg.ins_key) and any(not acked(k) for k, _c in seg.removes)

    def _visible_at_prefix(
        self, seg: Segment, max_key: int, exclude_key: int, squash: bool = False
    ) -> bool:
        """Visibility in the local view truncated at pending key ``max_key``:
        everything acked plus own pending ops with stamp key < ``max_key``
        (``exclude_key`` additionally hides one remove stamp — the op being
        regenerated itself). This is the perspective a *resubmitted* op must
        encode positions in: earlier pending ops will be sequenced before it,
        later pending ops after (ref client.ts regeneratePendingOp:1452).
        Under ``squash``, squashed-out segments vanish from position space."""
        if squash and self._squashed(seg):
            return False
        if not self._occurred_before(seg.ins_key, max_key):
            return False
        return not any(
            self._occurred_before(key, max_key) and key != exclude_key
            for key, _client in seg.removes
        )

    def _occurred_before(self, key: int, max_key: int) -> bool:
        """Will the op with this stamp be sequenced before the pending op
        whose (original) key is ``max_key``? True for acked ops, earlier
        original pending ops, and already-regenerated ops of this replay."""
        return acked(key) or key < max_key or key in self._regenerated_keys

    def regenerate_pending(
        self,
        local_seq: int,
        new_local_seq,
        squash: bool = False,
        new_client: int | None = None,
    ) -> list[tuple[int, dict]]:
        """Re-mint the pending op with this localSeq against current state.

        Returns ``[(fresh_local_seq, wire_op_dict), ...]``: a remove/annotate
        whose range was split by interleaved acked removes becomes multiple
        ops; an op whose target content vanished — or, under ``squash``, an
        insert that a later pending remove fully covers — becomes zero ops.
        ``new_local_seq()`` allocates a fresh localSeq per emitted op and the
        affected segments are RE-STAMPED with it, so each re-minted op acks
        independently (ref regeneratePendingOp mints new segment groups,
        client.ts:1452).
        """
        key = encode_stamp(-1, local_seq)
        ob = next((o for o in self.obliterates if o.key == key), None)
        if ob is not None:
            return self._regenerate_obliterate(ob, key, new_local_seq, squash, new_client)
        # (kind, pos1, pos2, payload, [segments]) collected before re-stamping
        # so position math sees unmodified stamps throughout.
        plans: list[tuple[int, int, int, object, list[Segment]]] = []

        # Pending insert: contiguous run of segments carrying this ins stamp.
        ins_segs: list[Segment] = []
        pos = 0
        ins_pos = -1
        for seg in self.segments:
            if seg.ins_key == key and not (squash and self._squashed(seg)):
                if ins_pos < 0:
                    ins_pos = pos
                ins_segs.append(seg)
            if self._visible_at_prefix(seg, key, exclude_key=-1, squash=squash):
                pos += len(seg.text)
        if ins_pos >= 0:
            from .markers import regenerated_insert_spec

            spec = regenerated_insert_spec([
                (s.text, {str(p): v for p, (v, k) in s.props.items() if k == key})
                for s in ins_segs
            ])
            plans.append((0, ins_pos, -1, spec, ins_segs))

        # Pending remove / annotate: maximal visible runs carrying the stamp.
        pos = 0
        rem_run: tuple[int, int, list[Segment]] | None = None
        ann_run: tuple[int, int, dict, list[Segment]] | None = None

        def flush_remove() -> None:
            nonlocal rem_run
            if rem_run is not None:
                plans.append((1, rem_run[0], rem_run[1], None, rem_run[2]))
            rem_run = None

        def flush_annotate() -> None:
            nonlocal ann_run
            if ann_run is not None:
                plans.append((2, ann_run[0], ann_run[1], ann_run[2], ann_run[3]))
            ann_run = None

        for seg in self.segments:
            if not self._visible_at_prefix(seg, key, exclude_key=key, squash=squash):
                continue  # invisible: breaks neither runs nor position space
            if any(k == key for k, _c in seg.removes):
                if rem_run is None:
                    rem_run = (pos, pos + len(seg.text), [seg])
                else:
                    rem_run = (rem_run[0], pos + len(seg.text), rem_run[2] + [seg])
            else:
                flush_remove()
            props = {str(p): v for p, (v, k) in seg.props.items() if k == key}
            if props:
                if ann_run is None or props != ann_run[2]:
                    flush_annotate()
                    ann_run = (pos, pos + len(seg.text), props, [seg])
                else:
                    ann_run = (ann_run[0], pos + len(seg.text), props, ann_run[3] + [seg])
            else:
                flush_annotate()
            pos += len(seg.text)
        flush_remove()
        flush_annotate()

        # Squashed segments are dead: never resubmitted, never acked. Drop
        # (keeping obliterate anchors resident; invisible everywhere anyway).
        if squash:
            anchored = self._anchored_ids()
            self.segments = [
                s for s in self.segments
                if id(s) in anchored or not self._squashed(s)
            ]

        out: list[tuple[int, dict]] = []
        # A remove split into several re-minted ops: the receiver applies
        # them SEQUENTIALLY, and each later op's perspective includes its
        # earlier siblings (same client), so later pieces must shift left by
        # the length the earlier pieces already removed.
        removed_before = 0
        for kind, pos1, pos2, payload, segs in plans:
            fresh = new_local_seq()
            fresh_key = encode_stamp(-1, fresh)
            self._regenerated_keys.add(fresh_key)
            if kind == 0:
                for s in segs:
                    s.ins_key = fresh_key
                    if new_client is not None:
                        # Resubmission happens under a new connection identity;
                        # remote replicas will stamp the new short id.
                        s.ins_client = new_client
                    # Same-op props (insertMarker) re-mint with the insert.
                    for p, (v, k2) in list(s.props.items()):
                        if k2 == key:
                            s.props[p] = (v, fresh_key)
                out.append((fresh, {"type": 0, "pos1": pos1, "seg": payload}))
            elif kind == 1:
                for s in segs:
                    s.removes = sorted(
                        (fresh_key if k == key else k,
                         new_client if new_client is not None and k == key else c)
                        for k, c in s.removes
                    )
                out.append(
                    (fresh, {"type": 1, "pos1": pos1 - removed_before,
                             "pos2": pos2 - removed_before})
                )
                removed_before += pos2 - pos1
            else:
                for s in segs:
                    for p, (v, k) in list(s.props.items()):
                        if k == key:
                            s.props[p] = (v, fresh_key)
                out.append(
                    (fresh, {"type": 2, "pos1": pos1, "pos2": pos2, "props": payload})
                )
        return out

    def _regenerate_obliterate(
        self, ob: Obliterate, key: int, new_local_seq, squash: bool, new_client: int | None
    ) -> list[tuple[int, dict]]:
        """Re-mint a pending obliterate against current state: recompute the
        sided endpoint places in the prefix-visible space the resubmitted op
        will be interpreted in, and re-stamp every segment it marked.  The
        regenerated op is always emitted in sided form (type 5), which
        subsumes the plain form.  Reference analog: the experimental
        mergeTreeEnableObliterateReconnect path (client.ts
        regeneratePendingOp + obliterate range fixup)."""
        index_of = {id(s): i for i, s in enumerate(self.segments)}
        s_i = index_of.get(id(ob.start_seg), len(self.segments))
        e_i = index_of.get(id(ob.end_seg), len(self.segments))
        b_s = b_e = total = 0
        for i, seg in enumerate(self.segments):
            if not self._visible_at_prefix(seg, key, exclude_key=key, squash=squash):
                continue
            n = len(seg.text)
            if i < s_i or (i == s_i and ob.start_side == SIDE_AFTER):
                b_s += n
            if i < e_i or (i == e_i and ob.end_side == SIDE_AFTER):
                b_e += n
            total += n

        # Express the surviving boundaries as sided places; a boundary whose
        # anchor char vanished from the prefix view degrades to the nearest
        # expressible place (slide semantics).
        if ob.start_side == SIDE_AFTER and b_s > 0:
            start = {"pos": b_s - 1, "before": False}
        else:
            start = {"pos": b_s, "before": True}
        if ob.end_side == SIDE_BEFORE and b_e < total:
            end = {"pos": b_e, "before": True}
        elif b_e > 0:
            end = {"pos": b_e - 1, "before": False}
        else:
            end = None

        start_char = start["pos"]
        end_char = end["pos"] if end is not None else -1
        start_bound = start["pos"] + (0 if start["before"] else 1)
        end_bound = (end["pos"] + (0 if end["before"] else 1)) if end is not None else -1
        if (
            end is None
            or not (0 <= start_char <= end_char < total)
            or start_bound > end_bound
        ):
            # The whole range (and any place to re-anchor it) is gone from
            # the prefix view: the op is never resubmitted, so retire the
            # obliterate — strip its (never-to-ack) stamps and drop the
            # record so it stops swallowing future concurrent inserts.
            for seg in self.segments:
                if any(k == key for k, _c in seg.removes):
                    seg.removes = [(k, c) for k, c in seg.removes if k != key]
            self.obliterates.remove(ob)
            self.slice_keys.discard(key)
            return []

        # Re-stamp the marked segments and the obliterate record itself so
        # the re-minted op acks independently.
        fresh = new_local_seq()
        fresh_key = encode_stamp(-1, fresh)
        self._regenerated_keys.add(fresh_key)
        for seg in self.segments:
            if any(k == key for k, _c in seg.removes):
                seg.removes = sorted(
                    (fresh_key if k == key else k,
                     new_client if new_client is not None and k == key else c)
                    for k, c in seg.removes
                )
        ob.key = fresh_key
        if new_client is not None:
            ob.client = new_client
        self.slice_keys.discard(key)
        self.slice_keys.add(fresh_key)
        return [(fresh, {"type": 5, "pos1": start, "pos2": end})]

    # ------------------------------------------------------------ checkpoint
    def export_summary(self) -> dict:
        """Merge-tree snapshot: the acked segment array with full stamps
        (ref snapshotV1.ts:42 — header + segment chunks; we keep one chunk;
        stamps above minSeq are required so concurrent in-flight remote ops
        rebase correctly against the loaded state)."""
        segs = []
        for s in self.segments:
            if not acked(s.ins_key) or any(not acked(k) for k, _c in s.removes):
                raise RuntimeError("summarize with pending merge-tree state")
            entry = {
                "text": s.text,
                "ins": [s.ins_key, s.ins_client],
                "removes": [[k, c] for k, c in s.removes],
                "props": {str(p): [v, k] for p, (v, k) in sorted(s.props.items())},
            }
            if s.attr is not None:
                entry["attr"] = [[o, k] for o, k in s.attr]
            segs.append(entry)
        seg_index = {id(s): i for i, s in enumerate(self.segments)}
        obs = []
        # Issuers append their own obliterate at issuance, remotes at apply:
        # stamp-key order is the replica-independent canonical order.
        for ob in sorted(self.obliterates, key=lambda o: o.key):
            if not acked(ob.key):
                raise RuntimeError("summarize with pending merge-tree state")
            obs.append(
                {
                    "key": ob.key,
                    "client": ob.client,
                    "start": seg_index.get(id(ob.start_seg), -1),
                    "startSide": ob.start_side,
                    "end": seg_index.get(id(ob.end_seg), -1),
                    "endSide": ob.end_side,
                    "refSeq": ob.ref_seq,
                }
            )
        # Slice keys still observable from the summary (present on a segment
        # or in the window) — keeps remove-type labels through round-trips.
        live = {k for s in self.segments for k, _c in s.removes} | {
            ob.key for ob in self.obliterates
        }
        return {
            "segments": segs,
            "obliterates": obs,
            "minSeq": self.min_seq,
            "sliceKeys": sorted(self.slice_keys & live),
        }

    def import_summary(self, summary: dict) -> None:
        self.min_seq = summary["minSeq"]
        self.segments = [
            Segment(
                text=e["text"],
                ins_key=e["ins"][0],
                ins_client=e["ins"][1],
                removes=[(k, c) for k, c in e["removes"]],
                props={int(p): (v, k) for p, (v, k) in e["props"].items()},
                attr=(
                    [(o, k) for o, k in e["attr"]]
                    if "attr" in e else None
                ),
            )
            for e in summary["segments"]
        ]
        segs = self.segments
        self.obliterates = [
            Obliterate(
                key=o["key"],
                client=o["client"],
                start_seg=segs[o["start"]] if o["start"] >= 0 else None,
                start_side=o["startSide"],
                end_seg=segs[o["end"]] if o["end"] >= 0 else None,
                end_side=o["endSide"],
                ref_seq=o["refSeq"],
            )
            for o in summary.get("obliterates", [])
        ]
        self.slice_keys = set(summary.get("sliceKeys", [])) | {
            ob.key for ob in self.obliterates
        }

    # --------------------------------------------------------------- lifetime
    def update_min_seq(self, min_seq: int) -> None:
        if min_seq > self.min_seq:
            self.min_seq = min_seq
            # Obliterates below the window floor can never affect another
            # legal op (every refSeq >= minSeq sees them); release their
            # anchors first (ref Obliterates.setMinSeq).
            self.obliterates = [
                ob for ob in self.obliterates
                if not (acked(ob.key) and ob.key <= min_seq)
            ]
            self.zamboni()

    def _anchored_ids(self) -> set[int]:
        out: set[int] = set()
        for ob in self.obliterates:
            if ob.start_seg is not None:
                out.add(id(ob.start_seg))
            if ob.end_seg is not None:
                out.add(id(ob.end_seg))
        return out

    def zamboni(self) -> None:
        """Evict segments unreferenceable from any legal perspective.

        Segments anchoring a live obliterate are retained even when evictable
        (the anchor defines the obliterate's index window for concurrent
        inserts); they fall out once the obliterate leaves the collab window.
        """
        anchored = self._anchored_ids()
        self.segments = [
            s
            for s in self.segments
            if id(s) in anchored
            or not (s.removes and acked(s.removes[0][0]) and s.removes[0][0] <= self.min_seq)
        ]
