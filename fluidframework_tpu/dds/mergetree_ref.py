"""Pure-Python merge-tree oracle with reference-exact convergence semantics.

This is the differential-testing contract for the TPU kernel
(``fluidframework_tpu.ops.mergetree_kernel``): a flat list-of-segments
implementation of the reference's merge-tree CRDT, behaviorally equivalent to
merge-tree/src/mergeTree.ts on the op-application path but with none of the
B-tree machinery (the B-tree + PartialSequenceLengths exist only to make CPU
queries O(log n); a flat walk is the clearest statement of the semantics).

Semantics captured (studied from the reference, re-implemented):

- **Visibility** (perspective.ts ``PriorPerspective``): a segment is present
  from perspective ``(refSeq, viewClient)`` iff its insert has occurred
  (acked with seq <= refSeq, or issued by viewClient) and no remove on it has
  occurred.

- **Insert walk + tie-break** (mergeTree.ts ``insertRecursive`` /
  ``breakTie:1811``): an insert at position P walks segments left-to-right
  consuming perspective-visible length.  Landing mid-segment splits it.
  Landing on a boundary, the insert skips past invisible segments UNLESS the
  incoming stamp is greater than the segment's insert stamp (so among
  concurrent inserts at one position, later-sequenced ops sit closer to the
  front, and local unacked segments — which outrank every acked stamp — stay
  in front of incoming remote inserts), or the segment was removed by an
  acked remove stamped after the incoming insert (reconnect rebase case).

- **Set-remove** (mergeTree.ts ``markRangeRemoved:2292``): removes exactly
  the perspective-visible segments in [P1, P2), splitting boundary segments;
  overlapping removes keep the earliest stamp as the winner (removes[0]).

- **Annotate** (mergeTree.ts ``annotateRange:2009`` + PropertiesManager):
  per-(segment, key) last-writer-wins by stamp order; a pending local
  annotate outranks (masks) every acked one until acked itself.

- **Ack** (client.ts ``ackPendingSegment``): the originating client converts
  pending stamps (localSeq) to acked stamps (seq) when its own op returns.

- **Zamboni** (zamboni.ts:33): segments whose winning remove is acked at or
  below the MSN are unreferenceable from every legal perspective and are
  evicted.

Overlapping removes: the FULL list of remove stamps is retained per segment
(reference ``seg.removes``, kept stamp-sorted).  This is required for
correctness, not just attribution: a segment must be invisible to any
perspective whose client is among the removers, even when the *winning*
(earliest) remove is outside that perspective's refSeq
(perspective.ts ``isSegmentPresent``: ``removes.some(hasOccurred)``).
The TPU kernel carries a fixed number of remover slots per segment with
overflow detection for the same reason.
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace

from ..protocol.stamps import (
    ALL_ACKED,
    NO_REMOVE,
    acked,
    encode_stamp,
    has_occurred,
)


@dataclass
class Segment:
    """One run of text plus its operation stamps (columnar tuple on TPU)."""

    text: str
    ins_key: int
    ins_client: int
    # Overlapping remove stamps as (key, client), sorted by key; the first
    # entry is the winning (earliest) remove — reference seg.removes[0].
    removes: list[tuple[int, int]] = field(default_factory=list)
    # prop id -> (value, stamp key of the write that set it)
    props: dict[int, tuple[int, int]] = field(default_factory=dict)

    @property
    def rem_key(self) -> int:
        return self.removes[0][0] if self.removes else NO_REMOVE

    def visible(self, ref_seq: int, view_client: int) -> bool:
        if not has_occurred(self.ins_key, self.ins_client, ref_seq, view_client):
            return False
        return not any(
            has_occurred(key, client, ref_seq, view_client)
            for key, client in self.removes
        )


class RefMergeTree:
    """Flat-array merge-tree replica for one document."""

    def __init__(self, local_client: int = -3) -> None:
        self.segments: list[Segment] = []
        self.local_client = local_client
        self.min_seq = 0

    # ------------------------------------------------------------------ views
    def visible_text(self, ref_seq: int = ALL_ACKED, view_client: int | None = None) -> str:
        vc = self.local_client if view_client is None else view_client
        return "".join(
            s.text for s in self.segments if s.visible(ref_seq, vc)
        )

    def visible_length(self, ref_seq: int = ALL_ACKED, view_client: int | None = None) -> int:
        vc = self.local_client if view_client is None else view_client
        return sum(len(s.text) for s in self.segments if s.visible(ref_seq, vc))

    def annotations(self, ref_seq: int = ALL_ACKED, view_client: int | None = None) -> list[dict[int, int]]:
        """Per visible character: {prop_id: value} (for differential tests)."""
        vc = self.local_client if view_client is None else view_client
        out: list[dict[int, int]] = []
        for s in self.segments:
            if s.visible(ref_seq, vc):
                props = {k: v for k, (v, _key) in sorted(s.props.items())}
                out.extend(props for _ in s.text)
        return out

    # ------------------------------------------------------------- primitives
    def _split(self, i: int, offset: int) -> None:
        """Split segment i at text offset, preserving all stamps (ref split)."""
        seg = self.segments[i]
        assert 0 < offset < len(seg.text)
        left = replace(
            seg, text=seg.text[:offset], removes=list(seg.removes), props=dict(seg.props)
        )
        right = replace(
            seg, text=seg.text[offset:], removes=list(seg.removes), props=dict(seg.props)
        )
        self.segments[i : i + 1] = [left, right]

    def _tiebreak(self, seg: Segment, op_key: int) -> bool:
        """mergeTree.ts breakTie leaf case (pos == 0, invisible segment)."""
        if op_key > seg.ins_key:
            return True
        return (
            bool(seg.removes)
            and acked(seg.removes[0][0])
            and seg.removes[0][0] > op_key
        )

    def _find_insert_index(
        self, pos: int, op_key: int, ref_seq: int, view_client: int
    ) -> int:
        """Replicates the inserting walk; may split a segment. Returns index
        at which to insert the new segment into ``self.segments``."""
        rem = pos
        i = 0
        while i < len(self.segments):
            seg = self.segments[i]
            vlen = len(seg.text) if seg.visible(ref_seq, view_client) else 0
            if rem < vlen:
                if rem == 0:
                    return i
                self._split(i, rem)
                return i + 1
            if rem == 0 and vlen == 0 and self._tiebreak(seg, op_key):
                return i
            rem -= vlen
            i += 1
        if rem != 0:
            raise ValueError(f"insert position {pos} beyond visible length")
        return len(self.segments)

    def _range_indices(
        self, pos1: int, pos2: int, ref_seq: int, view_client: int
    ) -> list[int]:
        """Split boundaries and return indices of perspective-visible segments
        wholly inside [pos1, pos2)."""
        assert pos1 <= pos2
        out: list[int] = []
        covered = 0
        i = 0
        while i < len(self.segments) and covered < pos2:
            seg = self.segments[i]
            if not seg.visible(ref_seq, view_client):
                i += 1
                continue
            seg_end = covered + len(seg.text)
            if seg_end <= pos1:
                covered = seg_end
                i += 1
                continue
            if covered < pos1:
                # Split off the prefix before the range.
                self._split(i, pos1 - covered)
                covered = pos1
                i += 1
                continue
            if seg_end > pos2:
                # Split off the suffix after the range.
                self._split(i, pos2 - covered)
                seg_end = pos2
            out.append(i)
            covered = seg_end
            i += 1
        if covered < pos2:
            raise ValueError(f"range [{pos1},{pos2}) beyond visible length")
        return out

    # -------------------------------------------------------------------- ops
    def apply_insert(
        self,
        pos: int,
        text: str,
        op_key: int,
        op_client: int,
        ref_seq: int,
    ) -> None:
        idx = self._find_insert_index(pos, op_key, ref_seq, op_client)
        self.segments.insert(
            idx, Segment(text=text, ins_key=op_key, ins_client=op_client)
        )

    def apply_remove(
        self, pos1: int, pos2: int, op_key: int, op_client: int, ref_seq: int
    ) -> None:
        for i in self._range_indices(pos1, pos2, ref_seq, op_client):
            seg = self.segments[i]
            # Overlapping removes accumulate, stamp-sorted (ref seg.removes).
            seg.removes.append((op_key, op_client))
            seg.removes.sort()

    def apply_annotate(
        self,
        pos1: int,
        pos2: int,
        prop: int,
        value: int,
        op_key: int,
        op_client: int,
        ref_seq: int,
    ) -> None:
        for i in self._range_indices(pos1, pos2, ref_seq, op_client):
            seg = self.segments[i]
            prev = seg.props.get(prop)
            # LWW by stamp order; pending local writes outrank acked remotes.
            if prev is None or op_key > prev[1]:
                seg.props[prop] = (value, op_key)

    # -------------------------------------------------------------------- ack
    def ack(self, local_seq: int, seq: int) -> None:
        """Convert pending stamps with this localSeq to the acked seq."""
        local_key = encode_stamp(-1, local_seq)
        for seg in self.segments:
            if seg.ins_key == local_key:
                seg.ins_key = seq
            if any(key == local_key for key, _ in seg.removes):
                seg.removes = sorted(
                    (seq if key == local_key else key, client)
                    for key, client in seg.removes
                )
            for prop, (value, key) in list(seg.props.items()):
                if key == local_key:
                    seg.props[prop] = (value, seq)

    # --------------------------------------------------------------- lifetime
    def update_min_seq(self, min_seq: int) -> None:
        if min_seq > self.min_seq:
            self.min_seq = min_seq
            self.zamboni()

    def zamboni(self) -> None:
        """Evict segments unreferenceable from any legal perspective."""
        self.segments = [
            s
            for s in self.segments
            if not (s.removes and acked(s.removes[0][0]) and s.removes[0][0] <= self.min_seq)
        ]
