"""Channel-contract DDS implementations (the runtime-hosted forms).

These are the DDSes as plugged into the runtime layer through the channel
boundary (runtime/channel.py) — the reference's SharedObject subclasses seen
through IChannelFactory/IDeltaHandler (shared-object-base/src/sharedObject.ts).
The standalone classes in shared_string.py / shared_map.py remain the
direct-wire forms used by the kernel differential harnesses; the op formats
and CRDT semantics are identical.
"""

from __future__ import annotations

import json
from typing import Any

from ..protocol.stamps import ALL_ACKED, encode_stamp
from .markers import (
    MARKER_ID_KEY,
    REF_TILE,
    TILE_LABELS_KEY,
    assert_no_marker_plane,
    marker_char,
    marker_json,
    spec_length,
    strip_markers,
)
from .mergetree_ref import SIDE_AFTER, SIDE_BEFORE, RefMergeTree
from .sequence_intervals import (
    SENTINEL_POS,
    IntervalCollection,
    StringOpLog,
    place_boundary,
    transform_position,
)
from .shared_string import decode_obliterate_places as _decode_obliterate_places
from ..protocol.channel import Channel, MessageCollection

# Default merge-tree backend for channel-hosted SharedStrings: None -> the
# Python oracle.  Tests swap in the TPU kernel backend here to run the whole
# channel/container suite differentially (the IChannelFactory plugin
# boundary the north star gates on, channel.ts:294).
_STRING_BACKEND_FACTORY = None


def set_string_backend_factory(factory) -> None:
    """Install a zero-arg factory for SharedStringChannel backends (None
    restores the oracle default)."""
    global _STRING_BACKEND_FACTORY
    _STRING_BACKEND_FACTORY = factory


class LocalReference:
    """A position that follows the text (ref merge-tree localReference.ts:232
    LocalReferenceCollection): per-replica, NEVER replicated — cursor
    anchors, selection endpoints.  SlideOnRemove semantics: removing the
    containing range slides the reference to the range start.  Internally
    anchored in converged coordinates and transformed by every sequenced
    edit; ``position`` resolves into the local view (acked + own pending)."""

    def __init__(self, channel: "SharedStringChannel", conv_pos: int) -> None:
        self._channel = channel
        self.conv = conv_pos
        self.alive = True

    @property
    def position(self) -> int:
        assert self.alive, "reference was removed"
        return self._channel.backend.converged_to_local(self.conv)

    def remove(self) -> None:
        self.alive = False
        self._channel._local_refs.discard(self)


class SharedStringChannel(Channel):
    """SharedString over the channel boundary (ref SharedStringClass +
    merge-tree Client, sequence/src/sharedString.ts, merge-tree/src/client.ts).

    Local metadata per pending op: {"localSeq": n} — round-tripped by the
    container's PendingStateManager for ack zip and resubmit.

    Properties are RICH (ref PropertiesManager: arbitrary keys and JSON
    values): the channel interns keys/values to int ids for the columnar
    backends and resolves them at every boundary (wire ops and summaries
    carry raw values, so interning order never has to agree across
    replicas).
    """

    channel_type = "sharedString"

    def __init__(self, channel_id: str, backend: RefMergeTree | None = None) -> None:
        super().__init__(channel_id)
        if backend is None:
            backend = (
                _STRING_BACKEND_FACTORY() if _STRING_BACKEND_FACTORY else RefMergeTree()
            )
        self.backend = backend
        self._local_seq = 0
        # Interval collections (ref sequence/src/intervalCollection.ts):
        # named range sets anchored into this string; endpoints transform
        # with every sequenced string edit (sequence_intervals.py).
        self._collections: dict[str, IntervalCollection] = {}
        self._op_log = StringOpLog()
        # Converged-event listeners: (kind, pos, length, local_seq|None) per
        # sequenced edit, in converged coordinates (undo-redo range tracking).
        self._converged_listeners: list = []
        # Local references (never replicated; converged coordinates).
        self._local_refs: set[LocalReference] = set()
        # Rich-property intern tables: key/value <-> int id (backends are
        # int-columnar).  Replica-local; raw forms ride wire + summaries.
        self._prop_ids: dict[str, int] = {}
        self._prop_names: list[str] = []
        self._val_ids: dict[str, int] = {}
        self._val_raw: list[Any] = []

    # ------------------------------------------------------------ local edits
    def _next_local_seq(self) -> int:
        self._local_seq += 1
        return self._local_seq

    def insert_text(self, pos: int, text: str) -> int:
        assert text
        assert_no_marker_plane(text)
        ls = self._next_local_seq()
        self.backend.apply_insert(
            pos, text, encode_stamp(-1, ls), self.backend.local_client, ALL_ACKED
        )
        self.submit_local_message(
            {"type": 0, "pos1": pos, "seg": text}, {"localSeq": ls}
        )
        return ls

    def insert_marker(
        self, pos: int, ref_type: int = REF_TILE, props: dict | None = None
    ) -> int:
        """Insert a length-1 marker segment (ref sharedString.ts:42
        insertMarker, mergeTreeNodes.ts:495 Marker).  The marker and its
        initial properties apply under ONE stamp, so ack/resubmit treat
        them as the single op they are on the wire."""
        ls = self._next_local_seq()
        key = encode_stamp(-1, ls)
        self._apply_insert_spec(
            marker_json(ref_type, props), pos, key,
            self.backend.local_client, ALL_ACKED,
        )
        self.submit_local_message(
            {"type": 0, "pos1": pos, "seg": marker_json(ref_type, props)},
            {"localSeq": ls},
        )
        return ls

    def _apply_insert_spec(
        self, seg, pos: int, key: int, client: int, ref_seq: int
    ) -> list:
        """Apply one wire insert spec (IJSONSegment: bare text, annotated
        {text, props}, marker {marker:{refType}, props}, or a LIST of those
        — a regenerated insert whose split parts carry different props) to
        the backend.  Properties apply as (pos, pos+1) annotates in the
        SAME perspective: the op's own segment is visible to (ref_seq,
        sender) — own ops have occurred — so the range lands exactly on the
        inserted segment.

        This is the op-apply/decode boundary for the reserved marker plane:
        only a {"marker": {...}} spec may produce U+E000..U+F8FF
        codepoints.  Bare/annotated text smuggling plane codepoints is
        rejected (ValueError) — accepting it would make every replica
        silently reinterpret peer 'text' as markers, breaking the
        text/length invariants the local insert_text API already guards."""
        if isinstance(seg, list):
            out: list = []
            off = 0
            for part in seg:
                out.extend(
                    self._apply_insert_spec(part, pos + off, key, client, ref_seq)
                )
                off += spec_length(part)
            return out
        if isinstance(seg, str):
            text, props = seg, None
            assert_no_marker_plane(text)
        elif "marker" in seg:
            text = marker_char(seg["marker"]["refType"])
            props = seg.get("props")
        else:
            text, props = seg["text"], seg.get("props")
            assert_no_marker_plane(text)
        ins = self.backend.apply_insert(pos, text, key, client, ref_seq)
        for name, value in (props or {}).items():
            self.backend.apply_annotate(
                pos, pos + len(text),
                self._prop_id(name), self._val_id(value),
                key, client, ref_seq,
            )
        return [ins]

    def remove_range(self, pos1: int, pos2: int) -> int:
        assert pos1 < pos2
        ls = self._next_local_seq()
        self.backend.apply_remove(
            pos1, pos2, encode_stamp(-1, ls), self.backend.local_client, ALL_ACKED
        )
        self.submit_local_message(
            {"type": 1, "pos1": pos1, "pos2": pos2}, {"localSeq": ls}
        )
        return ls

    def obliterate_range(self, pos1: int, pos2: int) -> int:
        """Slice-remove [pos1, pos2): also swallows concurrent inserts into
        the range (ref client.ts applyObliterateRangeOp, ops.ts OBLITERATE)."""
        assert pos1 < pos2
        ls = self._next_local_seq()
        self.backend.apply_obliterate(
            pos1, SIDE_BEFORE, pos2 - 1, SIDE_AFTER,
            encode_stamp(-1, ls), self.backend.local_client, ALL_ACKED,
        )
        self.submit_local_message(
            {"type": 4, "pos1": pos1, "pos2": pos2}, {"localSeq": ls}
        )
        return ls

    def obliterate_range_sided(
        self, start: tuple[int, bool], end: tuple[int, bool]
    ) -> int:
        """Sided obliterate: endpoints are (char pos, before) places; an
        After (before=False) start / Before end expands the range to swallow
        concurrent inserts adjacent to the exclusive endpoint
        (ref ops.ts OBLITERATE_SIDED, mergeTreeEnableSidedObliterate)."""
        from .shared_string import validate_obliterate_places

        s1 = SIDE_BEFORE if start[1] else SIDE_AFTER
        s2 = SIDE_BEFORE if end[1] else SIDE_AFTER
        validate_obliterate_places(
            start[0], s1, end[0], s2, self.backend.visible_length()
        )
        ls = self._next_local_seq()
        self.backend.apply_obliterate(
            start[0], s1, end[0], s2,
            encode_stamp(-1, ls), self.backend.local_client, ALL_ACKED,
        )
        self.submit_local_message(
            {
                "type": 5,
                "pos1": {"pos": start[0], "before": start[1]},
                "pos2": {"pos": end[0], "before": end[1]},
            },
            {"localSeq": ls},
        )
        return ls

    # ------------------------------------------------------------- properties
    def _prop_id(self, prop) -> int:
        name = prop if isinstance(prop, str) else str(prop)
        if name not in self._prop_ids:
            self._prop_ids[name] = len(self._prop_names)
            self._prop_names.append(name)
        return self._prop_ids[name]

    def _val_id(self, value) -> int:
        key = json.dumps(value, sort_keys=True, separators=(",", ":"))
        if key not in self._val_ids:
            self._val_ids[key] = len(self._val_raw)
            # Store the JSON-CANONICAL form, not the caller's object: a
            # replica across a real wire sees the round-tripped value (tuple
            # -> list, int dict keys -> str), and resolved views/summaries
            # must agree byte for byte.
            self._val_raw.append(json.loads(key))
        return self._val_ids[key]

    def annotate_range(self, pos1: int, pos2: int, prop, value) -> None:
        """Annotate with an arbitrary key and JSON value (ref
        annotateRange + PropertiesManager rich property maps)."""
        assert pos1 < pos2
        ls = self._next_local_seq()
        self.backend.apply_annotate(
            pos1, pos2, self._prop_id(prop), self._val_id(value),
            encode_stamp(-1, ls), self.backend.local_client, ALL_ACKED,
        )
        name = prop if isinstance(prop, str) else str(prop)
        self.submit_local_message(
            {"type": 2, "pos1": pos1, "pos2": pos2, "props": {name: value}},
            {"localSeq": ls},
        )

    def annotations(self) -> list[dict]:
        """Per local-view POSITION: resolved {key: value} property maps.
        Positions include markers (whose entry is the marker's own props),
        so this list aligns with visible_length / insert positions, NOT
        with ``text`` (which excludes markers) — the reference's
        getPropertiesAtPosition is position-based the same way."""
        out = []
        for d in self.backend.annotations(
            ALL_ACKED, self.backend.local_client
        ):
            out.append(
                {self._prop_names[p]: self._val_raw[v] for p, v in d.items()}
            )
        return out

    # --------------------------------------------------------------- markers
    def _resolve_marker(self, pos: int, rt: int, props: dict) -> dict:
        return {
            "position": pos,
            "refType": rt,
            "props": {
                self._prop_names[p]: self._val_raw[v]
                for p, v in props.items()
            },
        }

    def _raw_marker_prop(self, props: dict, name: str):
        """One resolved property off a raw scan entry without materializing
        the rest (queries over marker-heavy documents stay cheap)."""
        pid = self._prop_ids.get(name)
        return self._val_raw[props[pid]] if pid in props else None

    def markers(self) -> list[dict]:
        """Visible markers in the local view:
        [{"position", "refType", "props"}] (resolved property maps)."""
        return [
            self._resolve_marker(pos, rt, props)
            for pos, rt, props in self.backend.marker_scan(
                ALL_ACKED, self.backend.local_client
            )
        ]

    def get_marker_from_id(self, marker_id: str) -> dict | None:
        """Marker with props[markerId] == id, or None (ref client.ts
        getMarkerFromId via the marker-id hash)."""
        for pos, rt, props in self.backend.marker_scan(
            ALL_ACKED, self.backend.local_client
        ):
            if self._raw_marker_prop(props, MARKER_ID_KEY) == marker_id:
                return self._resolve_marker(pos, rt, props)
        return None

    def annotate_marker(self, marker_id: str, props: dict) -> None:
        """Annotate the marker with this id (ref sharedString.ts
        annotateMarker): ALL properties ride ONE annotate op under one
        stamp over the marker's 1-position range — atomic across
        reconnect resubmission, one ack."""
        m = self.get_marker_from_id(marker_id)
        if m is None:
            raise KeyError(f"no marker with id {marker_id!r}")
        pos = m["position"]
        ls = self._next_local_seq()
        key = encode_stamp(-1, ls)
        for name, value in props.items():
            self.backend.apply_annotate(
                pos, pos + 1, self._prop_id(name), self._val_id(value),
                key, self.backend.local_client, ALL_ACKED,
            )
        self.submit_local_message(
            {"type": 2, "pos1": pos, "pos2": pos + 1, "props": dict(props)},
            {"localSeq": ls},
        )

    def get_text_and_markers(self, label: str) -> tuple[list[str], list[dict]]:
        """Parallel (text runs, tile markers) — one text run PER labeled
        tile (the text since the previous tile), trailing text after the
        last tile excluded, exactly the reference's gatherTextAndMarkers
        shape (ref sharedString.ts getTextAndMarkers)."""
        raw = self.position_text()
        cuts = [
            m for m in self.backend.marker_scan(
                ALL_ACKED, self.backend.local_client
            )
            if label in (self._raw_marker_prop(m[2], TILE_LABELS_KEY) or [])
        ]
        texts: list[str] = []
        markers: list[dict] = []
        start = 0
        for m in cuts:
            texts.append(strip_markers(raw[start:m[0]]))
            markers.append(self._resolve_marker(*m))
            start = m[0] + 1
        return texts, markers

    def search_for_marker(
        self, pos: int, label: str, forwards: bool = True
    ) -> dict | None:
        """Nearest marker at-or-after (forwards) / at-or-before pos whose
        referenceTileLabels include ``label`` — the reference's tile search
        (client.ts searchForMarker / mergeTree searchForMarker)."""
        best = None
        for m in self.backend.marker_scan(
            ALL_ACKED, self.backend.local_client
        ):
            if label not in (self._raw_marker_prop(m[2], TILE_LABELS_KEY) or []):
                continue
            if forwards:
                if m[0] >= pos:
                    return self._resolve_marker(*m)  # scan is position-ordered
            elif m[0] <= pos:
                best = m
            else:
                break
        return self._resolve_marker(*best) if best is not None else None

    # ------------------------------------------------------- local references
    def create_local_reference(self, pos: int) -> LocalReference:
        """Anchor a reference at local-view position ``pos`` (ref
        createLocalReferencePosition, SlideOnRemove)."""
        conv = self.backend.converged_position(
            pos, ALL_ACKED, self.backend.local_client
        )
        ref = LocalReference(self, conv)
        self._local_refs.add(ref)
        return ref

    # ------------------------------------------------------------- intervals
    def _converged_length(self) -> int:
        from ..protocol.stamps import NON_COLLAB_CLIENT

        return self.backend.visible_length(ALL_ACKED, NON_COLLAB_CLIENT)

    def get_interval_collection(self, label: str) -> IntervalCollection:
        """Named interval collection over this string (ref
        sharedString.getIntervalCollection). The collection's length_fn is
        the LOCAL view (what the author sees when adding); converged-space
        lengths are passed explicitly at sequencing time."""
        if label not in self._collections:
            # Length in POSITIONS (markers count), not text chars.
            self._collections[label] = IntervalCollection(
                label, self._submit_interval_op,
                lambda: self.backend.visible_length(),
            )
        return self._collections[label]

    def _submit_interval_op(self, label: str, op: dict) -> None:
        self.submit_local_message(
            {"type": 3, "label": label, "op": op},
            {"intervalRef": self._connection.ref_seq()},
        )

    def _resolve_interval_op(self, op: dict, ref_seq: int, sender: int) -> dict:
        """Resolve the op's endpoints — expressed in the sender's
        perspective (acked at its refSeq + its own prior ops, all sequenced
        by now thanks to per-client FIFO) — into converged coordinates, the
        space interval endpoints live in. Exact perspective walk, so no
        positional drift between replicas (the merge-tree-reference analog).
        Sided endpoints resolve their character position and keep the side;
        the start/end sentinels (pos=-1) pass through untouched."""
        out = dict(op)
        n = self._converged_length()
        for k in ("start", "end"):
            if out.get(k) is not None and out[k] != SENTINEL_POS:
                out[k] = min(
                    self.backend.converged_position(out[k], ref_seq, sender),
                    max(n - 1, 0) if "startSide" in out or "endSide" in out else n,
                )
        if out.get("end") is not None and out.get("start") is not None:
            if "startSide" in out or "endSide" in out:
                ss = out.get("startSide", 0)
                es = out.get("endSide", 0)
                if place_boundary(out["start"], ss) > place_boundary(
                    out["end"], es
                ):
                    out["end"], out["endSide"] = out["start"], ss
            elif out["end"] < out["start"]:
                out["end"] = out["start"]
        return out

    def _record_converged_events(
        self, kind: str, ranges, seq: int, local_seq: int | None = None
    ) -> None:
        """Slide interval endpoints over the converged-coordinate ranges an
        op touched. Removal ranges come in pre-removal coordinates and are
        applied back-to-front so earlier positions stay valid."""
        ordered = ranges if kind == "insert" else list(reversed(ranges))
        for pos, length in ordered:
            self._op_log.record(seq, kind, pos, length)
            for coll in self._collections.values():
                coll.transform_endpoints(kind, pos, length)
            for ref in self._local_refs:
                ref.conv = transform_position(ref.conv, kind, pos, length)
            for listener in list(self._converged_listeners):
                listener(kind, pos, length, local_seq)
        # Sentinel-degrade/crossing cleanup is only meaningful (and the
        # length query only paid) when sided intervals exist.
        if ordered and any(c.has_sided() for c in self._collections.values()):
            n = self._converged_length()
            for coll in self._collections.values():
                coll.finalize_op(n)

    # ---------------------------------------------------------------- inbound
    def process_messages(self, collection: MessageCollection) -> None:
        env = collection.envelope
        for m in collection.messages:
            c = m.contents
            sender = self._connection.short_id(env.client_id)
            if c["type"] == 3:
                coll = self.get_interval_collection(c["label"])
                coll.apply_sequenced(
                    self._resolve_interval_op(c["op"], env.ref_seq, sender), m.local
                )
                continue
            # Apply, keeping the exact segments this op touched (identity,
            # not seq: grouped batches share sequence numbers).
            ins_segs: list = []
            rem_segs: list = []
            if m.local:
                ins_segs, rem_segs = self.backend.ack(
                    m.local_metadata["localSeq"], env.seq, sender,
                    ref_seq=env.ref_seq,
                )
            elif c["type"] == 0:
                ins_segs = self._apply_insert_spec(
                    c["seg"], c["pos1"], env.seq, sender, env.ref_seq
                )
            elif c["type"] == 1:
                rem_segs = self.backend.apply_remove(
                    c["pos1"], c["pos2"], env.seq, sender, env.ref_seq
                )
            elif c["type"] == 2:
                for prop, value in c["props"].items():
                    self.backend.apply_annotate(
                        c["pos1"], c["pos2"],
                        self._prop_id(prop), self._val_id(value),
                        env.seq, sender, env.ref_seq,
                    )
            elif c["type"] in (4, 5):
                p1, s1, p2, s2 = _decode_obliterate_places(c)
                rem_segs = self.backend.apply_obliterate(
                    p1, s1, p2, s2, env.seq, sender, env.ref_seq
                )
            else:
                raise ValueError(f"unsupported merge-tree op type {c['type']}")
            ls = m.local_metadata["localSeq"] if m.local else None
            if c["type"] == 0:
                self._record_converged_events(
                    "insert", self.backend.converged_insert_ranges(ins_segs), env.seq, ls
                )
            elif c["type"] in (1, 4, 5):
                self._record_converged_events(
                    "remove",
                    self.backend.converged_removed_ranges(rem_segs, env.seq),
                    env.seq,
                    ls,
                )
        self.backend.update_min_seq(env.min_seq)
        self._op_log.trim(env.min_seq)

    def on_min_seq(self, min_seq: int) -> None:
        self.backend.update_min_seq(min_seq)

    # ----------------------------------------------------- reconnect / stash
    def resubmit(self, contents: Any, local_metadata: Any, squash: bool = False) -> None:
        if contents.get("type") == 3:
            # Pending interval op: slide its endpoints over everything
            # sequenced since it was authored, then resubmit fresh.
            op = dict(contents["op"])
            ref = local_metadata["intervalRef"]
            sided = "startSide" in op or "endSide" in op
            # Degrade bound: the author's LOCAL view (acked + own pending,
            # including inserts resubmitted ahead of this op) — endpoints
            # anchored in own pending text must NOT collapse, while a
            # genuine forward slide off a removed suffix still degrades to
            # the "end" sentinel exactly like finalize_op on connected
            # replicas.
            n_local = self.backend.visible_length() if sided else 0
            for k, sk in (("start", "startSide"), ("end", "endSide")):
                if op.get(k) is None:
                    continue
                if sided:
                    if op[k] != SENTINEL_POS:
                        op[k], op[sk] = self._op_log.transform_place_from(
                            op[k], op.get(sk, 0), ref
                        )
                        if op[k] >= n_local:
                            from .sequence_intervals import Side

                            op[k], op[sk] = SENTINEL_POS, Side.BEFORE
                else:
                    op[k] = self._op_log.transform_from(op[k], ref)
            if op.get("start") is not None and op.get("end") is not None:
                if sided:
                    if place_boundary(op["start"], op.get("startSide", 0)) > \
                            place_boundary(op["end"], op.get("endSide", 0)):
                        op["end"] = op["start"]
                        op["endSide"] = op.get("startSide", 0)
                elif op["end"] < op["start"]:
                    op["end"] = op["start"]
            self.submit_local_message(
                {"type": 3, "label": contents["label"], "op": op},
                {"intervalRef": self._connection.ref_seq()},
            )
            return
        regenerated = self.backend.regenerate_pending(
            local_metadata["localSeq"], self._next_local_seq, squash=squash
        )
        for fresh_ls, op in regenerated:
            if op.get("type") == 2:
                # The backend speaks interned ids; the wire carries raw
                # property keys/values.
                op = dict(op)
                op["props"] = {
                    self._prop_names[int(p)]: self._val_raw[v]
                    for p, v in op["props"].items()
                }
            elif op.get("type") == 0 and isinstance(op.get("seg"), (dict, list)):
                # Marker / annotated-insert spec (or a per-props-run spec
                # list from regeneration): resolve interned prop ids to
                # their raw wire forms, part by part.
                def resolve(seg):
                    if isinstance(seg, str):
                        return seg
                    seg = dict(seg)
                    seg["props"] = {
                        self._prop_names[int(p)]: self._val_raw[v]
                        for p, v in seg.get("props", {}).items()
                    }
                    return seg

                op = dict(op)
                seg = op["seg"]
                op["seg"] = (
                    [resolve(part) for part in seg]
                    if isinstance(seg, list)
                    else resolve(seg)
                )
            self.submit_local_message(op, {"localSeq": fresh_ls})

    def apply_stashed(self, contents: Any) -> Any:
        """Re-mint a stashed op as a fresh local edit (ref applyStashedOp,
        merge-tree client.ts:1329): apply locally with a pending stamp, do
        NOT submit — the pending-state replay will resubmit it."""
        c = contents
        if c.get("type") == 3:
            coll = self.get_interval_collection(c["label"])
            coll._pending.append(dict(c["op"]))
            return {"intervalRef": self._connection.ref_seq()}
        ls = self._next_local_seq()
        key = encode_stamp(-1, ls)
        short = self.backend.local_client
        if c["type"] == 0:
            self._apply_insert_spec(c["seg"], c["pos1"], key, short, ALL_ACKED)
        elif c["type"] == 1:
            self.backend.apply_remove(c["pos1"], c["pos2"], key, short, ALL_ACKED)
        elif c["type"] == 2:
            for prop, value in c["props"].items():
                self.backend.apply_annotate(
                    c["pos1"], c["pos2"],
                    self._prop_id(prop), self._val_id(value),
                    key, short, ALL_ACKED,
                )
        elif c["type"] in (4, 5):
            p1, s1, p2, s2 = _decode_obliterate_places(c)
            self.backend.apply_obliterate(p1, s1, p2, s2, key, short, ALL_ACKED)
        else:
            raise ValueError(f"unsupported merge-tree op type {c['type']}")
        return {"localSeq": ls}

    # ------------------------------------------------------------ checkpoint
    def summarize(self) -> dict[str, Any]:
        """Merge-tree snapshot (backend-owned; ref snapshotV1.ts:42) plus
        the channel's interval collections and converged op log.  Interned
        property ids resolve to their raw forms so summaries are identical
        across replicas regardless of interning order."""
        out = self.backend.export_summary()
        for seg in out["segments"]:
            seg["props"] = {
                self._prop_names[int(p)]: [self._val_raw[v], k]
                for p, (v, k) in seg["props"].items()
            }
        # Lazily-materialized empty collections are omitted so replicas
        # that never touched a label summarize identically.
        out["intervals"] = {
            label: coll.summarize()
            for label, coll in self._collections.items()
            if coll.sequenced or coll._pending
        }
        out["opLog"] = self._op_log.to_json()
        return out

    def load(self, summary: dict[str, Any]) -> None:
        for label, data in summary.get("intervals", {}).items():
            self.get_interval_collection(label).load(data)
        self._op_log.load_json(summary.get("opLog", []))
        summary = dict(summary)
        summary["segments"] = [
            {
                **seg,
                "props": {
                    str(self._prop_id(p)): [self._val_id(v), k]
                    for p, (v, k) in seg["props"].items()
                },
            }
            for seg in summary["segments"]
        ]
        self.backend.import_summary(summary)

    # ------------------------------------------------------------------ views
    @property
    def text(self) -> str:
        # Local view: all acked ops + own pending (sentinel-stamped) ops.
        return self.backend.visible_text(ALL_ACKED, self.backend.local_client)

    def position_text(self) -> str:
        """The local view as a POSITION-indexed string: marker codepoints
        kept, so len() == visible_length and slicing by positions is exact
        (undo capture; ``text`` excludes markers and is shorter)."""
        return self.backend.visible_text(
            ALL_ACKED, self.backend.local_client, raw=True
        )

    # ------------------------------------------------------- attribution
    @staticmethod
    def _attr_key(key) -> dict[str, Any]:
        """Internal run key -> reference AttributionKey shape
        (runtime-definitions/src/attribution.ts: OpAttributionKey
        {type:"op", seq} / LocalAttributionKey / DetachedAttributionKey)."""
        return {"type": "op", "seq": key} if isinstance(key, int) else key

    def attribution_at(self, pos: int) -> dict[str, Any]:
        """Attribution key for the visible character at ``pos`` (ref
        attributionCollection.ts getAtOffset:203).  Resolve op keys to
        {user, timestamp} through the framework OpStreamAttributor."""
        return self._attr_key(
            self.backend.attribution_at(pos, ALL_ACKED, self.backend.local_client)
        )

    def attribution_range(
        self, start: int = 0, end: int | None = None
    ) -> list[dict[str, Any]]:
        """[{offset, key}] runs covering [start, end) (ref
        getKeysInOffsetRange:213: the first entry's offset may precede
        ``start`` when a run straddles it)."""
        runs = self.backend.attribution_runs(
            ALL_ACKED, self.backend.local_client
        )
        length = self.backend.visible_length(
            ALL_ACKED, self.backend.local_client
        )
        hi = length if end is None else min(end, length)
        out = []
        for i, (off, key) in enumerate(runs):
            run_end = runs[i + 1][0] if i + 1 < len(runs) else length
            # Keep only runs that actually intersect [start, hi).
            if run_end <= start or off >= hi:
                continue
            out.append({"offset": off, "key": self._attr_key(key)})
        return out


class PendingOverlayChannel(Channel):
    """Base for LWW-style DDSes: sequenced state + an ordered overlay of
    pending local ops. Owns the pendingId bookkeeping shared by map/cell:
    head-pop on ack, verbatim resubmit (position-free ops), stash re-entry,
    newest-first rollback. Subclasses implement ``_apply`` (sequenced state
    transition) and read through ``self._pending`` for optimistic views."""

    def __init__(self, channel_id: str) -> None:
        super().__init__(channel_id)
        self._pending: list[tuple[int, dict]] = []  # (pending_id, op)
        self._next_pending = 0

    def _submit(self, op: dict) -> None:
        self._next_pending += 1
        self._pending.append((self._next_pending, op))
        self.submit_local_message(op, {"pendingId": self._next_pending})

    def process_messages(self, collection: MessageCollection) -> None:
        for m in collection.messages:
            if m.local:
                pid = m.local_metadata["pendingId"]
                assert self._pending and self._pending[0][0] == pid, "pending skew"
                self._pending.pop(0)
            self._apply(m.contents)

    def _apply(self, op: dict) -> None:
        raise NotImplementedError

    def resubmit(self, contents: Any, local_metadata: Any, squash: bool = False) -> None:
        # LWW ops are position-free: verbatim resubmission is exact. The
        # pending entry stays in place; re-register its id with the metadata.
        pid = local_metadata["pendingId"]
        assert any(p[0] == pid for p in self._pending), "resubmit of unknown pending op"
        self.submit_local_message(contents, {"pendingId": pid})

    def apply_stashed(self, contents: Any) -> Any:
        self._next_pending += 1
        self._pending.append((self._next_pending, contents))
        return {"pendingId": self._next_pending}

    def rollback(self, contents: Any, local_metadata: Any) -> None:
        pid = local_metadata["pendingId"]
        assert self._pending and self._pending[-1][0] == pid, (
            "rollback must undo the latest local op first"
        )
        self._pending.pop()


class SharedMapChannel(PendingOverlayChannel):
    """SharedMap over the channel boundary (ref MapKernel, map/src/mapKernel.ts).

    Sequenced state applies ops in order; local reads overlay the pending
    list (a pending set/delete/clear masks remote values until acked —
    mapKernel.ts:707-852).
    """

    channel_type = "sharedMap"

    def __init__(self, channel_id: str) -> None:
        super().__init__(channel_id)
        self.sequenced: dict[str, Any] = {}

    # ------------------------------------------------------------ local edits
    def set(self, key: str, value: Any) -> None:
        self._submit({"type": "set", "key": key, "value": value})

    def delete(self, key: str) -> None:
        self._submit({"type": "delete", "key": key})

    def clear(self) -> None:
        self._submit({"type": "clear"})

    # ---------------------------------------------------------------- inbound
    def _apply(self, op: dict) -> None:
        kind = op["type"]
        if kind == "set":
            self.sequenced[op["key"]] = op["value"]
        elif kind == "delete":
            self.sequenced.pop(op["key"], None)
        elif kind == "clear":
            self.sequenced.clear()
        else:
            raise ValueError(f"unknown map op {kind}")

    # ------------------------------------------------------------ checkpoint
    def summarize(self) -> dict[str, Any]:
        return {"entries": dict(self.sequenced)}

    def load(self, summary: dict[str, Any]) -> None:
        self.sequenced = dict(summary["entries"])

    # ------------------------------------------------------------------ views
    def get(self, key: str) -> Any:
        for _pid, op in reversed(self._pending):
            if op["type"] == "clear":
                return None
            if op.get("key") == key:
                return op["value"] if op["type"] == "set" else None
        return self.sequenced.get(key)

    def keys(self) -> set[str]:
        out = set(self.sequenced)
        for _pid, op in self._pending:
            if op["type"] == "set":
                out.add(op["key"])
            elif op["type"] == "delete":
                out.discard(op["key"])
            else:
                out.clear()
        return out

    def items(self) -> dict[str, Any]:
        return {k: self.get(k) for k in self.keys()}


class ChannelTypeFactory:
    """Minimal IChannelFactory: a type string bound to a constructor."""

    def __init__(self, cls: type[Channel]) -> None:
        self.channel_type = cls.channel_type
        self._cls = cls

    def create(self, channel_id: str) -> Channel:
        return self._cls(channel_id)


SharedStringFactory = ChannelTypeFactory(SharedStringChannel)
SharedMapFactory = ChannelTypeFactory(SharedMapChannel)


def default_registry() -> dict[str, Any]:
    """Type string -> factory map covering the full DDS family (ref
    ISharedObjectRegistry + the fluid-framework re-export surface)."""
    from .extras import EXTRA_DDS_FACTORIES
    from .ot import SharedJsonOTFactory
    from .ot_json1 import SharedJson1Factory
    from .property_dds import PropertyTreeFactory
    from .shared_matrix import SharedMatrixFactory
    from .small import SMALL_DDS_FACTORIES
    from .tree import SharedTreeFactory

    out: dict[str, Any] = {
        SharedStringFactory.channel_type: SharedStringFactory,
        SharedMapFactory.channel_type: SharedMapFactory,
        SharedTreeFactory.channel_type: SharedTreeFactory,
    }
    out.update(SMALL_DDS_FACTORIES)
    out.update(EXTRA_DDS_FACTORIES)
    out[SharedMatrixFactory.channel_type] = SharedMatrixFactory
    out[SharedJsonOTFactory.channel_type] = SharedJsonOTFactory
    out[SharedJson1Factory.channel_type] = SharedJson1Factory
    out[PropertyTreeFactory.channel_type] = PropertyTreeFactory
    return out
