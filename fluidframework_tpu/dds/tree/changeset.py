"""The SharedTree changeset algebra: marks, rebase, invert, apply.

Reference parity: the ChangeRebaser contract (tree/src/core/rebase/
changeRebaser.ts:41 — rebase/invert laws) realized by one uniform mark-based
field change kind (sequence-field, feature-libraries/sequence-field/), which
subsumes the reference's optional/value fields (a value field is a
1-element sequence; a set is remove+insert). Node value overwrites are a
separate LWW slot on ``NodeChange`` like the reference's value changesets.

Coordinates discipline: ``rebase(a, b)`` requires a and b to share an input
context and returns a in the context *after* b. Convergence does NOT rely on
OT transform properties — the EditManager constructs the trunk version of
every commit deterministically from the same inputs on every replica
(editmanager.py), so identical state follows by construction; the rebase
laws are still property-tested (tests/test_tree_changeset.py) because they
are what makes rebased edits preserve intent.

Tie-break rules (deterministic, documented contract):
- concurrent inserts at one position: the earlier-sequenced content stays
  left; a rebased insert lands after it.
- an insert into a concurrently-removed range slides to the range start.
- remove/remove overlap: the later remove drops the overlap (cells already
  gone); modify under a removed node is dropped.
- concurrent value sets: later-sequenced wins (rebased set survives).

Enrichment (repair data): ``apply_node_change`` fills ``Remove.detached``
and value-change old values in place, so applied changes are invertible —
the reference's resubmit/undo enrichment (defaultResubmitMachine.ts).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Optional

from .forest import Node


# ---------------------------------------------------------------------------
# Mark model
# ---------------------------------------------------------------------------


@dataclass
class Skip:
    """Pass over ``count`` nodes unchanged (consumes N, produces N)."""

    count: int


@dataclass
class Insert:
    """Insert ``content`` at the current position (consumes 0, produces N)."""

    content: list[Node]


@dataclass
class Remove:
    """Remove ``count`` nodes (consumes N, produces 0). ``detached`` holds
    the removed subtrees once applied (repair data for invert/revive)."""

    count: int
    detached: Optional[list[Node]] = None


@dataclass
class Modify:
    """Apply a nested NodeChange to one node (consumes 1, produces 1)."""

    change: "NodeChange"


Mark = Skip | Insert | Remove | Modify


@dataclass
class NodeChange:
    """Changes to one node: an optional value overwrite plus per-field mark
    lists. ``value`` is (new,) before apply and (new, old) after (enriched
    for invert)."""

    value: Optional[tuple] = None
    fields: dict[str, list[Mark]] = field(default_factory=dict)

    def is_empty(self) -> bool:
        return self.value is None and not any(self.fields.values())


# ---------------------------------------------------------------------------
# Codec (wire format for ops/summaries)
# ---------------------------------------------------------------------------


def marks_to_json(marks: list[Mark]) -> list:
    out = []
    for m in marks:
        if isinstance(m, Skip):
            out.append(["s", m.count])
        elif isinstance(m, Insert):
            out.append(["i", [n.to_json() for n in m.content]])
        elif isinstance(m, Remove):
            out.append(
                ["r", m.count]
                if m.detached is None
                else ["r", m.count, [n.to_json() for n in m.detached]]
            )
        else:
            out.append(["m", change_to_json(m.change)])
    return out


def marks_from_json(data: list) -> list[Mark]:
    out: list[Mark] = []
    for e in data:
        kind = e[0]
        if kind == "s":
            out.append(Skip(e[1]))
        elif kind == "i":
            out.append(Insert([Node.from_json(n) for n in e[1]]))
        elif kind == "r":
            out.append(
                Remove(e[1], [Node.from_json(n) for n in e[2]] if len(e) > 2 else None)
            )
        else:
            out.append(Modify(change_from_json(e[1])))
    return out


def change_to_json(change: NodeChange) -> dict:
    out: dict[str, Any] = {}
    if change.value is not None:
        out["v"] = list(change.value)
    if change.fields:
        out["f"] = {k: marks_to_json(m) for k, m in change.fields.items()}
    return out


def change_from_json(data: dict) -> NodeChange:
    return NodeChange(
        value=tuple(data["v"]) if "v" in data else None,
        fields={k: marks_from_json(m) for k, m in data.get("f", {}).items()},
    )


def clone_change(change: NodeChange) -> NodeChange:
    return change_from_json(change_to_json(change))


# ---------------------------------------------------------------------------
# Rebase
# ---------------------------------------------------------------------------


def _consumes(m: Mark) -> int:
    if isinstance(m, (Skip, Remove)):
        return m.count
    if isinstance(m, Modify):
        return 1
    return 0


def _split(m: Mark, n: int) -> tuple[Mark, Mark | None]:
    """Split a consuming mark into a prefix consuming n and the remainder."""
    c = _consumes(m)
    assert 0 < n <= c
    if n == c:
        return m, None
    if isinstance(m, Skip):
        return Skip(n), Skip(c - n)
    if isinstance(m, Remove):
        det = m.detached
        return (
            Remove(n, det[:n] if det is not None else None),
            Remove(c - n, det[n:] if det is not None else None),
        )
    raise AssertionError("Modify cannot be split")


class _MarkStream:
    """Cursor over a mark list with implicit infinite trailing Skip."""

    def __init__(self, marks: list[Mark]) -> None:
        self._marks = [m for m in marks if _consumes(m) > 0 or isinstance(m, Insert)]
        self._i = 0

    def peek(self) -> Mark | None:
        return self._marks[self._i] if self._i < len(self._marks) else None

    def pop(self) -> Mark:
        m = self._marks[self._i]
        self._i += 1
        return m

    def push_front(self, m: Mark) -> None:
        self._i -= 1
        self._marks[self._i] = m

    def exhausted(self) -> bool:
        return self._i >= len(self._marks)


def _emit(out: list[Mark], m: Mark) -> None:
    """Append a mark, coalescing adjacent same-kind Skip/Remove runs."""
    if isinstance(m, Skip) and m.count == 0:
        return
    if isinstance(m, Remove) and m.count == 0:
        return
    if out:
        last = out[-1]
        if isinstance(last, Skip) and isinstance(m, Skip):
            out[-1] = Skip(last.count + m.count)
            return
        if (
            isinstance(last, Remove)
            and isinstance(m, Remove)
            and (last.detached is None) == (m.detached is None)
        ):
            out[-1] = Remove(
                last.count + m.count,
                (last.detached + m.detached) if last.detached is not None else None,
            )
            return
        if isinstance(last, Insert) and isinstance(m, Insert):
            out[-1] = Insert(last.content + m.content)
            return
    out.append(m)


def rebase_marks(a: list[Mark], b: list[Mark], a_after: bool = True) -> list[Mark]:
    """Rebase mark list ``a`` over ``b`` (same input context) — the result
    reads against the context with b applied.

    ``a_after`` is the tie-break side (sided OT): True when a is the
    later-sequenced change (its inserts land after b's at a shared position);
    False when a is the earlier-sequenced/trunk change being carried over a
    local pending one (its inserts stay left). The two sides are exact
    mirrors, which is what makes the convergence square commute."""
    sa, sb = _MarkStream(a), _MarkStream(b)
    out: list[Mark] = []
    while not (sa.exhausted() and sb.exhausted()):
        ma, mb = sa.peek(), sb.peek()
        a_ins = ma is not None and isinstance(ma, Insert)
        b_ins = mb is not None and isinstance(mb, Insert)
        # Tie at one boundary: the winner's (earlier-sequenced) content lands
        # left; skipping b's content keeps a's ranges from swallowing it.
        if b_ins and (a_after or not a_ins):
            sb.pop()
            _emit(out, Skip(len(mb.content)))
            continue
        if a_ins:
            sa.pop()
            _emit(out, ma)
            continue
        if ma is None:
            # a is done; the rest of b only affects positions a never touches.
            break
        if mb is None:
            sa.pop()
            _emit(out, ma)
            continue
        # Both consume input: advance over min(count) positions together.
        n = min(_consumes(ma), _consumes(mb))
        a_part, a_rest = _split(sa.pop(), n) if not isinstance(ma, Modify) else (sa.pop(), None)
        b_part, b_rest = _split(sb.pop(), n) if not isinstance(mb, Modify) else (sb.pop(), None)
        if a_rest is not None:
            sa.push_front(a_rest)
        if b_rest is not None:
            sb.push_front(b_rest)
        if isinstance(b_part, Remove):
            # Those positions are gone: a's skip/remove/modify there drops.
            continue
        if isinstance(a_part, Modify) and isinstance(b_part, Modify):
            _emit(out, Modify(rebase_node_change(a_part.change, b_part.change, a_after)))
        else:
            # b Skip or b Modify leave a's mark structurally intact.
            _emit(out, a_part)
    return out


def rebase_node_change(a: NodeChange, b: NodeChange, a_after: bool = True) -> NodeChange:
    """Rebase one node's change over another's. Value: the later-sequenced
    set wins (LWW) — a keeps its value when it is the later side, and drops
    it when the earlier side is carried over a later set. Fields: pairwise
    sided mark rebase."""
    value = a.value
    if a.value is not None and b.value is not None and not a_after:
        value = None
    out = NodeChange(value=value)
    for key, a_marks in a.fields.items():
        b_marks = b.fields.get(key)
        out.fields[key] = (
            rebase_marks(a_marks, b_marks, a_after) if b_marks else list(a_marks)
        )
    return out


# ---------------------------------------------------------------------------
# Invert (requires an applied/enriched change)
# ---------------------------------------------------------------------------


def invert_marks(marks: list[Mark]) -> list[Mark]:
    out: list[Mark] = []
    for m in marks:
        if isinstance(m, Skip):
            _emit(out, m)
        elif isinstance(m, Insert):
            _emit(out, Remove(len(m.content), [n.clone() for n in m.content]))
        elif isinstance(m, Remove):
            assert m.detached is not None, "invert of unapplied remove"
            _emit(out, Insert([n.clone() for n in m.detached]))
        else:
            _emit(out, Modify(invert_node_change(m.change)))
    return out


def invert_node_change(change: NodeChange) -> NodeChange:
    value = None
    if change.value is not None:
        assert len(change.value) == 2, "invert of unapplied value change"
        value = (change.value[1], change.value[0])
    return NodeChange(
        value=value,
        fields={k: invert_marks(m) for k, m in change.fields.items()},
    )


# ---------------------------------------------------------------------------
# Apply (mutates the forest; enriches the change in place)
# ---------------------------------------------------------------------------


def apply_marks(nodes: list[Node], marks: list[Mark]) -> None:
    pos = 0
    for m in marks:
        if isinstance(m, Skip):
            pos += m.count
        elif isinstance(m, Insert):
            nodes[pos:pos] = [n.clone() for n in m.content]
            pos += len(m.content)
        elif isinstance(m, Remove):
            assert pos + m.count <= len(nodes), "remove past end of field"
            m.detached = [n for n in nodes[pos : pos + m.count]]
            del nodes[pos : pos + m.count]
        else:
            apply_node_change(nodes[pos], m.change)
            pos += 1
    assert pos <= len(nodes), "marks walk past end of field"


def apply_node_change(node: Node, change: NodeChange) -> None:
    if change.value is not None:
        new = change.value[0]
        change.value = (new, node.value)
        node.value = new
    for key, marks in change.fields.items():
        apply_marks(node.fields.setdefault(key, []), marks)


# ---------------------------------------------------------------------------
# Edit builders (path-addressed convenience constructors)
# ---------------------------------------------------------------------------


def _wrap(path: list[tuple[str, int]], leaf: NodeChange) -> NodeChange:
    """Nest a NodeChange under a path of (field_key, index) steps."""
    for key, idx in reversed(path):
        leaf = NodeChange(fields={key: [Skip(idx), Modify(leaf)]} if idx else {key: [Modify(leaf)]})
    return leaf


def make_set_value(path: list[tuple[str, int]], value: Any) -> NodeChange:
    """Overwrite the leaf value of the node at ``path``."""
    assert path, "cannot set a value on the virtual root"
    prefix, (key, idx) = path[:-1], path[-1]
    inner = NodeChange(value=(value,))
    marks: list[Mark] = [Skip(idx)] if idx else []
    marks.append(Modify(inner))
    return _wrap(prefix, NodeChange(fields={key: marks}))


def make_insert(
    path: list[tuple[str, int]], field_key: str, index: int, content: list[Node]
) -> NodeChange:
    """Insert ``content`` at ``index`` of ``field_key`` under the node at
    ``path`` (path [] addresses the virtual root / root field)."""
    marks: list[Mark] = [Skip(index)] if index else []
    marks.append(Insert([n.clone() for n in content]))
    return _wrap(path, NodeChange(fields={field_key: marks}))


def make_remove(
    path: list[tuple[str, int]], field_key: str, index: int, count: int
) -> NodeChange:
    marks: list[Mark] = [Skip(index)] if index else []
    marks.append(Remove(count))
    return _wrap(path, NodeChange(fields={field_key: marks}))
