"""The SharedTree changeset algebra: marks, rebase, invert, apply.

Reference parity: the ChangeRebaser contract (tree/src/core/rebase/
changeRebaser.ts:41 — rebase/invert laws) realized by one uniform mark-based
field change kind (sequence-field, feature-libraries/sequence-field/), which
subsumes the reference's optional/value fields (a value field is a
1-element sequence; a set is remove+insert). Node value overwrites are a
separate LWW slot on ``NodeChange`` like the reference's value changesets.

Coordinates discipline: ``rebase(a, b)`` requires a and b to share an input
context and returns a in the context *after* b. Convergence does NOT rely on
OT transform properties — the EditManager constructs the trunk version of
every commit deterministically from the same inputs on every replica
(editmanager.py), so identical state follows by construction; the rebase
laws are still property-tested (tests/test_tree_changeset.py) because they
are what makes rebased edits preserve intent.

Tie-break rules (deterministic, documented contract):
- concurrent inserts at one position: the earlier-sequenced content stays
  left; a rebased insert lands after it.
- an insert into a concurrently-removed range slides to the range start.
- remove/remove overlap: the later remove drops the overlap (cells already
  gone); modify under a removed node is dropped.
- concurrent value sets: later-sequenced wins (rebased set survives).

Enrichment (repair data): ``apply_node_change`` fills ``Remove.detached``
and value-change old values in place, so applied changes are invertible —
the reference's resubmit/undo enrichment (defaultResubmitMachine.ts).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Optional

# Kind codes are the protocol-layer mark schema (shared with the pooled
# columns and the device kernels); re-exported here so dds-internal users
# keep their historical import site.
from ...protocol.mark_schema import (  # noqa: F401  (re-export shim)
    K_INSERT,
    K_MODIFY,
    K_MOVEIN,
    K_MOVEOUT,
    K_REMOVE,
    K_SKIP,
)
from .forest import Node


# ---------------------------------------------------------------------------
# Mark model
# ---------------------------------------------------------------------------


@dataclass(slots=True)
class Skip:
    """Pass over ``count`` nodes unchanged (consumes N, produces N)."""

    K = K_SKIP  # protocol mark-schema kind code (class-level, not a field)

    count: int


@dataclass(slots=True)
class Insert:
    """Insert ``content`` at the current position (consumes 0, produces N)."""

    K = K_INSERT

    content: list[Node]


@dataclass(slots=True)
class Remove:
    """Remove ``count`` nodes (consumes N, produces 0). ``detached`` holds
    the removed subtrees once applied (repair data for invert/revive)."""

    K = K_REMOVE

    count: int
    detached: Optional[list[Node]] = None


@dataclass(slots=True)
class Modify:
    """Apply a nested NodeChange to one node (consumes 1, produces 1)."""

    K = K_MODIFY

    change: "NodeChange"


@dataclass(slots=True)
class MoveOut:
    """Detach ``count`` nodes into the move register ``id`` (consumes N,
    produces 0).  ``offset`` is the first node's index within the ORIGINAL
    move — rebasing can split one move into discontiguous pieces, and the
    register must keep the move's original internal order regardless of
    where the pieces ended up (ref sequence-field moveOut/moveIn pair with
    cell ids)."""

    K = K_MOVEOUT

    count: int
    id: int
    offset: int = 0


@dataclass(slots=True)
class MoveIn:
    """Attach nodes of move register ``id`` here (consumes 0, produces
    ``count``).  ``offset`` selects which original-move offsets to attach
    (None = the whole register, sorted by offset) — needed when inverting a
    split move, whose inverse returns each piece to its own origin."""

    K = K_MOVEIN

    id: int
    count: int
    offset: int | None = None


Mark = Skip | Insert | Remove | Modify | MoveOut | MoveIn


@dataclass(slots=True)
class NodeChange:
    """Changes to one node: an optional value overwrite plus per-field
    changes.  ``value`` is (new,) before apply and (new, old) after
    (enriched for invert).

    A field change is EITHER a bare ``list[Mark]`` (the sequence field
    kind — wire format unchanged) or a kind-tagged change object
    (field_kinds.py: optional/value/registered extensions); every
    node-level operation dispatches per field through the registry
    (ref modular-schema/fieldKind.ts)."""

    value: Optional[tuple] = None
    fields: dict[str, Any] = field(default_factory=dict)

    def is_empty(self) -> bool:
        from .field_kinds import kind_of

        return self.value is None and all(
            kind_of(fc).is_empty(fc) for fc in self.fields.values()
        )


# ---------------------------------------------------------------------------
# Codec (wire format for ops/summaries)
# ---------------------------------------------------------------------------


def marks_to_json(marks: list[Mark]) -> list:
    out = []
    for m in marks:
        if isinstance(m, Skip):
            out.append(["s", m.count])
        elif isinstance(m, Insert):
            out.append(["i", [n.to_json() for n in m.content]])
        elif isinstance(m, Remove):
            out.append(
                ["r", m.count]
                if m.detached is None
                else ["r", m.count, [n.to_json() for n in m.detached]]
            )
        elif isinstance(m, MoveOut):
            out.append(["mo", m.count, m.id, m.offset])
        elif isinstance(m, MoveIn):
            out.append(["mi", m.id, m.count, m.offset])
        else:
            out.append(["m", change_to_json(m.change)])
    return out


def marks_from_json(data: list) -> list[Mark]:
    out: list[Mark] = []
    for e in data:
        kind = e[0]
        if kind == "s":
            out.append(Skip(e[1]))
        elif kind == "i":
            out.append(Insert([Node.from_json(n) for n in e[1]]))
        elif kind == "r":
            out.append(
                Remove(e[1], [Node.from_json(n) for n in e[2]] if len(e) > 2 else None)
            )
        elif kind == "mo":
            out.append(MoveOut(e[1], e[2], e[3] if len(e) > 3 else 0))
        elif kind == "mi":
            out.append(MoveIn(e[1], e[2], e[3] if len(e) > 3 else None))
        else:
            out.append(Modify(change_from_json(e[1])))
    return out


def change_to_json(change: NodeChange) -> dict:
    from .field_kinds import field_change_to_json

    out: dict[str, Any] = {}
    if change.value is not None:
        out["v"] = list(change.value)
    if change.fields:
        out["f"] = {
            k: field_change_to_json(fc) for k, fc in change.fields.items()
        }
    return out


def change_from_json(data: dict) -> NodeChange:
    from .field_kinds import field_change_from_json

    return NodeChange(
        value=tuple(data["v"]) if "v" in data else None,
        fields={
            k: field_change_from_json(m) for k, m in data.get("f", {}).items()
        },
    )


def _clone_mark(m: Mark) -> Mark:
    if isinstance(m, Skip):
        return Skip(m.count)
    if isinstance(m, Insert):
        return Insert([n.clone() for n in m.content])
    if isinstance(m, Remove):
        return Remove(
            m.count,
            [n.clone() for n in m.detached] if m.detached is not None else None,
        )
    if isinstance(m, MoveOut):
        return MoveOut(m.count, m.id, m.offset)
    if isinstance(m, MoveIn):
        return MoveIn(m.id, m.count, m.offset)
    return Modify(clone_change(m.change))


def _clone_field_change(fc):
    """Deep clone of one field change: mark lists clone mark-by-mark
    (SequenceFieldKind.clone is intentionally shallow for the rebase hot
    path), other kinds through their registry clone."""
    from .field_kinds import kind_of

    if isinstance(fc, list):
        return [_clone_mark(m) for m in fc]
    return kind_of(fc).clone(fc)


def clone_change(change: NodeChange) -> NodeChange:
    """Structural deep clone — no JSON codec pass; every sequenced commit
    is cloned once for the trunk-forest apply (shared_tree.py), so this
    is delta-pump hot-path code."""
    return NodeChange(
        value=tuple(change.value) if change.value is not None else None,
        fields={
            k: _clone_field_change(fc) for k, fc in change.fields.items()
        },
    )


# ---------------------------------------------------------------------------
# Rebase
# ---------------------------------------------------------------------------


def _consumes(m: Mark) -> int:
    if isinstance(m, (Skip, Remove, MoveOut)):
        return m.count
    if isinstance(m, Modify):
        return 1
    return 0


def _emit(out: list[Mark], m: Mark) -> None:
    """Append a mark, coalescing adjacent same-kind runs."""
    if isinstance(m, (Skip, Remove, MoveOut)) and m.count == 0:
        return
    if isinstance(m, MoveIn) and m.count == 0:
        return
    if out:
        last = out[-1]
        if isinstance(last, Skip) and isinstance(m, Skip):
            out[-1] = Skip(last.count + m.count)
            return
        if (
            isinstance(last, Remove)
            and isinstance(m, Remove)
            and (last.detached is None) == (m.detached is None)
        ):
            out[-1] = Remove(
                last.count + m.count,
                (last.detached + m.detached) if last.detached is not None else None,
            )
            return
        if isinstance(last, Insert) and isinstance(m, Insert):
            out[-1] = Insert(last.content + m.content)
            return
        if (
            isinstance(last, MoveOut)
            and isinstance(m, MoveOut)
            and last.id == m.id
            and last.offset + last.count == m.offset
        ):
            out[-1] = MoveOut(last.count + m.count, last.id, last.offset)
            return
    out.append(m)


class _Fates:
    """Per-input-node fates and boundary maps of one mark list ``b``.

    For every input position of b's context: whether the node survives into
    b's output, where it lands (moves followed to their destination), and
    any nested change b applied to it.  For every input boundary: the output
    boundary before/after b's productions there — the sided tie-break
    coordinates for rebasing boundary marks (Insert/MoveIn)."""

    GONE = ("gone", None, None)

    def __init__(self, b: list[Mark]) -> None:
        # fate[i] = ("keep", out_pos, nested_change|None) | ("gone",..)
        #         | ("moved", (move_id, offset), nested)
        self.fate: list[tuple] = []
        # MoveIn sites in mark order: (id, slice offset|None, count, out base)
        self._move_ins: list[tuple[int, int | None, int, int]] = []
        self._move_offsets: dict[int, list[int]] = {}  # id -> piece offsets
        self._offset_dest: dict[tuple[int, int], int] | None = None
        in_pos = 0
        out_pos = 0
        b_start = {}  # out position when each input boundary is reached
        prods = {}    # outputs b produces AT each input boundary
        for m in b:
            if in_pos not in b_start:
                b_start[in_pos] = out_pos
            if isinstance(m, Skip):
                for _ in range(m.count):
                    self.fate.append(("keep", out_pos, None))
                    out_pos += 1
                    in_pos += 1
                    b_start.setdefault(in_pos, out_pos)
            elif isinstance(m, Modify):
                self.fate.append(("keep", out_pos, m.change))
                out_pos += 1
                in_pos += 1
                b_start.setdefault(in_pos, out_pos)
            elif isinstance(m, Remove):
                for _ in range(m.count):
                    self.fate.append(self.GONE)
                    in_pos += 1
                    b_start.setdefault(in_pos, out_pos)
            elif isinstance(m, MoveOut):
                for off in range(m.count):
                    self.fate.append(("moved", (m.id, m.offset + off), None))
                    self._move_offsets.setdefault(m.id, []).append(
                        m.offset + off
                    )
                    in_pos += 1
                    b_start.setdefault(in_pos, out_pos)
            elif isinstance(m, Insert):
                prods[in_pos] = prods.get(in_pos, 0) + len(m.content)
                out_pos += len(m.content)
            elif isinstance(m, MoveIn):
                self._move_ins.append((m.id, m.offset, m.count, out_pos))
                prods[in_pos] = prods.get(in_pos, 0) + m.count
                out_pos += m.count
        self._tail_in = in_pos
        self._tail_out = out_pos
        self._b_start = b_start
        self._prods = prods

    def _dest_of(self, mid: int, off: int) -> int | None:
        """Output position of the moved node with original offset ``off`` —
        resolved by replaying apply_marks' register pop policy over b's
        MoveIn sites (slice MoveIns of one id each take their own nodes)."""
        if self._offset_dest is None:
            self._offset_dest = {}
            remaining = {
                k: sorted(v) for k, v in self._move_offsets.items()
            }
            for in_id, in_off, count, base in self._move_ins:
                pool = remaining.get(in_id, [])
                if in_off is None:
                    picked = pool[:]
                else:
                    picked = [o for o in pool if o >= in_off][:count]
                for i, o in enumerate(picked):
                    self._offset_dest[(in_id, o)] = base + i
                remaining[in_id] = [o for o in pool if o not in picked]
        return self._offset_dest.get((mid, off))

    def node(self, i: int) -> tuple[str, int | None, "NodeChange | None"]:
        """(kind, out_pos, nested) for input node i — moves resolved per
        piece offset (split moves keep original internal order; slice
        MoveIns each own their offsets)."""
        if i < len(self.fate):
            kind, payload, nested = self.fate[i]
            if kind == "moved":
                mid, off = payload
                dest = self._dest_of(mid, off)
                if dest is None:
                    return ("gone", None, nested)  # dangling move register
                return ("keep", dest, nested)
            return (kind, payload, nested)
        # Beyond b's marks: implicit trailing Skip.
        return ("keep", self._tail_out + (i - self._tail_in), None)

    def out_boundary(self, p: int, after_productions: bool) -> int:
        """Output boundary for input boundary p.  ``after_productions``
        implements the tie-break: True puts the rebased boundary mark AFTER
        b's own Insert/MoveIn content at p (a is the later-sequenced side),
        False before it.  A boundary inside a b-removed run slides to the
        run's start (both sided forms collapse there)."""
        if p in self._b_start:
            before = self._b_start[p]
        else:
            # Beyond b's marks: implicit trailing Skip (every interior
            # boundary is recorded during the walk).
            assert p >= self._tail_in, f"unrecorded interior boundary {p}"
            return self._tail_out + (p - self._tail_in)
        if not after_productions:
            return before
        # Only productions AT THIS input boundary count: content b produced
        # at later (possibly output-adjacent) boundaries stays to the right
        # of a mark anchored at p.
        return before + self._prods.get(p, 0)


def rebase_marks(a: list[Mark], b: list[Mark], a_after: bool = True) -> list[Mark]:
    """Rebase mark list ``a`` over ``b`` (same input context) — the result
    reads against the context with b applied.

    ``a_after`` is the tie-break side (sided OT): True when a is the
    later-sequenced change (its inserts land after b's at a shared position);
    False when a is the earlier-sequenced/trunk change being carried over a
    local pending one (its inserts stay left). The two sides are exact
    mirrors, which is what makes the convergence square commute.

    Algorithm (fate map, two phases): phase 1 computes every b-context
    node's fate — surviving output position (moves followed to their
    destination, ref sequence-field move effects), removal, or nested
    change — plus sided output coordinates for every input boundary.
    Phase 2 re-places each of a's marks by fate (per-node marks follow
    their node; boundary marks map through the sided boundary), sorts by
    output position, and emits with Skip gaps.  Unlike a stream merge this
    handles marks whose target moved LEFT of the cursor, which is what
    makes Move a first-class mark."""
    fates = _Fates(b)
    # Placements: (out_pos, kind_order, seq, mark) — kind_order 0 for
    # boundary marks (land before the node at that position), 1 for node
    # marks; seq preserves a's original order among equals.
    placements: list[tuple[int, int, int, Mark]] = []
    move_alive: dict[int, set[int]] = {}  # a's move id -> surviving offsets
    pending_movein: list[tuple[int, int, int, MoveIn]] = []
    in_pos = 0
    seq = 0
    for m in a:
        seq += 1
        if isinstance(m, Skip):
            in_pos += m.count
        elif isinstance(m, Insert):
            bp = fates.out_boundary(in_pos, after_productions=a_after)
            placements.append((bp, 0, seq, Insert(m.content)))
        elif isinstance(m, MoveIn):
            bp = fates.out_boundary(in_pos, after_productions=a_after)
            pending_movein.append((bp, 0, seq, MoveIn(m.id, m.count, m.offset)))
        elif isinstance(m, Modify):
            kind, pos, nested = fates.node(in_pos)
            if kind == "keep":
                change = (
                    rebase_node_change(m.change, nested, a_after)
                    if nested is not None
                    else m.change
                )
                placements.append((pos, 1, seq, Modify(change)))
            in_pos += 1
        elif isinstance(m, Remove):
            for off in range(m.count):
                kind, pos, _nested = fates.node(in_pos)
                if kind == "keep":
                    det = (
                        [m.detached[off]] if m.detached is not None else None
                    )
                    placements.append((pos, 1, seq, Remove(1, det)))
                in_pos += 1
        elif isinstance(m, MoveOut):
            alive = move_alive.setdefault(m.id, set())
            for off in range(m.count):
                # Move-vs-move conflict: when b ALSO moved this node, the
                # later-sequenced move owns it — the earlier side's MoveOut
                # drops (ref sequence-field move-effect competition).
                b_moved = (
                    in_pos < len(fates.fate)
                    and fates.fate[in_pos][0] == "moved"
                )
                kind, pos, _nested = fates.node(in_pos)
                if kind == "keep" and not (b_moved and not a_after):
                    placements.append(
                        (pos, 1, seq, MoveOut(1, m.id, m.offset + off))
                    )
                    alive.add(m.offset + off)
                in_pos += 1
    # MoveIn counts shrink to the surviving MoveOut offsets of their slice;
    # fully-emptied moves drop.
    for bp, ko, sq, mi in pending_movein:
        alive = move_alive.get(mi.id, set())
        if mi.offset is None:
            n_alive = len(alive)
        else:
            n_alive = sum(
                1 for o in alive if mi.offset <= o < mi.offset + mi.count
            )
        if n_alive > 0:
            placements.append((bp, ko, sq, MoveIn(mi.id, n_alive, mi.offset)))

    placements.sort(key=lambda t: (t[0], t[1], t[2]))
    out: list[Mark] = []
    cursor = 0
    for pos, _ko, _sq, mark in placements:
        if pos > cursor:
            _emit(out, Skip(pos - cursor))
            cursor = pos
        _emit(out, mark)
        cursor += _consumes(mark)
    return out


_kind_of = None


def _get_kind_of():
    """Lazily-cached field_kinds.kind_of (changeset cannot import
    field_kinds at module scope — field_kinds imports changeset — and the
    per-call ``from .field_kinds import kind_of`` paid importlib overhead
    on every rebase/compose dispatch in the trunk-translation hot path)."""
    global _kind_of
    if _kind_of is None:
        from .field_kinds import kind_of as k

        _kind_of = k
    return _kind_of


def rebase_node_change(a: NodeChange, b: NodeChange, a_after: bool = True) -> NodeChange:
    """Rebase one node's change over another's. Value: the later-sequenced
    set wins (LWW) — a keeps its value when it is the later side, and drops
    it when the earlier side is carried over a later set. Fields: pairwise
    per-kind rebase through the registry."""
    kind_of = _kind_of or _get_kind_of()

    value = a.value
    if a.value is not None and b.value is not None and not a_after:
        value = None
    out = NodeChange(value=value)
    for key, a_fc in a.fields.items():
        b_fc = b.fields.get(key)
        if b_fc is None:
            out.fields[key] = kind_of(a_fc).clone(a_fc)
            continue
        kind = kind_of(a_fc)
        b_kind = kind_of(b_fc)
        if kind is not b_kind:
            if getattr(kind, "is_sequence", False) and getattr(
                b_kind, "is_sequence", False
            ):
                # Sequence FAMILY (one pooled span, one object list):
                # same algebra, different storage — rebase through the
                # shared mark-list view.  The object-list result is what
                # a pure-object replica computes, so replicas converge
                # regardless of which representation each one holds.
                out.fields[key] = rebase_marks(
                    kind.as_mark_list(a_fc), b_kind.as_mark_list(b_fc),
                    a_after,
                )
                continue
            # Two producers spoke genuinely different kinds for one field
            # (a typed view racing an untyped/schema-less writer).
            # Degrade DETERMINISTICALLY instead of crashing the delta
            # pump: the later-sequenced side drops its field change, the
            # earlier side carries through untouched — every replica
            # computes the same outcome from the same sequence order.
            if a_after:
                continue
            out.fields[key] = kind_of(a_fc).clone(a_fc)
            continue
        out.fields[key] = kind.rebase(a_fc, b_fc, a_after)
    return out


def compose_node_change(a: NodeChange, b: NodeChange) -> NodeChange:
    """Compose node changes (b reads a's output context; result reads a's
    input context) — the third leg of the ChangeRebaser triple
    (changeRebaser.ts:41), dispatched per field kind."""
    kind_of = _kind_of or _get_kind_of()

    if b.value is not None:
        # Enrichment is carried by tuple LENGTH (2 = applied), never by the
        # prior's None-ness — None is a legitimate recorded prior.
        a_applied = a.value is not None and len(a.value) == 2
        if a_applied or len(b.value) == 2:
            value = (b.value[0], a.value[1] if a_applied else b.value[1])
        else:
            value = (b.value[0],)
    else:
        value = a.value
    out = NodeChange(value=value)
    for key in {**a.fields, **b.fields}:
        a_fc, b_fc = a.fields.get(key), b.fields.get(key)
        # One-sided branches CLONE: applying the composed change enriches
        # it in place (value tuples, Remove.detached), and sharing
        # structure with the inputs would silently rewrite the original
        # commits (applied_log / trunk) and corrupt their later invert.
        if a_fc is None:
            out.fields[key] = _clone_field_change(b_fc)
        elif b_fc is None:
            out.fields[key] = _clone_field_change(a_fc)
        elif kind_of(a_fc) is kind_of(b_fc):
            out.fields[key] = kind_of(a_fc).compose(a_fc, b_fc)
        else:
            out.fields[key] = _compose_mixed_kinds(a_fc, b_fc)
    return out


def _compose_mixed_kinds(a_fc, b_fc):
    """Compose a field's SEQUENTIAL history written under two different
    kinds (mixed typed/untyped producers, which rebase now tolerates):

    - a later optional SET shadows everything a did -> b alone;
    - a later optional NESTED edit targets the field's single resident
      node -> fold as a Modify at position 0 of a's marks;
    - later sequence marks over an optional change -> convert a to its
      mark/content form and fold b in (collapsing to <=1 node).
    """
    from .field_kinds import OptionalChange, compose_marks, kind_of

    # Normalize sequence-family operands to bare mark lists (a pooled
    # columnar span composes through the same object algebra — compose is
    # an offline path, never the pooled trunk fold).
    if not isinstance(a_fc, (list, OptionalChange)):
        k = kind_of(a_fc)
        if getattr(k, "is_sequence", False):
            a_fc = k.as_mark_list(a_fc)
    if not isinstance(b_fc, (list, OptionalChange)):
        k = kind_of(b_fc)
        if getattr(k, "is_sequence", False):
            b_fc = k.as_mark_list(b_fc)
    if isinstance(a_fc, list) and isinstance(b_fc, list):
        # Both were sequence-family (one pooled, one object): after
        # normalization this is a plain sequence compose.
        return compose_marks(a_fc, b_fc)
    if isinstance(b_fc, OptionalChange):
        if b_fc.set is not None:
            # Whole-content shadow — but b's recorded prior (set[1]) lives
            # in a's OUTPUT context, and the composed change reads a's
            # INPUT context: unwind a's marks from the prior so that
            # invert(compose) restores a's input state, not the
            # intermediate (mirrors the _safe_invert unwind in
            # OptionalFieldKind.compose).
            out = kind_of(b_fc).clone(b_fc)
            if len(out.set) == 2 and out.set[1] is not None:
                content = [out.set[1]]
                try:
                    inv = invert_marks(a_fc)
                except AssertionError:
                    # Unapplied/unenriched a: no repair data to protect.
                    inv = None
                if inv is not None:
                    try:
                        apply_marks(content, inv)
                    except (IndexError, AssertionError):
                        # a's output had residents beyond the recorded
                        # prior; keep the prior as-is (deterministic
                        # degrade, same on every replica).
                        pass
                    else:
                        out.set = (out.set[0], content[0] if content else None)
            return out
        return compose_marks(a_fc, [Modify(b_fc.nested)])
    # a is the optional change; b is sequence marks over a's output.
    assert isinstance(a_fc, OptionalChange)
    if a_fc.set is None:
        return compose_marks([Modify(a_fc.nested)], b_fc)
    new = a_fc.set[0]
    content = [new.clone()] if new is not None else []
    apply_marks(content, [_clone_mark(m) for m in b_fc])
    return OptionalChange(
        kind=a_fc.kind,
        set=(content[0] if content else None,) + tuple(a_fc.set[1:]),
    )


# ---------------------------------------------------------------------------
# Invert (requires an applied/enriched change)
# ---------------------------------------------------------------------------


def invert_marks(marks: list[Mark]) -> list[Mark]:
    # Per-id original offsets of this changeset's MoveOut pieces: inverting
    # a MoveIn that received a SPLIT move must hand each node back under its
    # original offset (the destination block's order is sorted-offsets).
    offsets_by_id: dict[int, list[int]] = {}
    for m in marks:
        if isinstance(m, MoveOut):
            offsets_by_id.setdefault(m.id, []).extend(
                range(m.offset, m.offset + m.count)
            )
    out: list[Mark] = []
    for m in marks:
        if isinstance(m, Skip):
            _emit(out, m)
        elif isinstance(m, Insert):
            _emit(out, Remove(len(m.content), [n.clone() for n in m.content]))
        elif isinstance(m, Remove):
            assert m.detached is not None, "invert of unapplied remove"
            _emit(out, Insert([n.clone() for n in m.detached]))
        elif isinstance(m, MoveOut):
            # The inverse moves this piece back to its own origin.
            _emit(out, MoveIn(m.id, m.count, m.offset))
        elif isinstance(m, MoveIn):
            if m.offset is not None:
                _emit(out, MoveOut(m.count, m.id, m.offset))
            else:
                # The destination block holds the surviving pieces in
                # sorted-original-offset order: move each back out under its
                # own offset so the returning MoveIn pieces find it.
                for off in sorted(offsets_by_id.get(m.id, range(m.count))):
                    _emit(out, MoveOut(1, m.id, off))
        else:
            _emit(out, Modify(invert_node_change(m.change)))
    return out


def invert_node_change(change: NodeChange) -> NodeChange:
    from .field_kinds import kind_of

    value = None
    if change.value is not None:
        assert len(change.value) == 2, "invert of unapplied value change"
        value = (change.value[1], change.value[0])
    return NodeChange(
        value=value,
        fields={k: kind_of(fc).invert(fc) for k, fc in change.fields.items()},
    )


# ---------------------------------------------------------------------------
# Apply (mutates the forest; enriches the change in place)
# ---------------------------------------------------------------------------


class _MoveRegister:
    """Placeholder emitted where a MoveIn lands before its MoveOut has been
    walked (moves can point either direction); resolved in a second pass."""

    def __init__(self, move_id: int, count: int, offset: int | None) -> None:
        self.move_id = move_id
        self.count = count
        self.offset = offset


def apply_marks(nodes: list[Node], marks: list[Mark]) -> None:
    """Single-pass rebuild: consume the input node list per mark, emitting
    the output; MoveIn emits a register placeholder patched once every
    MoveOut of the list has detached its nodes (a move may land left OR
    right of its source).

    Skip/Modify-only lists (the trunk checkpoint fold's dominant shape —
    value sets and nested edits) apply IN PLACE: no output list rebuild,
    no O(field) extend per edit."""
    structural = False
    for m in marks:
        if not isinstance(m, (Skip, Modify)):
            structural = True
            break
    if not structural:
        pos = 0
        for m in marks:
            if isinstance(m, Skip):
                pos += m.count
            else:
                apply_node_change(nodes[pos], m.change)
                pos += 1
        assert pos <= len(nodes), "marks walk past end of field"
        return
    out: list = []
    registers: dict[int, dict[int, Node]] = {}  # id -> {original offset: node}
    pos = 0
    for m in marks:
        if isinstance(m, Skip):
            out.extend(nodes[pos : pos + m.count])
            pos += m.count
        elif isinstance(m, Insert):
            out.extend(n.clone() for n in m.content)
        elif isinstance(m, Remove):
            assert pos + m.count <= len(nodes), "remove past end of field"
            m.detached = [n for n in nodes[pos : pos + m.count]]
            pos += m.count
        elif isinstance(m, MoveOut):
            assert pos + m.count <= len(nodes), "move-out past end of field"
            reg = registers.setdefault(m.id, {})
            for off in range(m.count):
                reg[m.offset + off] = nodes[pos + off]
            pos += m.count
        elif isinstance(m, MoveIn):
            out.append(_MoveRegister(m.id, m.count, m.offset))
        else:
            apply_node_change(nodes[pos], m.change)
            out.append(nodes[pos])
            pos += 1
    assert pos <= len(nodes), "marks walk past end of field"
    out.extend(nodes[pos:])
    resolved: list[Node] = []
    for item in out:
        if isinstance(item, _MoveRegister):
            reg = registers.get(item.move_id, {})
            if item.offset is None:
                picked = sorted(reg)
            else:
                # A slice MoveIn (inverse of a split move): its own offsets.
                picked = sorted(o for o in reg if o >= item.offset)[: item.count]
            assert len(picked) == item.count, (
                f"move register {item.move_id}: {len(picked)} nodes for a "
                f"MoveIn of {item.count}"
            )
            resolved.extend(reg.pop(o) for o in picked)
        else:
            resolved.append(item)
    nodes[:] = resolved


def apply_node_change(node: Node, change: NodeChange) -> None:
    from .field_kinds import kind_of

    if change.value is not None:
        new = change.value[0]
        change.value = (new, node.value)
        node.value = new
    for key, fc in change.fields.items():
        kind_of(fc).apply(node.fields.setdefault(key, []), fc)


# ---------------------------------------------------------------------------
# Commits: atomic sequences of changesets (transactions) + constraints
# ---------------------------------------------------------------------------
# A commit is a list of NodeChanges applied in order as ONE sequenced unit —
# the wire/trunk form of a transaction (ref shared-tree Transactor squashes
# into one commit; here the sequence itself is the unit, so no separate
# compose algebra is needed: rebase/invert/apply fold over the elements).
#
# Revision constraints (ref shared-tree runtime.constraints /
# modular-changeset revision constraints): a commit may declare that a node
# must still satisfy a predicate at sequencing time; rebasing the commit
# over a concurrent change that breaks the predicate turns the WHOLE commit
# into a no-op (``violated``).  Constraint paths rebase along with the
# commit so later checks stay in valid coordinates.
#
#   {"type": "nodeInDocument", "path": [[field, idx], ...]}
#       violated when a concurrent change detaches/replaces any node on
#       the path (ref nodeExistsConstraint).
#   {"type": "noChange", "path": [...]}
#       additionally violated when the subtree at path was edited at all.


class Commit(list):
    """list[NodeChange] plus constraint metadata.  Plain lists remain
    accepted everywhere (constraint-free commits)."""

    def __init__(self, changes=(), constraints=None, violated=False) -> None:
        super().__init__(changes)
        self.constraints = list(constraints or [])
        self.violated = violated


def _commit_meta(c) -> tuple[list, bool]:
    return getattr(c, "constraints", []), getattr(c, "violated", False)


def rebase_constraint_path(
    path: list, change: NodeChange
) -> tuple[list | None, bool]:
    """Carry a constraint path through one NodeChange.  Returns
    (rebased path | None when a node on the path was detached/replaced,
    whether the subtree at the path was edited)."""
    from .field_kinds import kind_of

    cur: NodeChange | None = change
    out: list = []
    for key, idx in path:
        fc = cur.fields.get(key) if cur is not None else None
        if fc is None:
            out.append([key, idx])
            cur = None
            continue
        kind = kind_of(fc)
        if getattr(kind, "is_sequence", False):
            # Sequence-family kinds (object mark lists AND pooled columnar
            # spans) expose the mark-list view the fate map walks.
            fates = _Fates(kind.as_mark_list(fc))
            k, pos, nested = fates.node(idx)
            if k != "keep":
                return None, True
            out.append([key, pos])
            cur = nested
        else:  # optional/value: a set replaces the resident node
            if fc.set is not None:
                return None, True
            out.append([key, idx])
            cur = fc.nested
    touched = cur is not None and not cur.is_empty()
    return out, touched


def _rebase_constraints(
    constraints: list, x: NodeChange
) -> tuple[list, bool]:
    """All constraint paths through one concurrent change; returns
    (updated constraints, violated)."""
    out = []
    for c in constraints:
        path, touched = rebase_constraint_path(c["path"], x)
        if path is None or (c["type"] == "noChange" and touched):
            return constraints, True
        out.append({**c, "path": path})
    return out, False


def rebase_commit_over_change(
    a: "Commit", x: NodeChange, a_after: bool = True
) -> "Commit":
    """Rebase the commit a = [c1..cn] over one change x sharing c1's input
    context: each element rebases over x carried through its predecessors.

    Constraints evaluate ONLY on the later/unsequenced side
    (``a_after=True``): a commit that is already sequenced settled its
    constraints at sequencing time, and re-judging it against LATER local
    pending edits (the bridge's a_after=False leg) would void it on some
    replicas only — divergence."""
    constraints, violated = _commit_meta(a)
    if constraints and not violated and a_after:
        constraints, violated = _rebase_constraints(constraints, x)
        if violated:
            return Commit([], constraints, violated=True)
    out = Commit(constraints=constraints, violated=violated)
    if violated:
        return out
    for c in a:
        out.append(rebase_node_change(c, x, a_after))
        x = rebase_node_change(x, c, not a_after)
    return out


def rebase_commit(a: "Commit", b: "Commit", a_after: bool = True) -> "Commit":
    """Rebase commit a over commit b (same input context).  Constraint
    violation anywhere in b voids a (the transaction no-ops)."""
    for x in b:
        a = rebase_commit_over_change(a, x, a_after)
        # Carrying x forward happens inside the helper per element; for the
        # next b element we need a's ORIGINAL context advanced by x, which
        # is exactly what successive iteration provides.
    return a


def invert_commit(cs: "Commit") -> "Commit":
    return Commit([invert_node_change(c) for c in reversed(cs)])


def compose_commit(cs: "Commit") -> NodeChange:
    """Squash a commit into ONE NodeChange (offline tooling; the trunk
    pipeline keeps commits as element lists)."""
    if not cs:
        return NodeChange()
    out = cs[0]
    for c in cs[1:]:
        out = compose_node_change(out, c)
    return out


def apply_commit(root: Node, cs: "Commit") -> None:
    for c in cs:
        apply_node_change(root, c)


def rollback_staged(root: Node, staged: list[NodeChange], applied_log: list[NodeChange]) -> None:
    """Transaction abort: invert and apply the staged changes newest-first,
    recording the inverses on the coordinate trail (shared by channel and
    branch transactions)."""
    for change in reversed(staged):
        inverse = invert_commit([change])
        apply_commit(root, inverse)
        applied_log.extend(inverse)


def clone_commit(cs: "Commit") -> "Commit":
    constraints, violated = _commit_meta(cs)
    return Commit(
        [clone_change(c) for c in cs],
        [dict(c, path=[list(p) for p in c["path"]]) for c in constraints],
        violated,
    )


def commit_to_json(cs: "Commit"):
    changes = [change_to_json(c) for c in cs]
    constraints, violated = _commit_meta(cs)
    if not constraints and not violated:
        return changes  # bare-list wire shape (constraint-free compat)
    return {"changes": changes, "constraints": constraints,
            "violated": violated}


def commit_from_json(data) -> "Commit":
    if isinstance(data, dict):
        return Commit(
            [change_from_json(c) for c in data["changes"]],
            data.get("constraints"),
            data.get("violated", False),
        )
    return Commit([change_from_json(c) for c in data])


# ---------------------------------------------------------------------------
# Edit builders (path-addressed convenience constructors)
# ---------------------------------------------------------------------------


def _wrap(path: list[tuple[str, int]], leaf: NodeChange) -> NodeChange:
    """Nest a NodeChange under a path of (field_key, index) steps."""
    for key, idx in reversed(path):
        leaf = NodeChange(fields={key: [Skip(idx), Modify(leaf)]} if idx else {key: [Modify(leaf)]})
    return leaf


def make_set_value(path: list[tuple[str, int]], value: Any) -> NodeChange:
    """Overwrite the leaf value of the node at ``path``."""
    assert path, "cannot set a value on the virtual root"
    prefix, (key, idx) = path[:-1], path[-1]
    inner = NodeChange(value=(value,))
    marks: list[Mark] = [Skip(idx)] if idx else []
    marks.append(Modify(inner))
    return _wrap(prefix, NodeChange(fields={key: marks}))


def make_insert_marks(index: int, content: list[Node]) -> list[Mark]:
    marks: list[Mark] = [Skip(index)] if index else []
    marks.append(Insert([n.clone() for n in content]))
    return marks


def make_remove_marks(index: int, count: int) -> list[Mark]:
    marks: list[Mark] = [Skip(index)] if index else []
    marks.append(Remove(count))
    return marks


def make_insert(
    path: list[tuple[str, int]], field_key: str, index: int, content: list[Node]
) -> NodeChange:
    """Insert ``content`` at ``index`` of ``field_key`` under the node at
    ``path`` (path [] addresses the virtual root / root field)."""
    return _wrap(path, NodeChange(fields={field_key: make_insert_marks(index, content)}))


def make_remove(
    path: list[tuple[str, int]], field_key: str, index: int, count: int
) -> NodeChange:
    return _wrap(path, NodeChange(fields={field_key: make_remove_marks(index, count)}))


def make_optional_set(
    path: list[tuple[str, int]], field_key: str, content: "Node | None",
    kind: str = "optional",
) -> NodeChange:
    """Replace the whole content of an optional/value field under ``path``
    (None clears an optional field; ref optional-field set/clear)."""
    from .field_kinds import OptionalChange

    return _wrap(path, NodeChange(fields={
        field_key: OptionalChange(
            kind=kind, set=(content.clone() if content is not None else None,)
        )
    }))


def make_optional_edit(
    path: list[tuple[str, int]], field_key: str, nested: NodeChange,
    kind: str = "optional",
) -> NodeChange:
    """Edit the node RESIDENT in an optional/value field (same-kind nested
    form — a field's kind is fixed by schema, so edits and sets of one
    field always rebase under the same registry entry)."""
    from .field_kinds import OptionalChange

    return _wrap(path, NodeChange(fields={
        field_key: OptionalChange(kind=kind, nested=nested)
    }))


def node_exists_constraint(path: list[tuple[str, int]]) -> dict:
    """The transaction no-ops if the node at ``path`` was detached by a
    concurrent edit (ref runtime.constraints nodeInDocument)."""
    return {"type": "nodeInDocument", "path": [list(p) for p in path]}


def no_change_constraint(path: list[tuple[str, int]]) -> dict:
    """Stricter: the transaction no-ops if the subtree at ``path`` was
    edited at all concurrently."""
    return {"type": "noChange", "path": [list(p) for p in path]}


_move_counter = 0


def make_move_marks(src_index: int, count: int, dst_index: int) -> list[Mark]:
    """The field-level mark list of a same-field move (see make_move)."""
    global _move_counter
    _move_counter += 1
    mid = _move_counter
    marks: list[Mark] = []
    if dst_index <= src_index:
        if dst_index:
            marks.append(Skip(dst_index))
        marks.append(MoveIn(mid, count))
        if src_index > dst_index:
            marks.append(Skip(src_index - dst_index))
        marks.append(MoveOut(count, mid))
    elif dst_index >= src_index + count:
        if src_index:
            marks.append(Skip(src_index))
        marks.append(MoveOut(count, mid))
        gap = dst_index - src_index - count
        if gap:
            marks.append(Skip(gap))
        marks.append(MoveIn(mid, count))
    else:  # destination inside the moved range: identity
        if src_index:
            marks.append(Skip(src_index))
        marks.append(MoveOut(count, mid))
        marks.append(MoveIn(mid, count))
    return marks


def make_move(
    path: list[tuple[str, int]],
    field_key: str,
    src_index: int,
    count: int,
    dst_index: int,
) -> NodeChange:
    """Move ``count`` nodes from ``src_index`` to the boundary ``dst_index``
    of the same field, both in PRE-move coordinates (ref sequence-field
    moveOut/moveIn pair).  A destination inside the moved range is the
    identity move."""
    return _wrap(
        path,
        NodeChange(fields={field_key: make_move_marks(src_index, count, dst_index)}),
    )
