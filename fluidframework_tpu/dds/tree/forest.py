"""Tree state store: object forest + columnar uniform chunks.

Reference parity: the object forest (tree/src/feature-libraries/object-forest/)
is the general-purpose mutable store; ``UniformChunk``
(feature-libraries/chunked-forest/uniformChunk.ts:42) is the reference's
columnar, shape-deduplicated value representation — reproduced here as a
numpy-backed column store because it is exactly the layout TPU kernels want
(see ops/tree_kernel.py for the batched value-update kernels over chunk
columns).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Iterator

import numpy as np


@dataclass
class Node:
    """One tree node: a type tag, an optional leaf value, and named fields
    each holding an ordered sequence of child nodes (every field is a
    sequence; value/optional fields are schema-constrained sequences, the
    same unification the reference's modular schema uses)."""

    type: str
    value: Any = None
    fields: dict[str, list["Node"]] = field(default_factory=dict)

    # ------------------------------------------------------------------ codec
    def to_json(self) -> dict:
        out: dict[str, Any] = {"t": self.type}
        if self.value is not None:
            out["v"] = self.value
        # Canonical form: an EMPTIED sequence field is identical to one that
        # never existed (the reference's forests prune empty fields the same
        # way), so replicas that took different routes to the same tree
        # serialize identically — and match the columnar materialization,
        # which has no rows to represent an empty field with.
        present = {
            k: [c.to_json() for c in children]
            for k, children in self.fields.items()
            if children
        }
        if present:
            out["f"] = present
        return out

    @staticmethod
    def from_json(data: dict) -> "Node":
        return Node(
            type=data["t"],
            value=data.get("v"),
            fields={
                k: [Node.from_json(c) for c in children]
                for k, children in data.get("f", {}).items()
            },
        )

    def clone(self) -> "Node":
        # Structural clone (no JSON codec pass — this sits on the trunk
        # apply hot path).  Empty fields prune, matching the canonical
        # to_json form; non-scalar leaf values deep-copy via the codec
        # (they are rare; scalars dominate).
        v = self.value
        if not isinstance(v, (int, float, str, bool, type(None))):
            import json as _json

            v = _json.loads(_json.dumps(v))
        return Node(
            type=self.type,
            value=v,
            fields={
                k: [c.clone() for c in children]
                for k, children in self.fields.items()
                if children
            },
        )

    def child(self, field_key: str, index: int) -> "Node":
        return self.fields[field_key][index]

    def equal(self, other: "Node") -> bool:
        return self.to_json() == other.to_json()


ROOT_FIELD = ""


class Forest:
    """The document's tree state: a virtual root node whose ``ROOT_FIELD``
    sequence holds the root content. Mutated only through changeset apply
    (changeset.apply_node_change) so every replica performs identical
    transitions."""

    def __init__(self) -> None:
        self.root = Node(type="__root__")
        self.root.fields[ROOT_FIELD] = []

    # ------------------------------------------------------------------ views
    @property
    def root_field(self) -> list[Node]:
        return self.root.fields.setdefault(ROOT_FIELD, [])

    def node_at(self, path: list[tuple[str, int]]) -> Node:
        """Resolve a path of (field_key, index) steps from the virtual root."""
        node = self.root
        for key, idx in path:
            node = node.fields[key][idx]
        return node

    def iter_nodes(self) -> Iterator[tuple[list[tuple[str, int]], Node]]:
        """Depth-first cursor over (path, node) — the forest cursor analog
        (reference ITreeCursor over object forest)."""

        def walk(node: Node, path: list[tuple[str, int]]):
            for key, children in node.fields.items():
                for i, child in enumerate(children):
                    cpath = path + [(key, i)]
                    yield cpath, child
                    yield from walk(child, cpath)

        yield from walk(self.root, [])

    # ------------------------------------------------------------------ codec
    def to_json(self) -> dict:
        return {"root": [n.to_json() for n in self.root_field]}

    def load_json(self, data: dict) -> None:
        self.root = Node(type="__root__")
        self.root.fields[ROOT_FIELD] = [Node.from_json(n) for n in data["root"]]

    def equal(self, other: "Forest") -> bool:
        return self.to_json() == other.to_json()


# ---------------------------------------------------------------------------
# Uniform chunks: columnar representation of shape-uniform subtree arrays
# ---------------------------------------------------------------------------

def _encode_column(col: list) -> Any:
    """ndarray-back a column only when it is type-homogeneous: all int or
    all float (a mixed column through np.asarray would coerce ints to floats
    and change values across a summary roundtrip)."""
    if col and all(type(v) is int for v in col):
        return np.asarray(col, dtype=np.int64)
    if col and all(type(v) is float for v in col):
        return np.asarray(col, dtype=np.float64)
    return list(col)


@dataclass
class UniformChunk:
    """A run of sibling subtrees that all share one shape, stored as value
    columns (one column per leaf position in the shape) — the reference's
    chunked-forest layout (uniformChunk.ts:42) and the natural device layout:
    numeric columns are contiguous ndarrays a kernel can gather/scatter.

    ``shape``   — the per-subtree template as a Node with leaf values elided
                  (value slots marked by leaf type tag).
    ``columns`` — list (one per leaf slot, in cursor order) of length-N
                  arrays/lists of values.
    """

    shape: Node
    columns: list[Any]
    count: int

    @staticmethod
    def try_encode(nodes: list[Node]) -> "UniformChunk | None":
        """Columnarize if every node shares the same shape (type structure);
        returns None when the run is not uniform."""
        if len(nodes) < 2:
            return None
        template = _shape_of(nodes[0])
        for n in nodes[1:]:
            if _shape_of(n).to_json() != template.to_json():
                return None
        slots = [[] for _ in range(_leaf_count(template))]
        for n in nodes:
            for i, v in enumerate(_leaf_values(n)):
                slots[i].append(v)
        columns: list[Any] = [_encode_column(col) for col in slots]
        return UniformChunk(shape=template, columns=columns, count=len(nodes))

    def decode(self) -> list[Node]:
        # One bulk host conversion per COLUMN (tolist == elementwise
        # .item(): python scalars out), not one sync per element per row —
        # the per-element form is the jit-host-sync-loop antipattern
        # fftpu-check flags, and decode() runs once per chunk per summary
        # load with count x columns elements.
        cols = [
            np.asarray(c).tolist() if isinstance(c, np.ndarray) else c
            for c in self.columns
        ]
        out = []
        for i in range(self.count):
            out.append(_fill_shape(self.shape, iter(c[i] for c in cols)))
        return out

    def to_json(self) -> dict:
        return {
            "shape": self.shape.to_json(),
            "count": self.count,
            "columns": [
                c.tolist() if isinstance(c, np.ndarray) else c for c in self.columns
            ],
        }

    @staticmethod
    def from_json(data: dict) -> "UniformChunk":
        return UniformChunk(
            shape=Node.from_json(data["shape"]),
            count=data["count"],
            columns=[_encode_column(c) for c in data["columns"]],
        )


def _shape_of(node: Node) -> Node:
    """Type structure with values elided. Field keys are traversed in sorted
    order everywhere in this codec: shape equality is dict-order-insensitive,
    so the value-slot ordering must be too or columns misalign between
    siblings built with different field insertion orders."""
    return Node(
        type=node.type,
        value=None,
        fields={k: [_shape_of(c) for c in node.fields[k]] for k in sorted(node.fields)},
    )


def _leaf_count(shape: Node) -> int:
    # EVERY node owns a value slot (a node may carry both a value and
    # children); structural nodes just column None.
    n = 1
    for k in sorted(shape.fields):
        for c in shape.fields[k]:
            n += _leaf_count(c)
    return n


def _leaf_values(node: Node) -> list[Any]:
    out = [node.value]
    for k in sorted(node.fields):
        for c in node.fields[k]:
            out.extend(_leaf_values(c))
    return out


def _fill_shape(shape: Node, values: Iterator[Any]) -> Node:
    value = next(values)
    return Node(
        type=shape.type,
        value=value,
        fields={
            k: [_fill_shape(c, values) for c in shape.fields[k]]
            for k in sorted(shape.fields)
        },
    )


def encode_field_chunked(nodes: list[Node]) -> list[dict]:
    """Summary codec for a field: greedy runs of shape-uniform siblings become
    uniform chunks, the rest stay plain nodes (reference forest-summary with
    incremental chunk reuse is approximated by whole-field chunk encode)."""
    out: list[dict] = []
    i = 0
    while i < len(nodes):
        j = i + 1
        template = _shape_of(nodes[i]).to_json()
        while j < len(nodes) and _shape_of(nodes[j]).to_json() == template:
            j += 1
        chunk = UniformChunk.try_encode(nodes[i:j]) if j - i >= 4 else None
        if chunk is not None:
            out.append({"chunk": chunk.to_json()})
        else:
            out.extend({"node": n.to_json()} for n in nodes[i:j])
        i = j
    return out


def decode_field_chunked(entries: list[dict]) -> list[Node]:
    out: list[Node] = []
    for e in entries:
        if "chunk" in e:
            out.extend(UniformChunk.from_json(e["chunk"]).decode())
        else:
            out.append(Node.from_json(e["node"]))
    return out
