"""Tree state store: object forest + columnar uniform chunks.

Reference parity: the object forest (tree/src/feature-libraries/object-forest/)
is the general-purpose mutable store; ``UniformChunk``
(feature-libraries/chunked-forest/uniformChunk.ts:42) is the reference's
columnar, shape-deduplicated value representation — reproduced here as a
numpy-backed column store because it is exactly the layout TPU kernels want
(see ops/tree_kernel.py for the batched value-update kernels over chunk
columns).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Iterator

import numpy as np


@dataclass
class Node:
    """One tree node: a type tag, an optional leaf value, and named fields
    each holding an ordered sequence of child nodes (every field is a
    sequence; value/optional fields are schema-constrained sequences, the
    same unification the reference's modular schema uses)."""

    type: str
    value: Any = None
    fields: dict[str, list["Node"]] = field(default_factory=dict)

    # ------------------------------------------------------------------ codec
    def to_json(self) -> dict:
        out: dict[str, Any] = {"t": self.type}
        if self.value is not None:
            out["v"] = self.value
        if self.fields:
            out["f"] = {
                k: [c.to_json() for c in children] for k, children in self.fields.items()
            }
        return out

    @staticmethod
    def from_json(data: dict) -> "Node":
        return Node(
            type=data["t"],
            value=data.get("v"),
            fields={
                k: [Node.from_json(c) for c in children]
                for k, children in data.get("f", {}).items()
            },
        )

    def clone(self) -> "Node":
        return Node.from_json(self.to_json())

    def child(self, field_key: str, index: int) -> "Node":
        return self.fields[field_key][index]

    def equal(self, other: "Node") -> bool:
        return self.to_json() == other.to_json()


ROOT_FIELD = ""


class Forest:
    """The document's tree state: a virtual root node whose ``ROOT_FIELD``
    sequence holds the root content. Mutated only through changeset apply
    (changeset.apply_node_change) so every replica performs identical
    transitions."""

    def __init__(self) -> None:
        self.root = Node(type="__root__")
        self.root.fields[ROOT_FIELD] = []

    # ------------------------------------------------------------------ views
    @property
    def root_field(self) -> list[Node]:
        return self.root.fields.setdefault(ROOT_FIELD, [])

    def node_at(self, path: list[tuple[str, int]]) -> Node:
        """Resolve a path of (field_key, index) steps from the virtual root."""
        node = self.root
        for key, idx in path:
            node = node.fields[key][idx]
        return node

    def iter_nodes(self) -> Iterator[tuple[list[tuple[str, int]], Node]]:
        """Depth-first cursor over (path, node) — the forest cursor analog
        (reference ITreeCursor over object forest)."""

        def walk(node: Node, path: list[tuple[str, int]]):
            for key, children in node.fields.items():
                for i, child in enumerate(children):
                    cpath = path + [(key, i)]
                    yield cpath, child
                    yield from walk(child, cpath)

        yield from walk(self.root, [])

    # ------------------------------------------------------------------ codec
    def to_json(self) -> dict:
        return {"root": [n.to_json() for n in self.root_field]}

    def load_json(self, data: dict) -> None:
        self.root = Node(type="__root__")
        self.root.fields[ROOT_FIELD] = [Node.from_json(n) for n in data["root"]]

    def equal(self, other: "Forest") -> bool:
        return self.to_json() == other.to_json()


# ---------------------------------------------------------------------------
# Uniform chunks: columnar representation of shape-uniform subtree arrays
# ---------------------------------------------------------------------------

_NUMERIC_KINDS = {"int", "float"}


@dataclass
class UniformChunk:
    """A run of sibling subtrees that all share one shape, stored as value
    columns (one column per leaf position in the shape) — the reference's
    chunked-forest layout (uniformChunk.ts:42) and the natural device layout:
    numeric columns are contiguous ndarrays a kernel can gather/scatter.

    ``shape``   — the per-subtree template as a Node with leaf values elided
                  (value slots marked by leaf type tag).
    ``columns`` — list (one per leaf slot, in cursor order) of length-N
                  arrays/lists of values.
    """

    shape: Node
    columns: list[Any]
    count: int

    @staticmethod
    def try_encode(nodes: list[Node]) -> "UniformChunk | None":
        """Columnarize if every node shares the same shape (type structure);
        returns None when the run is not uniform."""
        if len(nodes) < 2:
            return None
        template = _shape_of(nodes[0])
        for n in nodes[1:]:
            if _shape_of(n).to_json() != template.to_json():
                return None
        slots = [[] for _ in range(_leaf_count(template))]
        for n in nodes:
            for i, v in enumerate(_leaf_values(n)):
                slots[i].append(v)
        columns: list[Any] = []
        for col in slots:
            if all(isinstance(v, (int, float)) and not isinstance(v, bool) for v in col):
                columns.append(np.asarray(col))
            else:
                columns.append(list(col))
        return UniformChunk(shape=template, columns=columns, count=len(nodes))

    def decode(self) -> list[Node]:
        out = []
        for i in range(self.count):
            values = [
                (c[i].item() if isinstance(c, np.ndarray) else c[i])
                for c in self.columns
            ]
            out.append(_fill_shape(self.shape, iter(values)))
        return out

    def to_json(self) -> dict:
        return {
            "shape": self.shape.to_json(),
            "count": self.count,
            "columns": [
                c.tolist() if isinstance(c, np.ndarray) else c for c in self.columns
            ],
        }

    @staticmethod
    def from_json(data: dict) -> "UniformChunk":
        return UniformChunk(
            shape=Node.from_json(data["shape"]),
            count=data["count"],
            columns=[
                np.asarray(c)
                if c and all(isinstance(v, (int, float)) and not isinstance(v, bool) for v in c)
                else c
                for c in data["columns"]
            ],
        )


def _shape_of(node: Node) -> Node:
    """Type structure with values elided (leaf slots keep only their type)."""
    return Node(
        type=node.type,
        value=None,
        fields={k: [_shape_of(c) for c in v] for k, v in node.fields.items()},
    )


def _leaf_count(shape: Node) -> int:
    n = 1 if not shape.fields else 0
    for children in shape.fields.values():
        for c in children:
            n += _leaf_count(c)
    return n


def _leaf_values(node: Node) -> list[Any]:
    if not node.fields:
        return [node.value]
    out = []
    for children in node.fields.values():
        for c in children:
            out.extend(_leaf_values(c))
    return out


def _fill_shape(shape: Node, values: Iterator[Any]) -> Node:
    if not shape.fields:
        return Node(type=shape.type, value=next(values))
    return Node(
        type=shape.type,
        fields={
            k: [_fill_shape(c, values) for c in children]
            for k, children in shape.fields.items()
        },
    )


def encode_field_chunked(nodes: list[Node]) -> list[dict]:
    """Summary codec for a field: greedy runs of shape-uniform siblings become
    uniform chunks, the rest stay plain nodes (reference forest-summary with
    incremental chunk reuse is approximated by whole-field chunk encode)."""
    out: list[dict] = []
    i = 0
    while i < len(nodes):
        j = i + 1
        template = _shape_of(nodes[i]).to_json()
        while j < len(nodes) and _shape_of(nodes[j]).to_json() == template:
            j += 1
        chunk = UniformChunk.try_encode(nodes[i:j]) if j - i >= 4 else None
        if chunk is not None:
            out.append({"chunk": chunk.to_json()})
        else:
            out.extend({"node": n.to_json()} for n in nodes[i:j])
        i = j
    return out


def decode_field_chunked(entries: list[dict]) -> list[Node]:
    out: list[Node] = []
    for e in entries:
        if "chunk" in e:
            out.extend(UniformChunk.from_json(e["chunk"]).decode())
        else:
            out.append(Node.from_json(e["node"]))
    return out
