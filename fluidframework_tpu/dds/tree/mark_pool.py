"""Pooled, slotted columnar mark store for the tree changeset hot path.

The object-mark fold (changeset.py) pays one Python object per mark per
rebase — ``Mark.__init__`` alone measured ~30% of config5 host time, and
the EditManager window fold re-materializes every mark of every cached
cross-rebase stream entry on every commit.  This module keeps the SAME
mark algebra but stores sequence-field mark lists as parallel int32/object
columns inside reusable pool blocks:

- ``MarkPool``: fixed-size blocks of four ``array('i')`` columns
  (kind / a / b / c) plus one object column (insert content, removed
  subtrees, nested NodeChanges).  Lists are bump-allocated contiguous
  spans; a block whose spans all died is recycled through a free list, so
  steady-state rebase traffic allocates no new storage at all
  (``mark_pool_hit_rate`` in engine health is the recycle rate).
- ``PooledMarks``: an immutable (pool, block, start, n) span handle.
  Changesets hold these handles in their ``fields`` dicts; the field-kind
  registry dispatches them through ``PooledSequenceFieldKind`` so the
  generic rebase/compose/invert algebra works unchanged.
- rebase runs as COLUMN passes (``_rebase_cols``): the per-input-node fate
  map and sided boundary map of changeset.rebase_marks computed over runs
  instead of per-node Python objects, with two structural fast paths —
  a non-structural ``b`` (only Skip/Modify) returns ``a``'s span UNCHANGED
  when no Modify positions collide (the incremental change-propagation
  reuse: a commit rebasing over a disjoint trunk window keeps its cached
  stream spans instead of re-materializing marks), and the fused
  ``rebase_pair`` computes both legs of the EditManager bridge from one
  pass instead of two mirrored walks.

Byte-identity contract: every pooled operation produces the same wire
JSON (``marks_to_json`` shape) as the object path — the object fold stays
alive as the fuzz oracle (``TreeBatchEngine(mark_pool=False)``,
``EditManager(mark_pool=None)``), the same pattern as every prior kernel
migration.  Mark lists containing moves (both sides structural) fall back
to the object ``rebase_marks`` — materialize, rebase, re-pool — so the
fallback IS the oracle and cannot diverge.

Pooled spans are immutable after ``seal``: enrichment (apply-time
``Remove.detached`` / value priors) only ever happens on the MATERIALIZED
trunk commit the EditManager returns, never on pooled stream state, which
is what makes identity sharing across fold stages safe.
"""

from __future__ import annotations

from array import array
from typing import Any

from .changeset import (
    Commit,
    Insert,
    Modify,
    MoveIn,
    MoveOut,
    NodeChange,
    Remove,
    Skip,
    _commit_meta,
    change_to_json,
    rebase_marks,
)
from .field_kinds import (
    FIELD_KINDS,
    FieldKind,
    OptionalChange,
    field_change_from_json,
    kind_of,
)
from .forest import Node

# Kind codes, span flags and sentinels are the protocol-layer mark schema
# (protocol/mark_schema.py) — shared with the device kernels.  Historical
# local names kept: every pass below reads them, and dds-internal callers
# import them from here.
from ...protocol.mark_schema import (  # noqa: F401  (re-export shim)
    F_CANONICAL,
    F_INSERT,
    F_MODIFY,
    F_MOVE,
    F_REMOVE,
    F_STRUCTURAL as _F_STRUCTURAL,
    K_INSERT,
    K_MODIFY,
    K_MOVEIN,
    K_MOVEOUT,
    K_REMOVE,
    K_SKIP,
    NONE_OFF as _NONE_OFF,
)


class _Block:
    """One pool block: parallel int columns + an object column."""

    __slots__ = ("kind", "a", "b", "c", "obj", "used", "live")

    def __init__(self, size: int) -> None:
        zeros = array("i", bytes(4 * size))
        self.kind = array("i", zeros)
        self.a = array("i", zeros)
        self.b = array("i", zeros)
        self.c = array("i", zeros)
        self.obj: list = [None] * size
        self.used = 0
        self.live = 0  # live spans (block recycles at zero)


class MarkPool:
    """Slab allocator for mark-list spans with whole-block recycling.

    One pool is shared across a fleet (TreeBatchEngine owns one for all
    its EditManagers) so occupancy and reuse gauges are fleet-wide."""

    BLOCK = 4096

    __slots__ = (
        "block_size", "blocks", "_free", "_cur",
        "spans_allocated", "blocks_allocated", "blocks_recycled",
        "reuse_hits", "live_slots",
    )

    def __init__(self, block_size: int = BLOCK) -> None:
        self.block_size = block_size
        self.blocks: list[_Block] = []
        self._free: list[int] = []
        self._cur = -1
        self.spans_allocated = 0
        self.blocks_allocated = 0
        self.blocks_recycled = 0
        self.reuse_hits = 0  # rebases answered by an existing span
        self.live_slots = 0

    # ------------------------------------------------------------ allocation
    def _fresh_block(self, size: int) -> int:
        if size <= self.block_size and self._free:
            self.blocks_recycled += 1
            idx = self._free.pop()
            self.blocks[idx].used = 0
            return idx
        self.blocks_allocated += 1
        self.blocks.append(_Block(max(size, self.block_size)))
        return len(self.blocks) - 1

    def _alloc(self, n: int) -> tuple[int, int]:
        """Reserve a contiguous span of n slots -> (block index, start)."""
        if n > self.block_size:
            bi = self._fresh_block(n)  # oversized: dedicated block
        else:
            bi = self._cur
            if bi < 0 or self.blocks[bi].used + n > len(self.blocks[bi].obj):
                bi = self._cur = self._fresh_block(self.block_size)
        blk = self.blocks[bi]
        start = blk.used
        blk.used += n
        blk.live += 1
        self.spans_allocated += 1
        self.live_slots += n
        return bi, start

    def _release(self, bi: int, start: int, n: int) -> None:
        blk = self.blocks[bi]
        blk.obj[start : start + n] = [None] * n  # drop object refs now
        blk.live -= 1
        self.live_slots -= n
        if blk.live == 0 and bi != self._cur:
            if len(blk.obj) == self.block_size:
                self._free.append(bi)
            # Oversized blocks are one-shot; keep the slot list entry (a
            # tombstone) so span handles stay valid indices.

    # ----------------------------------------------------------------- stats
    def occupancy(self) -> float:
        total = sum(len(b.obj) for b in self.blocks)
        return self.live_slots / total if total else 0.0

    def stats(self) -> dict:
        return {
            "mark_pool_blocks": len(self.blocks),
            "mark_pool_blocks_recycled": self.blocks_recycled,
            "mark_pool_spans": self.spans_allocated,
            "mark_pool_live_slots": self.live_slots,
            "mark_pool_reuse_hits": self.reuse_hits,
            "pool_occupancy": round(self.occupancy(), 4),
        }

    # ------------------------------------------------------------------ seal
    def seal(self, ks: list, as_: list, bs: list, cs: list, objs: list,
             flags: int) -> "PooledMarks":
        n = len(ks)
        bi, start = self._alloc(n)
        blk = self.blocks[bi]
        if n <= 4:
            # Tiny spans (the overwhelming majority): per-element stores
            # beat four list->array conversions.
            bk, ba, bb, bc, bo = blk.kind, blk.a, blk.b, blk.c, blk.obj
            for i in range(n):
                j = start + i
                bk[j] = ks[i]
                ba[j] = as_[i]
                bb[j] = bs[i]
                bc[j] = cs[i]
                bo[j] = objs[i]
        else:
            end = start + n
            blk.kind[start:end] = array("i", ks)
            blk.a[start:end] = array("i", as_)
            blk.b[start:end] = array("i", bs)
            blk.c[start:end] = array("i", cs)
            blk.obj[start:end] = objs
        return PooledMarks(self, bi, start, n, flags)


class PooledMarks:
    """Immutable columnar mark list: a span handle into a MarkPool.

    ``kind`` (the class attribute) routes registry dispatch: the field-kind
    registry resolves pooled lists to PooledSequenceFieldKind, so the
    generic changeset algebra (rebase_node_change & co.) works on pooled
    changesets without modification."""

    __slots__ = ("pool", "blk", "start", "n", "flags", "_mods", "_runs")

    kind = "sequence_pooled"  # registry tag (never an instance attribute)

    def __init__(self, pool: MarkPool, blk: int, start: int, n: int,
                 flags: int) -> None:
        self.pool = pool
        self.blk = blk
        self.start = start
        self.n = n
        self.flags = flags
        self._mods = None  # lazy ((input_pos, span_idx), ...) Modify sites
        self._runs = None  # lazy fate-run decomposition (see _b_runs)

    def __del__(self) -> None:
        pool = getattr(self, "pool", None)
        if pool is not None:
            pool._release(self.blk, self.start, self.n)

    def __len__(self) -> int:
        return self.n

    def modify_sites(self) -> tuple:
        """((input position, index within span), ...) of the Modify marks —
        cached on the immutable span, so collision scans in the identity
        fast path cost one tuple walk instead of a rebuilt dict."""
        sites = self._mods
        if sites is None:
            out = []
            pos = 0
            b = self.pool.blocks[self.blk]
            ks, as_, s = b.kind, b.a, self.start
            for i in range(self.n):
                k = ks[s + i]
                if k == K_MODIFY:
                    out.append((pos, i))
                    pos += 1
                elif k != K_INSERT and k != K_MOVEIN:
                    pos += as_[s + i]  # skip/remove/moveout consume
            sites = self._mods = tuple(out)
        return sites

    # ------------------------------------------------------------- accessors
    def columns(self) -> tuple:
        """(kind, a, b, c, obj, start) raw column views for one pass."""
        b = self.pool.blocks[self.blk]
        return b.kind, b.a, b.b, b.c, b.obj, self.start

    def columns_padded(self, max_marks: int):
        """Device-code padded columns ``(kind[M], count[M], det[M])`` as
        int32 ndarrays — the kernel-encoding export.

        Kinds are DEVICE codes (pool code + DEVICE_CODE_OFFSET; 0 pads),
        counts are the ``a`` column, ``det`` flags Remove marks whose
        detached payload is held host-side.  The int columns are read
        through one ``np.frombuffer`` view over the pool block (no Mark
        objects, no per-mark int boxing); only the object column needs a
        short walk for the det flags.  Raises ValueError when the span is
        wider than ``max_marks`` (callers treat that as kernel
        ineligibility, not an error path)."""
        import numpy as np

        n = self.n
        if n > max_marks:
            raise ValueError(f"span width {n} exceeds kernel width {max_marks}")
        blk = self.pool.blocks[self.blk]
        s = self.start
        kind = np.zeros((max_marks,), np.int32)
        cnt = np.zeros((max_marks,), np.int32)
        det = np.zeros((max_marks,), np.int32)
        if n:
            kv = np.frombuffer(blk.kind, dtype=np.intc)[s : s + n]
            kind[:n] = kv
            kind[:n] += 1  # DEVICE_CODE_OFFSET: 0 becomes the NOOP pad
            cnt[:n] = np.frombuffer(blk.a, dtype=np.intc)[s : s + n]
            objs = blk.obj
            for i in range(n):
                if kv[i] == K_REMOVE and objs[s + i] is not None:
                    det[i] = 1
        return kind, cnt, det

    def iter_runs(self):
        """Yield (kind, a, b, c, obj) per mark without materializing Mark
        objects (the engine's flatten walk and codecs ride this)."""
        ks, as_, bs, cs, objs, s = self.columns()
        for i in range(s, s + self.n):
            yield ks[i], as_[i], bs[i], cs[i], objs[i]

    # ----------------------------------------------------------------- codec
    def to_json(self) -> list:
        out = []
        for k, a, b, c, obj in self.iter_runs():
            if k == K_SKIP:
                out.append(["s", a])
            elif k == K_INSERT:
                out.append(["i", [n.to_json() for n in obj]])
            elif k == K_REMOVE:
                out.append(
                    ["r", a] if obj is None
                    else ["r", a, [n.to_json() for n in obj]]
                )
            elif k == K_MOVEOUT:
                out.append(["mo", a, b, c])
            elif k == K_MOVEIN:
                out.append(["mi", b, a, None if c == _NONE_OFF else c])
            else:
                out.append(["m", change_to_json(obj)])
        return out

    def to_marks(self) -> list:
        """Materialize object Marks (oracle boundary; shares content/nested
        refs exactly like object-mode rebase outputs do)."""
        out: list = []
        for k, a, b, c, obj in self.iter_runs():
            if k == K_SKIP:
                out.append(Skip(a))
            elif k == K_INSERT:
                out.append(Insert(list(obj)))
            elif k == K_REMOVE:
                out.append(Remove(a, list(obj) if obj is not None else None))
            elif k == K_MOVEOUT:
                out.append(MoveOut(a, b, c))
            elif k == K_MOVEIN:
                out.append(MoveIn(b, a, None if c == _NONE_OFF else c))
            else:
                out.append(Modify(unpool_change(obj)))
        return out

    def to_marks_cloned(self) -> list:
        """Materialize with the clone discipline of ``clone_commit`` in ONE
        pass (fresh marks, cloned content/repair nodes) — the trunk-return
        boundary, where the caller apply-enriches the result in place."""
        out: list = []
        for k, a, b, c, obj in self.iter_runs():
            if k == K_SKIP:
                out.append(Skip(a))
            elif k == K_INSERT:
                out.append(Insert([n.clone() for n in obj]))
            elif k == K_REMOVE:
                out.append(Remove(
                    a,
                    [n.clone() for n in obj] if obj is not None else None,
                ))
            elif k == K_MOVEOUT:
                out.append(MoveOut(a, b, c))
            elif k == K_MOVEIN:
                out.append(MoveIn(b, a, None if c == _NONE_OFF else c))
            else:
                out.append(Modify(unpool_change(obj)))
        return out


class _Builder:
    """Coalescing emitter mirroring changeset._emit, writing columns."""

    __slots__ = ("ks", "as_", "bs", "cs", "objs", "flags")

    def __init__(self) -> None:
        self.ks: list[int] = []
        self.as_: list[int] = []
        self.bs: list[int] = []
        self.cs: list[int] = []
        self.objs: list = []
        self.flags = F_CANONICAL

    def emit(self, k: int, a: int, b: int = 0, c: int = 0, obj=None) -> None:
        if a == 0 and k != K_MODIFY:
            return  # zero-count marks drop (MODIFY carries a == 1)
        ks = self.ks
        if ks:
            j = len(ks) - 1
            lk = ks[j]
            if lk == k:
                if k == K_SKIP:
                    self.as_[j] += a
                    return
                if k == K_REMOVE and (
                    (self.objs[j] is None) == (obj is None)
                ):
                    self.as_[j] += a
                    if obj is not None:
                        self.objs[j] = self.objs[j] + obj
                    return
                if k == K_INSERT:
                    self.as_[j] += a
                    self.objs[j] = self.objs[j] + obj
                    return
                if (
                    k == K_MOVEOUT
                    and self.bs[j] == b
                    and self.cs[j] + self.as_[j] == c
                ):
                    self.as_[j] += a
                    return
        if k == K_INSERT:
            self.flags |= F_INSERT
        elif k == K_REMOVE:
            self.flags |= F_REMOVE
        elif k == K_MODIFY:
            self.flags |= F_MODIFY
        elif k != K_SKIP:
            self.flags |= F_MOVE
        ks.append(k)
        self.as_.append(a)
        self.bs.append(b)
        self.cs.append(c)
        self.objs.append(obj)

    def seal(self, pool: MarkPool) -> PooledMarks:
        # The emit path never leaves a trailing Skip (placements only) —
        # from_marks/from_json sealing passes through here too and trims.
        if self.ks and self.ks[-1] == K_SKIP:
            self.flags &= ~F_CANONICAL  # raw list had a trailing skip
        return pool.seal(
            self.ks, self.as_, self.bs, self.cs, self.objs, self.flags
        )


# ---------------------------------------------------------------------------
# Pool / unpool codecs
# ---------------------------------------------------------------------------


def _pool_raw(pool: MarkPool, rows: list) -> PooledMarks:
    """Seal raw (k, a, b, c, obj) rows, computing flags + canonicality
    (no coalescing — the rows mirror an existing wire/object list)."""
    ks: list[int] = []
    as_: list[int] = []
    bs: list[int] = []
    cs: list[int] = []
    objs: list = []
    flags = F_CANONICAL
    for k, a, b, c, obj in rows:
        if k == K_INSERT:
            flags |= F_INSERT
        elif k == K_REMOVE:
            flags |= F_REMOVE
        elif k == K_MODIFY:
            flags |= F_MODIFY
        elif k != K_SKIP:
            flags |= F_MOVE
        if a == 0 and k != K_MODIFY:
            flags &= ~F_CANONICAL  # object _emit would have dropped it
        if ks:
            j = len(ks) - 1
            lk = ks[j]
            if (
                (lk == k == K_SKIP)
                or (lk == k == K_INSERT)
                or (lk == k == K_REMOVE and (objs[j] is None) == (obj is None))
                or (lk == k == K_MOVEOUT and bs[j] == b
                    and cs[j] + as_[j] == c)
            ):
                flags &= ~F_CANONICAL  # object _emit would have coalesced
        ks.append(k)
        as_.append(a)
        bs.append(b)
        cs.append(c)
        objs.append(obj)
    if ks and ks[-1] == K_SKIP:
        flags &= ~F_CANONICAL
    return pool.seal(ks, as_, bs, cs, objs, flags)


def pool_marks(pool: MarkPool, marks: list) -> PooledMarks:
    """Object mark list -> pooled span (shares content/nested refs; nested
    NodeChanges convert recursively so every sequence field in the pooled
    universe dispatches to the pooled kind)."""
    rows = []
    for m in marks:
        if isinstance(m, Skip):
            rows.append((K_SKIP, m.count, 0, 0, None))
        elif isinstance(m, Insert):
            rows.append((K_INSERT, len(m.content), 0, 0, list(m.content)))
        elif isinstance(m, Remove):
            rows.append((
                K_REMOVE, m.count, 0, 0,
                list(m.detached) if m.detached is not None else None,
            ))
        elif isinstance(m, MoveOut):
            rows.append((K_MOVEOUT, m.count, m.id, m.offset, None))
        elif isinstance(m, MoveIn):
            rows.append((
                K_MOVEIN, m.count, m.id,
                _NONE_OFF if m.offset is None else m.offset, None,
            ))
        else:
            rows.append((K_MODIFY, 1, 0, 0, pool_change(pool, m.change)))
    return _pool_raw(pool, rows)


def pool_marks_from_json(pool: MarkPool, data: list) -> PooledMarks:
    """Wire marks JSON -> pooled span directly: the wire decode that never
    constructs a Mark object (pairs with the native tree decoder, which
    hands the numeric plane over as columns already)."""
    rows = []
    for e in data:
        kind = e[0]
        if kind == "s":
            rows.append((K_SKIP, e[1], 0, 0, None))
        elif kind == "i":
            rows.append((
                K_INSERT, len(e[1]), 0, 0,
                [Node.from_json(n) for n in e[1]],
            ))
        elif kind == "r":
            rows.append((
                K_REMOVE, e[1], 0, 0,
                [Node.from_json(n) for n in e[2]] if len(e) > 2 else None,
            ))
        elif kind == "mo":
            rows.append((K_MOVEOUT, e[1], e[2], e[3] if len(e) > 3 else 0,
                         None))
        elif kind == "mi":
            off = e[3] if len(e) > 3 else None
            rows.append((
                K_MOVEIN, e[2], e[1],
                _NONE_OFF if off is None else off, None,
            ))
        else:
            rows.append((K_MODIFY, 1, 0, 0,
                         pool_change_from_json(pool, e[1])))
    return _pool_raw(pool, rows)


def pool_field_change(pool: MarkPool, fc):
    if isinstance(fc, PooledMarks):
        return fc
    if isinstance(fc, list):
        return pool_marks(pool, fc)
    if isinstance(fc, OptionalChange) and fc.nested is not None:
        return OptionalChange(
            kind=fc.kind, set=fc.set, nested=pool_change(pool, fc.nested)
        )
    return fc


def pool_change(pool: MarkPool, change: NodeChange) -> NodeChange:
    return NodeChange(
        value=change.value,
        fields={
            k: pool_field_change(pool, fc) for k, fc in change.fields.items()
        },
    )


def pool_change_from_json(pool: MarkPool, data: dict) -> NodeChange:
    return NodeChange(
        value=tuple(data["v"]) if "v" in data else None,
        fields={
            k: (
                pool_marks_from_json(pool, m)
                if isinstance(m, list)
                else pool_field_change(pool, field_change_from_json(m))
            )
            for k, m in data.get("f", {}).items()
        },
    )


def pool_commit(pool: MarkPool, commit) -> Commit:
    if getattr(commit, "_pooled", False):
        return commit
    constraints, violated = _commit_meta(commit)
    out = Commit(
        [pool_change(pool, c) for c in commit], constraints, violated
    )
    out._pooled = True
    return out


def pool_commit_from_json(pool: MarkPool, data) -> Commit:
    """Wire commit JSON -> pooled Commit (the mark_alloc phase of the
    pooled ingest: zero Mark objects constructed)."""
    if isinstance(data, dict):
        out = Commit(
            [pool_change_from_json(pool, c) for c in data["changes"]],
            data.get("constraints"),
            data.get("violated", False),
        )
    else:
        out = Commit([pool_change_from_json(pool, c) for c in data])
    out._pooled = True
    return out


def _unpool_field(fc):
    from .changeset import _clone_field_change

    if isinstance(fc, PooledMarks):
        return fc.to_marks_cloned()
    return _clone_field_change(fc)


def unpool_change(change: NodeChange) -> NodeChange:
    return NodeChange(
        value=tuple(change.value) if change.value is not None else None,
        fields={k: _unpool_field(fc) for k, fc in change.fields.items()},
    )


def unpool_commit(commit) -> Commit:
    constraints, violated = _commit_meta(commit)
    return Commit(
        [unpool_change(c) for c in commit],
        [dict(c, path=[list(p) for p in c["path"]]) for c in constraints],
        violated,
    )


def pool_commit_from_native(
    pool: MarkPool, data: bytes, msg_row, chgs, flds, marks, spans
) -> Commit:
    """Assemble one wire message's pooled Commit from the native tree
    decoder's column tables (native/ingest.cpp ``ing_tree_decode``): the
    numeric mark plane lands as columns verbatim, and only the object
    payload spans (insert content, removed subtrees, nested changes,
    non-sequence field kinds) pay a ``json.loads``."""
    import json

    chg_start, chg_count = msg_row[8], msg_row[9]
    changes = []
    for ci in range(chg_start, chg_start + chg_count):
        fld_start, fld_count, v_span = chgs[ci]
        fields = {}
        for fi in range(fld_start, fld_start + fld_count):
            key_span, mark_start, mark_count, opaque_span = flds[fi]
            off, ln = spans[key_span]
            key = data[off : off + ln].decode()
            if opaque_span >= 0:
                off, ln = spans[opaque_span]
                fields[key] = pool_field_change(pool, field_change_from_json(
                    json.loads(data[off : off + ln])
                ))
                continue
            rows = []
            for mi in range(mark_start, mark_start + mark_count):
                k, a, b, c, ps = marks[mi]
                if k == K_INSERT:
                    off, ln = spans[ps]
                    content = [
                        Node.from_json(n)
                        for n in json.loads(data[off : off + ln])
                    ]
                    rows.append((K_INSERT, len(content), 0, 0, content))
                elif k == K_REMOVE:
                    det = None
                    if ps >= 0:
                        off, ln = spans[ps]
                        det = [
                            Node.from_json(n)
                            for n in json.loads(data[off : off + ln])
                        ]
                    rows.append((K_REMOVE, a, 0, 0, det))
                elif k == K_MODIFY:
                    off, ln = spans[ps]
                    rows.append((K_MODIFY, 1, 0, 0, pool_change_from_json(
                        pool, json.loads(data[off : off + ln])
                    )))
                else:  # skip / moveout / movein: pure column rows
                    rows.append((k, a, b, c, None))
            fields[key] = _pool_raw(pool, rows)
        value = None
        if v_span >= 0:
            off, ln = spans[v_span]
            value = tuple(json.loads(data[off : off + ln]))
        changes.append(NodeChange(value=value, fields=fields))
    out = Commit(changes)
    out._pooled = True
    return out


# ---------------------------------------------------------------------------
# Columnar rebase
# ---------------------------------------------------------------------------


def _rebase_fallback(pool: MarkPool, a: PooledMarks, b: PooledMarks,
                     a_after: bool) -> PooledMarks:
    """Moves on both sides: materialize and run the object oracle, then
    re-pool — the fallback IS the oracle, so it cannot diverge."""
    return pool_marks(pool, rebase_marks(a.to_marks(), b.to_marks(), a_after))


def _rebase_over_nonstructural(
    pool: MarkPool, a: PooledMarks, b: PooledMarks, a_after: bool
) -> PooledMarks:
    """Fast path: b is only Skip/Modify, a is canonical — positions are
    unchanged, so a's span is reused verbatim unless one of a's own
    Modifies collides with a b Modify (then only those nested changes
    rebase; identical nested results still reuse the span)."""
    if not (b.flags & F_MODIFY) or not (a.flags & F_MODIFY):
        pool.reuse_hits += 1
        return a
    am = a.modify_sites()
    bm = b.modify_sites()
    new_objs = None
    a_objs = pool.blocks[a.blk].obj
    b_objs = b.pool.blocks[b.blk].obj
    bj = 0
    nb = len(bm)
    for pos, ai in am:
        while bj < nb and bm[bj][0] < pos:
            bj += 1
        if bj >= nb:
            break
        if bm[bj][0] == pos:
            cur = a_objs[a.start + ai]
            rebased = rebase_change_id(cur, b_objs[b.start + bm[bj][1]],
                                       a_after)
            if rebased is not cur:
                if new_objs is None:
                    new_objs = list(a_objs[a.start : a.start + a.n])
                new_objs[ai] = rebased
    if new_objs is None:
        pool.reuse_hits += 1
        return a
    ks, as_, bs_, cs_, _objs, s = a.columns()
    out = pool.seal(
        list(ks[s : s + a.n]), list(as_[s : s + a.n]),
        list(bs_[s : s + a.n]), list(cs_[s : s + a.n]), new_objs, a.flags,
    )
    out._mods = a._mods  # same shape, same sites
    return out


def _b_runs(b: PooledMarks):
    """Decompose b into fate runs + boundary productions (the columnar
    _Fates): runs of (in_start, in_end, out_start, gone?, nested) plus
    {boundary: produced} for Insert content.  Cached on the immutable
    span — stream entries reused across fold steps decompose once."""
    cached = b._runs
    if cached is not None:
        return cached
    runs: list[tuple[int, int, int, bool, Any]] = []
    prods: dict[int, int] = {}
    in_pos = out_pos = 0
    for k, a, _bb, _cc, obj in b.iter_runs():
        if k == K_SKIP:
            runs.append((in_pos, in_pos + a, out_pos, False, None))
            in_pos += a
            out_pos += a
        elif k == K_MODIFY:
            runs.append((in_pos, in_pos + 1, out_pos, False, obj))
            in_pos += 1
            out_pos += 1
        elif k == K_REMOVE:
            runs.append((in_pos, in_pos + a, out_pos, True, None))
            in_pos += a
        else:  # K_INSERT (moves excluded by the caller)
            prods[in_pos] = prods.get(in_pos, 0) + a
            out_pos += a
    b._runs = (runs, prods, in_pos, out_pos)
    return b._runs


def _rebase_cols(pool: MarkPool, a: PooledMarks, b: PooledMarks,
                 a_after: bool) -> PooledMarks:
    """General columnar rebase (no moves on either side): fate runs for b,
    one monotone walk over a's columns emitting placements, then the
    sorted gap-and-coalesce emission — changeset.rebase_marks re-expressed
    over runs instead of per-node mark objects."""
    runs, prods, tail_in, tail_out = _b_runs(b)
    nruns = len(runs)

    # Placements: (out_pos, kind_order, seq, (k, a, b, c, obj)).
    placements: list[tuple[int, int, int, tuple]] = []
    in_pos = 0
    seq = 0
    ri = 0  # monotone run pointer (all queries non-decreasing in in_pos)

    def boundary(p: int, after: bool) -> int:
        nonlocal ri
        while ri < nruns and runs[ri][1] < p:
            ri += 1
        if p == 0:
            # Output before boundary 0 excluding productions AT 0 is
            # definitionally 0 — a run starting at 0 has its out_start
            # AFTER any leading-Insert production, so the generic
            # run-relative formula below would double-count it.
            before = 0
        elif ri < nruns and runs[ri][0] <= p:
            s0, _e0, o0, gone, _n = runs[ri]
            before = o0 if gone else o0 + (p - s0)
        else:
            return tail_out + (p - tail_in)  # beyond b: no productions
        return before + prods.get(p, 0) if after else before

    def node(i: int):
        nonlocal ri
        while ri < nruns and runs[ri][1] <= i:
            ri += 1
        if ri < nruns and runs[ri][0] <= i:
            s0, _e0, o0, gone, nested = runs[ri]
            if gone:
                return None, None
            return o0 + (i - s0), nested
        return tail_out + (i - tail_in), None

    ks, as_, bs_, cs_, objs, s = a.columns()
    for idx in range(s, s + a.n):
        k = ks[idx]
        cnt = as_[idx]
        seq += 1
        if k == K_SKIP:
            in_pos += cnt
        elif k == K_INSERT:
            bp = boundary(in_pos, a_after)
            placements.append((bp, 0, seq, (K_INSERT, cnt, 0, 0, objs[idx])))
        elif k == K_MODIFY:
            pos, nested = node(in_pos)
            if pos is not None:
                ch = objs[idx]
                if nested is not None:
                    ch = rebase_change_id(ch, nested, a_after)
                placements.append((pos, 1, seq, (K_MODIFY, 1, 0, 0, ch)))
            in_pos += 1
        elif k == K_REMOVE:
            det = objs[idx]
            off = 0
            while off < cnt:
                pos, _nested = node(in_pos)
                if pos is None:
                    # Inside a gone run: skip to its end in one hop.
                    end = min(runs[ri][1], in_pos + (cnt - off))
                    off += end - in_pos
                    in_pos = end
                    continue
                # Keep segment: contiguous until the run ends.
                end = runs[ri][1] if ri < nruns else in_pos + (cnt - off)
                seg = min(end, in_pos + (cnt - off)) - in_pos
                placements.append((
                    pos, 1, seq,
                    (K_REMOVE, seg, 0, 0,
                     det[off : off + seg] if det is not None else None),
                ))
                off += seg
                in_pos += seg
    # Sort only when a placement landed out of order (move-free lists walk
    # in placement order already; nested b-removals can reorder segments).
    for i in range(1, len(placements)):
        if placements[i][:3] < placements[i - 1][:3]:
            placements.sort(key=lambda t: (t[0], t[1], t[2]))
            break

    out = _Builder()
    cursor = 0
    for pos, _ko, _sq, (k, cnt, bb, cc, obj) in placements:
        if pos > cursor:
            out.emit(K_SKIP, pos - cursor)
            cursor = pos
        out.emit(k, cnt, bb, cc, obj)
        if k == K_REMOVE or k == K_MODIFY:
            cursor += cnt if k == K_REMOVE else 1
    return out.seal(pool)


def _single_insert(x: PooledMarks):
    """[Insert] / [Skip, Insert] pattern -> (skip, content) else None."""
    blk = x.pool.blocks[x.blk]
    s = x.start
    if x.n == 1:
        if blk.kind[s] == K_INSERT:
            return 0, blk.obj[s]
    elif x.n == 2 and blk.kind[s] == K_SKIP and blk.kind[s + 1] == K_INSERT:
        return blk.a[s], blk.obj[s + 1]
    return None


def rebase_pooled_marks(pool: MarkPool, a: PooledMarks, b: PooledMarks,
                        a_after: bool) -> PooledMarks:
    if a.n == 0:
        pool.reuse_hits += 1
        return a  # empty rebases to empty (and empty spans are canonical)
    if not (b.flags & _F_STRUCTURAL) and (a.flags & F_CANONICAL):
        return _rebase_over_nonstructural(pool, a, b, a_after)
    if a.n <= 2 and b.n <= 2 and (a.flags & F_CANONICAL):
        # Closed form for the conflicting-insert hot pair: the sided
        # boundary map of two single-insert lists is one comparison.
        pa = _single_insert(a)
        if pa is not None:
            pb = _single_insert(b)
            if pb is not None and pa[1] and pb[1]:
                j, content = pa
                k, b_content = pb
                if j > k or (j == k and a_after):
                    bp = j + len(b_content)
                else:
                    pool.reuse_hits += 1
                    return a  # b landed after a's boundary: untouched
                return pool.seal(
                    [K_SKIP, K_INSERT], [bp, len(content)], [0, 0], [0, 0],
                    [None, content], F_INSERT | F_CANONICAL,
                )
    if (a.flags | b.flags) & F_MOVE:
        return _rebase_fallback(pool, a, b, a_after)
    return _rebase_cols(pool, a, b, a_after)


# ---------------------------------------------------------------------------
# Registry kind
# ---------------------------------------------------------------------------


class PooledSequenceFieldKind(FieldKind):
    """Sequence-field algebra over pooled spans.  Serializes to the BARE
    wire list (byte-compatible with SequenceFieldKind); compose/invert/
    apply materialize through the object oracle (they are offline paths —
    the trunk pipeline only rebases)."""

    name = "sequence_pooled"
    is_sequence = True

    def __init__(self, pool: MarkPool | None = None) -> None:
        # Operations recover the pool from their operands; the ctor pool
        # is only the from_json target.
        self.pool = pool or MarkPool()

    def as_mark_list(self, change: PooledMarks) -> list:
        return change.to_marks()

    def clone(self, change: PooledMarks) -> PooledMarks:
        return change  # immutable span: sharing is the point

    def rebase(self, a: PooledMarks, b: PooledMarks, a_after: bool):
        return rebase_pooled_marks(a.pool, a, b, a_after)

    def invert(self, change: PooledMarks):
        from .changeset import invert_marks

        return pool_marks(change.pool, invert_marks(change.to_marks()))

    def compose(self, a: PooledMarks, b: PooledMarks):
        from .field_kinds import compose_marks

        return pool_marks(a.pool, compose_marks(a.to_marks(), b.to_marks()))

    def apply(self, nodes: list, change: PooledMarks) -> None:
        # Pooled spans are immutable; enrichment must never target them.
        raise AssertionError(
            "apply on a pooled mark list (materialize with unpool first)"
        )

    def to_json(self, change: PooledMarks):
        return change.to_json()

    def from_json(self, data):
        return pool_marks_from_json(self.pool, data)

    def is_empty(self, change: PooledMarks) -> bool:
        return change.n == 0


POOLED_SEQUENCE = PooledSequenceFieldKind()
FIELD_KINDS[POOLED_SEQUENCE.name] = POOLED_SEQUENCE


# ---------------------------------------------------------------------------
# Identity-aware changeset fold (the EditManager hot path)
# ---------------------------------------------------------------------------


def rebase_change_id(a: NodeChange, b: NodeChange, a_after: bool) -> NodeChange:
    """changeset.rebase_node_change with identity detection: when no field
    actually changed (disjoint keys, pooled fast-path span reuse) the
    ORIGINAL NodeChange is returned, so whole fold stages share structure
    instead of re-materializing equal changesets.  Safe because pooled
    changes are immutable (enrichment happens on the materialized trunk
    clone only); byte-equal to the object path by construction."""
    value = a.value
    if a.value is not None and b.value is not None and not a_after:
        value = None
    changed = value is not a.value
    b_fields = b.fields
    a_fields = a.fields
    if len(a_fields) == 1 and not changed:
        # Single-field commits are the wire norm: resolve the one pair
        # without building a fields dict on the identity path.
        (key, a_fc), = a_fields.items()
        b_fc = b_fields.get(key)
        if b_fc is None:
            return a
        if type(a_fc) is PooledMarks and type(b_fc) is PooledMarks:
            out_fc = rebase_pooled_marks(a_fc.pool, a_fc, b_fc, a_after)
            if out_fc is a_fc:
                return a
            return NodeChange(value=value, fields={key: out_fc})
    fields = {}
    for key, a_fc in a.fields.items():
        b_fc = b_fields.get(key)
        if b_fc is None:
            fields[key] = a_fc  # pooled/optional clone == share
            continue
        if type(a_fc) is PooledMarks and type(b_fc) is PooledMarks:
            # The dominant pair: skip the registry double-dispatch.
            out_fc = rebase_pooled_marks(a_fc.pool, a_fc, b_fc, a_after)
        else:
            kind = kind_of(a_fc)
            b_kind = kind_of(b_fc)
            if kind is not b_kind:
                if getattr(kind, "is_sequence", False) and getattr(
                    b_kind, "is_sequence", False
                ):
                    # Mixed sequence-family storage: rebase through the
                    # shared mark-list view (same as the object algebra).
                    out_fc = rebase_marks(
                        kind.as_mark_list(a_fc),
                        b_kind.as_mark_list(b_fc), a_after,
                    )
                    changed = True
                    fields[key] = out_fc
                    continue
                if a_after:
                    changed = True  # deterministic degrade drops a's change
                    continue
                fields[key] = a_fc
                continue
            out_fc = kind.rebase(a_fc, b_fc, a_after)
        if out_fc is not a_fc:
            changed = True
        fields[key] = out_fc
    if not changed:
        return a
    return NodeChange(value=value, fields=fields)


def _rebase_commit_over_change_id(a: Commit, x: NodeChange,
                                  a_after: bool) -> Commit:
    """Mirror of changeset.rebase_commit_over_change with identity reuse."""
    from .changeset import _rebase_constraints

    constraints, violated = _commit_meta(a)
    if constraints and not violated and a_after:
        constraints, violated = _rebase_constraints(constraints, x)
        if violated:
            out = Commit([], constraints, violated=True)
            out._pooled = True
            return out
    if violated:
        out = Commit([], constraints, violated)
        out._pooled = True
        return out
    changes = []
    changed = False
    for c in a:
        rc = rebase_change_id(c, x, a_after)
        if rc is not c:
            changed = True
        changes.append(rc)
        x = rebase_change_id(x, c, not a_after)
    if not changed and constraints == getattr(a, "constraints", []):
        return a
    out = Commit(changes, constraints, violated)
    out._pooled = True
    return out


def rebase_commit_id(a: Commit, b: Commit, a_after: bool) -> Commit:
    for x in b:
        a = _rebase_commit_over_change_id(a, x, a_after)
    return a


def _swap_modify_objs(pool: MarkPool, a: PooledMarks, new_objs) -> PooledMarks:
    """Copy a span with substituted object column (nested-rebase swaps)."""
    ks, as_, bs_, cs_, _objs, s = a.columns()
    out = pool.seal(
        list(ks[s : s + a.n]), list(as_[s : s + a.n]),
        list(bs_[s : s + a.n]), list(cs_[s : s + a.n]), new_objs, a.flags,
    )
    out._mods = a._mods
    return out


def _rebase_marks_pair(a: PooledMarks, b: PooledMarks):
    """Both bridge legs of one span pair in a single descent:
    ``(rebase(a, b, a_after=True), rebase(b, a, a_after=False))``.
    Fused for the two symmetric hot shapes — non-structural vs
    non-structural (one collision scan serves both sides) and
    single-insert vs single-insert (one boundary comparison serves both
    closed forms); everything else runs the two single-leg rebases."""
    af, bf = a.flags, b.flags
    if not ((af | bf) & _F_STRUCTURAL) and (af & bf & F_CANONICAL):
        if not (af & F_MODIFY) or not (bf & F_MODIFY):
            a.pool.reuse_hits += 2
            return a, b
        am = a.modify_sites()
        bm = b.modify_sites()
        new_a = new_b = None
        a_objs = a.pool.blocks[a.blk].obj
        b_objs = b.pool.blocks[b.blk].obj
        bj = 0
        nb = len(bm)
        for pos, ai in am:
            while bj < nb and bm[bj][0] < pos:
                bj += 1
            if bj >= nb:
                break
            if bm[bj][0] == pos:
                bi = bm[bj][1]
                ca = a_objs[a.start + ai]
                cb = b_objs[b.start + bi]
                na, nbch = rebase_change_pair(ca, cb)
                if na is not ca:
                    if new_a is None:
                        new_a = list(a_objs[a.start : a.start + a.n])
                    new_a[ai] = na
                if nbch is not cb:
                    if new_b is None:
                        new_b = list(b_objs[b.start : b.start + b.n])
                    new_b[bi] = nbch
        if new_a is None:
            a.pool.reuse_hits += 1
            out_a = a
        else:
            out_a = _swap_modify_objs(a.pool, a, new_a)
        if new_b is None:
            b.pool.reuse_hits += 1
            out_b = b
        else:
            out_b = _swap_modify_objs(b.pool, b, new_b)
        return out_a, out_b
    if a.n <= 2 and b.n <= 2 and (af & bf & F_CANONICAL):
        pa = _single_insert(a)
        if pa is not None:
            pb = _single_insert(b)
            if pb is not None and pa[1] and pb[1]:
                j, ca = pa
                k, cb = pb
                # leg1 (a later): shifts when j >= k; leg2 (b earlier):
                # shifts only when k > j — one comparison, both answers.
                if j >= k:
                    out_a = a.pool.seal(
                        [K_SKIP, K_INSERT], [j + len(cb), len(ca)],
                        [0, 0], [0, 0], [None, ca],
                        F_INSERT | F_CANONICAL,
                    )
                    b.pool.reuse_hits += 1
                    return out_a, b
                a.pool.reuse_hits += 1
                out_b = b.pool.seal(
                    [K_SKIP, K_INSERT], [k + len(ca), len(cb)],
                    [0, 0], [0, 0], [None, cb],
                    F_INSERT | F_CANONICAL,
                )
                return a, out_b
    return (
        rebase_pooled_marks(a.pool, a, b, True),
        rebase_pooled_marks(b.pool, b, a, False),
    )


def rebase_change_pair(a: NodeChange, b: NodeChange):
    """Both bridge legs of one NodeChange pair in a single descent —
    byte-equal to ``(rebase_change_id(a, b, True),
    rebase_change_id(b, a, False))``."""
    value_a = a.value  # the later-sequenced side always keeps its value
    value_b = b.value
    if a.value is not None and b.value is not None:
        value_b = None  # earlier side carried over a later set: LWW drop
    a_fields = a.fields
    b_fields = b.fields
    if len(a_fields) == 1 and len(b_fields) == 1:
        (ka, a_fc), = a_fields.items()
        (kb, b_fc), = b_fields.items()
        if ka != kb:
            out_a = a
            out_b = b if value_b is b.value else NodeChange(
                value=value_b, fields={kb: b_fc}
            )
            return out_a, out_b
        if type(a_fc) is PooledMarks and type(b_fc) is PooledMarks:
            na_fc, nb_fc = _rebase_marks_pair(a_fc, b_fc)
            out_a = a if na_fc is a_fc else NodeChange(
                value=value_a, fields={ka: na_fc}
            )
            if nb_fc is b_fc and value_b is b.value:
                out_b = b
            else:
                out_b = NodeChange(value=value_b, fields={kb: nb_fc})
            return out_a, out_b
    return (
        rebase_change_id(a, b, True),
        rebase_change_id(b, a, False),
    )


def rebase_pair(c: Commit, x: Commit) -> tuple[Commit, Commit]:
    """One bridge step of the EditManager fold: returns
    (c rebased over x with a_after=True, x rebased over c with
    a_after=False) — the mirrored pair.  For the dominant single-element
    commits the two legs come out of ONE pass (they are each other's
    carried intermediates); longer commits fall back to the two mirrored
    folds, byte-identical to the object path either way."""
    # Both sides are pooled Commits by contract (the fold pools at entry),
    # so constraint metadata is direct attribute access.
    if len(c) == 1 and len(x) == 1 and not c.constraints \
            and not x.constraints and not c.violated and not x.violated:
        c0, x0 = c[0], x[0]
        nc, nx = rebase_change_pair(c0, x0)
        if nc is c0:
            out_c = c
        else:
            out_c = Commit([nc])
            out_c._pooled = True
        if nx is x0:
            out_x = x
        else:
            out_x = Commit([nx])
            out_x._pooled = True
        return out_c, out_x
    return rebase_commit_id(c, x, True), rebase_commit_id(x, c, False)
