"""Device-side rebase window dispatch for the EditManager fold (PR 19).

The pooled fold (mark_pool.rebase_pair) walks each window entry with
Python column passes; this module moves the whole window onto the
[windows x commits] tensor plane of ops/tree_kernel.rebase_window_kernel.
The division of labour:

* ``encode_commit`` walks one pooled single-change Commit into the
  kernel's ``RebaseEnc`` columns — interior [Skip(p), Modify] levels as
  (field, pos) pairs, the leaf as padded mark columns with
  source-index handles into the commit's own span.  Anything the
  columns cannot express (multi-change commits, constraints, moves,
  multi-field levels, non-canonical spans, width/depth overflow) is
  ineligible; the verdict is cached on the Commit (``_dev_enc``).
* ``DeviceRebaser.fold`` dispatches the eligible window prefix in one
  jitted scan, decodes the surviving prefix back to pooled Commits
  (identity steps reuse the original objects outright; changed steps
  reattach object payloads through the source handles), and finishes
  the suffix on the pooled fold — the byte-identity oracle.  Every
  host-finished step is counted in ``fallback_steps``, never silent.

Object payloads (insert content, nested Modify changesets, detached
Remove subtrees) never ride the device: the kernel carries source-index
ranges and the decode re-attaches the original objects, so decoded
commits serialize byte-identically to the pooled fold's outputs.
"""

from __future__ import annotations

import numpy as np

from ...observability.flight_recorder import span
from ...ops.tree_kernel import (
    REBASE_MAX_DEPTH,
    REBASE_MAX_MARKS,
    RebaseEnc,
    rebase_window_jit,
)
from ...protocol.mark_schema import (
    DEVICE_CODE_OFFSET,
    F_CANONICAL,
    F_INSERT,
    F_MODIFY,
    F_MOVE,
    F_REMOVE,
    K_INSERT,
    K_MODIFY,
    K_REMOVE,
    K_SKIP,
)
from .changeset import Commit, NodeChange
from .mark_pool import PooledMarks, rebase_pair

_PD = REBASE_MAX_DEPTH
_M = REBASE_MAX_MARKS
_ARANGE = np.arange(_M, dtype=np.int32)
_ZEROS = np.zeros((_M,), np.int32)

# Sentinel distinguishing "never encoded" from "encoded: ineligible".
_INELIGIBLE = False


class CommitEncoding:
    """Device columns for one eligible Commit plus the host-side keys
    (field names, value tuples, nested NodeChanges, the leaf span) the
    decode needs to rebuild byte-identical pooled commits."""

    __slots__ = (
        "dep", "fld", "pos", "val", "kind", "cnt", "det", "n",
        "names", "vals", "nodes", "leaf",
    )

    def __init__(self, dep, fld, pos, val, kind, cnt, det, n,
                 names, vals, nodes, leaf) -> None:
        self.dep = dep
        self.fld = fld
        self.pos = pos
        self.val = val
        self.kind = kind
        self.cnt = cnt
        self.det = det
        self.n = n
        self.names = names
        self.vals = vals
        self.nodes = nodes
        self.leaf = leaf


class DeviceRebaser:
    """Window dispatcher shared by a fleet's EditManagers (one instance
    keeps the field-interning table and the health counters fleet-wide,
    mirroring the engines' shared MarkPool)."""

    def __init__(self, pool) -> None:
        self.pool = pool
        self._fields: dict[str, int] = {}
        self.device_steps = 0     # window steps resolved on device
        self.fallback_steps = 0   # window steps finished by the pooled fold
        self.windows = 0          # folds that dispatched at least one step
        self.encode_rejects = 0   # commits that failed the eligibility walk

    # ------------------------------------------------------------- interning
    def _field_id(self, key: str) -> int:
        return self._fields.setdefault(key, len(self._fields))

    # -------------------------------------------------------------- encoding
    def encode_commit(self, commit):
        """CommitEncoding for an eligible pooled Commit, else None.
        The verdict (either way) is cached on the commit — pooled
        commits are immutable, so the cache can never go stale."""
        enc = getattr(commit, "_dev_enc", None)
        if enc is not None:
            return None if enc is _INELIGIBLE else enc
        enc = self._encode(commit)
        commit._dev_enc = _INELIGIBLE if enc is None else enc
        if enc is None:
            self.encode_rejects += 1
        return enc

    def _encode(self, commit):
        if len(commit) != 1 or commit.constraints or commit.violated:
            return None
        nc = commit[0]
        fld = np.full((_PD + 1,), -1, np.int32)
        pos = np.zeros((_PD,), np.int32)
        val = np.zeros((_PD + 1,), np.int32)
        names: list = []
        vals: list = []
        nodes: list = []
        level = 0
        while True:
            nodes.append(nc)
            vals.append(nc.value)
            if nc.value is not None:
                val[level] = 1
            fields = nc.fields
            if not fields:
                # value-only (or empty) leaf: fld stays -1
                names.append(None)
                return CommitEncoding(
                    np.int32(level), fld, pos, val,
                    _ZEROS, _ZEROS, _ZEROS, np.int32(0),
                    names, vals, nodes, None,
                )
            if len(fields) != 1:
                return None
            (key, fc), = fields.items()
            if type(fc) is not PooledMarks:
                return None
            if level < _PD:
                # interior test: exactly [Skip(p), Modify] (the nested
                # wire norm) keeps walking the spine
                ks, as_, _bs, _cs, objs, s = fc.columns()
                nested = None
                if fc.n == 2 and ks[s] == K_SKIP and ks[s + 1] == K_MODIFY:
                    nested = objs[s + 1]
                    p = as_[s]
                elif fc.n == 1 and ks[s] == K_MODIFY:
                    nested = objs[s]
                    p = 0
                if type(nested) is NodeChange:
                    fld[level] = self._field_id(key)
                    pos[level] = p
                    names.append(key)
                    nc = nested
                    level += 1
                    continue
            flags = fc.flags
            if flags & F_MOVE or not flags & F_CANONICAL or fc.n > _M:
                return None
            kind, cnt, det = fc.columns_padded(_M)
            fld[level] = self._field_id(key)
            names.append(key)
            return CommitEncoding(
                np.int32(level), fld, pos, val, kind, cnt, det,
                np.int32(fc.n), names, vals, nodes, fc,
            )

    # -------------------------------------------------------------- decoding
    def _seal_interior(self, p: int, nested) -> PooledMarks:
        """[Skip(p), Modify(nested)] (or bare [Modify]) as a fresh span."""
        if p > 0:
            return self.pool.seal(
                [K_SKIP, K_MODIFY], [p, 1], [0, 0], [0, 0],
                [None, nested], F_MODIFY | F_CANONICAL,
            )
        return self.pool.seal(
            [K_MODIFY], [1], [0], [0], [nested], F_MODIFY | F_CANONICAL,
        )

    def _seal_leaf(self, enc: CommitEncoding, kindv, cntv, slov, shiv,
                   nlive: int) -> PooledMarks:
        """Device leaf columns -> pooled span, object payloads reattached
        through the source-index handles into the ORIGINAL leaf span.
        Raw rows + seal (no Mark objects): the kernel's coalescing
        emission mirrors the host builder, so the columns are already
        canonical."""
        ks: list[int] = []
        as_: list[int] = []
        zs: list[int] = []
        objs: list = []
        flags = F_CANONICAL
        if enc.leaf is not None:
            sk, _sa, _sb, _sc, sobjs, ss = enc.leaf.columns()
        else:
            sk = sobjs = ()
            ss = 0
        for i in range(nlive):
            k = int(kindv[i]) - DEVICE_CODE_OFFSET
            a = int(cntv[i])
            obj = None
            if k == K_INSERT:
                flags |= F_INSERT
                lo = int(slov[i])
                hi = int(shiv[i])
                if lo == hi:
                    obj = sobjs[ss + lo]  # shared, like the host emit
                else:
                    # merged insert group: concatenate the original
                    # K_INSERT payloads in source order
                    obj = []
                    for j in range(lo, hi + 1):
                        if sk[ss + j] == K_INSERT:
                            obj = obj + sobjs[ss + j]
            elif k == K_REMOVE:
                # detached payloads only survive identity steps (which
                # never decode) — the kernel's det gate guarantees it
                flags |= F_REMOVE
            elif k == K_MODIFY:
                flags |= F_MODIFY
                obj = sobjs[ss + int(slov[i])]
            ks.append(k)
            as_.append(a)
            zs.append(0)
            objs.append(obj)
        return self.pool.seal(ks, as_, zs, list(zs), objs, flags)

    def _decode_side(self, enc: CommitEncoding, out: RebaseEnc, i: int,
                     drops=None):
        """Rebuild one side's pooled Commit (+ fresh encoding stamp) from
        step ``i`` of the window outputs."""
        dep = int(np.asarray(out.dep)[i])
        posv = np.asarray(out.pos)[i]
        kindv = np.asarray(out.kind)[i]
        cntv = np.asarray(out.cnt)[i]
        nlive = int(np.asarray(out.n)[i])
        slov = np.asarray(out.slo)[i]
        shiv = np.asarray(out.shi)[i]
        names = enc.names
        vals = list(enc.vals[: dep + 1])
        if drops is not None:
            for lvl in range(dep + 1):
                if drops[lvl]:
                    vals[lvl] = None
        # leaf level
        leaf_span = None
        if names[dep] is None:
            fields: dict = {}
        else:
            leaf_span = self._seal_leaf(enc, kindv, cntv, slov, shiv, nlive)
            fields = {names[dep]: leaf_span}
        nc = NodeChange(value=vals[dep], fields=fields)
        nodes = [nc]
        for lvl in range(dep - 1, -1, -1):
            nc = NodeChange(value=vals[lvl], fields={
                names[lvl]: self._seal_interior(int(posv[lvl]), nc),
            })
            nodes.append(nc)
        nodes.reverse()
        out_commit = Commit([nc])
        out_commit._pooled = True
        new_enc = CommitEncoding(
            np.int32(dep), enc.fld, posv.astype(np.int32),
            np.asarray([1 if v is not None else 0
                        for v in vals] + [0] * (_PD - dep), np.int32),
            kindv.astype(np.int32), cntv.astype(np.int32), _ZEROS,
            np.int32(nlive), names[: dep + 1], vals, nodes, leaf_span,
        )
        out_commit._dev_enc = new_enc
        return out_commit

    # ------------------------------------------------------------ dispatch
    @staticmethod
    def _stack(encs: list, pad: int) -> RebaseEnc:
        """Window encodings -> one [C]-leading RebaseEnc (pads are zero
        rows gated off by the eligibility mask)."""
        import jax.numpy as jnp

        deps = [e.dep for e in encs] + [np.int32(0)] * pad
        z1 = np.full((_PD + 1,), -1, np.int32)
        zp = np.zeros((_PD,), np.int32)
        zv = np.zeros((_PD + 1,), np.int32)
        flds = [e.fld for e in encs] + [z1] * pad
        poss = [e.pos for e in encs] + [zp] * pad
        valz = [e.val for e in encs] + [zv] * pad
        kinds = [e.kind for e in encs] + [_ZEROS] * pad
        cnts = [e.cnt for e in encs] + [_ZEROS] * pad
        dets = [e.det for e in encs] + [_ZEROS] * pad
        ns = [e.n for e in encs] + [np.int32(0)] * pad
        slos = [_ARANGE] * (len(encs) + pad)
        return RebaseEnc(
            jnp.asarray(np.asarray(deps, np.int32)),
            jnp.asarray(np.stack(flds)), jnp.asarray(np.stack(poss)),
            jnp.asarray(np.stack(valz)), jnp.asarray(np.stack(kinds)),
            jnp.asarray(np.stack(cnts)), jnp.asarray(np.stack(dets)),
            jnp.asarray(np.asarray(ns, np.int32)),
            jnp.asarray(np.stack(slos)), jnp.asarray(np.stack(slos)),
        )

    @staticmethod
    def _enc_dev(e: CommitEncoding) -> RebaseEnc:
        import jax.numpy as jnp

        return RebaseEnc(
            jnp.asarray(e.dep), jnp.asarray(e.fld), jnp.asarray(e.pos),
            jnp.asarray(e.val), jnp.asarray(e.kind), jnp.asarray(e.cnt),
            jnp.asarray(e.det), jnp.asarray(e.n),
            jnp.asarray(_ARANGE), jnp.asarray(_ARANGE),
        )

    def fold(self, c: Commit, xs: list):
        """One EditManager window fold: returns (final c, new xs values,
        stage values), device prefix + pooled-fold suffix.  ``xs`` is the
        list of window commits (tseq bookkeeping stays with the caller);
        the three return lists line up with it."""
        import jax

        n = len(xs)
        with span("rebase_kernel_encode", window=n):
            enc_c = self.encode_commit(c)
            encs: list = []
            if enc_c is not None:
                for x in xs:
                    e = self.encode_commit(x)
                    if e is None:
                        break
                    encs.append(e)
        p = len(encs)
        k = 0
        new_xs: list = []
        stages: list = []
        if p:
            self.windows += 1
            cap = 1 << (p - 1).bit_length()
            with span("rebase_kernel_dispatch", window=n, steps=p, cap=cap):
                import jax.numpy as jnp

                elig = jnp.asarray(
                    np.asarray([True] * p + [False] * (cap - p)))
                _final, outs = rebase_window_jit(
                    self._enc_dev(enc_c), self._stack(encs, cap - p), elig)
                outs = jax.device_get(outs)
            with span("rebase_kernel_decode", window=n, steps=p):
                valid = np.asarray(outs.valid)
                while k < p and valid[k]:
                    k += 1
                id_c = np.asarray(outs.id_c)
                id_x = np.asarray(outs.id_x)
                drops = np.asarray(outs.x_drop)
                for i in range(k):
                    if id_x[i]:
                        new_xs.append(xs[i])
                    else:
                        new_xs.append(self._decode_side(
                            encs[i], outs.x, i, drops=drops[i]))
                    if not id_c[i]:
                        # stage source handles compose into the ORIGINAL
                        # c across scan steps — decode against enc_c
                        c = self._decode_side(enc_c, outs.stage, i)
                    stages.append(c)
        # pooled-fold suffix: ineligible entries, invalidated steps, and
        # everything behind them (prefix-validity contract)
        for i in range(k, n):
            c, xw = rebase_pair(c, xs[i])
            new_xs.append(xw)
            stages.append(c)
        self.device_steps += k
        self.fallback_steps += n - k
        return c, new_xs, stages

    # --------------------------------------------------------------- gauges
    def stats(self) -> dict:
        total = self.device_steps + self.fallback_steps
        return {
            "device_rebase_steps": self.device_steps,
            "rebase_fallbacks": self.fallback_steps,
            "rebase_windows": self.windows,
            "rebase_encode_rejects": self.encode_rejects,
            "device_rebase_fraction": (
                round(self.device_steps / total, 4) if total else 0.0
            ),
        }
