"""Read path for the REFERENCE SharedTree summary format.

The reference repo commits real SharedTree summaries its own regression
tests load (`packages/dds/tree/src/test/shared-tree/summary-load-snapshots/
singleTree-<strategy>-<version>-1.json`, per its README: "summaries written
by past versions still load with the current code").  Loading those files
here proves tree-format fidelity against artifacts this repo did not
produce (VERDICT r4 next #6).

Summary shape (ITree JSON): indexes/{EditManager,Schema,Forest,
DetachedFieldIndex}, each a tree of blobs.  The Forest blob is the
chunked-forest FieldBatch codec (tree/src/feature-libraries/chunked-forest/
codec/format.ts): interned shape table + per-key data streams —

- ``{"c": {type?, value?, fields?: [[key, shapeId]...], extraFields?}}``:
  a TreeShape.  Unfixed parts stream inline: type string, then (when
  ``value`` is absent) a has-value bool (+ the value), then one stream
  item per declared field (decoded under that field's shape), then — with
  ``extraFields`` — one item holding ``[key, fieldData, ...]`` pairs.
- ``{"a": shapeId}``: a node ARRAY: one stream item, an array that is
  itself a stream of back-to-back shape-``shapeId`` node encodings.

Both the Uncompressed strategy (generic ``{"c":{"extraFields"}} + {"a"}``
pair) and the Compressed strategy (schema-specialized shape dictionary)
decode through the same two rules.  The schema blob's node kinds
(Value/Optional/Sequence fields, ``com.fluidframework.leaf.*`` leaves) map
onto this repo's SchemaRegistry/FieldKind model.
"""

from __future__ import annotations

import json
import os
from typing import Any

from .forest import Node
from .schema import FieldKind, FieldSchema, NodeSchema, SchemaRegistry

# Overridable for checkouts living elsewhere (CI, other machines); the
# tests skip cleanly when the directory is absent.
SNAPSHOT_DIR = os.path.join(
    os.environ.get("FFTPU_REFERENCE_DIR", "/root/reference"),
    "packages/dds/tree/src/test/shared-tree/summary-load-snapshots",
)

# Reference leaf schema identifiers -> this repo's leaf type tags.
LEAF_TYPE_MAP = {
    "com.fluidframework.leaf.number": "number",
    "com.fluidframework.leaf.string": "string",
    "com.fluidframework.leaf.boolean": "boolean",
    "com.fluidframework.leaf.null": "null",
    "com.fluidframework.leaf.handle": "handle",
}

FIELD_KIND_MAP = {
    "Value": FieldKind.VALUE,
    "Optional": FieldKind.OPTIONAL,
    "Sequence": FieldKind.SEQUENCE,
    "Identifier": FieldKind.VALUE,
    "Forbidden": FieldKind.OPTIONAL,
}


def summary_snapshot_files(strategy: str | None = None) -> list[str]:
    if not os.path.isdir(SNAPSHOT_DIR):
        return []
    out = []
    for f in sorted(os.listdir(SNAPSHOT_DIR)):
        if not f.endswith(".json"):
            continue
        if strategy is not None and f"-{strategy}-" not in f:
            continue
        out.append(os.path.join(SNAPSHOT_DIR, f))
    return out


# --------------------------------------------------------------- ITree walk


def _itree_blobs(tree: dict, prefix: str = "") -> dict[str, str]:
    """Flatten an ITree node to {path: blob content}."""
    out: dict[str, str] = {}
    for name, entry in tree.get("tree", {}).items():
        path = f"{prefix}/{name}" if prefix else name
        if entry["type"] == 1:
            out.update(_itree_blobs(entry, path))
        else:
            out[path] = entry["content"]
    return out


# ----------------------------------------------------------- FieldBatch codec


class _Stream:
    def __init__(self, items: list) -> None:
        self.items = items
        self.pos = 0

    def next(self):
        v = self.items[self.pos]
        self.pos += 1
        return v

    @property
    def done(self) -> bool:
        return self.pos >= len(self.items)


def _map_type(t: str) -> str:
    return LEAF_TYPE_MAP.get(t, t)


def _read_node(shapes: list, spec: dict, stream: _Stream) -> Node:
    t = spec["type"] if "type" in spec else stream.next()
    if "value" in spec:
        value = stream.next() if spec["value"] is True else None
    else:
        value = stream.next() if stream.next() else None
    fields: dict[str, list[Node]] = {}
    for key, sid in spec.get("fields", []):
        fields[key] = _read_field(shapes, sid, stream)
    if "extraFields" in spec:
        extra = stream.next()
        it = _Stream(extra)
        while not it.done:
            key = it.next()
            fields[key] = _read_field(shapes, spec["extraFields"], it)
    return Node(
        type=_map_type(t),
        value=value,
        fields={k: v for k, v in fields.items() if v},
    )


def _read_field(shapes: list, sid: int, stream: _Stream) -> list[Node]:
    shape = shapes[sid]
    if "a" in shape:
        inner = shapes[shape["a"]]
        assert "c" in inner, f"array of non-node shape {inner}"
        sub = _Stream(stream.next())
        out = []
        while not sub.done:
            out.append(_read_node(shapes, inner["c"], sub))
        return out
    assert "c" in shape, f"unsupported shape {shape}"
    return [_read_node(shapes, shape["c"], stream)]


def decode_field_batch(content: str) -> dict[str, list[Node]]:
    """One Forest blob -> {field key: nodes} (rootFieldKey carries the
    document content)."""
    batch = json.loads(content)
    fields = batch["fields"]
    shapes = fields["shapes"]
    out: dict[str, list[Node]] = {}
    for key, data in zip(batch["keys"], fields["data"]):
        stream = _Stream(data)
        sid = stream.next()
        nodes = _read_field(shapes, sid, stream)
        assert stream.done, f"trailing forest data under key {key!r}"
        out[key] = nodes
    return out


# ----------------------------------------------------------------- schema


def schema_from_reference(content: str) -> SchemaRegistry:
    data = json.loads(content)
    reg = SchemaRegistry()
    for name, spec in data.get("nodes", {}).items():
        # SchemaFormat v2 wraps the node spec in {"kind": {...}}; v1 is
        # flat — identical payload either way.
        spec = spec.get("kind", spec) if "leaf" not in spec else spec
        if "leaf" in spec:
            continue  # leaves are built-in kinds in this repo's registry
        holder = spec.get("object") or spec.get("map") or {}
        fields = {
            key: FieldSchema(
                FIELD_KIND_MAP[fs["kind"]],
                {_map_type(t) for t in fs.get("types", [])},
            )
            for key, fs in holder.items()
        }
        reg.add(NodeSchema(_map_type(name), fields))
    root = data.get("root")
    if root:
        reg.root = FieldSchema(
            FIELD_KIND_MAP[root["kind"]],
            {_map_type(t) for t in root.get("types", [])},
        )
    return reg


# ----------------------------------------------------------------- encoder

INV_LEAF_TYPE_MAP = {v: k for k, v in LEAF_TYPE_MAP.items()}
INV_FIELD_KIND_MAP = {
    FieldKind.VALUE: "Value",
    FieldKind.OPTIONAL: "Optional",
    FieldKind.SEQUENCE: "Sequence",
}
# Reference ValueSchema enum order (core/schema-stored/format.ts).
LEAF_CODES = {"number": 0, "string": 1, "boolean": 2, "handle": 3, "null": 4}


def _inv_type(t: str) -> str:
    return INV_LEAF_TYPE_MAP.get(t, t)


def _encode_node_stream(n: Node, out: list) -> None:
    """Inverse of _read_node for the generic Uncompressed shape pair
    ({"c": {"extraFields": 1}} + {"a": 0}).  A null LEAF carries an
    explicit null value on the wire (reference encodeValue pushes
    [true, null] — null !== undefined); our model signals nullness by the
    node type, so the type decides the has-value flag there."""
    out.append(_inv_type(n.type))
    if n.value is not None or n.type == "null":
        out.append(True)
        out.append(n.value)
    else:
        out.append(False)
    fields: list = []
    for key, kids in n.fields.items():
        arr: list = []
        for c in kids:
            _encode_node_stream(c, arr)
        fields.append(key)
        fields.append(arr)
    out.append(fields)


def encode_field_batch(
    root_field: list[Node],
    fields_version: int,
    top_version: int,
    other_fields: dict[str, list[Node]] | None = None,
    key_order: list[str] | None = None,
) -> str:
    """Forest blob in the reference's UNCOMPRESSED FieldBatch encoding —
    the write path matching decode_field_batch (byte-identical against
    the committed artifacts, tests/test_tree_summary_artifacts.py).
    ``other_fields`` carries non-root forest keys (detached subtrees) in
    ``key_order``, so nothing the original stored is dropped."""
    fields = {"rootFieldKey": root_field, **(other_fields or {})}
    keys = key_order or list(fields)
    assert set(keys) == set(fields), "key_order must cover every field"
    data = []
    for key in keys:
        stream: list = []
        for n in fields[key]:
            _encode_node_stream(n, stream)
        data.append([1, stream])
    return json.dumps({
        "keys": keys,
        "fields": {
            "version": fields_version,
            "identifiers": [],
            "shapes": [{"c": {"extraFields": 1}}, {"a": 0}],
            "data": data,
        },
        "version": top_version,
    }, separators=(",", ":"))


def schema_to_reference(reg: SchemaRegistry, version: int) -> str:
    """SchemaString blob (v1 flat / v2 kind-wrapped) from the registry.
    Node entries sort by full name (leaves carried by reference from the
    registry's allowed-type mentions), matching the reference's
    deterministic serialization."""
    leaves: set[str] = set()

    def note(types: set[str]) -> None:
        for t in types:
            if t in LEAF_CODES:
                leaves.add(t)

    for node in reg.nodes.values():
        for fs in node.fields.values():
            note(fs.allowed_types)
    if reg.root:
        note(reg.root.allowed_types)

    entries: dict[str, Any] = {}
    for t in leaves:
        entries[_inv_type(t)] = {"leaf": LEAF_CODES[t]}
    for name, node in reg.nodes.items():
        entries[_inv_type(name)] = {"object": {
            key: {
                "kind": INV_FIELD_KIND_MAP[fs.kind],
                "types": sorted(_inv_type(t) for t in fs.allowed_types),
            }
            for key, fs in node.fields.items()
        }}
    nodes = {k: entries[k] for k in sorted(entries)}
    if version >= 2:
        nodes = {k: {"kind": v} for k, v in nodes.items()}
    out: dict[str, Any] = {"version": version, "nodes": nodes}
    if reg.root:
        out["root"] = {
            "kind": INV_FIELD_KIND_MAP[reg.root.kind],
            "types": sorted(_inv_type(t) for t in reg.root.allowed_types),
        }
    return json.dumps(out, separators=(",", ":"))


def encode_reference_tree_summary(loaded: dict[str, Any]) -> str:
    """The FULL summary file (ITree JSON, tab-indented like the
    reference's JSON.stringify(x, undefined, "\\t")) regenerated from a
    load_reference_tree_summary result — the Uncompressed write path."""
    fmt = loaded["format"]
    if not fmt.get("schema_lossless", True):
        raise ValueError(
            "schema uses constructs outside the registry's lossless subset "
            "(map nodes / Identifier or Forbidden kinds); refusing to "
            "regenerate a semantically different schema"
        )

    def blob(content: str) -> dict:
        return {"type": 2, "content": content}

    def index(name: str, blob_name: str, content: str) -> dict:
        entries: dict[str, Any] = {}
        if name in loaded["versions"]:  # mirror the loader's optionality
            entries[".metadata"] = blob(json.dumps(
                {"version": loaded["versions"][name]}, separators=(",", ":")
            ))
        entries[blob_name] = blob(content)
        return {"type": 1, "tree": entries}

    other = {
        k: v for k, v in loaded.get("forest_fields", {}).items()
        if k != "rootFieldKey"
    }
    tree = {
        "EditManager": index("EditManager", "String", json.dumps(
            loaded["edit_manager"], separators=(",", ":")
        )),
        "Schema": index("Schema", "SchemaString", schema_to_reference(
            loaded["schema"], fmt["schema_version"]
        )),
        "Forest": index("Forest", fmt["forest_blob"], encode_field_batch(
            loaded["root_field"],
            fmt["forest_fields_version"],
            fmt["forest_top_version"],
            other_fields=other,
            key_order=loaded.get("forest_key_order"),
        )),
        "DetachedFieldIndex": index(
            "DetachedFieldIndex", "DetachedFieldIndexBlob",
            json.dumps(loaded["detached"], separators=(",", ":")),
        ),
    }
    doc = {"type": 1, "tree": {
        ".metadata": blob(json.dumps(
            {"version": fmt["top_version"]}, separators=(",", ":")
        )),
        "indexes": {"type": 1, "tree": tree},
    }}
    return json.dumps(doc, indent="\t") + "\n"


# ------------------------------------------------------------------ loader


def load_reference_tree_summary(path: str) -> dict[str, Any]:
    """Load one committed reference summary.  Returns
    {root_field: [Node], schema: SchemaRegistry, edit_manager: dict,
    detached: dict, versions: {index: int}}."""
    blobs = _itree_blobs(json.load(open(path, encoding="utf-8")))

    def index_blob(index: str, *names: str) -> str:
        for n in names:
            key = f"indexes/{index}/{n}"
            if key in blobs:
                return blobs[key]
        raise KeyError(f"no blob for index {index} in {sorted(blobs)}")

    forest_raw = index_blob("Forest", "ForestTree", "contents")
    forest_fields = decode_field_batch(forest_raw)
    em = json.loads(index_blob("EditManager", "String"))
    detached = json.loads(
        index_blob("DetachedFieldIndex", "DetachedFieldIndexBlob", "contents")
    )
    versions = {
        idx: json.loads(blobs[f"indexes/{idx}/.metadata"])["version"]
        for idx in ("EditManager", "Schema", "Forest", "DetachedFieldIndex")
        if f"indexes/{idx}/.metadata" in blobs
    }
    schema_raw = index_blob("Schema", "SchemaString")
    forest_parsed = json.loads(forest_raw)
    schema_data = json.loads(schema_raw)
    # Is the schema inside the registry's lossless subset?  (map nodes and
    # Identifier/Forbidden kinds FOLD on load; the encoder refuses to
    # regenerate them silently.)
    lossless = True
    for spec in schema_data.get("nodes", {}).values():
        spec = spec.get("kind", spec) if "leaf" not in spec else spec
        if "map" in spec:
            lossless = False
        for fs in (spec.get("object") or {}).values():
            if fs["kind"] not in ("Value", "Optional", "Sequence"):
                lossless = False
    return {
        "root_field": forest_fields.get("rootFieldKey", []),
        "forest_fields": forest_fields,
        "forest_key_order": list(forest_fields),
        "schema": schema_from_reference(schema_raw),
        "edit_manager": em,
        "detached": detached,
        "versions": versions,
        # Format stamps for the write path (encode_reference_tree_summary).
        "format": {
            "top_version": json.loads(blobs[".metadata"])["version"]
            if ".metadata" in blobs else 1,
            "schema_version": schema_data.get("version", 1),
            "schema_lossless": lossless,
            "forest_blob": "contents"
            if "indexes/Forest/contents" in blobs else "ForestTree",
            "forest_top_version": forest_parsed.get("version", 1),
            "forest_fields_version": forest_parsed["fields"]["version"],
        },
    }
