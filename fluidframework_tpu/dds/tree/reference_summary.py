"""Read path for the REFERENCE SharedTree summary format.

The reference repo commits real SharedTree summaries its own regression
tests load (`packages/dds/tree/src/test/shared-tree/summary-load-snapshots/
singleTree-<strategy>-<version>-1.json`, per its README: "summaries written
by past versions still load with the current code").  Loading those files
here proves tree-format fidelity against artifacts this repo did not
produce (VERDICT r4 next #6).

Summary shape (ITree JSON): indexes/{EditManager,Schema,Forest,
DetachedFieldIndex}, each a tree of blobs.  The Forest blob is the
chunked-forest FieldBatch codec (tree/src/feature-libraries/chunked-forest/
codec/format.ts): interned shape table + per-key data streams —

- ``{"c": {type?, value?, fields?: [[key, shapeId]...], extraFields?}}``:
  a TreeShape.  Unfixed parts stream inline: type string, then (when
  ``value`` is absent) a has-value bool (+ the value), then one stream
  item per declared field (decoded under that field's shape), then — with
  ``extraFields`` — one item holding ``[key, fieldData, ...]`` pairs.
- ``{"a": shapeId}``: a node ARRAY: one stream item, an array that is
  itself a stream of back-to-back shape-``shapeId`` node encodings.

Both the Uncompressed strategy (generic ``{"c":{"extraFields"}} + {"a"}``
pair) and the Compressed strategy (schema-specialized shape dictionary)
decode through the same two rules.  The schema blob's node kinds
(Value/Optional/Sequence fields, ``com.fluidframework.leaf.*`` leaves) map
onto this repo's SchemaRegistry/FieldKind model.
"""

from __future__ import annotations

import json
import os
from typing import Any

from .forest import Node
from .schema import FieldKind, FieldSchema, NodeSchema, SchemaRegistry

# Overridable for checkouts living elsewhere (CI, other machines); the
# tests skip cleanly when the directory is absent.
SNAPSHOT_DIR = os.path.join(
    os.environ.get("FFTPU_REFERENCE_DIR", "/root/reference"),
    "packages/dds/tree/src/test/shared-tree/summary-load-snapshots",
)

# Reference leaf schema identifiers -> this repo's leaf type tags.
LEAF_TYPE_MAP = {
    "com.fluidframework.leaf.number": "number",
    "com.fluidframework.leaf.string": "string",
    "com.fluidframework.leaf.boolean": "boolean",
    "com.fluidframework.leaf.null": "null",
    "com.fluidframework.leaf.handle": "handle",
}

FIELD_KIND_MAP = {
    "Value": FieldKind.VALUE,
    "Optional": FieldKind.OPTIONAL,
    "Sequence": FieldKind.SEQUENCE,
    "Identifier": FieldKind.VALUE,
    "Forbidden": FieldKind.OPTIONAL,
}


def summary_snapshot_files(strategy: str | None = None) -> list[str]:
    if not os.path.isdir(SNAPSHOT_DIR):
        return []
    out = []
    for f in sorted(os.listdir(SNAPSHOT_DIR)):
        if not f.endswith(".json"):
            continue
        if strategy is not None and f"-{strategy}-" not in f:
            continue
        out.append(os.path.join(SNAPSHOT_DIR, f))
    return out


# --------------------------------------------------------------- ITree walk


def _itree_blobs(tree: dict, prefix: str = "") -> dict[str, str]:
    """Flatten an ITree node to {path: blob content}."""
    out: dict[str, str] = {}
    for name, entry in tree.get("tree", {}).items():
        path = f"{prefix}/{name}" if prefix else name
        if entry["type"] == 1:
            out.update(_itree_blobs(entry, path))
        else:
            out[path] = entry["content"]
    return out


# ----------------------------------------------------------- FieldBatch codec


class _Stream:
    def __init__(self, items: list) -> None:
        self.items = items
        self.pos = 0

    def next(self):
        v = self.items[self.pos]
        self.pos += 1
        return v

    @property
    def done(self) -> bool:
        return self.pos >= len(self.items)


def _map_type(t: str) -> str:
    return LEAF_TYPE_MAP.get(t, t)


def _read_node(shapes: list, spec: dict, stream: _Stream) -> Node:
    t = spec["type"] if "type" in spec else stream.next()
    if "value" in spec:
        value = stream.next() if spec["value"] is True else None
    else:
        value = stream.next() if stream.next() else None
    fields: dict[str, list[Node]] = {}
    for key, sid in spec.get("fields", []):
        fields[key] = _read_field(shapes, sid, stream)
    if "extraFields" in spec:
        extra = stream.next()
        it = _Stream(extra)
        while not it.done:
            key = it.next()
            fields[key] = _read_field(shapes, spec["extraFields"], it)
    return Node(
        type=_map_type(t),
        value=value,
        fields={k: v for k, v in fields.items() if v},
    )


def _read_field(shapes: list, sid: int, stream: _Stream) -> list[Node]:
    shape = shapes[sid]
    if "a" in shape:
        inner = shapes[shape["a"]]
        assert "c" in inner, f"array of non-node shape {inner}"
        sub = _Stream(stream.next())
        out = []
        while not sub.done:
            out.append(_read_node(shapes, inner["c"], sub))
        return out
    assert "c" in shape, f"unsupported shape {shape}"
    return [_read_node(shapes, shape["c"], stream)]


def decode_field_batch(content: str) -> dict[str, list[Node]]:
    """One Forest blob -> {field key: nodes} (rootFieldKey carries the
    document content)."""
    batch = json.loads(content)
    fields = batch["fields"]
    shapes = fields["shapes"]
    out: dict[str, list[Node]] = {}
    for key, data in zip(batch["keys"], fields["data"]):
        stream = _Stream(data)
        sid = stream.next()
        nodes = _read_field(shapes, sid, stream)
        assert stream.done, f"trailing forest data under key {key!r}"
        out[key] = nodes
    return out


# ----------------------------------------------------------------- schema


def schema_from_reference(content: str) -> SchemaRegistry:
    data = json.loads(content)
    reg = SchemaRegistry()
    for name, spec in data.get("nodes", {}).items():
        # SchemaFormat v2 wraps the node spec in {"kind": {...}}; v1 is
        # flat — identical payload either way.
        spec = spec.get("kind", spec) if "leaf" not in spec else spec
        if "leaf" in spec:
            continue  # leaves are built-in kinds in this repo's registry
        holder = spec.get("object") or spec.get("map") or {}
        fields = {
            key: FieldSchema(
                FIELD_KIND_MAP[fs["kind"]],
                {_map_type(t) for t in fs.get("types", [])},
            )
            for key, fs in holder.items()
        }
        reg.add(NodeSchema(_map_type(name), fields))
    root = data.get("root")
    if root:
        reg.root = FieldSchema(
            FIELD_KIND_MAP[root["kind"]],
            {_map_type(t) for t in root.get("types", [])},
        )
    return reg


# ------------------------------------------------------------------ loader


def load_reference_tree_summary(path: str) -> dict[str, Any]:
    """Load one committed reference summary.  Returns
    {root_field: [Node], schema: SchemaRegistry, edit_manager: dict,
    detached: dict, versions: {index: int}}."""
    blobs = _itree_blobs(json.load(open(path, encoding="utf-8")))

    def index_blob(index: str, *names: str) -> str:
        for n in names:
            key = f"indexes/{index}/{n}"
            if key in blobs:
                return blobs[key]
        raise KeyError(f"no blob for index {index} in {sorted(blobs)}")

    forest_fields = decode_field_batch(
        index_blob("Forest", "ForestTree", "contents")
    )
    em = json.loads(index_blob("EditManager", "String"))
    detached = json.loads(
        index_blob("DetachedFieldIndex", "DetachedFieldIndexBlob", "contents")
    )
    versions = {
        idx: json.loads(blobs[f"indexes/{idx}/.metadata"])["version"]
        for idx in ("EditManager", "Schema", "Forest", "DetachedFieldIndex")
        if f"indexes/{idx}/.metadata" in blobs
    }
    return {
        "root_field": forest_fields.get("rootFieldKey", []),
        "schema": schema_from_reference(index_blob("Schema", "SchemaString")),
        "edit_manager": em,
        "detached": detached,
        "versions": versions,
    }
