"""simple-tree: the declarative typed public API over SharedTree.

Reference parity: tree/src/simple-tree/ — ``SchemaFactory``
(api/schemaFactory.ts) lets applications DECLARE node schemas as classes
and then work with the document through typed objects instead of paths:

    sf = SchemaFactory("com.example.app")
    Point = sf.object("Point", x=sf.number, y=sf.number)
    Points = sf.array("Points", Point)

    view = channel.typed_view(TreeViewConfiguration(Points))
    view.initialize([Point(x=1, y=2)])
    view.root.insert_at_end(Point(x=3, y=4))
    view.root[0].x = 5                    # typed write -> changeset
    Tree.on(view.root[0], "nodeChanged", cb)

Python-idiomatic rather than a TS transcription: schema "classes" construct
UNHYDRATED content (plain forest Nodes); reading through a view hands back
HYDRATED typed handles bound to live paths (simple-tree's proxy hydration,
core/treeNodeKernel.ts).  Field access maps by field kind — required/
optional leaves read as scalars, node fields as typed handles, arrays as
sequences with the reference TreeArrayNode verbs (insert_at/insert_at_start/
insert_at_end/remove_at/remove_range/move_to_index — moves are REAL moves,
preserving identity under concurrent edits, not remove+insert).  The
``Tree`` helper namespace mirrors the reference's (api/tree.ts): key,
parent, schema, is_, status, on.  Plain data hydrates implicitly where the
schema is unambiguous (dicts for objects, lists for arrays, scalars for
leaves — simple-tree's implicit construction).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Iterable

from .forest import ROOT_FIELD, Node
from .changeset import make_insert, make_remove
from .schema import (
    ARRAY_FIELD,
    FieldKind,
    FieldSchema,
    LeafKind,
    NodeSchema,
    SchemaRegistry,
    leaf,
    schema_compat,
)


class _LeafType:
    """A leaf schema marker (SchemaFactory.number etc.)."""

    def __init__(self, kind: LeafKind) -> None:
        self.kind = kind
        self.name = kind.value

    def __repr__(self) -> str:
        return f"<leaf {self.name}>"


NUMBER = _LeafType(LeafKind.NUMBER)
STRING = _LeafType(LeafKind.STRING)
BOOLEAN = _LeafType(LeafKind.BOOLEAN)
NULL = _LeafType(LeafKind.NULL)

_LEAF_BY_NAME = {t.name: t for t in (NUMBER, STRING, BOOLEAN, NULL)}


@dataclass
class FieldSpec:
    """One declared field: kind + allowed child types (schema classes or
    leaf markers) — ref simple-tree FieldSchema (fieldSchema.ts)."""

    kind: FieldKind
    types: tuple

    def type_names(self) -> set[str]:
        return {t.name for t in self.types}


def required(*types) -> FieldSpec:
    return FieldSpec(FieldKind.VALUE, types)


def optional(*types) -> FieldSpec:
    return FieldSpec(FieldKind.OPTIONAL, types)


class NodeKind:
    OBJECT = "object"
    ARRAY = "array"


class TreeNodeSchema:
    """A declared node schema; calling it constructs unhydrated content.

    Instances of the reference's schema classes; here one object carries
    the declaration and the constructor."""

    def __init__(self, name: str, kind: str, fields: dict[str, FieldSpec]):
        self.name = name
        self.kind = kind
        self.fields = fields

    def __repr__(self) -> str:
        return f"<schema {self.kind} {self.name!r}>"

    # --------------------------------------------------------- construction
    def __call__(self, *args, **kwargs) -> Node:
        if self.kind == NodeKind.ARRAY:
            (items,) = args if args else (kwargs.pop("items", []),)
            assert not kwargs, "array schema takes a single iterable"
            spec = self.fields[ARRAY_FIELD]
            return Node(type=self.name, fields={
                ARRAY_FIELD: [_content_to_node(spec, it) for it in items]
            })
        assert not args, "object schema takes keyword fields"
        out = Node(type=self.name)
        for key, spec in self.fields.items():
            if key in kwargs:
                v = kwargs.pop(key)
                if spec.kind == FieldKind.SEQUENCE:
                    out.fields[key] = [_content_to_node(spec, it) for it in v]
                else:
                    out.fields[key] = [_content_to_node(spec, v)]
            elif spec.kind == FieldKind.VALUE:
                raise TypeError(f"{self.name}: missing required field {key!r}")
        if kwargs:
            raise TypeError(f"{self.name}: unknown fields {sorted(kwargs)}")
        return out

    # ---------------------------------------------------------------- schema
    def to_node_schema(self) -> NodeSchema:
        return NodeSchema(self.name, {
            k: FieldSchema(s.kind, s.type_names())
            for k, s in self.fields.items()
        })


def _content_to_node(spec: FieldSpec, v: Any) -> Node:
    """Implicit construction (ref simple-tree insertable content): Nodes
    pass through; scalars become leaves; dicts/lists hydrate through the
    spec when exactly one non-leaf type is allowed."""
    if isinstance(v, Node):
        return v
    if isinstance(v, (dict, list)):
        object_types = [
            t for t in spec.types if isinstance(t, TreeNodeSchema)
        ]
        if len(object_types) != 1:
            raise TypeError(
                f"ambiguous implicit construction for {v!r}: "
                f"{len(object_types)} candidate node types"
            )
        t = object_types[0]
        if isinstance(v, list):
            return t(v)
        return t(**v)
    return leaf(v)


def _find_node(root: Node, target: Node) -> list[tuple[str, int]] | None:
    """Locate ``target`` (by object identity) under ``root``; returns its
    path or None when detached (the anchor relocation walk)."""
    stack: list[tuple[Node, list[tuple[str, int]]]] = [(root, [])]
    while stack:
        node, path = stack.pop()
        for key, children in node.fields.items():
            for i, c in enumerate(children):
                if c is target:
                    return path + [(key, i)]
                stack.append((c, path + [(key, i)]))
    return None


class SchemaFactory:
    """Declares schemas in a namespace (ref api/schemaFactory.ts:
    SchemaFactory scoping: type identifiers are '<scope>.<name>')."""

    number = NUMBER
    string = STRING
    boolean = BOOLEAN
    null = NULL

    def __init__(self, scope: str) -> None:
        self.scope = scope
        self._declared: dict[str, TreeNodeSchema] = {}

    def _qualify(self, name: str) -> str:
        return f"{self.scope}.{name}" if self.scope else name

    def object(self, name: str, /, **fields) -> TreeNodeSchema:
        """An object node kind; field values are leaf markers, schema
        objects, or FieldSpec (required(...)/optional(...))."""
        specs = {
            k: (v if isinstance(v, FieldSpec) else required(v))
            for k, v in fields.items()
        }
        return self._declare(TreeNodeSchema(
            self._qualify(name), NodeKind.OBJECT, specs
        ))

    def array(self, name: str, /, *item_types) -> TreeNodeSchema:
        return self._declare(TreeNodeSchema(
            self._qualify(name), NodeKind.ARRAY,
            {ARRAY_FIELD: FieldSpec(FieldKind.SEQUENCE, item_types)},
        ))

    def _declare(self, schema: TreeNodeSchema) -> TreeNodeSchema:
        if schema.name in self._declared:
            raise ValueError(f"schema {schema.name!r} already declared")
        self._declared[schema.name] = schema
        return schema


@dataclass
class TreeViewConfiguration:
    """ref simple-tree TreeViewConfiguration: the root schema."""

    schema: TreeNodeSchema | FieldSpec

    def root_spec(self) -> FieldSpec:
        s = self.schema
        return s if isinstance(s, FieldSpec) else required(s)


def _collect_registry(root: FieldSpec) -> tuple[SchemaRegistry, dict[str, TreeNodeSchema]]:
    """One traversal of the declared schema graph yields both the stored
    SchemaRegistry and the name -> declaration map hydration uses."""
    reg = SchemaRegistry()
    reg.root = FieldSchema(root.kind, root.type_names())
    schemas: dict[str, TreeNodeSchema] = {}

    def walk(spec: FieldSpec) -> None:
        for t in spec.types:
            if isinstance(t, TreeNodeSchema) and t.name not in schemas:
                schemas[t.name] = t
                reg.add(t.to_node_schema())
                for sub in t.fields.values():
                    walk(sub)

    walk(root)
    return reg, schemas


# ---------------------------------------------------------------------------
# Hydrated typed handles
# ---------------------------------------------------------------------------


class TypedNode:
    """A hydrated handle to one node — IDENTITY-stable, not positional
    (simple-tree's hydrated TreeNode; core/treeNodeKernel.ts anchors).

    The handle anchors to the forest Node object at hydration; when edits
    shift its position (a sibling removal, a move), ``_node`` relocates the
    anchor and rebinds the path, so the handle keeps naming the SAME node
    rather than whatever now sits at its old coordinates."""

    def __init__(self, view: "SimpleTreeView", path: list[tuple[str, int]]):
        object.__setattr__(self, "_view", view)
        object.__setattr__(self, "_path", list(path))
        object.__setattr__(
            self, "_anchor", view._channel.forest.node_at(path)
        )

    # ------------------------------------------------------------- plumbing
    def _node(self) -> Node:
        forest = self._view._channel.forest
        try:
            n = forest.node_at(self._path)
        except (IndexError, KeyError):
            n = None
        if n is self._anchor:
            return n
        # Positional drift: relocate the anchored node and rebind.
        path = _find_node(forest.root, self._anchor)
        if path is None:
            raise KeyError("node removed from the document")
        object.__setattr__(self, "_path", path)
        return self._anchor

    def _schema(self) -> TreeNodeSchema:
        return self._view._schemas[self._node().type]

    def _spec(self, key: str) -> FieldSpec:
        try:
            return self._schema().fields[key]
        except KeyError:
            raise AttributeError(
                f"{self._node().type} has no field {key!r}"
            ) from None

    def _read_field(self, key: str):
        spec = self._spec(key)
        children = self._node().fields.get(key, [])
        if spec.kind == FieldKind.SEQUENCE:
            return [
                self._view._hydrate(self._path + [(key, i)])
                for i in range(len(children))
            ]
        if not children:
            return None
        return self._view._hydrate(self._path + [(key, 0)])

    def to_json(self) -> dict:
        return self._node().to_json()

    def __eq__(self, other) -> bool:
        return isinstance(other, TypedNode) and self._anchor is other._anchor

    def __hash__(self) -> int:
        return id(self._anchor)


class TreeObjectNode(TypedNode):
    """Typed attribute access: reads unwrap leaves, writes submit
    changesets (ref simple-tree ObjectNode property proxies)."""

    def __getattr__(self, key: str):
        if key.startswith("_"):
            raise AttributeError(key)
        return self._read_field(key)

    def __setattr__(self, key: str, value) -> None:
        spec = self._spec(key)
        if spec.kind == FieldKind.SEQUENCE:
            raise AttributeError(
                f"sequence field {key!r} edits through its array handle"
            )
        node = self._node()
        count = len(node.fields.get(key, []))
        from .changeset import NodeChange
        from .field_kinds import OptionalChange

        fkind = "value" if spec.kind == FieldKind.VALUE else "optional"
        if (
            spec.kind in (FieldKind.VALUE, FieldKind.OPTIONAL)
            and count == 1
            and not isinstance(value, (Node, dict, list))
            and value is not None
            and node.fields[key][0].type == leaf(value).type
        ):
            # Same-leaf-kind overwrite: a nested value SET, not a replace
            # (keeps node identity so concurrent edits merge as value
            # LWW).  Expressed through the field's OWN kind — one field,
            # one rebaser (mixing sequence marks in would kind-conflict).
            self._view.submit_field(self._path, key, OptionalChange(
                kind=fkind, nested=NodeChange(value=(value,)),
            ))
            return
        if value is None and spec.kind == FieldKind.VALUE:
            # Validate BEFORE any submit: a raise must leave no edit behind.
            raise ValueError(f"required field {key!r} cannot be cleared")
        # Whole-content replace rides the OPTIONAL/VALUE field kind
        # (field_kinds.py): one atomic set with later-sequenced-wins
        # semantics.  A remove+insert pair would let two concurrent
        # replaces double-insert (two children in a 0..1 field).
        content = None if value is None else _content_to_node(spec, value)
        self._view.submit_field(self._path, key, OptionalChange(
            kind=fkind,
            set=(content.clone() if content is not None else None,),
        ))


class TreeArrayNode(TypedNode):
    """Sequence verbs of the reference TreeArrayNode (arrayNode.ts)."""

    def _count(self) -> int:
        return len(self._node().fields.get(ARRAY_FIELD, []))

    def __len__(self) -> int:
        return self._count()

    def __getitem__(self, i: int):
        n = self._count()
        if i < 0:
            i += n
        if not 0 <= i < n:
            raise IndexError(i)
        return self._view._hydrate(self._path + [(ARRAY_FIELD, i)])

    def __iter__(self):
        return (self[i] for i in range(len(self)))

    def _content(self, items: Iterable) -> list[Node]:
        spec = self._spec(ARRAY_FIELD)
        return [_content_to_node(spec, it) for it in items]

    def _submit_marks(self, marks: list) -> None:
        self._view.submit_field(self._path, ARRAY_FIELD, marks)

    def insert_at(self, index: int, *items) -> None:
        from .changeset import make_insert_marks

        self._node()  # rebind the path BEFORE building the submit
        self._submit_marks(make_insert_marks(index, self._content(items)))

    def insert_at_start(self, *items) -> None:
        self.insert_at(0, *items)

    def insert_at_end(self, *items) -> None:
        self.insert_at(self._count(), *items)

    def remove_at(self, index: int) -> None:
        from .changeset import make_remove_marks

        self._node()  # rebind before using the path
        self._submit_marks(make_remove_marks(index, 1))

    def remove_range(self, start: int, end: int) -> None:
        from .changeset import make_remove_marks

        self._node()
        self._submit_marks(make_remove_marks(start, end - start))

    def move_to_index(self, dest: int, source: int, count: int = 1) -> None:
        """A REAL move (identity-preserving under concurrency), not
        remove+insert (ref arrayNode.ts moveToIndex/moveRangeToIndex)."""
        from .changeset import make_move_marks

        self._node()
        self._submit_marks(make_move_marks(source, count, dest))

    def move_to_start(self, source: int, count: int = 1) -> None:
        self.move_to_index(0, source, count)

    def move_to_end(self, source: int, count: int = 1) -> None:
        self.move_to_index(self._count(), source, count)

    def values(self) -> list:
        """Leaf values of the items (None for non-leaf items)."""
        return [
            c.value for c in self._node().fields.get(ARRAY_FIELD, [])
        ]


class SimpleTreeView:
    """The schematize gate + typed root (ref schematizingTreeView.ts via
    channel.view_with; compatibility/upgrade semantics shared with
    schema.SchemaView)."""

    def __init__(self, channel, config: TreeViewConfiguration) -> None:
        self._channel = channel
        self._root_spec = config.root_spec()
        self.view_schema, self._schemas = _collect_registry(self._root_spec)

    # ----------------------------------------------------------------- gate
    @property
    def compatibility(self):
        return schema_compat(self.view_schema, self._channel.schema)

    def upgrade_schema(self) -> None:
        c = self.compatibility
        if not c.can_upgrade:
            raise RuntimeError("view schema cannot upgrade the stored schema")
        if not c.is_equivalent:
            self._channel.set_schema(self.view_schema)

    def initialize(self, content) -> None:
        """Set the stored schema AND the root content (ref
        TreeView.initialize): only valid on an empty/compatible document."""
        self.upgrade_schema()
        existing = len(self._channel.forest.root_field)
        if existing:
            self._channel.submit_change(
                make_remove([], ROOT_FIELD, 0, existing)
            )
        self._channel.submit_change(make_insert(
            [], ROOT_FIELD, 0, [_content_to_node(self._root_spec, content)]
        ))

    # ---------------------------------------------------------------- reads
    def _gate(self) -> None:
        if not self.compatibility.can_view:
            raise RuntimeError(
                "view schema cannot read the document's stored schema"
            )

    def _hydrate(self, path: list[tuple[str, int]]):
        node = self._channel.forest.node_at(path)
        schema = self._schemas.get(node.type)
        if schema is None:  # leaf
            return node.value
        if schema.kind == NodeKind.ARRAY:
            return TreeArrayNode(self, path)
        return TreeObjectNode(self, path)

    @property
    def root(self):
        self._gate()
        if not self._channel.forest.root_field:
            return None
        return self._hydrate([(ROOT_FIELD, 0)])

    # --------------------------------------------------------------- writes
    def _submit(self, change) -> None:
        self._gate()
        self._channel.submit_change(change)

    # Every typed-view write wraps its ancestor path steps BY FIELD KIND:
    # a step through a required/optional field encodes as that kind's
    # nested change, a step through an array/root field as sequence marks.
    # One field, one rebaser — a concurrent whole-field replace
    # (OptionalChange) and a nested edit descending through the same field
    # must meet under the same kind (changeset.rebase_node_change).
    def _step_kind(self, path: list, depth: int) -> FieldKind:
        if depth == 0:
            return FieldKind.SEQUENCE  # the document root field
        key, _idx = path[depth]
        parent = self._channel.forest.node_at(path[:depth])
        schema = self._schemas.get(parent.type)
        if schema is None or key not in schema.fields:
            return FieldKind.SEQUENCE
        return schema.fields[key].kind

    def _wrap_path(self, path: list, leaf: "NodeChange") -> "NodeChange":
        from .changeset import Modify, NodeChange, Skip
        from .field_kinds import OptionalChange

        for depth in reversed(range(len(path))):
            key, idx = path[depth]
            kind = self._step_kind(path, depth)
            if kind == FieldKind.SEQUENCE:
                marks: list = [Skip(idx)] if idx else []
                marks.append(Modify(leaf))
                leaf = NodeChange(fields={key: marks})
            else:
                leaf = NodeChange(fields={key: OptionalChange(
                    kind="value" if kind == FieldKind.VALUE else "optional",
                    nested=leaf,
                )})
        return leaf

    def submit_field(self, path: list, field_key: str, field_change) -> None:
        """Submit one field's change with kind-aware ancestor wrapping."""
        from .changeset import NodeChange

        self._submit(self._wrap_path(
            path, NodeChange(fields={field_key: field_change})
        ))


# ---------------------------------------------------------------------------
# The Tree helper namespace (ref simple-tree api/tree.ts)
# ---------------------------------------------------------------------------


class Tree:
    """Static helpers over hydrated nodes, mirroring the reference
    ``Tree``/``TreeBeta`` surface."""

    @staticmethod
    def key(node: TypedNode):
        """The node's key under its parent: field name, or index within an
        array (ref Tree.key)."""
        node._node()  # rebind to the anchor's current position
        fld, idx = node._path[-1]
        if len(node._path) == 1:
            return idx  # root field position
        parent = node._view._channel.forest.node_at(node._path[:-1])
        parent_schema = node._view._schemas.get(parent.type)
        if parent_schema is not None and parent_schema.kind == NodeKind.ARRAY:
            return idx
        return fld

    @staticmethod
    def parent(node: TypedNode):
        """The parent node handle, or None at the root (ref Tree.parent)."""
        node._node()
        if len(node._path) <= 1:
            return None
        return node._view._hydrate(node._path[:-1])

    @staticmethod
    def schema(node: TypedNode) -> TreeNodeSchema:
        return node._schema()

    @staticmethod
    def is_(node, schema: TreeNodeSchema) -> bool:
        return isinstance(node, TypedNode) and node._node().type == schema.name

    @staticmethod
    def status(node: TypedNode) -> str:
        """"inDocument" | "removed" (ref TreeStatus)."""
        try:
            node._node()
            return "inDocument"
        except (IndexError, KeyError):
            return "removed"

    @staticmethod
    def on(node: TypedNode, event: str, fn: Callable[[], None]) -> Callable[[], None]:
        """Subscribe to "nodeChanged" (this node's own content) or
        "treeChanged" (anything in its subtree) — ref TreeNode events
        (api/treeNodeApi.ts).  Returns the unsubscribe handle."""
        if event not in ("nodeChanged", "treeChanged"):
            raise ValueError(f"unknown event {event!r}")
        view = node._view

        def snapshot():
            try:
                n = node._node()  # identity-stable: follows the anchor
            except (IndexError, KeyError):
                return None
            if event == "treeChanged":
                return n.to_json()
            # nodeChanged: the node's own value plus its DIRECT children's
            # identities/values — a leaf child's value IS the object's
            # property in this model (ref nodeChanged fires on property
            # writes, api/treeNodeApi.ts).
            return (n.value, sorted(
                (k, tuple((c.type, c.value) for c in v))
                for k, v in n.fields.items()
            ))

        last = [snapshot()]

        def on_change() -> None:
            cur = snapshot()
            if cur != last[0]:
                last[0] = cur
                fn()

        return view._channel.add_change_listener(on_change)
