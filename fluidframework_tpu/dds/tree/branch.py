"""Local branches over a SharedTree: fork / edit / rebase / merge.

Reference parity: shared-tree-core/branch.ts (SharedTreeBranch —
``branch()``, ``rebaseOnto``, ``merge``) surfaced through the public
``TreeBranch``/``branch()`` API (shared-tree/independentView.ts,
simple-tree TreeBranch). A branch is an isolated line of development:

- ``fork()`` snapshots the parent's current (optimistic) forest;
- edits on the branch apply only to the branch's forest and NEVER ship;
- ``rebase_onto_parent()`` pulls everything the parent applied since the
  fork (remote commits and the parent's own edits alike), rebasing the
  branch's pending commits over it — the same inverse/apply/re-apply
  sandwich the channel runs for in-flight local edits (editmanager.bridge);
- ``merge_into_parent()`` rebases, then replays the branch's commits onto
  the parent inside one atomic transaction (one sequenced commit on the
  wire) and disposes the branch.

Branches nest: a branch exposes the same {forest, applied_log,
submit_change, transaction} surface the channel does, so ``fork()`` of a
branch yields a grandchild with identical semantics.
"""

from __future__ import annotations

from contextlib import contextmanager
from typing import Any

from .changeset import (
    Commit,
    NodeChange,
    apply_commit,
    clone_commit,
    rollback_staged,
)
from .editmanager import bridge
from .forest import Forest, Node, ROOT_FIELD


class TreeBranch:
    """An isolated fork of a SharedTree (channel or another branch)."""

    def __init__(self, parent) -> None:
        self._parent = parent
        self.forest = Forest()
        self.forest.root = parent.forest.root.clone()
        # Parent coordinate trail position this branch has integrated up to.
        self._base = len(parent.applied_log)
        # Branch-local commits, each a Commit (list of NodeChange), in
        # branch-tip coordinates.
        self._commits: list[Commit] = []
        # The branch's own coordinate trail (for nested forks).
        self.applied_log: list[NodeChange] = []
        self._txn: list[NodeChange] | None = None
        self.disposed = False

    # ------------------------------------------------------------ local edits
    def submit_change(self, change: NodeChange) -> None:
        self._check_alive()
        apply_commit(self.forest.root, [change])
        self.applied_log.append(change)
        if self._txn is not None:
            self._txn.append(change)
            return
        self._commits.append([change])

    @contextmanager
    def transaction(self):
        """Atomic scope on the branch: one commit, abort rolls back."""
        self._check_alive()
        if self._txn is not None:
            raise RuntimeError("transactions do not nest")
        self._txn = []
        try:
            yield self
        except BaseException:
            staged, self._txn = self._txn, None
            rollback_staged(self.forest.root, staged, self.applied_log)
            raise
        staged, self._txn = self._txn, None
        if staged:
            self._commits.append(staged)

    @property
    def view(self):
        from .schema import TreeView

        # The document schema lives on the channel at the root of the
        # branch chain; nested branches walk up to it.
        p = self._parent
        while isinstance(p, TreeBranch):
            p = p._parent
        return TreeView(self.forest, self.submit_change, p.schema)

    def fork(self) -> "TreeBranch":
        self._check_alive()
        if self._txn is not None:
            raise RuntimeError("fork inside an open transaction")
        return TreeBranch(self)

    # ---------------------------------------------------------------- rebase
    def rebase_onto_parent(self) -> None:
        """Integrate everything the parent applied since the fork (ref
        branch.ts rebaseOnto): each parent change is bridged over the
        branch's pending commits exactly like a remote trunk commit over the
        channel's in-flight edits."""
        self._check_alive()
        if self._txn is not None:
            raise RuntimeError("rebase inside an open transaction")
        parent_log = self._parent.applied_log
        for change in parent_log[self._base:]:
            pairs = [(i, c) for i, c in enumerate(self._commits)]
            pairs, bridged = bridge(pairs, clone_commit([change]))
            self._commits = [c for _i, c in pairs]
            apply_commit(self.forest.root, bridged)
            self.applied_log.extend(bridged)
        self._base = len(parent_log)

    def merge_into_parent(self) -> None:
        """Rebase onto the parent, then replay the branch's commits on the
        parent atomically (one transaction -> one wire commit when the
        parent is the channel; ref branch.ts merge squash). Disposes the
        branch."""
        self._check_alive()
        if self._txn is not None:
            raise RuntimeError("merge inside an open transaction")
        self.rebase_onto_parent()
        if self._commits:
            # Commits are cleared only after the parent transaction lands:
            # a failure (e.g. parent inside an open transaction) leaves the
            # branch intact for a retry.
            with self._parent.transaction():
                for commit in self._commits:
                    for change in commit:
                        self._parent.submit_change(clone_commit([change])[0])
            self._commits = []
        self.dispose()

    # ------------------------------------------------------------------ misc
    @property
    def has_changes(self) -> bool:
        return bool(self._commits)

    def dispose(self) -> None:
        self.disposed = True

    def _check_alive(self) -> None:
        if self.disposed:
            raise RuntimeError("branch is disposed")
