"""Stored schema + typed view layer for SharedTree.

Reference parity: tree/src/core/schema-stored/ (stored schema, sequenced as
ops so all replicas agree) and tree/src/simple-tree/ (the public typed API:
object/array/leaf node kinds with field kinds required/optional/sequence).

The ``TreeView`` proxies translate reads into forest cursor walks and writes
into path-addressed changesets submitted through the channel — the analog of
simple-tree's proxy layer generating modular changesets.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from enum import Enum
from typing import Any, Callable

from .changeset import NodeChange, make_insert, make_remove, make_set_value
from .forest import Forest, Node, ROOT_FIELD


class FieldKind(str, Enum):
    VALUE = "value"  # exactly one child
    OPTIONAL = "optional"  # zero or one child
    SEQUENCE = "sequence"  # any number of children


class LeafKind(str, Enum):
    NUMBER = "number"
    STRING = "string"
    BOOLEAN = "boolean"
    NULL = "null"


LEAF_TYPES = {k.value for k in LeafKind}


@dataclass
class FieldSchema:
    kind: FieldKind
    allowed_types: set[str]

    def to_json(self) -> dict:
        return {"kind": self.kind.value, "types": sorted(self.allowed_types)}

    @staticmethod
    def from_json(d: dict) -> "FieldSchema":
        return FieldSchema(FieldKind(d["kind"]), set(d["types"]))


@dataclass
class NodeSchema:
    """An object node kind: named fields with schemas. Arrays are object
    nodes with a single SEQUENCE field (key "") — the same normalization the
    reference's simple-tree ArrayNode uses internally."""

    name: str
    fields: dict[str, FieldSchema] = field(default_factory=dict)

    def to_json(self) -> dict:
        return {"name": self.name, "fields": {k: f.to_json() for k, f in self.fields.items()}}

    @staticmethod
    def from_json(d: dict) -> "NodeSchema":
        return NodeSchema(
            d["name"], {k: FieldSchema.from_json(f) for k, f in d["fields"].items()}
        )


ARRAY_FIELD = ""


def array_schema(name: str, item_types: set[str]) -> NodeSchema:
    return NodeSchema(name, {ARRAY_FIELD: FieldSchema(FieldKind.SEQUENCE, item_types)})


@dataclass
class SchemaRegistry:
    """The document's stored schema: node kinds + the root field schema."""

    nodes: dict[str, NodeSchema] = field(default_factory=dict)
    root: FieldSchema | None = None

    def add(self, schema: NodeSchema) -> NodeSchema:
        self.nodes[schema.name] = schema
        return schema

    def to_json(self) -> dict:
        return {
            "nodes": {k: s.to_json() for k, s in self.nodes.items()},
            "root": self.root.to_json() if self.root else None,
        }

    @staticmethod
    def from_json(d: dict) -> "SchemaRegistry":
        reg = SchemaRegistry(
            nodes={k: NodeSchema.from_json(s) for k, s in d["nodes"].items()},
            root=FieldSchema.from_json(d["root"]) if d["root"] else None,
        )
        return reg

    # ------------------------------------------------------------- validation
    def check_node(self, node: Node) -> list[str]:
        """Validate a subtree; returns a list of violations (empty = ok)."""
        errors: list[str] = []
        if node.type in LEAF_TYPES:
            kind = node.type
            v = node.value
            ok = (
                (kind == LeafKind.NUMBER and isinstance(v, (int, float)) and not isinstance(v, bool))
                or (kind == LeafKind.STRING and isinstance(v, str))
                or (kind == LeafKind.BOOLEAN and isinstance(v, bool))
                or (kind == LeafKind.NULL and v is None)
            )
            if not ok:
                errors.append(f"leaf {kind} holds incompatible value {v!r}")
            if node.fields:
                errors.append(f"leaf {kind} has fields")
            return errors
        schema = self.nodes.get(node.type)
        if schema is None:
            return [f"unknown node type {node.type!r}"]
        for key, fs in schema.fields.items():
            children = node.fields.get(key, [])
            n = len(children)
            if fs.kind == FieldKind.VALUE and n != 1:
                errors.append(f"{node.type}.{key}: value field has {n} children")
            if fs.kind == FieldKind.OPTIONAL and n > 1:
                errors.append(f"{node.type}.{key}: optional field has {n} children")
            for c in children:
                if c.type not in fs.allowed_types:
                    errors.append(f"{node.type}.{key}: type {c.type!r} not allowed")
                errors.extend(self.check_node(c))
        for key in node.fields:
            if key not in schema.fields and node.fields[key]:
                errors.append(f"{node.type}: unexpected field {key!r}")
        return errors

    def check_forest(self, forest: Forest) -> list[str]:
        errors: list[str] = []
        roots = forest.root_field
        if self.root is not None:
            n = len(roots)
            if self.root.kind == FieldKind.VALUE and n != 1:
                errors.append(f"root: value field has {n} children")
            for r in roots:
                if r.type not in self.root.allowed_types:
                    errors.append(f"root: type {r.type!r} not allowed")
        for r in roots:
            errors.extend(self.check_node(r))
        return errors


# ---------------------------------------------------------------------------
# Schema evolution (ref tree/src/shared-tree/schematizingTreeView.ts
# compatibility + simple-tree SchemaCompatibilityStatus: a VIEW schema is
# checked against the document's STORED schema; viewing requires every
# stored-schema document to be readable under the view schema, upgrading
# replaces the stored schema with the view schema when that holds).
# ---------------------------------------------------------------------------

_KIND_WIDTH = {FieldKind.VALUE: 0, FieldKind.OPTIONAL: 1, FieldKind.SEQUENCE: 2}


@dataclass
class SchemaCompatibility:
    """ref simple-tree SchemaCompatibilityStatus {isEquivalent, canView,
    canUpgrade}."""

    is_equivalent: bool
    can_view: bool
    can_upgrade: bool


def field_subsumes(view: FieldSchema, stored: FieldSchema) -> bool:
    """Every field content valid under ``stored`` is valid under ``view``:
    multiplicity may widen (value -> optional -> sequence) and allowed
    types may grow, never shrink."""
    if _KIND_WIDTH[view.kind] < _KIND_WIDTH[stored.kind]:
        return False
    return stored.allowed_types <= view.allowed_types


def _subsumes(wider: SchemaRegistry, narrower: SchemaRegistry) -> bool:
    """Every document valid under ``narrower`` is valid under ``wider``:
    ``wider`` must know every ``narrower`` node type with each field
    widened-or-equal; it may add node types freely but may add NEW fields
    to an existing type only with non-VALUE kinds (existing documents lack
    the field entirely)."""
    if narrower.root is not None:
        if wider.root is None or not field_subsumes(wider.root, narrower.root):
            return False
    for name, s in narrower.nodes.items():
        w = wider.nodes.get(name)
        if w is None:
            return False
        for key, fs in s.fields.items():
            wf = w.fields.get(key)
            if wf is None or not field_subsumes(wf, fs):
                return False
        for key, wf in w.fields.items():
            if key not in s.fields and wf.kind == FieldKind.VALUE:
                return False  # new required field: old documents can't satisfy
    return True


def schema_compat(view: SchemaRegistry, stored: SchemaRegistry) -> SchemaCompatibility:
    """Compare a view schema against the stored schema.

    ``can_upgrade`` needs the view to subsume the stored schema (stored
    documents stay valid once the view schema replaces it).  ``can_view``
    is stricter — no-upgrade compatibility: edits written under the view
    schema must also satisfy the CURRENT stored schema, so the two must
    subsume each other (a strictly wider view only grants upgrade; ref
    SchemaCompatibilityStatus canView vs canUpgrade)."""
    forward = _subsumes(view, stored)
    return SchemaCompatibility(
        is_equivalent=view.to_json() == stored.to_json(),
        can_view=forward and _subsumes(stored, view),
        can_upgrade=forward,
    )


class SchemaView:
    """The gate a client goes through to read/edit a document with ITS OWN
    schema (ref ITree.viewWith -> TreeView with .compatibility and
    .upgradeSchema). Reads/edits raise until the view schema can read the
    stored schema; upgrade_schema ships the view schema as the new stored
    schema when permitted."""

    def __init__(self, channel, view_schema: SchemaRegistry) -> None:
        self._channel = channel
        self.view_schema = view_schema

    @property
    def compatibility(self) -> SchemaCompatibility:
        return schema_compat(self.view_schema, self._channel.schema)

    @property
    def root(self):
        c = self.compatibility
        if not c.can_view:
            raise RuntimeError(
                "view schema cannot read the document's stored schema "
                "(compatibility.can_view is False)"
            )
        return TreeView(
            self._channel.forest, self._channel.submit_change, self.view_schema
        ).root

    def upgrade_schema(self) -> None:
        c = self.compatibility
        if not c.can_upgrade:
            raise RuntimeError("view schema cannot upgrade the stored schema")
        if not c.is_equivalent:
            self._channel.set_schema(self.view_schema)


# ---------------------------------------------------------------------------
# Leaf construction helpers
# ---------------------------------------------------------------------------


def leaf(value: Any) -> Node:
    if value is None:
        return Node(type=LeafKind.NULL.value, value=None)
    if isinstance(value, bool):
        return Node(type=LeafKind.BOOLEAN.value, value=value)
    if isinstance(value, (int, float)):
        return Node(type=LeafKind.NUMBER.value, value=value)
    if isinstance(value, str):
        return Node(type=LeafKind.STRING.value, value=value)
    raise TypeError(f"not a leaf value: {value!r}")


def build_node(type_name: str, **fields: Any) -> Node:
    """Construct an object node; field values may be leaf scalars, Nodes, or
    lists thereof."""
    out = Node(type=type_name)
    for key, v in fields.items():
        items = v if isinstance(v, list) else [v]
        out.fields[key] = [i if isinstance(i, Node) else leaf(i) for i in items]
    return out


# ---------------------------------------------------------------------------
# Typed view (proxy layer)
# ---------------------------------------------------------------------------


class TreeView:
    """A read/write view over a SharedTree channel's forest. Reads resolve
    through live forest paths; writes submit path-addressed changesets via
    ``submit_change`` (provided by the channel)."""

    def __init__(
        self,
        forest: Forest,
        submit_change: Callable[[NodeChange], None],
        registry: SchemaRegistry | None = None,
    ) -> None:
        self._forest = forest
        self._submit = submit_change
        self.registry = registry

    # ----------------------------------------------------------------- reads
    @property
    def root(self) -> "NodeProxy | None":
        roots = self._forest.root_field
        return NodeProxy(self, [(ROOT_FIELD, 0)]) if roots else None

    def node(self, path: list[tuple[str, int]]) -> "NodeProxy":
        return NodeProxy(self, path)

    # ---------------------------------------------------------------- writes
    def set_root(self, node: Node) -> None:
        count = len(self._forest.root_field)
        if count:
            self._submit(make_remove([], ROOT_FIELD, 0, count))
        self._submit(make_insert([], ROOT_FIELD, 0, [node]))


class NodeProxy:
    """Typed handle to one node at a live path."""

    def __init__(self, view: TreeView, path: list[tuple[str, int]]) -> None:
        self._view = view
        self._path = path

    def _node(self) -> Node:
        return self._view._forest.node_at(self._path)

    # ----------------------------------------------------------------- reads
    @property
    def type(self) -> str:
        return self._node().type

    @property
    def value(self) -> Any:
        return self._node().value

    def get(self, key: str) -> "NodeProxy | None":
        children = self._node().fields.get(key, [])
        return NodeProxy(self._view, self._path + [(key, 0)]) if children else None

    def scalar(self, key: str) -> Any:
        """Read the leaf value of a value/optional field."""
        children = self._node().fields.get(key, [])
        return children[0].value if children else None

    def children(self, key: str = ARRAY_FIELD) -> list["NodeProxy"]:
        n = len(self._node().fields.get(key, []))
        return [NodeProxy(self._view, self._path + [(key, i)]) for i in range(n)]

    def __len__(self) -> int:
        return len(self._node().fields.get(ARRAY_FIELD, []))

    def __getitem__(self, i: int) -> "NodeProxy":
        return NodeProxy(self._view, self._path + [(ARRAY_FIELD, i)])

    def to_json(self) -> dict:
        return self._node().to_json()

    # ---------------------------------------------------------------- writes
    def set_value(self, value: Any) -> None:
        self._view._submit(make_set_value(self._path, value))

    def set(self, key: str, value: Any) -> None:
        """Overwrite a value/optional field with one leaf/node."""
        node = value if isinstance(value, Node) else leaf(value)
        count = len(self._node().fields.get(key, []))
        if count:
            self._view._submit(make_remove(self._path, key, 0, count))
        self._view._submit(make_insert(self._path, key, 0, [node]))

    def clear(self, key: str) -> None:
        count = len(self._node().fields.get(key, []))
        if count:
            self._view._submit(make_remove(self._path, key, 0, count))

    def insert(self, index: int, items: list, key: str = ARRAY_FIELD) -> None:
        nodes = [i if isinstance(i, Node) else leaf(i) for i in items]
        self._view._submit(make_insert(self._path, key, index, nodes))

    def remove(self, index: int, count: int = 1, key: str = ARRAY_FIELD) -> None:
        self._view._submit(make_remove(self._path, key, index, count))
