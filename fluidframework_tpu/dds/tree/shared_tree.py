"""SharedTree over the channel boundary.

Reference parity: SharedTreeKernel (tree/src/shared-tree/sharedTree.ts:176)
+ SharedTreeCore (shared-tree-core/sharedTreeCore.ts:92): sequenced edits
flow into the EditManager, the forest tracks trunk-tip state overlaid with
the local branch, resubmit rebases pending edits onto the current trunk
(defaultResubmitMachine.ts), and summaries carry forest + EditManager state
(editManagerSummarizer.ts, forest-summary).

Transactions (ref shared-tree Transactor / branch.ts): edits inside
``with tree.transaction():`` apply optimistically as they are made and ship
as ONE atomic commit on exit; abort rolls the forest back with the
enriched inverses.

Revision ids are compressed (ref id-compressor/src/idCompressor.ts op-space
discipline): each replica mints session-space ids, ships the op-space form
plus its id-creation range on the wire, and every replica finalizes ranges
in total order — so revision tags cost an int on the wire instead of a
UUID, and summaries decompress them to stable UUIDs.

Wire op formats:
  {"type": "edit", "rev": op-space id, "sid": session uuid,
   "idRange": [first, last] | None, "changes": [<changeset json>...]}
  {"type": "schema", "schema": <schema json>}   (LWW by sequence order)
"""

from __future__ import annotations

from contextlib import contextmanager
from typing import Any, Callable

from ...runtime.channel import Channel, MessageCollection
from ...utils.id_compressor import IdCompressor, IdCreationRange
from .changeset import (
    Commit,
    NodeChange,
    apply_commit,
    clone_commit,
    commit_from_json,
    commit_to_json,
    invert_commit,
    rollback_staged,
)
from .editmanager import EditManager, bridge
from .forest import Forest, Node, decode_field_chunked, encode_field_chunked, ROOT_FIELD
from .schema import SchemaRegistry, TreeView


class SharedTreeChannel(Channel):
    """One replica of a SharedTree document."""

    channel_type = "sharedTree"

    def __init__(self, channel_id: str) -> None:
        super().__init__(channel_id)
        self.forest = Forest()  # trunk-tip state + local pending overlay
        self.idc = IdCompressor()
        self.em = EditManager(
            encode_rev=self._rev_to_stable, decode_rev=self._rev_from_stable
        )
        self.schema = SchemaRegistry()
        # Local branch: pending commits in trunk-tip coordinates, continuously
        # rebased as remote commits land (the sandwich).
        self._local_pending: list[tuple[Any, Commit]] = []
        self._txn: list[NodeChange] | None = None
        self.on_change: Callable[[], None] | None = None  # view invalidation
        # Multiplexed change listeners (simple-tree node events ride these).
        self._change_listeners: list[Callable[[], None]] = []
        # Every change applied to the forest, in application order (local
        # edits and bridged remote commits alike) — the coordinate trail
        # undo-redo revertibles rebase their inverses over.
        self.applied_log: list[NodeChange] = []

    # ------------------------------------------------------------- revisions
    # A revision tag is the WIRE pair (session uuid, op-space id): identical
    # on every replica, hashable, and comparable without any normalization
    # ordering concerns (the op-space discipline of idCompressor.ts:400).
    # Summaries re-encode tags as stable UUIDs so they stay meaningful after
    # the minting session's clusters are the only thing a loader knows.

    def _rev_to_stable(self, rev: tuple[str, int]) -> str:
        return self.idc.decompress(
            self.idc.normalize_to_session_space(rev[1], rev[0])
        )

    def _rev_from_stable(self, stable: str) -> tuple[str, int]:
        return ("", self.idc.recompress(stable))

    # ------------------------------------------------------------ local edits
    def submit_change(self, change: NodeChange) -> None:
        """Apply a local edit optimistically; ships immediately, or as part
        of the enclosing transaction's atomic commit.  The forest apply
        enriches the change (repair data), and the enriched form is what
        goes on the wire so every replica integrates the exact same
        changeset object."""
        apply_commit(self.forest.root, [change])
        self.applied_log.append(change)
        if self._txn is not None:
            self._txn.append(change)
            self._notify()
            return
        self._ship_commit([change])
        self._notify()

    def _ship_commit(self, commit: Commit) -> None:
        raw = self.idc.generate_compressed_id()
        rng = self.idc.take_next_creation_range()
        rev = (self.idc.session_id, self.idc.normalize_to_op_space(raw))
        self._local_pending.append((rev, commit))
        self.submit_local_message(
            {
                "type": "edit",
                "rev": rev[1],
                "sid": rev[0],
                "idRange": (
                    [rng.first_gen_count, rng.last_gen_count] if rng else None
                ),
                "changes": commit_to_json(commit),
            },
            {"rev": rev},
        )

    # ------------------------------------------------------------ transactions
    @contextmanager
    def transaction(self):
        """Atomic edit scope: everything submitted inside lands as one
        commit (one sequence number, all-or-nothing against concurrency);
        an exception rolls the forest back and ships nothing."""
        if self._txn is not None:
            raise RuntimeError("transactions do not nest")
        self._txn = []
        try:
            yield self
        except BaseException:
            staged, self._txn = self._txn, None
            rollback_staged(self.forest.root, staged, self.applied_log)
            self._notify()
            raise
        staged, self._txn = self._txn, None
        if staged:
            self._ship_commit(staged)
        self._notify()

    def set_schema(self, registry: SchemaRegistry) -> None:
        self.schema = registry
        self.submit_local_message(
            {"type": "schema", "schema": registry.to_json()}, {"rev": None}
        )

    def typed_view(self, config) -> "SimpleTreeView":
        """The declarative typed API (ref ITree.viewWith over simple-tree
        schema classes; dds/tree/simple_tree.py SchemaFactory)."""
        from .simple_tree import SimpleTreeView

        return SimpleTreeView(self, config)

    def view_with(self, view_schema: SchemaRegistry):
        """Open the document under the CLIENT's schema (ref ITree.viewWith):
        returns a SchemaView whose .compatibility reports
        {is_equivalent, can_view, can_upgrade} against the stored schema and
        whose .upgrade_schema() ships the view schema when permitted."""
        from .schema import SchemaView

        return SchemaView(self, view_schema)

    def fork(self):
        """Branch the tree at its current (optimistic) state (ref
        branch.ts / TreeBranch): edits on the fork are local-only until
        merge_into_parent ships them as one atomic commit."""
        from .branch import TreeBranch

        if self._txn is not None:
            raise RuntimeError("fork inside an open transaction")
        return TreeBranch(self)

    @property
    def view(self) -> TreeView:
        return TreeView(self.forest, self.submit_change, self.schema)

    def add_change_listener(self, fn: Callable[[], None]) -> Callable[[], None]:
        """Subscribe to every forest change (local or remote); returns the
        unsubscribe handle."""
        self._change_listeners.append(fn)

        def unsubscribe() -> None:  # idempotent (double-off is a no-op)
            if fn in self._change_listeners:
                self._change_listeners.remove(fn)

        return unsubscribe

    def _notify(self) -> None:
        if self.on_change is not None:
            self.on_change()
        for fn in list(self._change_listeners):
            fn()

    # ---------------------------------------------------------------- inbound
    def _finalize_ids(self, c: dict) -> None:
        if c.get("idRange"):
            self.idc.finalize_creation_range(
                IdCreationRange(
                    session_id=c["sid"],
                    first_gen_count=c["idRange"][0],
                    last_gen_count=c["idRange"][1],
                )
            )

    @staticmethod
    def _wire_revision(c: dict) -> tuple[str, int]:
        """Revision tags ARE the wire pair — identical on every replica and
        equal by value across submit/ack/trunk with no normalization races."""
        return (c["sid"], c["rev"])

    def process_messages(self, collection: MessageCollection) -> None:
        if self._txn is not None:
            # The reference's Transactor is synchronous within one JS turn,
            # so sequenced ops can never interleave an open transaction.
            # Enforce the same discipline: the staged edits are not part of
            # _local_pending yet, so bridging an incoming commit here would
            # apply it at coordinates that ignore them (and abort could not
            # restore converged state).
            raise RuntimeError(
                "sequenced ops arrived inside an open transaction — finish "
                "or abort the transaction before pumping the delta stream"
            )
        env = collection.envelope
        for m in collection.messages:
            c = m.contents
            if c["type"] == "schema":
                self.schema = SchemaRegistry.from_json(c["schema"])
                continue
            self._finalize_ids(c)
            rev = self._wire_revision(c)
            change = commit_from_json(c["changes"])
            trunk_change = self.em.add_sequenced(
                client_id=env.client_id,
                revision=rev,
                change=change,
                ref_seq=env.ref_seq,
                seq=env.seq,
            )
            if m.local:
                # Our own edit reached the trunk: the forest already shows it.
                assert self._local_pending and self._local_pending[0][0] == rev, (
                    "local branch FIFO skew"
                )
                self._local_pending.pop(0)
            else:
                # Sandwich: rebase the local branch over the new trunk commit
                # and apply its bridged form to the optimistic forest.
                self._local_pending, x = bridge(
                    self._local_pending, clone_commit(trunk_change)
                )
                apply_commit(self.forest.root, x)
                self.applied_log.extend(x)
        self.em.advance_min_seq(env.min_seq)
        self._notify()

    def on_min_seq(self, min_seq: int) -> None:
        self.em.advance_min_seq(min_seq)

    def on_client_leave(self, client_id: str, seq: int) -> None:
        self.em.on_client_leave(client_id)

    # ----------------------------------------------------- reconnect / stash
    def resubmit(self, contents: Any, local_metadata: Any, squash: bool = False) -> None:
        """Resubmit the CURRENT (trunk-tip rebased) form of the pending
        commit — merge-tree regeneratePendingOp's analog for tree edits."""
        if contents["type"] == "schema":
            self.submit_local_message(contents, {"rev": None})
            return
        rev = local_metadata["rev"]
        for r, commit in self._local_pending:
            if r == rev:
                self.submit_local_message(
                    {
                        "type": "edit",
                        "rev": contents["rev"],
                        "sid": contents["sid"],
                        "idRange": contents.get("idRange"),
                        "changes": commit_to_json(commit),
                    },
                    {"rev": rev},
                )
                return
        raise AssertionError(f"resubmit of unknown pending edit {rev}")

    def apply_stashed(self, contents: Any) -> Any:
        if contents["type"] == "schema":
            self.schema = SchemaRegistry.from_json(contents["schema"])
            return {"rev": None}
        commit = commit_from_json(contents["changes"])
        # The stash rides the ORIGINAL session's ids; keep them as the
        # pending key (sid, op-space id) — stable without finalization.
        rev = (contents["sid"], contents["rev"])
        apply_commit(self.forest.root, commit)
        self.applied_log.extend(commit)
        self._local_pending.append((rev, commit))
        self._notify()
        return {"rev": rev}

    def rollback(self, contents: Any, local_metadata: Any) -> None:
        rev = local_metadata["rev"]
        assert self._local_pending and self._local_pending[-1][0] == rev, (
            "rollback must undo the latest local edit first"
        )
        _, commit = self._local_pending.pop()
        inverse = invert_commit(commit)
        apply_commit(self.forest.root, inverse)
        self.applied_log.extend(inverse)
        # The rolled-back op never ships, so its id range must return to the
        # unshipped pool or the NEXT op's range would leave a finalization
        # gap on every replica (LIFO: this was the newest take).
        if contents.get("idRange"):
            self.idc.untake_creation_range(contents["idRange"][0])
        self._notify()

    # ------------------------------------------------------------ checkpoint
    def summarize(self) -> dict[str, Any]:
        if self._local_pending:
            raise RuntimeError("summarize with pending tree edits")
        return {
            "forest": encode_field_chunked(self.forest.root_field),
            "editManager": self.em.summarize(),
            "schema": self.schema.to_json(),
            "idCompressor": self.idc.serialize(with_session=False),
        }

    def load(self, summary: dict[str, Any]) -> None:
        self.forest.root = Node(type="__root__")
        self.forest.root.fields[ROOT_FIELD] = decode_field_chunked(summary["forest"])
        if "idCompressor" in summary:
            self.idc = IdCompressor.deserialize(summary["idCompressor"])
        self.em = EditManager(
            encode_rev=self._rev_to_stable, decode_rev=self._rev_from_stable
        )
        self.em.load(summary["editManager"])
        self.schema = SchemaRegistry.from_json(summary["schema"])
        self._notify()


class _Factory:
    channel_type = SharedTreeChannel.channel_type

    def create(self, channel_id: str) -> SharedTreeChannel:
        return SharedTreeChannel(channel_id)


SharedTreeFactory = _Factory()
