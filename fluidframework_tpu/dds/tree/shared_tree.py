"""SharedTree over the channel boundary.

Reference parity: SharedTreeKernel (tree/src/shared-tree/sharedTree.ts:176)
+ SharedTreeCore (shared-tree-core/sharedTreeCore.ts:92): sequenced edits
flow into the EditManager, the forest tracks trunk-tip state overlaid with
the local branch, resubmit rebases pending edits onto the current trunk
(defaultResubmitMachine.ts), and summaries carry forest + EditManager state
(editManagerSummarizer.ts, forest-summary).

Transactions (ref shared-tree Transactor / branch.ts): edits inside
``with tree.transaction():`` apply optimistically as they are made and ship
as ONE atomic commit on exit; abort rolls the forest back with the
enriched inverses.

Revision ids are compressed (ref id-compressor/src/idCompressor.ts op-space
discipline): each replica mints session-space ids, ships the op-space form
plus its id-creation range on the wire, and every replica finalizes ranges
in total order — so revision tags cost an int on the wire instead of a
UUID, and summaries decompress them to stable UUIDs.

Wire op formats:
  {"type": "edit", "rev": op-space id, "sid": session uuid,
   "idRange": [first, last] | None, "changes": [<changeset json>...]}
  {"type": "schema", "schema": <schema json>}   (LWW by sequence order)
"""

from __future__ import annotations

from contextlib import contextmanager
from typing import Any, Callable

from ...protocol.channel import Channel, MessageCollection
from ...utils.id_compressor import IdCompressor, IdCreationRange
from .changeset import (
    Commit,
    NodeChange,
    apply_commit,
    clone_commit,
    commit_from_json,
    commit_to_json,
    invert_commit,
    rollback_staged,
)
from .editmanager import EditManager, bridge
from .forest import Forest, Node, decode_field_chunked, encode_field_chunked, ROOT_FIELD
from .schema import SchemaRegistry, TreeView


class SharedTreeChannel(Channel):
    """One replica of a SharedTree document."""

    channel_type = "sharedTree"

    def __init__(self, channel_id: str) -> None:
        super().__init__(channel_id)
        self.forest = Forest()  # trunk-tip state + local pending overlay
        # The SEQUENCED state alone (no local overlay): one trunk apply per
        # sequenced commit keeps it current, and it is the exact rebuild
        # base when a constraint violation voids a pending commit (the
        # inverse-rewind shortcut can diverge when LWW suppressed a
        # concurrent value set's repair data) — ref shared-tree trunk vs
        # checkout branch split.
        self._trunk_forest = Forest()
        self.idc = IdCompressor()
        self.em = EditManager(
            encode_rev=self._rev_to_stable, decode_rev=self._rev_from_stable
        )
        self.schema = SchemaRegistry()
        # Local branch: pending commits in trunk-tip coordinates, continuously
        # rebased as remote commits land (the sandwich).
        self._local_pending: list[tuple[Any, Commit]] = []
        self._txn: list[NodeChange] | None = None
        self._txn_constraints: list = []
        self.on_change: Callable[[], None] | None = None  # view invalidation
        # Multiplexed change listeners (simple-tree node events ride these).
        self._change_listeners: list[Callable[[], None]] = []
        # Incremental forest summarization (ref feature-libraries/
        # incrementalSummarizationUtils.ts): the root field summarizes in
        # fixed-size chunks of CHUNK_ROOTS subtrees; this maps chunk index
        # -> seq of the last sequenced change touching it, so summary_tree
        # can emit handles for chunks unchanged since the covered summary.
        self._chunk_seqs: dict[int, int] = {}
        # Every change applied to the forest, in application order (local
        # edits and bridged remote commits alike) — the coordinate trail
        # undo-redo revertibles rebase their inverses over.
        self.applied_log: list[NodeChange] = []

    # ------------------------------------------------------------- revisions
    # A revision tag is the WIRE pair (session uuid, op-space id): identical
    # on every replica, hashable, and comparable without any normalization
    # ordering concerns (the op-space discipline of idCompressor.ts:400).
    # Summaries re-encode tags as stable UUIDs so they stay meaningful after
    # the minting session's clusters are the only thing a loader knows.

    def _rev_to_stable(self, rev: tuple[str, int]) -> str:
        return self.idc.decompress(
            self.idc.normalize_to_session_space(rev[1], rev[0])
        )

    def _rev_from_stable(self, stable: str) -> tuple[str, int]:
        return ("", self.idc.recompress(stable))

    # ------------------------------------------------------------ local edits
    def submit_change(
        self, change: NodeChange, constraints: list | None = None
    ) -> None:
        """Apply a local edit optimistically; ships immediately, or as part
        of the enclosing transaction's atomic commit.  The forest apply
        enriches the change (repair data), and the enriched form is what
        goes on the wire so every replica integrates the exact same
        changeset object.

        ``constraints`` (changeset.node_exists_constraint /
        no_change_constraint): the edit becomes a no-op on EVERY replica if
        a concurrent sequenced change violates one (ref runtime.constraints
        nodeInDocument)."""
        apply_commit(self.forest.root, [change])
        self.applied_log.append(change)
        if self._txn is not None:
            self._txn.append(change)
            if constraints:
                self._txn_constraints.extend(constraints)
            self._notify()
            return
        self._ship_commit(Commit([change], constraints))
        self._notify()

    def _ship_commit(self, commit: Commit) -> None:
        raw = self.idc.generate_compressed_id()
        rng = self.idc.take_next_creation_range()
        rev = (self.idc.session_id, self.idc.normalize_to_op_space(raw))
        self._local_pending.append((rev, commit))
        self.submit_local_message(
            {
                "type": "edit",
                "rev": rev[1],
                "sid": rev[0],
                "idRange": (
                    [rng.first_gen_count, rng.last_gen_count] if rng else None
                ),
                "changes": commit_to_json(commit),
            },
            {"rev": rev},
        )

    # ------------------------------------------------------------ transactions
    @contextmanager
    def transaction(self, constraints: list | None = None):
        """Atomic edit scope: everything submitted inside lands as one
        commit (one sequence number, all-or-nothing against concurrency);
        an exception rolls the forest back and ships nothing.
        ``constraints`` void the whole transaction if violated by a
        concurrent sequenced edit (ref Transactor + runtime.constraints)."""
        if self._txn is not None:
            raise RuntimeError("transactions do not nest")
        self._txn = []
        self._txn_constraints = list(constraints or [])
        try:
            yield self
        except BaseException:
            staged, self._txn = self._txn, None
            rollback_staged(self.forest.root, staged, self.applied_log)
            self._notify()
            raise
        staged, self._txn = self._txn, None
        cons, self._txn_constraints = self._txn_constraints, []
        if staged:
            self._ship_commit(Commit(staged, cons))
        self._notify()

    def set_schema(self, registry: SchemaRegistry) -> None:
        self.schema = registry
        self.submit_local_message(
            {"type": "schema", "schema": registry.to_json()}, {"rev": None}
        )

    def typed_view(self, config) -> "SimpleTreeView":
        """The declarative typed API (ref ITree.viewWith over simple-tree
        schema classes; dds/tree/simple_tree.py SchemaFactory)."""
        from .simple_tree import SimpleTreeView

        return SimpleTreeView(self, config)

    def view_with(self, view_schema: SchemaRegistry):
        """Open the document under the CLIENT's schema (ref ITree.viewWith):
        returns a SchemaView whose .compatibility reports
        {is_equivalent, can_view, can_upgrade} against the stored schema and
        whose .upgrade_schema() ships the view schema when permitted."""
        from .schema import SchemaView

        return SchemaView(self, view_schema)

    def fork(self):
        """Branch the tree at its current (optimistic) state (ref
        branch.ts / TreeBranch): edits on the fork are local-only until
        merge_into_parent ships them as one atomic commit."""
        from .branch import TreeBranch

        if self._txn is not None:
            raise RuntimeError("fork inside an open transaction")
        return TreeBranch(self)

    @property
    def view(self) -> TreeView:
        return TreeView(self.forest, self.submit_change, self.schema)

    def add_change_listener(self, fn: Callable[[], None]) -> Callable[[], None]:
        """Subscribe to every forest change (local or remote); returns the
        unsubscribe handle."""
        self._change_listeners.append(fn)

        def unsubscribe() -> None:  # idempotent (double-off is a no-op)
            if fn in self._change_listeners:
                self._change_listeners.remove(fn)

        return unsubscribe

    def _notify(self) -> None:
        if self.on_change is not None:
            self.on_change()
        for fn in list(self._change_listeners):
            fn()

    # ---------------------------------------------------------------- inbound
    def _finalize_ids(self, c: dict) -> None:
        if c.get("idRange"):
            self.idc.finalize_creation_range(
                IdCreationRange(
                    session_id=c["sid"],
                    first_gen_count=c["idRange"][0],
                    last_gen_count=c["idRange"][1],
                )
            )

    @staticmethod
    def _wire_revision(c: dict) -> tuple[str, int]:
        """Revision tags ARE the wire pair — identical on every replica and
        equal by value across submit/ack/trunk with no normalization races."""
        return (c["sid"], c["rev"])

    def process_messages(self, collection: MessageCollection) -> None:
        if self._txn is not None:
            # The reference's Transactor is synchronous within one JS turn,
            # so sequenced ops can never interleave an open transaction.
            # Enforce the same discipline: the staged edits are not part of
            # _local_pending yet, so bridging an incoming commit here would
            # apply it at coordinates that ignore them (and abort could not
            # restore converged state).
            raise RuntimeError(
                "sequenced ops arrived inside an open transaction — finish "
                "or abort the transaction before pumping the delta stream"
            )
        env = collection.envelope
        for m in collection.messages:
            c = m.contents
            if c["type"] == "schema":
                self.schema = SchemaRegistry.from_json(c["schema"])
                continue
            self._finalize_ids(c)
            rev = self._wire_revision(c)
            change = commit_from_json(c["changes"])
            trunk_change = self.em.add_sequenced(
                client_id=env.client_id,
                revision=rev,
                change=change,
                ref_seq=env.ref_seq,
                seq=env.seq,
            )
            apply_commit(self._trunk_forest.root, clone_commit(trunk_change))
            if m.local:
                # Our own edit reached the trunk: the forest already shows it.
                assert self._local_pending and self._local_pending[0][0] == rev, (
                    "local branch FIFO skew"
                )
                self._local_pending.pop(0)
            else:
                # Sandwich: rebase the local branch over the new trunk commit
                # and apply its bridged form to the optimistic forest.
                had = [
                    getattr(cm, "violated", False)
                    for _r, cm in self._local_pending
                ]
                prev_pending = self._local_pending
                self._local_pending, x = bridge(
                    self._local_pending, clone_commit(trunk_change)
                )
                newly_voided = any(
                    getattr(cm, "violated", False) and not had[i]
                    for i, (_r, cm) in enumerate(self._local_pending)
                )
                if newly_voided:
                    # A constraint of OURS was violated by this concurrent
                    # commit: the optimistic overlay still shows the voided
                    # edit.  Rebuild from the EXACT trunk state (already
                    # advanced past this commit) plus the surviving rebased
                    # pending forms — inverse-rewind shortcuts can diverge
                    # when LWW suppressed a concurrent set's repair data.
                    # The applied_log gets a best-effort inverse trail so
                    # coordinate consumers (undo, tree-agent) keep a
                    # contiguous history.
                    for _rev, cm in reversed(prev_pending):
                        self.applied_log.extend(invert_commit(cm))
                    self.applied_log.extend(clone_commit(trunk_change))
                    self.forest.load_json(self._trunk_forest.to_json())
                    for _rev, cm in self._local_pending:
                        apply_commit(self.forest.root, cm)
                        self.applied_log.extend(cm)
                else:
                    apply_commit(self.forest.root, x)
                    self.applied_log.extend(x)
            # Mark AFTER the forest apply: the dirty range must span the
            # POST-change chunk count (a remote append growing the domain
            # past a chunk boundary must dirty the new tail chunk, or the
            # next summary emits a dangling handle).
            self._mark_chunks_dirty(trunk_change, env.seq)
        self.em.advance_min_seq(env.min_seq)
        self._notify()

    def on_min_seq(self, min_seq: int) -> None:
        self.em.advance_min_seq(min_seq)

    def on_client_leave(self, client_id: str, seq: int) -> None:
        self.em.on_client_leave(client_id)

    # ----------------------------------------------------- reconnect / stash
    def resubmit(self, contents: Any, local_metadata: Any, squash: bool = False) -> None:
        """Resubmit the CURRENT (trunk-tip rebased) form of the pending
        commit — merge-tree regeneratePendingOp's analog for tree edits."""
        if contents["type"] == "schema":
            self.submit_local_message(contents, {"rev": None})
            return
        rev = local_metadata["rev"]
        for r, commit in self._local_pending:
            if r == rev:
                self.submit_local_message(
                    {
                        "type": "edit",
                        "rev": contents["rev"],
                        "sid": contents["sid"],
                        "idRange": contents.get("idRange"),
                        "changes": commit_to_json(commit),
                    },
                    {"rev": rev},
                )
                return
        raise AssertionError(f"resubmit of unknown pending edit {rev}")

    def apply_stashed(self, contents: Any) -> Any:
        if contents["type"] == "schema":
            self.schema = SchemaRegistry.from_json(contents["schema"])
            return {"rev": None}
        commit = commit_from_json(contents["changes"])
        # The stash rides the ORIGINAL session's ids; keep them as the
        # pending key (sid, op-space id) — stable without finalization.
        rev = (contents["sid"], contents["rev"])
        apply_commit(self.forest.root, commit)
        self.applied_log.extend(commit)
        self._local_pending.append((rev, commit))
        self._notify()
        return {"rev": rev}

    def rollback(self, contents: Any, local_metadata: Any) -> None:
        rev = local_metadata["rev"]
        assert self._local_pending and self._local_pending[-1][0] == rev, (
            "rollback must undo the latest local edit first"
        )
        _, commit = self._local_pending.pop()
        inverse = invert_commit(commit)
        apply_commit(self.forest.root, inverse)
        self.applied_log.extend(inverse)
        # The rolled-back op never ships, so its id range must return to the
        # unshipped pool or the NEXT op's range would leave a finalization
        # gap on every replica (LIFO: this was the newest take).
        if contents.get("idRange"):
            self.idc.untake_creation_range(contents["idRange"][0])
        self._notify()

    # ------------------------------------------------------------ checkpoint
    CHUNK_ROOTS = 8  # chunk-domain subtrees per incremental summary chunk

    def _spine(self) -> tuple[list[str], Node]:
        """The incremental chunk DOMAIN: descend from the root field while
        there is exactly one child with exactly one non-empty field — so a
        document shaped as one root array node chunks over its ITEMS, not
        over the single root (the common app shape).  Returns
        (spine field keys, holder): holder.fields[fields[-1]] is the
        chunked children list."""
        holder = self.forest.root
        fields = [ROOT_FIELD]
        while True:
            children = holder.fields.get(fields[-1], [])
            if len(children) != 1:
                return fields, holder
            node = children[0]
            nonempty = [k for k, v in node.fields.items() if v]
            if len(nonempty) != 1:
                return fields, holder
            holder = node
            fields.append(nonempty[0])

    def _mark_chunks_dirty(self, trunk_commit, seq: int) -> None:
        """Chunk-level dirtiness from a sequenced trunk commit, walked down
        the chunk-domain spine: structural marks at a spine level (they can
        reshape the domain) dirty everything; a Modify descends; at the
        final level a Modify dirties its chunk and a structural mark
        dirties its chunk and every one after it (index shifts)."""
        from .changeset import Insert, Modify, Remove, Skip

        fields, holder = self._spine()
        if fields != getattr(self, "_domain_fields", None):
            # Domain reshaped since the last marking: previous chunk
            # indices are meaningless — every chunk re-uploads once.
            self._domain_fields = list(fields)
            self._chunk_seqs = {}
            dirty_all = True  # the loop below marks every current chunk
        else:
            dirty_all = False
        K = self.CHUNK_ROOTS
        n_chunks = max(1, -(-len(holder.fields.get(fields[-1], [])) // K))

        def final_walk(marks) -> tuple[list[int], int | None]:
            pos, points, floor = 0, [], None
            for mk in marks:
                if isinstance(mk, Skip):
                    pos += mk.count
                elif isinstance(mk, Modify):
                    points.append(pos)
                    pos += 1
                elif isinstance(mk, Insert):
                    floor = pos if floor is None else min(floor, pos)
                elif isinstance(mk, Remove):
                    floor = pos if floor is None else min(floor, pos)
                    pos += mk.count
                else:  # MoveOut/MoveIn and anything irregular
                    floor = 0
            return points, floor

        changes = list(trunk_commit)
        for level, fkey in enumerate(fields):
            last = level == len(fields) - 1
            next_changes = []
            for change in changes:
                for key, marks in change.fields.items():
                    if not isinstance(marks, list):
                        # Non-sequence field kinds (optional/value sets)
                        # reshape conservatively: re-upload every chunk.
                        from .field_kinds import kind_of

                        if not kind_of(marks).is_empty(marks):
                            dirty_all = True
                        continue
                    if key != fkey:
                        if marks:
                            dirty_all = True  # off-spine edit reshapes domain
                        continue
                    if last:
                        points, floor = final_walk(marks)
                        for p in points:
                            self._chunk_seqs[p // K] = seq
                        if floor is not None:
                            for k in range(floor // K, n_chunks):
                                self._chunk_seqs[k] = seq
                    else:
                        for mk in marks:
                            if isinstance(mk, Modify):
                                next_changes.append(mk.change)
                            elif not isinstance(mk, Skip):
                                dirty_all = True  # spine structure changed
            if last or dirty_all:
                break
            changes = next_changes
        if dirty_all:
            for k in range(n_chunks):
                self._chunk_seqs[k] = seq

    def _meta_summary(self) -> dict[str, Any]:
        """Everything but the forest — shared by the flat and incremental
        summary paths so the two can never skew."""
        return {
            "editManager": self.em.summarize(),
            "schema": self.schema.to_json(),
            "idCompressor": self.idc.serialize(with_session=False),
        }

    def summarize(self) -> dict[str, Any]:
        if self._local_pending:
            raise RuntimeError("summarize with pending tree edits")
        return {
            "forest": encode_field_chunked(self.forest.root_field),
            **self._meta_summary(),
        }

    def summary_tree(self, covered_seq: int | None, path: str) -> dict[str, Any]:
        """Incremental channel summary (ref incrementalSummarizationUtils):
        the forest splits into root-subtree chunks; chunks unchanged since
        the covered summary emit HANDLES into the previous snapshot instead
        of content.  Safe because any structural root change dirties every
        chunk at/after it, so a clean chunk held identical content at the
        same chunk index in the covered summary."""
        from ...protocol.snapshot_formats import blob, current_format, handle, tree

        if self._local_pending:
            raise RuntimeError("summarize with pending tree edits")
        K = self.CHUNK_ROOTS
        fields, holder = self._spine()
        domain = holder.fields.get(fields[-1], [])
        n_chunks = max(1, -(-len(domain) // K))
        # The OUTER forest: the root field with the chunk-domain children
        # removed (spliced back on load) — tiny, rides in the meta blob.
        holder.fields[fields[-1]] = []
        try:
            outer = encode_field_chunked(self.forest.root_field)
        finally:
            holder.fields[fields[-1]] = domain
        if fields != getattr(self, "_domain_fields", None):
            # The domain differs from the one _chunk_seqs was tracked
            # against (e.g. first summary after load): no handle is safe.
            covered_seq = None
        meta = {
            "type": self.channel_type,
            "fmt": current_format(self.channel_type),
            "summary": {
                **self._meta_summary(),
                "spine": fields,
                "outer": outer,
            },
        }
        chunks: dict[str, Any] = {}
        for k in range(n_chunks):
            chunk_path = f"{path}/forest/{k}"
            if (
                covered_seq is not None
                and self._chunk_seqs.get(k, 0) <= covered_seq
            ):
                chunks[str(k)] = handle(chunk_path)
            else:
                chunks[str(k)] = blob(
                    encode_field_chunked(domain[k * K : (k + 1) * K])
                )
        return tree({"meta": blob(meta), "forest": tree(chunks)})

    def load(self, summary: dict[str, Any]) -> None:
        self.forest.root = Node(type="__root__")
        self.forest.root.fields[ROOT_FIELD] = decode_field_chunked(summary["forest"])
        self._trunk_forest.load_json(self.forest.to_json())
        if "idCompressor" in summary:
            self.idc = IdCompressor.deserialize(summary["idCompressor"])
        self.em = EditManager(
            encode_rev=self._rev_to_stable, decode_rev=self._rev_from_stable
        )
        self.em.load(summary["editManager"])
        self.schema = SchemaRegistry.from_json(summary["schema"])
        self._notify()


def assemble_incremental_summary(
    meta_summary: dict[str, Any], chunk_lists: list[list], fmt: int = 1
) -> dict[str, Any]:
    """Reassemble a flat channel summary from a MATERIALIZED incremental
    tree: splice the concatenated chunk-domain children back into the
    outer forest at the spine's end (inverse of summary_tree's split).

    ``fmt`` is the snapshot format the summary was WRITTEN at; assembly is
    format-aware (it runs before the generic upgrade step, which only sees
    flat summaries) and must return the flat summary at that same format.
    Every shipped format so far shares this layout."""
    if fmt > 1:
        raise ValueError(
            f"unknown incremental sharedTree summary format {fmt}"
        )
    from .forest import decode_field_chunked, encode_field_chunked

    out = dict(meta_summary)
    spine = out.pop("spine")
    outer = out.pop("outer")
    pieces = [piece for chunk in chunk_lists for piece in chunk]
    if len(spine) == 1:
        out["forest"] = pieces  # the domain IS the root field
        return out
    outer_nodes = decode_field_chunked(outer)
    holder = outer_nodes[0]
    for f in spine[1:-1]:
        holder = holder.fields[f][0]
    holder.fields[spine[-1]] = decode_field_chunked(pieces)
    out["forest"] = encode_field_chunked(outer_nodes)
    return out


class _Factory:
    channel_type = SharedTreeChannel.channel_type
    # Registry hook: reassembles a materialized incremental summary into
    # the flat form (datastore dispatches by type, never by shape-sniff).
    assemble_incremental = staticmethod(assemble_incremental_summary)

    def create(self, channel_id: str) -> SharedTreeChannel:
        return SharedTreeChannel(channel_id)


SharedTreeFactory = _Factory()
