"""SharedTree over the channel boundary.

Reference parity: SharedTreeKernel (tree/src/shared-tree/sharedTree.ts:176)
+ SharedTreeCore (shared-tree-core/sharedTreeCore.ts:92): sequenced edits
flow into the EditManager, the forest tracks trunk-tip state overlaid with
the local branch, resubmit rebases pending edits onto the current trunk
(defaultResubmitMachine.ts), and summaries carry forest + EditManager state
(editManagerSummarizer.ts, forest-summary).

Wire op formats:
  {"type": "edit", "rev": str, "change": <changeset json>}
  {"type": "schema", "schema": <schema json>}   (LWW by sequence order)
"""

from __future__ import annotations

from typing import Any, Callable

from ...runtime.channel import Channel, MessageCollection
from .changeset import (
    NodeChange,
    apply_node_change,
    change_from_json,
    change_to_json,
    clone_change,
    invert_node_change,
)
from .editmanager import EditManager, bridge
from .forest import Forest, Node, decode_field_chunked, encode_field_chunked, ROOT_FIELD
from .schema import SchemaRegistry, TreeView


class SharedTreeChannel(Channel):
    """One replica of a SharedTree document."""

    channel_type = "sharedTree"

    def __init__(self, channel_id: str) -> None:
        super().__init__(channel_id)
        self.forest = Forest()  # trunk-tip state + local pending overlay
        self.em = EditManager()
        self.schema = SchemaRegistry()
        # Local branch: pending edits in trunk-tip coordinates, continuously
        # rebased as remote commits land (the sandwich).
        self._local_pending: list[tuple[str, NodeChange]] = []
        self._rev_counter = 0
        self.on_change: Callable[[], None] | None = None  # view invalidation
        # Every change applied to the forest, in application order (local
        # edits and bridged remote commits alike) — the coordinate trail
        # undo-redo revertibles rebase their inverses over.
        self.applied_log: list[NodeChange] = []

    # ------------------------------------------------------------ local edits
    def _mint_revision(self) -> str:
        self._rev_counter += 1
        owner = self._connection.client_id() if self._connection else "detached"
        return f"{owner}:{self._rev_counter}"

    def submit_change(self, change: NodeChange) -> None:
        """Apply a local edit optimistically and stage it for sequencing.
        The forest apply enriches the change (repair data), and the enriched
        form is what goes on the wire so every replica integrates the exact
        same changeset object."""
        rev = self._mint_revision()
        apply_node_change(self.forest.root, change)
        self.applied_log.append(change)
        self._local_pending.append((rev, change))
        self.submit_local_message(
            {"type": "edit", "rev": rev, "change": change_to_json(change)},
            {"rev": rev},
        )
        self._notify()

    def set_schema(self, registry: SchemaRegistry) -> None:
        self.schema = registry
        self.submit_local_message(
            {"type": "schema", "schema": registry.to_json()}, {"rev": None}
        )

    @property
    def view(self) -> TreeView:
        return TreeView(self.forest, self.submit_change, self.schema)

    def _notify(self) -> None:
        if self.on_change is not None:
            self.on_change()

    # ---------------------------------------------------------------- inbound
    def process_messages(self, collection: MessageCollection) -> None:
        env = collection.envelope
        for m in collection.messages:
            c = m.contents
            if c["type"] == "schema":
                self.schema = SchemaRegistry.from_json(c["schema"])
                continue
            change = change_from_json(c["change"])
            trunk_change = self.em.add_sequenced(
                client_id=env.client_id,
                revision=c["rev"],
                change=change,
                ref_seq=env.ref_seq,
                seq=env.seq,
            )
            if m.local:
                # Our own edit reached the trunk: the forest already shows it.
                assert self._local_pending and self._local_pending[0][0] == c["rev"], (
                    "local branch FIFO skew"
                )
                self._local_pending.pop(0)
            else:
                # Sandwich: rebase the local branch over the new trunk commit
                # and apply its bridged form to the optimistic forest.
                self._local_pending, x = bridge(self._local_pending, clone_change(trunk_change))
                apply_node_change(self.forest.root, x)
                self.applied_log.append(x)
        self.em.advance_min_seq(env.min_seq)
        self._notify()

    def on_min_seq(self, min_seq: int) -> None:
        self.em.advance_min_seq(min_seq)

    def on_client_leave(self, client_id: str, seq: int) -> None:
        self.em.on_client_leave(client_id)

    # ----------------------------------------------------- reconnect / stash
    def resubmit(self, contents: Any, local_metadata: Any, squash: bool = False) -> None:
        """Resubmit the CURRENT (trunk-tip rebased) form of the pending edit
        — merge-tree regeneratePendingOp's analog for tree changesets."""
        if contents["type"] == "schema":
            self.submit_local_message(contents, {"rev": None})
            return
        rev = local_metadata["rev"]
        for r, change in self._local_pending:
            if r == rev:
                self.submit_local_message(
                    {"type": "edit", "rev": rev, "change": change_to_json(change)},
                    {"rev": rev},
                )
                return
        raise AssertionError(f"resubmit of unknown pending edit {rev}")

    def apply_stashed(self, contents: Any) -> Any:
        if contents["type"] == "schema":
            self.schema = SchemaRegistry.from_json(contents["schema"])
            return {"rev": None}
        change = change_from_json(contents["change"])
        rev = contents["rev"]
        apply_node_change(self.forest.root, change)
        self.applied_log.append(change)
        self._local_pending.append((rev, change))
        self._notify()
        return {"rev": rev}

    def rollback(self, contents: Any, local_metadata: Any) -> None:
        rev = local_metadata["rev"]
        assert self._local_pending and self._local_pending[-1][0] == rev, (
            "rollback must undo the latest local edit first"
        )
        _, change = self._local_pending.pop()
        inverse = invert_node_change(change)
        apply_node_change(self.forest.root, inverse)
        self.applied_log.append(inverse)
        self._notify()

    # ------------------------------------------------------------ checkpoint
    def summarize(self) -> dict[str, Any]:
        if self._local_pending:
            raise RuntimeError("summarize with pending tree edits")
        return {
            "forest": encode_field_chunked(self.forest.root_field),
            "editManager": self.em.summarize(),
            "schema": self.schema.to_json(),
        }

    def load(self, summary: dict[str, Any]) -> None:
        self.forest.root = Node(type="__root__")
        self.forest.root.fields[ROOT_FIELD] = decode_field_chunked(summary["forest"])
        self.em.load(summary["editManager"])
        self.schema = SchemaRegistry.from_json(summary["schema"])
        self._notify()


class _Factory:
    channel_type = SharedTreeChannel.channel_type

    def create(self, channel_id: str) -> SharedTreeChannel:
        return SharedTreeChannel(channel_id)


SharedTreeFactory = _Factory()
