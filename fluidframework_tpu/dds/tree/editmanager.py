"""EditManager: deterministic trunk construction from sequenced commits.

Reference parity: tree/src/shared-tree-core/editManager.ts:73 — a trunk of
sequenced commits plus per-peer branches that cache each peer's in-flight
context, with MSN-driven trunk eviction (trimHistory :847,
advanceMinimumSequenceNumber :247).

Design (derived, not ported): for every peer P we simulate P's local branch
— ``base`` is the highest trunk sequence number P has integrated (its last
refSeq) and ``inflight`` holds P's submitted-but-not-yet-base-advanced
commits in P-local coordinates. Because every replica runs this exact
deterministic procedure over the same sequenced stream, every replica
computes the identical trunk version of every commit — convergence by
construction, independent of OT transform properties.

A commit is a LIST of changesets applied atomically (a single edit is a
1-element commit; a transaction is longer — changeset.Commit), so the whole
rebase machinery folds over commit elements.

Integration of a commit c from P (refSeq r, seq s):
1. advance P's branch base to r: walk trunk commits in (base, r]; P's own
   commits must head ``inflight`` (FIFO) and pop; others bridge-transform
   the inflight list (the same sandwich rebase P performed locally).
2. translate c to trunk coordinates: walk trunk commits in (r, s) on a COPY
   of the inflight list (P hasn't seen them): own commits pop from the copy,
   others rebase both the copy and c. FIFO ordering guarantees the copy
   drains exactly when c's turn comes.
3. append the original-coordinates c to P's inflight and the trunk-coords
   version to the trunk.

Revisions are opaque, replica-local hashable tags (the channel layer mints
them through the id-compressor); summaries serialize them through the
``encode_rev``/``decode_rev`` codec so the summary is replica-independent.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable

from .changeset import (
    Commit,
    clone_commit,
    commit_from_json,
    commit_to_json,
    rebase_commit,
)


@dataclass
class TrunkCommit:
    seq: int
    client_id: str
    revision: Any
    change: Commit  # trunk coordinates (context = previous trunk commit)
    # Pooled-mode cache: the same trunk commit extends EVERY peer's
    # translation stream; pooling it once (at integration, when the fold
    # already holds the pooled form) instead of per-peer is sound because
    # rebase outputs depend only on the b-side's STRUCTURE (mark kinds /
    # counts / positions), never on later apply-enrichment of the object
    # form (value-tuple arity, Remove.detached payloads).
    pooled: Any = None


@dataclass
class PeerBranch:
    base: int  # trunk seq this peer has integrated (its max refSeq seen)
    inflight: list[tuple[Any, Commit]] = field(default_factory=list)
    # ---- incremental translation stream (see add_sequenced) ----
    # Trunk seq the stream is current to (>= base; never rewinds).
    pos: int = 0
    # [(trunk_seq, x)]: other peers' trunk commits in (base-ish, pos],
    # each rebased through every one of THIS peer's in-flight commits that
    # was submitted before the trunk commit was integrated (maintained by
    # the fold write-back in add_sequenced).  An incoming commit from this
    # peer translates to trunk coordinates by folding over the (ref, seq]
    # slice of this list — O(window) rebases instead of re-walking the
    # trunk with a cloned in-flight scratch per commit (O(window x
    # inflight) and a full clone, the measured host-translation hotspot).
    xs: list[tuple[int, Commit]] = field(default_factory=list)
    # Post-load residue: in-flight commits integrated by a PREVIOUS
    # incarnation (their write-back state is lost), kept as local-coords
    # clones that stream extension bridges through until their trunk
    # entries are crossed.  Empty in steady state.
    scratch: list[Commit] = field(default_factory=list)
    # Parallel to ``inflight``: each element's fold intermediates
    # [(trunk_seq, commit-at-that-base)] recorded during its integration —
    # the exact values the old per-advance bridge walk recomputed, so
    # ``_advance`` materializes base moves by lookup instead of O(window x
    # inflight) rebases.  ``None`` marks a post-load element (stages lost
    # with the previous incarnation) which forces the legacy bridge walk
    # until it pops.
    stages: list = field(default_factory=list)


def bridge(inflight: list[tuple[Any, Commit]], incoming: Commit) -> tuple[
    list[tuple[Any, Commit]], Commit
]:
    """Transform an incoming commit through a branch's in-flight list: returns
    (inflight rebased over incoming, incoming rebased past the inflight) —
    the standard OT bridge both the EditManager and the local branch use.

    Sides: ``incoming`` is sequenced (earlier) and the in-flight commits are
    not (later), so the in-flight rebases with a_after=True and the incoming
    carries over them with a_after=False — the mirrored pair that makes both
    orders of application converge."""
    x = incoming
    out = []
    for rev, f in inflight:
        out.append((rev, rebase_commit(f, x, a_after=True)))
        x = rebase_commit(x, f, a_after=False)
    return out, x


def bridge_bare(commits: list[Commit], incoming: Commit) -> tuple[
    list[Commit], Commit
]:
    """``bridge`` over a bare Commit list (no revision tags) — the
    post-load scratch residue's fold.  One definition of the mirrored
    rebase pair, shared by stream extension and the compaction-floor
    advance."""
    x = incoming
    out = []
    for f in commits:
        out.append(rebase_commit(f, x, a_after=True))
        x = rebase_commit(x, f, a_after=False)
    return out, x


class EditManager:
    """Trunk + peer branches for one SharedTree instance.

    ``mark_pool`` switches the WHOLE peer-stream state (xs / stages /
    inflight / scratch) to the pooled columnar mark store
    (dds/tree/mark_pool.py): incoming commits pool once at integration,
    the window fold runs as column passes with span reuse for disjoint
    commits, and only the returned trunk commit materializes object marks
    (the caller apply-enriches that clone; pooled spans stay immutable).
    ``None``/falsy keeps the object fold — the byte-identity fuzz oracle.
    Pass a shared ``MarkPool`` so a fleet's gauges aggregate, or ``True``
    for a private pool.

    ``device_rebase`` (requires ``mark_pool``) dispatches each window
    fold's eligible prefix through the batched device kernel
    (dds/tree/device_rebase.py); ineligible or invalidated steps finish
    on the pooled fold, counted in the rebaser's fallback gauges.  Pass
    a shared ``DeviceRebaser`` so a fleet shares one interning table and
    one set of counters, or ``True`` for a private one."""

    def __init__(
        self,
        encode_rev: Callable[[Any], Any] | None = None,
        decode_rev: Callable[[Any], Any] | None = None,
        mark_pool=None,
        device_rebase=None,
    ) -> None:
        self.trunk: list[TrunkCommit] = []
        self.trunk_base = 0  # all commits with seq <= trunk_base are evicted
        self.peers: dict[str, PeerBranch] = {}
        self._encode_rev = encode_rev or (lambda r: r)
        self._decode_rev = decode_rev or (lambda r: r)
        self.pool = None
        if mark_pool:
            # One import at construction (module handle cached on the
            # instance): the fold calls these per commit per window entry,
            # and a function-local import there pays importlib machinery
            # on the hot path.
            from . import mark_pool as mp

            self._mp = mp
            self.pool = mark_pool if isinstance(mark_pool, mp.MarkPool) \
                else mp.MarkPool()
        self.rebaser = None
        if device_rebase and self.pool is not None:
            from .device_rebase import DeviceRebaser

            self.rebaser = (
                device_rebase if isinstance(device_rebase, DeviceRebaser)
                else DeviceRebaser(self.pool)
            )

    def _pool_commit(self, commit: Commit) -> Commit:
        """Pooled-mode conversion (idempotent); object mode passes through."""
        if self.pool is None:
            return commit
        return self._mp.pool_commit(self.pool, commit)

    def _pooled_trunk(self, t: TrunkCommit) -> Commit:
        """Pooled view of a trunk commit, cached on the commit (one
        conversion shared by every peer stream); object mode passes the
        change through untouched."""
        if self.pool is None:
            return t.change
        if t.pooled is None:
            t.pooled = self._mp.pool_commit(self.pool, t.change)
        return t.pooled

    # ------------------------------------------------------------------ query
    def _trunk_range(self, lo: int, hi: int) -> list[TrunkCommit]:
        """Trunk commits with lo < seq <= hi (retained window only)."""
        assert lo >= self.trunk_base, (
            f"trunk history below {self.trunk_base} was evicted (asked for {lo})"
        )
        return [t for t in self.trunk if lo < t.seq <= hi]

    # -------------------------------------------------------------- integrate
    def add_sequenced(
        self,
        client_id: str,
        revision: Any,
        change: Commit,
        ref_seq: int,
        seq: int,
    ) -> Commit:
        """Integrate one sequenced commit; returns its trunk-coordinates
        version (what a caller applies to trunk-tip state).

        Translation is INCREMENTAL: instead of re-walking the trunk range
        (ref_seq, seq] with a cloned copy of the peer's in-flight list per
        commit (the original O(window x inflight) bridge walk), each peer
        carries a cached translation stream ``xs`` of other peers' trunk
        commits already rebased through this peer's in-flight context.
        The incoming commit folds over the stream's (ref_seq, seq] slice,
        and the fold WRITES BACK the mirrored rebase (the bridge pair) so
        later commits from this peer see its effect — sound because a
        bridge transforms each list prefix independently of its suffix,
        so the cached prefix evolution is exactly what a fresh walk would
        recompute.  Entries at or below the peer's refSeq are dead (per-
        client refSeqs are monotone) and are dropped as the ref advances."""
        br = self.peers.get(client_id)
        if br is None:
            base = max(ref_seq, self.trunk_base)
            br = self.peers[client_id] = PeerBranch(base=base, pos=base)
        # 1. advance the peer's base to its refSeq (in-flight maintenance
        # for summaries and FIFO accounting; unchanged semantics).
        self._advance(client_id, br, ref_seq)
        # 2. extend the translation stream over trunk commits the stream
        # has not consumed.  Grouped batches give several commits one
        # sequence number; earlier same-seq commits from this client were
        # folded into the stream by their own write-back.
        for t in self._trunk_range(br.pos, seq):
            if t.client_id == client_id:
                # Own commit integrated by a previous incarnation (post-
                # load): its local-coords clone leaves the scratch residue
                # exactly when the walk crosses its trunk entry.
                if br.scratch:
                    br.scratch.pop(0)
                continue
            x = self._pooled_trunk(t)
            if br.scratch:
                br.scratch, x = bridge_bare(br.scratch, x)
            br.xs.append((t.seq, x))
        br.pos = max(br.pos, seq)
        assert not br.scratch, "peer had unsequenced ops ahead of this commit"
        # 3. drop stream entries the peer has integrated (ref monotone),
        # then fold the commit over the live slice with bridge write-back.
        xs = br.xs
        drop = 0
        while drop < len(xs) and xs[drop][0] <= ref_seq:
            drop += 1
        if drop:
            del xs[:drop]
        stage_list: list[tuple[int, Commit]] = []
        if self.pool is not None:
            # Pooled fold: both bridge legs come out of mark_pool's fused
            # pair (columnar rebase + identity span reuse for disjoint
            # commits); the peer stream keeps sharing unchanged spans
            # instead of re-materializing every mark per window entry.
            c = self._pool_commit(change)
            if self.rebaser is not None and xs:
                # Device window: eligible prefix in one jitted scan,
                # pooled-fold suffix (byte-identical either way; every
                # host-finished step counted in the rebaser's gauges).
                c, new_xs, stage_vals = self.rebaser.fold(
                    c, [x for _t, x in xs])
                for i in range(len(xs)):
                    xs[i] = (xs[i][0], new_xs[i])
                    stage_list.append((xs[i][0], stage_vals[i]))
            else:
                rebase_pair = self._mp.rebase_pair
                for i in range(len(xs)):
                    tseq, x = xs[i]
                    nxt, xw = rebase_pair(c, x)
                    xs[i] = (tseq, xw)
                    c = nxt
                    stage_list.append((tseq, c))
            ret = self._mp.unpool_commit(c)
            pooled_ret = c
            br.inflight.append((revision, self._pool_commit(change)))
        else:
            c = clone_commit(change)
            for i in range(len(xs)):
                tseq, x = xs[i]
                nxt = rebase_commit(c, x, a_after=True)
                xs[i] = (tseq, rebase_commit(x, c, a_after=False))
                c = nxt
                stage_list.append((tseq, c))
            # The recorded stages share Mark objects with each other AND
            # with the final fold value (rebase's per-field clones are
            # shallow), and the caller apply-ENRICHES the returned trunk
            # commit in place — so the trunk log and caller get a private
            # deep clone, keeping every recorded stage at its unapplied
            # form (what _advance materializes and summarize serializes,
            # exactly as the legacy bridge walk produced).  One clone per
            # commit, not per stage.
            pooled_ret = None
            ret = clone_commit(c) if stage_list else c
            br.inflight.append((revision, clone_commit(change)))
        br.stages.append(stage_list)
        self.trunk.append(TrunkCommit(
            seq=seq, client_id=client_id, revision=revision, change=ret,
            pooled=pooled_ret if self.pool is not None else None,
        ))
        return ret

    def _advance(self, client_id: str, br: PeerBranch, upto: int) -> None:
        """Advance the peer's base: pop own commits the base crosses and
        bring the surviving in-flight values to base coordinates.  Steady
        state materializes each value from its recorded fold stages (the
        bridge walk's exact outputs, captured when they were first
        computed); post-load elements (no stages) force the legacy
        O(window x inflight) bridge walk until they pop."""
        if upto <= br.base:
            return
        rng = self._trunk_range(br.base, upto)
        if any(s is None for s in br.stages):
            for t in rng:
                if t.client_id == client_id:
                    assert br.inflight and br.inflight[0][0] == t.revision, \
                        "peer FIFO skew"
                    br.inflight.pop(0)
                    br.stages.pop(0)
                else:
                    br.inflight, _ = bridge(
                        br.inflight, self._pooled_trunk(t)
                    )
        else:
            moved = False
            for t in rng:
                if t.client_id == client_id:
                    assert br.inflight and br.inflight[0][0] == t.revision, \
                        "peer FIFO skew"
                    br.inflight.pop(0)
                    br.stages.pop(0)
                else:
                    moved = True
            if moved:
                for i, stages in enumerate(br.stages):
                    val = None
                    for tseq, cm in stages:
                        if tseq <= upto:
                            val = cm
                        else:
                            break
                    if val is not None:
                        br.inflight[i] = (br.inflight[i][0], val)
        br.base = max(br.base, upto)

    # -------------------------------------------------------------- lifecycle
    def on_client_leave(self, client_id: str) -> None:
        self.peers.pop(client_id, None)

    def advance_min_seq(self, min_seq: int) -> None:
        """MSN floor advanced: every future refSeq is >= min_seq, so advance
        all peer branches there and evict the trunk prefix (trimHistory)."""
        if min_seq <= self.trunk_base:
            return
        for client_id, br in self.peers.items():
            if br.base < min_seq:
                self._advance(client_id, br, min_seq)
            # Translation-stream floor: every future refSeq from this peer
            # is >= min_seq, so entries at or below it can never be folded
            # again — and the stream position must stay inside retained
            # trunk history.  Skipped commits in (pos, min_seq] would only
            # have produced entries the ref GC dropped immediately.
            drop = 0
            while drop < len(br.xs) and br.xs[drop][0] <= min_seq:
                drop += 1
            if drop:
                del br.xs[:drop]
            if br.pos < min_seq:
                # Advance the stream position over the about-to-be-evicted
                # range.  The x entries it would have produced are dead
                # (all <= min_seq), but a post-load scratch residue still
                # pops/bridges through the range so its coordinates stay
                # consistent for entries beyond the floor.
                if br.scratch:
                    for t in self._trunk_range(br.pos, min_seq):
                        if not br.scratch:
                            break
                        if t.client_id == client_id:
                            br.scratch.pop(0)
                        else:
                            br.scratch, _ = bridge_bare(
                                br.scratch, self._pooled_trunk(t)
                            )
                br.pos = min_seq
        self.trunk = [t for t in self.trunk if t.seq > min_seq]
        self.trunk_base = min_seq

    # ------------------------------------------------------------ checkpoint
    def summarize(self) -> dict[str, Any]:
        """Trunk tail + peer branches (ref editManagerSummarizer.ts) — both
        are required for a loading client to integrate in-flight remote ops
        whose refSeq predates the snapshot sequence number."""
        return {
            "trunkBase": self.trunk_base,
            "trunk": [
                {
                    "seq": t.seq,
                    "client": t.client_id,
                    "rev": self._encode_rev(t.revision),
                    "change": commit_to_json(t.change),
                }
                for t in self.trunk
            ],
            "peers": {
                cid: {
                    "base": br.base,
                    "inflight": [
                        [self._encode_rev(rev), commit_to_json(ch)]
                        for rev, ch in br.inflight
                    ],
                }
                for cid, br in self.peers.items()
            },
        }

    def load(self, data: dict[str, Any]) -> None:
        self.trunk_base = data["trunkBase"]
        self.trunk = [
            TrunkCommit(
                seq=t["seq"],
                client_id=t["client"],
                revision=self._decode_rev(t["rev"]),
                change=commit_from_json(t["change"]),
            )
            for t in data["trunk"]
        ]
        self.peers = {}
        for cid, p in data["peers"].items():
            inflight = [
                (self._decode_rev(rev), self._pool_commit(
                    commit_from_json(ch)
                ))
                for rev, ch in p["inflight"]
            ]
            # The previous incarnation's fold write-back state is not part
            # of the summary; re-seed the stream from the in-flight clones
            # (extension bridges through them until their trunk entries
            # are crossed — the original walk, applied lazily).  Pooled
            # mode shares the immutable spans instead of cloning.
            self.peers[cid] = PeerBranch(
                base=p["base"],
                inflight=inflight,
                pos=p["base"],
                scratch=(
                    [ch for _rev, ch in inflight] if self.pool is not None
                    else [clone_commit(ch) for _rev, ch in inflight]
                ),
                stages=[None] * len(inflight),
            )
