"""EditManager: deterministic trunk construction from sequenced commits.

Reference parity: tree/src/shared-tree-core/editManager.ts:73 — a trunk of
sequenced commits plus per-peer branches that cache each peer's in-flight
context, with MSN-driven trunk eviction (trimHistory :847,
advanceMinimumSequenceNumber :247).

Design (derived, not ported): for every peer P we simulate P's local branch
— ``base`` is the highest trunk sequence number P has integrated (its last
refSeq) and ``inflight`` holds P's submitted-but-not-yet-base-advanced
commits in P-local coordinates. Because every replica runs this exact
deterministic procedure over the same sequenced stream, every replica
computes the identical trunk version of every commit — convergence by
construction, independent of OT transform properties.

A commit is a LIST of changesets applied atomically (a single edit is a
1-element commit; a transaction is longer — changeset.Commit), so the whole
rebase machinery folds over commit elements.

Integration of a commit c from P (refSeq r, seq s):
1. advance P's branch base to r: walk trunk commits in (base, r]; P's own
   commits must head ``inflight`` (FIFO) and pop; others bridge-transform
   the inflight list (the same sandwich rebase P performed locally).
2. translate c to trunk coordinates: walk trunk commits in (r, s) on a COPY
   of the inflight list (P hasn't seen them): own commits pop from the copy,
   others rebase both the copy and c. FIFO ordering guarantees the copy
   drains exactly when c's turn comes.
3. append the original-coordinates c to P's inflight and the trunk-coords
   version to the trunk.

Revisions are opaque, replica-local hashable tags (the channel layer mints
them through the id-compressor); summaries serialize them through the
``encode_rev``/``decode_rev`` codec so the summary is replica-independent.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable

from .changeset import (
    Commit,
    clone_commit,
    commit_from_json,
    commit_to_json,
    rebase_commit,
)


@dataclass
class TrunkCommit:
    seq: int
    client_id: str
    revision: Any
    change: Commit  # trunk coordinates (context = previous trunk commit)


@dataclass
class PeerBranch:
    base: int  # trunk seq this peer has integrated (its max refSeq seen)
    inflight: list[tuple[Any, Commit]] = field(default_factory=list)


def bridge(inflight: list[tuple[Any, Commit]], incoming: Commit) -> tuple[
    list[tuple[Any, Commit]], Commit
]:
    """Transform an incoming commit through a branch's in-flight list: returns
    (inflight rebased over incoming, incoming rebased past the inflight) —
    the standard OT bridge both the EditManager and the local branch use.

    Sides: ``incoming`` is sequenced (earlier) and the in-flight commits are
    not (later), so the in-flight rebases with a_after=True and the incoming
    carries over them with a_after=False — the mirrored pair that makes both
    orders of application converge."""
    x = incoming
    out = []
    for rev, f in inflight:
        out.append((rev, rebase_commit(f, x, a_after=True)))
        x = rebase_commit(x, f, a_after=False)
    return out, x


class EditManager:
    """Trunk + peer branches for one SharedTree instance."""

    def __init__(
        self,
        encode_rev: Callable[[Any], Any] | None = None,
        decode_rev: Callable[[Any], Any] | None = None,
    ) -> None:
        self.trunk: list[TrunkCommit] = []
        self.trunk_base = 0  # all commits with seq <= trunk_base are evicted
        self.peers: dict[str, PeerBranch] = {}
        self._encode_rev = encode_rev or (lambda r: r)
        self._decode_rev = decode_rev or (lambda r: r)

    # ------------------------------------------------------------------ query
    def _trunk_range(self, lo: int, hi: int) -> list[TrunkCommit]:
        """Trunk commits with lo < seq <= hi (retained window only)."""
        assert lo >= self.trunk_base, (
            f"trunk history below {self.trunk_base} was evicted (asked for {lo})"
        )
        return [t for t in self.trunk if lo < t.seq <= hi]

    # -------------------------------------------------------------- integrate
    def add_sequenced(
        self,
        client_id: str,
        revision: Any,
        change: Commit,
        ref_seq: int,
        seq: int,
    ) -> Commit:
        """Integrate one sequenced commit; returns its trunk-coordinates
        version (what a caller applies to trunk-tip state)."""
        br = self.peers.get(client_id)
        if br is None:
            br = self.peers[client_id] = PeerBranch(base=max(ref_seq, self.trunk_base))
        # 1. advance the peer's base to its refSeq.
        self._advance(client_id, br, ref_seq)
        # 2. translate to trunk coordinates over commits the peer hasn't seen.
        # Range is (ref_seq, seq] over the EXISTING trunk: grouped batches
        # give several commits one sequence number, and earlier same-seq
        # commits from this client are part of this commit's context.
        scratch = [(rev, clone_commit(ch)) for rev, ch in br.inflight]
        c = clone_commit(change)
        for t in self._trunk_range(ref_seq, seq):
            if t.client_id == client_id:
                assert scratch and scratch[0][0] == t.revision, "peer FIFO skew"
                scratch.pop(0)
            else:
                scratch, x = bridge(scratch, t.change)
                c = rebase_commit(c, x)
        assert not scratch, "peer had unsequenced ops ahead of this commit"
        br.inflight.append((revision, clone_commit(change)))
        self.trunk.append(TrunkCommit(seq=seq, client_id=client_id, revision=revision, change=c))
        return c

    def _advance(self, client_id: str, br: PeerBranch, upto: int) -> None:
        for t in self._trunk_range(br.base, upto):
            if t.client_id == client_id:
                assert br.inflight and br.inflight[0][0] == t.revision, "peer FIFO skew"
                br.inflight.pop(0)
            else:
                br.inflight, _ = bridge(br.inflight, t.change)
        br.base = max(br.base, upto)

    # -------------------------------------------------------------- lifecycle
    def on_client_leave(self, client_id: str) -> None:
        self.peers.pop(client_id, None)

    def advance_min_seq(self, min_seq: int) -> None:
        """MSN floor advanced: every future refSeq is >= min_seq, so advance
        all peer branches there and evict the trunk prefix (trimHistory)."""
        if min_seq <= self.trunk_base:
            return
        for client_id, br in self.peers.items():
            if br.base < min_seq:
                self._advance(client_id, br, min_seq)
        self.trunk = [t for t in self.trunk if t.seq > min_seq]
        self.trunk_base = min_seq

    # ------------------------------------------------------------ checkpoint
    def summarize(self) -> dict[str, Any]:
        """Trunk tail + peer branches (ref editManagerSummarizer.ts) — both
        are required for a loading client to integrate in-flight remote ops
        whose refSeq predates the snapshot sequence number."""
        return {
            "trunkBase": self.trunk_base,
            "trunk": [
                {
                    "seq": t.seq,
                    "client": t.client_id,
                    "rev": self._encode_rev(t.revision),
                    "change": commit_to_json(t.change),
                }
                for t in self.trunk
            ],
            "peers": {
                cid: {
                    "base": br.base,
                    "inflight": [
                        [self._encode_rev(rev), commit_to_json(ch)]
                        for rev, ch in br.inflight
                    ],
                }
                for cid, br in self.peers.items()
            },
        }

    def load(self, data: dict[str, Any]) -> None:
        self.trunk_base = data["trunkBase"]
        self.trunk = [
            TrunkCommit(
                seq=t["seq"],
                client_id=t["client"],
                revision=self._decode_rev(t["rev"]),
                change=commit_from_json(t["change"]),
            )
            for t in data["trunk"]
        ]
        self.peers = {
            cid: PeerBranch(
                base=p["base"],
                inflight=[
                    (self._decode_rev(rev), commit_from_json(ch))
                    for rev, ch in p["inflight"]
                ],
            )
            for cid, p in data["peers"].items()
        }
