"""SharedTree: the flagship hierarchical DDS, TPU-native re-design.

Reference parity: packages/dds/tree (SharedTreeKernel sharedTree.ts:176,
SharedTreeCore sharedTreeCore.ts:92, EditManager editManager.ts:73, the
ChangeRebaser contract changeRebaser.ts:41, modular change family under
feature-libraries/, chunked forest uniformChunk.ts:42, simple-tree typed
API).

Architecture here (tpu-first, not a port):
- ``forest``       — object forest (host) + columnar uniform chunks (the
                     device-friendly value representation).
- ``changeset``    — one uniform mark-based changeset algebra (sequence
                     fields subsume value/optional fields); pure functions
                     rebase/invert/apply with enrichment for repair data.
- ``editmanager``  — trunk + simulated per-peer branches; deterministic
                     trunk construction gives convergence by construction.
- ``schema``       — stored schema + typed simple-tree view layer.
- ``shared_tree``  — the channel-boundary DDS wiring it all together.

The batched/TPU form of the hot rebase arithmetic lives in
``fluidframework_tpu.ops.tree_kernel``.
"""

from .changeset import (
    Insert,
    Mark,
    Modify,
    NodeChange,
    Remove,
    Skip,
    apply_node_change,
    change_from_json,
    change_to_json,
    invert_node_change,
    rebase_node_change,
)
from .branch import TreeBranch
from .editmanager import EditManager, TrunkCommit
from .forest import Forest, Node, UniformChunk
from .schema import (
    FieldKind,
    FieldSchema,
    LeafKind,
    NodeSchema,
    SchemaCompatibility,
    SchemaRegistry,
    SchemaView,
    TreeView,
    schema_compat,
)
from .shared_tree import SharedTreeChannel, SharedTreeFactory

from .simple_tree import (
    SchemaFactory,
    SimpleTreeView,
    Tree,
    TreeArrayNode,
    TreeNodeSchema,
    TreeObjectNode,
    TreeViewConfiguration,
    optional,
    required,
)

__all__ = [
    "EditManager",
    "SchemaFactory",
    "SimpleTreeView",
    "Tree",
    "TreeArrayNode",
    "TreeNodeSchema",
    "TreeObjectNode",
    "TreeViewConfiguration",
    "optional",
    "required",
    "SchemaCompatibility",
    "SchemaView",
    "TreeBranch",
    "schema_compat",
    "FieldKind",
    "FieldSchema",
    "Forest",
    "Insert",
    "LeafKind",
    "Mark",
    "Modify",
    "Node",
    "NodeChange",
    "NodeSchema",
    "Remove",
    "SchemaRegistry",
    "SharedTreeChannel",
    "SharedTreeFactory",
    "Skip",
    "TreeView",
    "TrunkCommit",
    "UniformChunk",
    "apply_node_change",
    "change_from_json",
    "change_to_json",
    "invert_node_change",
    "rebase_node_change",
]
