"""Modular field kinds: pluggable per-field-kind change algebras.

Reference parity: the modular-schema FieldKind registry
(tree/src/feature-libraries/modular-schema/fieldKind.ts,
fieldChangeHandler.ts) — each field kind owns its change representation and
its rebaser (rebase/invert/compose, core/rebase/changeRebaser.ts:41), and
the node-level changeset dispatches per field through the registry.

Three built-in kinds (the reference's default-field-kinds):

- ``sequence``: the mark-list algebra of changeset.py (0..N nodes).  Its
  change TYPE stays the bare ``list[Mark]`` — wire format and device path
  are untouched.
- ``optional``: 0..1 nodes; a change either REPLACES the whole field
  content (``set``, later-sequenced-wins) or edits the resident node
  (``nested``).  Ref feature-libraries/optional-field/.
- ``value``: exactly-1 node; ``optional`` restricted to non-empty sets.

The registry is open (``register_field_kind``) — a schema extension can
ship its own kind with its own rebaser, the reference's extensibility
contract.

Compose: each kind also implements ``compose(a, b)`` (b reads a's output
context; result reads a's input context), giving the full ChangeRebaser
triple.  Sequence compose covers Skip/Insert/Remove/Modify; composing
across moves raises (the trunk pipeline never composes — commits stay
element lists — so compose is the offline squash/undo tool).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any

from .forest import Node

# Module-level import is safe (changeset never imports field_kinds at module
# scope); the previous per-call lazy imports in the rebase/compose hot path
# paid importlib machinery on every field dispatch.
from .changeset import (
    NodeChange,
    apply_marks,
    apply_node_change,
    change_from_json,
    change_to_json,
    compose_node_change,
    invert_marks,
    invert_node_change,
    marks_from_json,
    marks_to_json,
    rebase_marks,
    rebase_node_change,
)

# ---------------------------------------------------------------------------
# Optional / value field changes
# ---------------------------------------------------------------------------


@dataclass
class OptionalChange:
    """Change to a 0..1 field.  Exactly one of:

    - ``set``: ``(new,)`` before apply, ``(new, prior)`` after (enriched
      for invert) — new/prior are Node or None (None = empty field);
    - ``nested``: a NodeChange editing the resident node.
    """

    kind: str = "optional"
    set: tuple | None = None
    nested: Any | None = None  # NodeChange

    def is_empty(self) -> bool:
        return self.set is None and (self.nested is None or self.nested.is_empty())


class FieldKind:
    """One field kind's change algebra (ref fieldChangeHandler.ts)."""

    name: str

    def rebase(self, a, b, a_after: bool):
        raise NotImplementedError

    def invert(self, change):
        raise NotImplementedError

    def compose(self, a, b):
        raise NotImplementedError

    def apply(self, nodes: list[Node], change) -> None:
        raise NotImplementedError

    def to_json(self, change):
        raise NotImplementedError

    def from_json(self, data):
        raise NotImplementedError

    def is_empty(self, change) -> bool:
        raise NotImplementedError

    def clone(self, change):
        return self.from_json(self.to_json(change))


class SequenceFieldKind(FieldKind):
    """The mark-list algebra (changeset.py) behind the registry facade."""

    name = "sequence"
    # Sequence-FAMILY marker: this kind (and the pooled columnar kind in
    # mark_pool.py) can expose a bare mark-list view for the fate-map
    # consumers (constraint paths, mixed-kind compose).
    is_sequence = True

    def as_mark_list(self, change):
        return change

    def clone(self, change):
        return list(change)  # shallow, matching the historical copy

    def rebase(self, a, b, a_after: bool):
        return rebase_marks(a, b, a_after)

    def invert(self, change):
        return invert_marks(change)

    def compose(self, a, b):
        return compose_marks(a, b)

    def apply(self, nodes: list[Node], change) -> None:
        apply_marks(nodes, change)

    def to_json(self, change):
        return marks_to_json(change)  # bare list: wire-compatible

    def from_json(self, data):
        return marks_from_json(data)

    def is_empty(self, change) -> bool:
        return not change


class OptionalFieldKind(FieldKind):
    """0..1 field: whole-content replace with later-wins conflict rule
    (ref feature-libraries/optional-field/optionalField.ts)."""

    name = "optional"

    def _mk(self, **kw) -> OptionalChange:
        return OptionalChange(kind=self.name, **kw)

    def clone(self, change: OptionalChange) -> OptionalChange:
        return self.from_json(self.to_json(change))

    def rebase(self, a: OptionalChange, b: OptionalChange, a_after: bool):
        """Always returns a FRESH change object — a rebased pending form is
        later apply-enriched in place, and sharing structure with the
        original shipped commit would rewrite its repair data."""
        if b.set is not None:
            # b replaced the field content.
            if a.set is not None:
                # Concurrent sets: the later-sequenced one wins.
                return self.clone(a) if a_after else self._mk()
            # a edited a node b replaced: target gone.
            return self._mk()
        if b.nested is not None and a.nested is not None:
            return self._mk(
                nested=rebase_node_change(a.nested, b.nested, a_after)
            )
        return self.clone(a)

    def invert(self, change: OptionalChange):
        if change.is_empty():  # rebase can void a change (conflict loser)
            return self._mk()
        if change.set is not None:
            assert len(change.set) == 2, "invert of unapplied optional set"
            new, prior = change.set
            return self._mk(set=(
                prior.clone() if prior is not None else None,
                new.clone() if new is not None else None,
            ))
        return self._mk(nested=invert_node_change(change.nested))

    def compose(self, a: OptionalChange, b: OptionalChange):
        if b.set is not None:
            new = b.set[0]
            if a.set is not None and len(a.set) == 2:
                prior = a.set[1]
            elif len(b.set) == 2:
                # b's recorded prior lives in a's OUTPUT context; repair
                # data of the composed change must be in a's INPUT context,
                # so unwind a's nested edit from it (possible exactly when
                # a was applied/enriched — the squash-of-applied case).
                prior = b.set[1]
                if prior is not None and a.nested is not None:
                    prior = prior.clone()
                    apply_node_change(prior, _safe_invert(a.nested))
            else:
                prior = None
            out = (new, prior) if (
                len(b.set) == 2 or (a.set is not None and len(a.set) == 2)
            ) else (new,)
            return self._mk(set=tuple(
                n.clone() if isinstance(n, Node) else n for n in out
            ))
        if a.set is not None:
            # set then edit-the-new-content: fold the edit into the content.
            new = a.set[0].clone() if a.set[0] is not None else None
            if b.nested is not None:
                assert new is not None, "nested edit composed onto a clear"
                apply_node_change(new, b.nested)
            return self._mk(set=(new,) + tuple(a.set[1:]))
        if a.nested is not None and b.nested is not None:
            return self._mk(nested=compose_node_change(a.nested, b.nested))
        return a if b.is_empty() else b

    def apply(self, nodes: list[Node], change: OptionalChange) -> None:
        if change.is_empty():  # rebase can void a change (conflict loser)
            return
        if change.set is not None:
            # A schema-violating writer (raw sequence ops) can leave >1
            # node in a 0..1 field; a set COLLAPSES the field to its
            # content (prior records the first resident — the schema-legal
            # one — for invert).
            prior = nodes[0] if nodes else None
            new = change.set[0]
            change.set = (new, prior)  # enrich in place (invertibility)
            nodes[:] = [new.clone()] if new is not None else []
            return
        assert nodes, "nested change on an empty optional field"
        apply_node_change(nodes[0], change.nested)

    def to_json(self, change: OptionalChange):
        out: dict[str, Any] = {"k": self.name}
        if change.set is not None:
            out["set"] = [
                n.to_json() if n is not None else None for n in change.set
            ]
        if change.nested is not None:
            out["nested"] = change_to_json(change.nested)
        return out

    def from_json(self, data):
        return self._mk(
            set=tuple(
                Node.from_json(n) if n is not None else None
                for n in data["set"]
            )
            if "set" in data
            else None,
            nested=change_from_json(data["nested"]) if "nested" in data else None,
        )

    def is_empty(self, change: OptionalChange) -> bool:
        return change.is_empty()


class ValueFieldKind(OptionalFieldKind):
    """Exactly-1 field: optional restricted to non-empty content
    (ref default-field-kinds required field)."""

    name = "value"

    def apply(self, nodes: list[Node], change: OptionalChange) -> None:
        if change.set is not None:
            assert change.set[0] is not None, "value field cannot be cleared"
        super().apply(nodes, change)


# ---------------------------------------------------------------------------
# Registry
# ---------------------------------------------------------------------------

FIELD_KINDS: dict[str, FieldKind] = {}


def register_field_kind(kind: FieldKind) -> FieldKind:
    """Install a field kind (open registry — ref FieldKindRegistry)."""
    FIELD_KINDS[kind.name] = kind
    return kind


SEQUENCE = register_field_kind(SequenceFieldKind())
OPTIONAL = register_field_kind(OptionalFieldKind())
VALUE = register_field_kind(ValueFieldKind())


def kind_of(field_change) -> FieldKind:
    """Resolve a field change object to its kind: a bare list is the
    sequence kind (wire/back compat); tagged changes carry their kind."""
    if isinstance(field_change, list):
        return SEQUENCE
    return FIELD_KINDS[field_change.kind]


def field_change_to_json(fc):
    return kind_of(fc).to_json(fc)


def field_change_from_json(data):
    if isinstance(data, list):
        return SEQUENCE.from_json(data)
    return FIELD_KINDS[data["k"]].from_json(data)


def _safe_invert(nested):
    """Invert a nested NodeChange for repair-data context transport; an
    unenriched change (compose of never-applied changes, which carries no
    repair data to protect) inverts to the identity instead of asserting."""
    try:
        return invert_node_change(nested)
    except AssertionError:
        return NodeChange()


# ---------------------------------------------------------------------------
# Sequence compose (Skip/Insert/Remove/Modify; moves unsupported)
# ---------------------------------------------------------------------------


def compose_marks(a: list, b: list) -> list:
    """Compose mark lists: b reads a's OUTPUT context; the result reads a's
    INPUT context and is equivalent to applying a then b.

    Covers Skip/Insert/Remove/Modify (composing across moves raises —
    the trunk pipeline never composes, see module docstring).
    """
    from .changeset import (
        Insert,
        Modify,
        MoveIn,
        MoveOut,
        Remove,
        Skip,
        _emit,
        apply_node_change,
        clone_change,
        compose_node_change,
    )

    if any(isinstance(m, (MoveIn, MoveOut)) for m in a + b):
        raise NotImplementedError("compose across moves")

    # a's output as anchored items: ("in", in_pos, nested) kept inputs,
    # ("new", boundary_in_pos, node) inserted content.  a's removes anchor
    # at their input position.
    items: list[tuple] = []
    removed: list[tuple[int, Remove]] = []  # (in_pos, Remove(1, detached))
    in_pos = 0
    for m in a:
        if isinstance(m, Skip):
            for _ in range(m.count):
                items.append(("in", in_pos, None))
                in_pos += 1
        elif isinstance(m, Modify):
            items.append(("in", in_pos, m.change))
            in_pos += 1
        elif isinstance(m, Remove):
            for off in range(m.count):
                det = m.detached[off] if m.detached is not None else None
                removed.append((in_pos, Remove(1, [det] if det is not None else None)))
                in_pos += 1
        elif isinstance(m, Insert):
            for n in m.content:
                items.append(("new", in_pos, n.clone()))
    tail_in = in_pos  # items beyond a's marks keep 1:1 (implicit Skip)

    def item(i: int) -> tuple:
        if i < len(items):
            return items[i]
        return ("in", tail_in + (i - len(items)), None)

    # Walk b over the item list, producing placements anchored in a's INPUT
    # coordinates: (in_boundary, order, payload-mark).
    placements: list[tuple[int, int, int, Any]] = []
    seq = 0

    def anchor_of(i: int) -> int:
        kind, pos, _x = item(i)
        return pos

    out_pos = 0
    # Placements always carry CLONES of a's/b's nested changes and content:
    # applying the composed change enriches nested changes and repair data
    # in place, and sharing structure with the inputs would silently mutate
    # the original commits (applied_log / trunk), corrupting their invert.
    for m in b:
        seq += 1
        if isinstance(m, Skip):
            for _ in range(m.count):
                kind, pos, nested = item(out_pos)
                if kind == "in" and nested is not None:
                    placements.append((pos, 1, seq, Modify(clone_change(nested))))
                elif kind == "new":
                    placements.append((pos, 0, seq, Insert([item(out_pos)[2]])))
                out_pos += 1
        elif isinstance(m, Modify):
            kind, pos, nested = item(out_pos)
            if kind == "in":
                change = (
                    compose_node_change(nested, m.change)
                    if nested is not None
                    else clone_change(m.change)
                )
                placements.append((pos, 1, seq, Modify(change)))
            else:  # b edits a-inserted content: fold into the insert
                node = item(out_pos)[2]
                apply_node_change(node, clone_change(m.change))
                placements.append((pos, 0, seq, Insert([node])))
            out_pos += 1
        elif isinstance(m, Remove):
            for off in range(m.count):
                kind, pos, nested = item(out_pos)
                det = m.detached[off] if m.detached is not None else None
                if kind == "in":
                    if det is not None:
                        det = det.clone()
                        if nested is not None:
                            # b captured the node AFTER a's Modify; composed
                            # repair data must be a's-input-context content.
                            apply_node_change(det, _safe_invert(nested))
                    placements.append((
                        pos, 1, seq,
                        Remove(1, [det] if det is not None else None),
                    ))
                # b removing a-inserted content: both cancel (no mark).
                out_pos += 1
        elif isinstance(m, Insert):
            placements.append((
                anchor_of(out_pos), 0, seq,
                Insert([n.clone() for n in m.content]),
            ))
    # a-output items b never reached keep their a-effects.
    for i in range(out_pos, len(items)):
        kind, pos, nested = item(i)
        if kind == "new":
            placements.append((pos, 0, seq + 1, Insert([items[i][2]])))
        elif nested is not None:
            placements.append((pos, 1, seq + 1, Modify(clone_change(nested))))
    for pos, rm in removed:
        placements.append((pos, 1, 0, rm))

    placements.sort(key=lambda t: (t[0], t[1], t[2]))
    out: list = []
    cursor = 0
    for pos, _ko, _sq, mark in placements:
        if pos > cursor:
            _emit(out, Skip(pos - cursor))
            cursor = pos
        _emit(out, mark)
        if isinstance(mark, (Remove, Modify)):
            cursor += mark.count if isinstance(mark, Remove) else 1
    return out
