"""Host adapter: run a SharedString client on the TPU merge-tree kernel.

Implements the ``MergeTreeBackend`` protocol (the channel-boundary analog)
over a single-document ``DocState``, so the exact same client/service test
harness drives either the Python oracle or the JAX kernel — the differential
oracle setup the reference achieves with its fuzz suites.

This adapter is the *correctness* path (one jitted call per op).  The
*throughput* path batches ops across documents first — see
``models/doc_batch_engine.py``.
"""

from __future__ import annotations

import numpy as np
import jax

from ..ops import mergetree_kernel as mk
from ..protocol.stamps import ALL_ACKED


@jax.jit
def _apply_one(state: mk.DocState, op, payload) -> mk.DocState:
    return mk.apply_op(state, op, payload)


@jax.jit
def _compact(state: mk.DocState) -> mk.DocState:
    return mk.compact(state)


class KernelMergeTree:
    """Single-doc merge-tree replica backed by the columnar kernel."""

    def __init__(
        self,
        max_segments: int = 512,
        remove_slots: int = 4,
        prop_slots: int = 4,
        text_capacity: int = 8192,
        max_insert_len: int = 64,
        ob_slots: int = 8,
    ) -> None:
        self.state = mk.init_state(
            max_segments, remove_slots, prop_slots, text_capacity, ob_slots
        )
        self.max_insert_len = max_insert_len
        self._empty_payload = np.zeros((max_insert_len,), np.int32)
        # Host-interned property ids -> kernel prop slots.
        self._prop_slot: dict[int, int] = {}

    # ------------------------------------------------------------------ utils
    def _op(self, kind, key=0, client=-1, ref_seq=0, pos1=0, pos2=0, a=0, b=0):
        return np.array(
            [kind, key, client, ref_seq, pos1, pos2, a, b], np.int32
        )

    def _step(self, op, payload=None) -> None:
        p = self._empty_payload if payload is None else payload
        self.state = _apply_one(self.state, op, p)

    def check_errors(self) -> int:
        return int(self.state.error)

    def _slot_for(self, prop: int) -> int:
        if prop not in self._prop_slot:
            slot = len(self._prop_slot)
            if slot >= len(self.state.prop_keys):
                raise ValueError(f"out of prop slots for prop id {prop}")
            self._prop_slot[prop] = slot
        return self._prop_slot[prop]

    # ---------------------------------------------------------------- backend
    def apply_insert(self, pos, text, op_key, op_client, ref_seq) -> None:
        for op, payload in mk.encode_insert(
            pos, text, op_key, op_client, ref_seq, self.max_insert_len
        ):
            self._step(op, payload)

    def apply_remove(self, pos1, pos2, op_key, op_client, ref_seq) -> None:
        self._step(
            self._op(
                mk.OpKind.REMOVE, key=op_key, client=op_client, ref_seq=ref_seq,
                pos1=pos1, pos2=pos2,
            )
        )

    def apply_obliterate(self, pos1, side1, pos2, side2, op_key, op_client, ref_seq) -> None:
        self._step(
            mk.encode_obliterate(pos1, side1, pos2, side2, op_key, op_client, ref_seq)
        )

    def apply_annotate(self, pos1, pos2, prop, value, op_key, op_client, ref_seq) -> None:
        self._step(
            self._op(
                mk.OpKind.ANNOTATE, key=op_key, client=op_client, ref_seq=ref_seq,
                pos1=pos1, pos2=pos2, a=self._slot_for(prop), b=value,
            )
        )

    def ack(self, local_seq, seq) -> None:
        self._step(self._op(mk.OpKind.ACK, a=local_seq, b=seq))

    def update_min_seq(self, min_seq) -> None:
        prev = int(self.state.min_seq)
        if min_seq > prev:
            self.state = mk.set_min_seq(self.state, min_seq)
            self.state = _compact(self.state)

    def visible_text(self, ref_seq: int = ALL_ACKED, view_client: int | None = None) -> str:
        vc = -3 if view_client is None else view_client
        return mk.visible_text(self.state, ref_seq, vc)

    def annotations(self, ref_seq: int = ALL_ACKED, view_client: int | None = None):
        vc = -3 if view_client is None else view_client
        raw = mk.annotations(self.state, ref_seq, vc)
        inv = {v: k for k, v in self._prop_slot.items()}
        return [{inv[p]: v for p, v in d.items()} for d in raw]
