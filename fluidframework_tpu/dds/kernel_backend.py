"""Host adapter: run a SharedString client on the TPU merge-tree kernel.

Implements the FULL merge-tree backend protocol (the channel-boundary
analog, ref datastore-definitions/src/channel.ts:294) over a single-document
``DocState``, so the exact same channel/container test harness drives either
the Python oracle (``RefMergeTree``) or the JAX kernel — the differential
oracle setup the reference achieves with its fuzz suites.

Split of responsibilities:

- **Op application** (insert/remove/annotate/obliterate/ack) runs on device
  through the columnar kernel — one jitted call per op (the correctness
  path; the throughput path batches ops across documents first, see
  ``models/doc_batch_engine.py``).
- **Queries** (visible text, converged-coordinate translation for interval
  collections and undo, summaries) are host-side walks over a pulled
  snapshot of the columnar state — control-plane reads, mirroring
  ``mergetree_ref`` line for line.
- **Reconnect regeneration** splits host/device: the host PLANS the
  re-minted wire ops from a snapshot (ref client.ts regeneratePendingOp
  :1452), then re-stamps exactly the affected segments on device with
  ``mergetree_kernel.restamp`` (plus ``drop_squashed`` / ``strip_stamp``).
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np
import jax

from ..ops import mergetree_kernel as mk
from ..protocol.stamps import (
    ALL_ACKED,
    LOCAL_BASE,
    NO_REMOVE,
    NON_COLLAB_CLIENT,
    acked as _acked,
)


@jax.jit
def _apply_one(state: mk.DocState, op, payload) -> mk.DocState:
    return mk.apply_op(state, op, payload)


@jax.jit
def _compact(state: mk.DocState) -> mk.DocState:
    return mk.compact(state)


@dataclass
class _Seg:
    """Host mirror of one device segment (decoded columnar row)."""

    uid: int
    length: int
    ins_key: int
    ins_client: int
    obpre: int
    removes: list[tuple[int, int]]            # sorted (key, client)
    props: dict[int, tuple[int, int]] = field(default_factory=dict)  # slot -> (val, key)
    text: str | None = None

    def visible(self, ref_seq: int, view_client: int) -> bool:
        if not (self.ins_key <= ref_seq or self.ins_client == view_client):
            return False
        return not any(
            k <= ref_seq or c == view_client for k, c in self.removes
        )


@dataclass
class _Ob:
    """Host mirror of one obliterate-table record."""

    slot: int
    key: int
    client: int
    start_uid: int
    start_side: int
    end_uid: int
    end_side: int
    ref_seq: int


# ---------------------------------------------------------------------------
# Standalone DocState <-> host snapshot / summary converters.  These are the
# checkpoint/restore primitives shared by the single-doc backend below and
# the batched engines (models/doc_batch_engine.py): any packed ``DocState``
# row — batch slot, overflow lane, or restored checkpoint — round-trips
# through the same summary JSON schema as RefMergeTree.export_summary.
# ---------------------------------------------------------------------------


def pull_segments(state: mk.DocState, with_text: bool = False) -> list[_Seg]:
    """Pull the live segment rows of one DocState off device as host records."""
    s = state
    nseg = int(s.nseg)
    seg_uid = np.asarray(s.seg_uid)[:nseg]
    seg_len = np.asarray(s.seg_len)[:nseg]
    ins_key = np.asarray(s.ins_key)[:nseg]
    ins_client = np.asarray(s.ins_client)[:nseg]
    obpre = np.asarray(s.seg_obpre)[:nseg]
    rem_k = np.stack([np.asarray(a)[:nseg] for a in s.rem_keys]) if nseg else None
    rem_c = np.stack([np.asarray(a)[:nseg] for a in s.rem_clients]) if nseg else None
    prop_k = np.stack([np.asarray(a)[:nseg] for a in s.prop_keys]) if nseg else None
    prop_v = np.stack([np.asarray(a)[:nseg] for a in s.prop_vals]) if nseg else None
    texts: list[str | None] = [None] * nseg
    if with_text and nseg:
        pool = np.asarray(s.text)
        start = np.asarray(s.seg_start)[:nseg]
        texts = [
            "".join(chr(c) for c in pool[start[i] : start[i] + seg_len[i]])
            for i in range(nseg)
        ]
    out: list[_Seg] = []
    for i in range(nseg):
        removes = sorted(
            (int(rem_k[r, i]), int(rem_c[r, i]))
            for r in range(rem_k.shape[0])
            if rem_k[r, i] != NO_REMOVE
        )
        props = {
            p: (int(prop_v[p, i]), int(prop_k[p, i]))
            for p in range(prop_k.shape[0])
            if prop_k[p, i] >= 0
        }
        out.append(
            _Seg(
                uid=int(seg_uid[i]),
                length=int(seg_len[i]),
                ins_key=int(ins_key[i]),
                ins_client=int(ins_client[i]),
                obpre=int(obpre[i]),
                removes=removes,
                props=props,
                text=texts[i],
            )
        )
    return out


def pull_obliterates(state: mk.DocState) -> list[_Ob]:
    s = state
    keys = np.asarray(s.ob_key)
    out = []
    for i in range(keys.shape[0]):
        if keys[i] >= 0:
            out.append(
                _Ob(
                    slot=i,
                    key=int(keys[i]),
                    client=int(np.asarray(s.ob_client)[i]),
                    start_uid=int(np.asarray(s.ob_start_uid)[i]),
                    start_side=int(np.asarray(s.ob_start_side)[i]),
                    end_uid=int(np.asarray(s.ob_end_uid)[i]),
                    end_side=int(np.asarray(s.ob_end_side)[i]),
                    ref_seq=int(np.asarray(s.ob_ref_seq)[i]),
                )
            )
    return out


def state_to_summary(
    state: mk.DocState,
    prop_names: dict[int, object] | None = None,
    slice_keys: set[int] | None = None,
) -> dict:
    """One DocState -> summary JSON (identical schema to
    RefMergeTree.export_summary).  ``prop_names`` maps kernel prop slot ->
    property id; missing slots keep their slot number as the id."""
    segs = pull_segments(state, with_text=True)
    prop_names = prop_names or {}
    out_segs = []
    for seg in segs:
        if not _acked(seg.ins_key) or any(not _acked(k) for k, _c in seg.removes):
            raise RuntimeError("summarize with pending merge-tree state")
        out_segs.append(
            {
                "text": seg.text,
                "ins": [seg.ins_key, seg.ins_client],
                "removes": [[k, c] for k, c in seg.removes],
                "props": {
                    str(prop_names.get(p, p)): [v, k]
                    for p, (v, k) in sorted(seg.props.items())
                },
            }
        )
    uid_index = {seg.uid: i for i, seg in enumerate(segs)}
    obs = []
    for ob in sorted(pull_obliterates(state), key=lambda o: o.key):
        if not _acked(ob.key):
            raise RuntimeError("summarize with pending merge-tree state")
        obs.append(
            {
                "key": ob.key,
                "client": ob.client,
                "start": uid_index.get(ob.start_uid, -1),
                "startSide": ob.start_side,
                "end": uid_index.get(ob.end_uid, -1),
                "endSide": ob.end_side,
                "refSeq": ob.ref_seq,
            }
        )
    live = {k for seg in segs for k, _c in seg.removes} | {o["key"] for o in obs}
    return {
        "segments": out_segs,
        "obliterates": obs,
        "minSeq": int(state.min_seq),
        "sliceKeys": sorted((slice_keys or set()) & live),
    }


def summary_to_state(summary: dict, geometry: dict, slot_for) -> mk.DocState:
    """Summary JSON -> a fresh DocState packed at ``geometry`` (the
    checkpoint-restore and grow-replay base).  ``slot_for(prop_id)`` interns
    a property id to a kernel prop slot — callers keep their own table so
    later ops encode against the same slots.  Raises ValueError when the
    summary does not fit the geometry (callers grow and retry)."""
    import jax
    import jax.numpy as jnp

    return jax.tree.map(
        jnp.asarray, summary_to_state_host(summary, geometry, slot_for)
    )


def summary_to_state_host(summary: dict, geometry: dict, slot_for) -> mk.DocState:
    """``summary_to_state`` with the leaves left as HOST numpy arrays: the
    batched parallel restore packs many docs' rows host-side, stacks them,
    and ships ONE transfer + ONE scatter dispatch instead of a per-doc
    device round-trip (models/*.restore_from_checkpoints).  Byte-identical
    content to ``summary_to_state`` by construction (that wrapper is just
    ``jnp.asarray`` over this)."""
    S = geometry["max_segments"]
    T = geometry["text_capacity"]
    R = geometry["remove_slots"]
    P = geometry["prop_slots"]
    OB = geometry["ob_slots"]
    entries = summary["segments"]
    obs = summary.get("obliterates", [])
    if any("attr" in e for e in entries):
        raise ValueError(
            "kernel state cannot carry attribution override runs; "
            "load this summary into the oracle backend"
        )
    if len(entries) > S:
        raise ValueError(f"summary has {len(entries)} segments > capacity {S}")
    if len(obs) > OB:
        raise ValueError(f"summary has {len(obs)} obliterates > capacity {OB}")

    text_pool = np.zeros((T,), np.int32)
    seg_start = np.zeros((S,), np.int32)
    seg_len = np.zeros((S,), np.int32)
    ins_key = np.zeros((S,), np.int32)
    ins_client = np.full((S,), -1, np.int32)
    seg_uid = np.full((S,), -1, np.int32)
    rem_keys = np.full((R, S), NO_REMOVE, np.int32)
    rem_clients = np.full((R, S), -1, np.int32)
    prop_keys = np.full((P, S), -1, np.int32)
    prop_vals = np.zeros((P, S), np.int32)
    end = 0
    for i, e in enumerate(entries):
        txt = e["text"]
        if end + len(txt) > T:
            raise ValueError("summary text exceeds pool capacity")
        text_pool[end : end + len(txt)] = [ord(ch) for ch in txt]
        seg_start[i] = end
        seg_len[i] = len(txt)
        end += len(txt)
        ins_key[i] = e["ins"][0]
        ins_client[i] = e["ins"][1]
        seg_uid[i] = i
        if len(e["removes"]) > R:
            raise ValueError("summary removes exceed remove slots")
        for r, (k, c) in enumerate(e["removes"]):
            rem_keys[r, i] = k
            rem_clients[r, i] = c
        for p_str, (v, k) in e["props"].items():
            slot = slot_for(int(p_str))
            prop_keys[slot, i] = k
            prop_vals[slot, i] = v

    ob_key = np.full((OB,), -1, np.int32)
    ob_client = np.full((OB,), -1, np.int32)
    ob_start_uid = np.full((OB,), -1, np.int32)
    ob_end_uid = np.full((OB,), -1, np.int32)
    ob_start_side = np.zeros((OB,), np.int32)
    ob_end_side = np.zeros((OB,), np.int32)
    ob_ref_seq = np.full((OB,), -1, np.int32)
    for j, o in enumerate(obs):
        ob_key[j] = o["key"]
        ob_client[j] = o["client"]
        ob_start_uid[j] = o["start"]
        ob_end_uid[j] = o["end"]
        ob_start_side[j] = o["startSide"]
        ob_end_side[j] = o["endSide"]
        ob_ref_seq[j] = o["refSeq"]

    return mk.DocState(
        text=text_pool,
        text_end=np.asarray(end, np.int32),
        nseg=np.asarray(len(entries), np.int32),
        seg_start=seg_start,
        seg_len=seg_len,
        ins_key=ins_key,
        ins_client=ins_client,
        seg_uid=seg_uid,
        seg_obpre=np.full((S,), -1, np.int32),
        rem_keys=tuple(rem_keys[r] for r in range(R)),
        rem_clients=tuple(rem_clients[r] for r in range(R)),
        prop_keys=tuple(prop_keys[p] for p in range(P)),
        prop_vals=tuple(prop_vals[p] for p in range(P)),
        uid_next=np.asarray(len(entries), np.int32),
        ob_key=ob_key,
        ob_client=ob_client,
        ob_start_uid=ob_start_uid,
        ob_end_uid=ob_end_uid,
        ob_start_side=ob_start_side,
        ob_end_side=ob_end_side,
        ob_ref_seq=ob_ref_seq,
        min_seq=np.asarray(summary["minSeq"], np.int32),
        error=np.zeros((), np.int32),
    )


def state_geometry(state: mk.DocState) -> dict[str, int]:
    """The capacity axes of a packed DocState (engine geometry dict shape)."""
    return {
        "max_segments": int(state.seg_len.shape[0]),
        "text_capacity": int(state.text.shape[0]),
        "remove_slots": len(state.rem_keys),
        "prop_slots": len(state.prop_keys),
        "ob_slots": int(state.ob_key.shape[0]),
    }


class KernelMergeTree:
    """Single-doc merge-tree replica backed by the columnar kernel."""

    def __init__(
        self,
        max_segments: int = 512,
        remove_slots: int = 4,
        prop_slots: int = 4,
        text_capacity: int = 8192,
        max_insert_len: int = 64,
        ob_slots: int = 8,
        local_client: int = -3,
    ) -> None:
        self.state = mk.init_state(
            max_segments, remove_slots, prop_slots, text_capacity, ob_slots
        )
        self.max_insert_len = max_insert_len
        self.local_client = local_client
        # Mutation generation: bumped on EVERY self.state replacement so
        # host-side caches (marker_scan) invalidate without pinning the
        # superseded DocState.
        self._gen = 0
        self._empty_payload = np.zeros((max_insert_len,), np.int32)
        # Host-interned property ids -> kernel prop slots.
        self._prop_slot: dict[int, int] = {}
        # Stamp keys minted by regenerate_pending during a reconnect replay
        # (see mergetree_ref.RefMergeTree._regenerated_keys).
        self._regenerated_keys: set[int] = set()
        # Obliterate stamp keys, outliving the window record — mirrors
        # RefMergeTree.slice_keys so summaries stay schema-identical
        # across backends (v2 sliceKeys field).
        self.slice_keys: set[int] = set()

    # ------------------------------------------------------------------ utils
    def _op(self, kind, key=0, client=-1, ref_seq=0, pos1=0, pos2=0, a=0, b=0):
        return np.array(
            [kind, key, client, ref_seq, pos1, pos2, a, b], np.int32
        )

    def _step(self, op, payload=None) -> None:
        p = self._empty_payload if payload is None else payload
        self.state = _apply_one(self.state, op, p)
        self._gen += 1

    def check_errors(self) -> int:
        return int(self.state.error)

    def _slot_for(self, prop: int) -> int:
        if prop not in self._prop_slot:
            slot = len(self._prop_slot)
            if slot >= len(self.state.prop_keys):
                raise ValueError(f"out of prop slots for prop id {prop}")
            self._prop_slot[prop] = slot
        return self._prop_slot[prop]

    # --------------------------------------------------------------- snapshot
    def _segs(self, with_text: bool = False) -> list[_Seg]:
        """Pull the live segment rows off device as host records."""
        return pull_segments(self.state, with_text)

    def _obs(self) -> list[_Ob]:
        return pull_obliterates(self.state)

    def _stamp_uids(self, op_key: int, op_client: int) -> dict[int, int]:
        """uid -> number of remove slots carrying exactly (op_key, op_client)."""
        s = self.state
        nseg = int(s.nseg)
        if nseg == 0:
            return {}
        uid = np.asarray(s.seg_uid)[:nseg]
        counts = np.zeros((nseg,), np.int64)
        for k, c in zip(s.rem_keys, s.rem_clients):
            counts += (np.asarray(k)[:nseg] == op_key) & (
                np.asarray(c)[:nseg] == op_client
            )
        return {int(uid[i]): int(counts[i]) for i in range(nseg) if counts[i]}

    # ---------------------------------------------------------------- backend
    def apply_insert(self, pos, text, op_key, op_client, ref_seq) -> list[int]:
        """Apply an insert; returns the uids of the created segments (the
        channel's converged-event handles)."""
        # An insert chunk fails iff one of these latches (ERR_REM_OVERFLOW
        # can accompany a SUCCESSFUL insert — swallow-candidate overflow);
        # once any is latched the state is unreliable, so stop attributing.
        fail_bits = mk.ERR_SEG_OVERFLOW | mk.ERR_TEXT_OVERFLOW | mk.ERR_POS_RANGE
        uids: list[int] = []
        for op, payload in mk.encode_insert(
            pos, text, op_key, op_client, ref_seq, self.max_insert_len
        ):
            self._step(op, payload)
            if int(self.state.error) & fail_bits == 0:
                # The new segment's uid is always the last allocation of the
                # chunk's apply (_do_insert allocates the boundary-split uid
                # first, the new segment's uid last).
                uids.append(int(self.state.uid_next) - 1)
        return uids

    def apply_remove(self, pos1, pos2, op_key, op_client, ref_seq) -> list[int]:
        before = self._stamp_uids(op_key, op_client)
        self._step(
            self._op(
                mk.OpKind.REMOVE, key=op_key, client=op_client, ref_seq=ref_seq,
                pos1=pos1, pos2=pos2,
            )
        )
        after = self._stamp_uids(op_key, op_client)
        return [u for u, n in after.items() if n > before.get(u, 0)]

    def apply_obliterate(self, pos1, side1, pos2, side2, op_key, op_client, ref_seq) -> list[int]:
        before = self._stamp_uids(op_key, op_client)
        self._step(
            mk.encode_obliterate(pos1, side1, pos2, side2, op_key, op_client, ref_seq)
        )
        self.slice_keys.add(op_key)
        after = self._stamp_uids(op_key, op_client)
        return [u for u, n in after.items() if n > before.get(u, 0)]

    def apply_annotate(self, pos1, pos2, prop, value, op_key, op_client, ref_seq) -> None:
        self._step(
            self._op(
                mk.OpKind.ANNOTATE, key=op_key, client=op_client, ref_seq=ref_seq,
                pos1=pos1, pos2=pos2, a=self._slot_for(prop), b=value,
            )
        )

    def ack(self, local_seq, seq, client=None, ref_seq=None):
        """Convert pending stamps with this localSeq to the acked seq
        (re-stamping client id / obliterate refSeq when given — see
        mergetree_ref.RefMergeTree.ack).  Returns (inserted_uids,
        removed_uids) for the channel's converged events."""
        local_key = LOCAL_BASE + local_seq
        self._regenerated_keys.discard(local_key)
        if local_key in self.slice_keys:
            self.slice_keys.discard(local_key)
            self.slice_keys.add(seq)
        s = self.state
        nseg = int(s.nseg)
        ins_uids: list[int] = []
        rem_uids: list[int] = []
        if nseg:
            uid = np.asarray(s.seg_uid)[:nseg]
            ins_hit = np.asarray(s.ins_key)[:nseg] == local_key
            rem_hit = np.zeros((nseg,), bool)
            for k in s.rem_keys:
                rem_hit |= np.asarray(k)[:nseg] == local_key
            ins_uids = [int(u) for u in uid[ins_hit]]
            rem_uids = [int(u) for u in uid[rem_hit]]
        self._step(
            self._op(
                mk.OpKind.ACK,
                client=-1 if client is None else client,
                ref_seq=-1 if ref_seq is None else ref_seq,
                a=local_seq, b=seq,
            )
        )
        return ins_uids, rem_uids

    def update_min_seq(self, min_seq) -> None:
        prev = int(self.state.min_seq)
        if min_seq > prev:
            self.state = mk.set_min_seq(self.state, min_seq)
            self.state = _compact(self.state)
            self._gen += 1

    # ------------------------------------------------------------------ views
    def visible_text(
        self,
        ref_seq: int = ALL_ACKED,
        view_client: int | None = None,
        raw: bool = False,
    ) -> str:
        vc = self.local_client if view_client is None else view_client
        return mk.visible_text(self.state, ref_seq, vc, raw=raw)

    def visible_length(self, ref_seq: int = ALL_ACKED, view_client: int | None = None) -> int:
        vc = self.local_client if view_client is None else view_client
        return mk.visible_length(self.state, ref_seq, vc)

    def annotations(self, ref_seq: int = ALL_ACKED, view_client: int | None = None):
        vc = self.local_client if view_client is None else view_client
        raw = mk.annotations(self.state, ref_seq, vc)
        inv = {v: k for k, v in self._prop_slot.items()}
        return [{inv[p]: v for p, v in d.items()} for d in raw]

    def marker_scan(
        self, ref_seq: int = ALL_ACKED, view_client: int | None = None
    ) -> list[tuple[int, int, dict]]:
        """Visible markers as (position, refType, {prop_id: value_id}) —
        same shape as RefMergeTree.marker_scan (markers are ordinary
        1-char segments in the columns; only this host query decodes
        them).  The device readback is cached per mutation generation, so
        repeated queries against an unchanged replica (id lookup, tile
        search) cost one readback — and the cache never pins a superseded
        DocState (a state reference would hold the dead columns alive)."""
        from .markers import is_marker_text, marker_ref_type

        vc = self.local_client if view_client is None else view_client
        gen = self._gen
        cached = getattr(self, "_marker_cache", None)
        if cached is not None and cached[0] == (gen, ref_seq, vc):
            return cached[1]
        inv = {v: k for k, v in self._prop_slot.items()}
        out: list[tuple[int, int, dict]] = []
        pos = 0
        for seg in self._segs(with_text=True):
            if not seg.visible(ref_seq, vc):
                continue
            if is_marker_text(seg.text):
                out.append((
                    pos,
                    marker_ref_type(seg.text),
                    {inv[p]: v for p, (v, _k) in seg.props.items()},
                ))
            pos += seg.length
        self._marker_cache = ((gen, ref_seq, vc), out)
        return out

    def attribution_runs(
        self, ref_seq: int = ALL_ACKED, view_client: int | None = None
    ):
        """Run-length insert attribution over the visible text — the device
        columns ins_key/ins_client ARE the attribution data (ref
        attributionCollection.ts; VERDICT r3 missing #4).  Same shape as
        RefMergeTree.attribution_runs: [(start, key)], key = acked seq or
        {"type": "local"}."""
        vc = self.local_client if view_client is None else view_client
        runs: list[tuple[int, object]] = []
        pos = 0
        for seg in self._segs():
            if not seg.visible(ref_seq, vc):
                continue
            key = (
                seg.ins_key if seg.ins_key < LOCAL_BASE else {"type": "local"}
            )
            if not runs or runs[-1][1] != key:
                runs.append((pos, key))
            pos += seg.length
        return runs

    def attribution_at(
        self, pos: int, ref_seq: int = ALL_ACKED, view_client: int | None = None
    ):
        from .mergetree_ref import attribution_key_at

        vc = self.local_client if view_client is None else view_client
        if not 0 <= pos < self.visible_length(ref_seq, vc):
            raise ValueError(f"attribution offset {pos} out of range")
        return attribution_key_at(self.attribution_runs(ref_seq, vc), pos)

    # ----------------------------------------------------- converged queries
    # Host-side ports of mergetree_ref's converged-coordinate walks (the
    # coordinates interval collections and undo ranges live in).

    @staticmethod
    def _flatten_uids(segs) -> set[int]:
        out: set[int] = set()
        for x in segs:
            if isinstance(x, (list, tuple, set)):
                out.update(int(u) for u in x)
            else:
                out.add(int(x))
        return out

    def converged_position(self, pos: int, ref_seq: int, view_client: int) -> int:
        rem = pos
        conv = 0
        for seg in self._segs():
            p_len = seg.length if seg.visible(ref_seq, view_client) else 0
            c_vis = seg.visible(ALL_ACKED, NON_COLLAB_CLIENT)
            if rem < p_len:
                return conv + (rem if c_vis else 0)
            rem -= p_len
            if c_vis:
                conv += seg.length
        if rem == 0:
            return conv
        raise ValueError(f"position {pos} beyond perspective-visible length")

    def converged_insert_ranges(self, segs) -> list[tuple[int, int]]:
        wanted = self._flatten_uids(segs)
        out: list[tuple[int, int]] = []
        pos = 0
        for seg in self._segs():
            if seg.visible(ALL_ACKED, NON_COLLAB_CLIENT):
                if seg.uid in wanted:
                    out.append((pos, seg.length))
                pos += seg.length
        return out

    def converged_removed_ranges(self, segs, op_key: int) -> list[tuple[int, int]]:
        wanted = self._flatten_uids(segs)
        out: list[tuple[int, int]] = []
        pos = 0
        for seg in self._segs():
            if not _acked(seg.ins_key):
                continue
            acked_removes = [k for k, _c in seg.removes if _acked(k)]
            newly = seg.uid in wanted and all(k == op_key for k in acked_removes)
            alive = not acked_removes
            if newly:
                out.append((pos, seg.length))
            if newly or alive:
                pos += seg.length
        return out

    def converged_to_local(self, pos: int) -> int:
        conv = 0
        loc = 0
        for seg in self._segs():
            c_vis = seg.visible(ALL_ACKED, NON_COLLAB_CLIENT)
            l_vis = seg.visible(ALL_ACKED, self.local_client)
            n = seg.length
            if c_vis and pos < conv + n:
                return loc + (pos - conv) if l_vis else loc
            if c_vis:
                conv += n
            if l_vis:
                loc += n
        return loc

    def converged_spans_to_local(self, start: int, end: int) -> list[tuple[int, int]]:
        spans: list[list[int]] = []
        conv = 0
        loc = 0
        for seg in self._segs():
            c_vis = seg.visible(ALL_ACKED, NON_COLLAB_CLIENT)
            l_vis = seg.visible(ALL_ACKED, self.local_client)
            n = seg.length
            if c_vis:
                o1 = max(start, conv)
                o2 = min(end, conv + n)
                if o1 < o2 and l_vis:
                    s0 = loc + (o1 - conv)
                    e0 = loc + (o2 - conv)
                    if spans and spans[-1][1] == s0:
                        spans[-1][1] = e0
                    else:
                        spans.append([s0, e0])
                conv += n
            if l_vis:
                loc += n
        return [(s, e) for s, e in spans]

    # --------------------------------------------------------------- reconnect
    def _squashed(self, seg: _Seg) -> bool:
        return not _acked(seg.ins_key) and any(
            not _acked(k) for k, _c in seg.removes
        )

    def _occurred_before(self, key: int, max_key: int) -> bool:
        return _acked(key) or key < max_key or key in self._regenerated_keys

    def _visible_at_prefix(
        self, seg: _Seg, max_key: int, exclude_key: int, squash: bool = False
    ) -> bool:
        if squash and self._squashed(seg):
            return False
        if not self._occurred_before(seg.ins_key, max_key):
            return False
        return not any(
            self._occurred_before(key, max_key) and key != exclude_key
            for key, _client in seg.removes
        )

    def _restamp(
        self, uids: set[int] | None, old_key: int, fresh_key: int,
        new_client: int | None, cls: str,
    ) -> None:
        """Device-side selective re-stamp of one plan's segments."""
        s = self.state
        S = s.seg_len.shape[0]
        if uids is None:
            mask = np.ones((S,), bool)
        else:
            nseg = int(s.nseg)
            uid = np.asarray(s.seg_uid)
            mask = np.zeros((S,), bool)
            for i in range(nseg):
                if int(uid[i]) in uids:
                    mask[i] = True
        self._gen += 1
        self.state = mk.restamp(
            s,
            jax.numpy.asarray(mask),
            old_key,
            fresh_key,
            -1 if new_client is None else new_client,
            cls == "ins",
            cls in ("rem", "ob"),
            cls == "prop",
            cls == "ob",
        )

    def regenerate_pending(
        self,
        local_seq: int,
        new_local_seq,
        squash: bool = False,
        new_client: int | None = None,
    ) -> list[tuple[int, dict]]:
        """Re-mint the pending op with this localSeq against current state
        (ref client.ts regeneratePendingOp:1452; the host plan mirrors
        mergetree_ref.RefMergeTree.regenerate_pending step for step, the
        re-stamping runs on device)."""
        key = LOCAL_BASE + local_seq
        ob = next((o for o in self._obs() if o.key == key), None)
        if ob is not None:
            return self._regenerate_obliterate(ob, key, new_local_seq, squash, new_client)

        segs = self._segs(with_text=True)
        inv_prop = {v: k for k, v in self._prop_slot.items()}
        # (kind, pos1, pos2, payload, {uids}) collected before re-stamping.
        plans: list[tuple[int, int, int, object, set[int]]] = []

        # Pending insert: contiguous run of segments carrying this ins stamp.
        ins_segs: list[_Seg] = []
        pos = 0
        ins_pos = -1
        for seg in segs:
            if seg.ins_key == key and not (squash and self._squashed(seg)):
                if ins_pos < 0:
                    ins_pos = pos
                ins_segs.append(seg)
            if self._visible_at_prefix(seg, key, exclude_key=-1, squash=squash):
                pos += seg.length
        if ins_pos >= 0:
            from .markers import regenerated_insert_spec

            spec = regenerated_insert_spec([
                (s.text, {
                    str(inv_prop[p]): v
                    for p, (v, k) in s.props.items()
                    if k == key
                })
                for s in ins_segs
            ])
            plans.append((0, ins_pos, -1, spec, {s.uid for s in ins_segs}))

        # Pending remove / annotate: maximal visible runs carrying the stamp.
        pos = 0
        rem_run: tuple[int, int, set[int]] | None = None
        ann_run: tuple[int, int, dict, set[int]] | None = None

        def flush_remove() -> None:
            nonlocal rem_run
            if rem_run is not None:
                plans.append((1, rem_run[0], rem_run[1], None, rem_run[2]))
            rem_run = None

        def flush_annotate() -> None:
            nonlocal ann_run
            if ann_run is not None:
                plans.append((2, ann_run[0], ann_run[1], ann_run[2], ann_run[3]))
            ann_run = None

        for seg in segs:
            if not self._visible_at_prefix(seg, key, exclude_key=key, squash=squash):
                continue  # invisible: breaks neither runs nor position space
            if any(k == key for k, _c in seg.removes):
                if rem_run is None:
                    rem_run = (pos, pos + seg.length, {seg.uid})
                else:
                    rem_run = (rem_run[0], pos + seg.length, rem_run[2] | {seg.uid})
            else:
                flush_remove()
            props = {
                str(inv_prop[p]): v for p, (v, k) in seg.props.items() if k == key
            }
            if props:
                if ann_run is None or props != ann_run[2]:
                    flush_annotate()
                    ann_run = (pos, pos + seg.length, props, {seg.uid})
                else:
                    ann_run = (ann_run[0], pos + seg.length, props, ann_run[3] | {seg.uid})
            else:
                flush_annotate()
            pos += seg.length
        flush_remove()
        flush_annotate()

        if squash:
            self.state = mk.drop_squashed(self.state)
            self._gen += 1

        out: list[tuple[int, dict]] = []
        # Split removes shift later pieces left by what earlier pieces
        # removed (see mergetree_ref.regenerate_pending).
        removed_before = 0
        for kind, pos1, pos2, payload, uids in plans:
            fresh = new_local_seq()
            fresh_key = LOCAL_BASE + fresh
            self._regenerated_keys.add(fresh_key)
            if kind == 0:
                self._restamp(uids, key, fresh_key, new_client, "ins")
                # Same-op props (insertMarker) re-mint with the insert.
                self._restamp(uids, key, fresh_key, None, "prop")
                out.append((fresh, {"type": 0, "pos1": pos1, "seg": payload}))
            elif kind == 1:
                self._restamp(uids, key, fresh_key, new_client, "rem")
                out.append(
                    (fresh, {"type": 1, "pos1": pos1 - removed_before,
                             "pos2": pos2 - removed_before})
                )
                removed_before += pos2 - pos1
            else:
                self._restamp(uids, key, fresh_key, None, "prop")
                out.append(
                    (fresh, {"type": 2, "pos1": pos1, "pos2": pos2, "props": payload})
                )
        return out

    def _regenerate_obliterate(
        self, ob: _Ob, key: int, new_local_seq, squash: bool, new_client: int | None
    ) -> list[tuple[int, dict]]:
        """Port of mergetree_ref._regenerate_obliterate over the snapshot."""
        segs = self._segs()
        index_of = {seg.uid: i for i, seg in enumerate(segs)}
        s_i = index_of.get(ob.start_uid, len(segs))
        e_i = index_of.get(ob.end_uid, len(segs))
        b_s = b_e = total = 0
        for i, seg in enumerate(segs):
            if not self._visible_at_prefix(seg, key, exclude_key=key, squash=squash):
                continue
            n = seg.length
            if i < s_i or (i == s_i and ob.start_side == mk.SIDE_AFTER):
                b_s += n
            if i < e_i or (i == e_i and ob.end_side == mk.SIDE_AFTER):
                b_e += n
            total += n

        if ob.start_side == mk.SIDE_AFTER and b_s > 0:
            start = {"pos": b_s - 1, "before": False}
        else:
            start = {"pos": b_s, "before": True}
        if ob.end_side == mk.SIDE_BEFORE and b_e < total:
            end = {"pos": b_e, "before": True}
        elif b_e > 0:
            end = {"pos": b_e - 1, "before": False}
        else:
            end = None

        start_char = start["pos"]
        end_char = end["pos"] if end is not None else -1
        start_bound = start["pos"] + (0 if start["before"] else 1)
        end_bound = (end["pos"] + (0 if end["before"] else 1)) if end is not None else -1
        if (
            end is None
            or not (0 <= start_char <= end_char < total)
            or start_bound > end_bound
        ):
            # Range gone from the prefix view: retire the obliterate (strip
            # its never-to-ack stamps, free its record slot).
            self.state = mk.strip_stamp(self.state, key)
            self._gen += 1
            self.slice_keys.discard(key)
            return []

        fresh = new_local_seq()
        fresh_key = LOCAL_BASE + fresh
        self._regenerated_keys.add(fresh_key)
        self._restamp(None, key, fresh_key, new_client, "ob")
        self.slice_keys.discard(key)
        self.slice_keys.add(fresh_key)
        return [(fresh, {"type": 5, "pos1": start, "pos2": end})]

    # ------------------------------------------------------------ checkpoint
    def export_summary(self) -> dict:
        """Merge-tree snapshot in the shared summary JSON (identical schema
        to RefMergeTree.export_summary; ref snapshotV1.ts:42)."""
        inv_prop = {v: k for k, v in self._prop_slot.items()}
        return state_to_summary(self.state, inv_prop, self.slice_keys)

    def import_summary(self, summary: dict) -> None:
        """Rebuild device state from summary JSON (fresh text pool, uids =
        segment indices, obliterate anchors resolved by index).  Attribution
        override runs (reference V1 snapshots with universalized below-MSN
        stamps) are refused loudly — load those into the oracle backend."""
        state = summary_to_state(
            summary, state_geometry(self.state), self._slot_for
        )
        self.slice_keys = set(summary.get("sliceKeys", [])) | {
            o["key"] for o in summary.get("obliterates", [])
        }
        self._gen += 1
        self.state = state
