"""SharedMap: host-side LWW key-value DDS with optimistic pending overlay.

Reference parity: map's ``MapKernel`` (packages/dds/map/src/mapKernel.ts).
The *sequenced* (converged) state applies every set/delete/clear in sequence
order; the local optimistic view overlays the client's pending ops — a
pending set/delete/clear masks remote values until acked
(mapKernel.ts:707-852 message handlers), which is exactly LWW given that a
pending op will be sequenced after everything currently acked.

Wire op format: {"type": "set"|"delete"|"clear", "key"?: str, "value"?: any}
(matching the reference's IMapOperation JSON shape).
"""

from __future__ import annotations

from collections import deque
from typing import Any

from ..protocol.messages import MessageType, Nack, SequencedMessage, UnsequencedMessage


class SharedMap:
    """One client replica of a collaborative LWW map."""

    def __init__(self, client_id: str) -> None:
        self.client_id = client_id
        self.sequenced: dict[str, Any] = {}
        self._pending: deque[dict] = deque()
        self._client_seq = 0
        self._ref_seq = 0
        self.outbox: list[UnsequencedMessage] = []

    # ------------------------------------------------------------- local edits
    def set(self, key: str, value: Any) -> None:
        self._submit({"type": "set", "key": key, "value": value})

    def delete(self, key: str) -> None:
        self._submit({"type": "delete", "key": key})

    def clear(self) -> None:
        self._submit({"type": "clear"})

    def _submit(self, contents: dict) -> None:
        self._client_seq += 1
        self._pending.append(contents)
        self.outbox.append(
            UnsequencedMessage(
                client_id=self.client_id,
                client_seq=self._client_seq,
                ref_seq=self._ref_seq,
                type=MessageType.OP,
                contents=contents,
            )
        )

    def take_outbox(self) -> list[UnsequencedMessage]:
        out = self.outbox
        self.outbox = []
        return out

    # --------------------------------------------------------------- inbound
    def process(self, msg: SequencedMessage) -> None:
        self._ref_seq = msg.seq
        if msg.type != MessageType.OP:
            return
        if msg.client_id == self.client_id:
            pending = self._pending.popleft()
            assert pending["type"] == msg.contents["type"], "pending skew"
            self._apply(msg.contents)
        else:
            self._apply(msg.contents)

    def process_nack(self, nack: Nack) -> None:
        raise RuntimeError(
            f"map op nacked for {self.client_id!r}: {nack.reason}; "
            "reconnect/resubmit is required"
        )

    def _apply(self, op: dict) -> None:
        kind = op["type"]
        if kind == "set":
            self.sequenced[op["key"]] = op["value"]
        elif kind == "delete":
            self.sequenced.pop(op["key"], None)
        elif kind == "clear":
            self.sequenced.clear()
        else:
            raise ValueError(f"unknown map op {kind}")

    # ----------------------------------------------------------------- views
    def get(self, key: str) -> Any:
        """Optimistic local read: pending ops mask the sequenced state."""
        for op in reversed(self._pending):
            if op["type"] == "clear":
                return None
            if op.get("key") == key:
                return op["value"] if op["type"] == "set" else None
        return self.sequenced.get(key)

    def keys(self) -> set[str]:
        """Optimistic key set."""
        out = set(self.sequenced)
        for op in self._pending:  # in issue order
            if op["type"] == "set":
                out.add(op["key"])
            elif op["type"] == "delete":
                out.discard(op["key"])
            else:  # clear
                out.clear()
        return out

    def items(self) -> dict[str, Any]:
        return {k: self.get(k) for k in self.keys()}
