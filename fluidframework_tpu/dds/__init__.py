"""Host-side DDS implementations and pure-Python differential oracles.

The oracles (``*_ref.py``) implement the reference's convergence semantics
exactly, in plain Python, and serve as the differential-testing contract for
the TPU kernels in ``fluidframework_tpu.ops`` — the same role the TypeScript
implementations play for the reference's fuzz suites.
"""
