"""SharedMatrix: 2-D sparse matrix over two permutation merge-trees.

Reference parity: packages/dds/matrix/src/matrix.ts — rows and cols are
independent merge-tree permutation vectors whose elements are stable
*handles*; cells are stored by (rowHandle, colHandle); a set-cell op carries
(row, col) positions that each replica resolves to handles under the op's
perspective (matrix.ts adjustPosition in processMessagesCore:1010).  Cell
conflicts: LWW by sequence order, or FWW once switched
(shouldSetCellBasedOnFWW, matrix.ts:987 — a remote write loses iff another
client wrote the cell after the op's refSeq).

Handle allocation is deterministic-by-sequencing: every replica allocates
real handles when a row/col insert op is *applied in sequence order*, so all
replicas agree without the reference's handle-table ack machinery.  Local
pending inserts use provisional handles from a disjoint range, remapped when
the insert acks (the reference achieves the same with per-op handle metadata).

Permutation vectors reuse ``RefMergeTree`` with handles chr-encoded into the
segment text (a handle is a codepoint; capacity 0x80000 real + provisional).
"""

from __future__ import annotations

from collections import deque
from typing import Any

from ..protocol.messages import MessageType, Nack, SequencedMessage, UnsequencedMessage
from ..protocol.stamps import ALL_ACKED, encode_stamp
from .mergetree_ref import RefMergeTree

PROV_BASE = 0x80000  # provisional (pending-local) handle space


class _Perm:
    """A permutation vector: merge-tree of chr-encoded handles."""

    def __init__(self) -> None:
        self.tree = RefMergeTree()
        self.next_handle = 0
        self.next_prov = PROV_BASE

    def alloc(self, n: int) -> str:
        # Handles are codepoints; the marker plane (U+E000..U+F8FF,
        # dds/markers.py) is reserved and stripped by visible_text, so
        # allocation skips it — handles are opaque, gaps are free.
        from .markers import MARKER_CP_BASE, MARKER_CP_END

        out = []
        for _ in range(n):
            if MARKER_CP_BASE <= self.next_handle < MARKER_CP_END:
                self.next_handle = MARKER_CP_END
            out.append(chr(self.next_handle))
            self.next_handle += 1
        return "".join(out)

    def alloc_prov(self, n: int) -> str:
        h = self.next_prov
        self.next_prov += n
        return "".join(chr(h + i) for i in range(n))

    def handle_at(self, pos: int, ref_seq: int, view_client: int) -> int:
        text = self.tree.visible_text(ref_seq, view_client)
        if pos >= len(text):
            raise IndexError(f"position {pos} beyond permutation length {len(text)}")
        return ord(text[pos])

    def handles(self, ref_seq: int, view_client: int) -> list[int]:
        return [ord(c) for c in self.tree.visible_text(ref_seq, view_client)]

    def remap_acked(self, seq: int) -> dict[int, int]:
        """After ack rewrote stamps localSeq->seq, replace provisional
        handles in just-acked segments with real ones (allocation order =
        segment order = deterministic across replicas)."""
        mapping: dict[int, int] = {}
        for seg in self.tree.segments:
            if seg.ins_key == seq and seg.text and ord(seg.text[0]) >= PROV_BASE:
                real = self.alloc(len(seg.text))
                for old_ch, new_ch in zip(seg.text, real):
                    mapping[ord(old_ch)] = ord(new_ch)
                seg.text = real
        return mapping


class SharedMatrix:
    """One client replica of a collaborative sparse 2-D matrix."""

    def __init__(self, client_id: str) -> None:
        self.client_id = client_id
        self.short_client = -1
        self.rows = _Perm()
        self.cols = _Perm()
        # Consensus cell state: (rowHandle, colHandle) -> value
        self.cells: dict[tuple[int, int], Any] = {}
        # FWW tracker: (rh, ch) -> (seq, clientId) of last applied write
        self._last_write: dict[tuple[int, int], tuple[int, str]] = {}
        self._fww = False
        # Optimistic overlay: (rh, ch) -> list of pending local values
        self._pending_cells: dict[tuple[int, int], list[Any]] = {}
        self._pending: deque[tuple[str, Any]] = deque()  # (kind, metadata)
        self._quorum: dict[str, int] = {}
        self._client_seq = 0
        self._local_seq = 0
        self._ref_seq = 0
        self.outbox: list[UnsequencedMessage] = []

    # ---------------------------------------------------------------- helpers
    def _require_joined(self) -> None:
        if self.short_client < 0:
            raise RuntimeError(
                f"matrix client {self.client_id!r} cannot edit before join delivery"
            )

    def _submit(self, contents: dict, pending_meta: Any) -> None:
        self._client_seq += 1
        self._pending.append((contents["type"], pending_meta))
        self.outbox.append(
            UnsequencedMessage(
                client_id=self.client_id,
                client_seq=self._client_seq,
                ref_seq=self._ref_seq,
                type=MessageType.OP,
                contents=contents,
            )
        )

    def take_outbox(self) -> list[UnsequencedMessage]:
        out = self.outbox
        self.outbox = []
        return out

    # ------------------------------------------------------------ local edits
    def switch_to_fww(self) -> None:
        """Switch cell conflict policy to first-writer-wins (one-way,
        reference switchSetCellPolicy matrix.ts:210); broadcast via the
        fwwMode flag on subsequent set ops."""
        self._fww = True

    def insert_rows(self, pos: int, count: int) -> None:
        self._require_joined()
        assert count > 0
        self._local_seq += 1
        prov = self.rows.alloc_prov(count)
        self.rows.tree.apply_insert(
            pos, prov, encode_stamp(-1, self._local_seq), self.short_client, ALL_ACKED
        )
        self._submit(
            {"type": "insertRows", "pos": pos, "count": count},
            ("rows", self._local_seq),
        )

    def insert_cols(self, pos: int, count: int) -> None:
        self._require_joined()
        assert count > 0
        self._local_seq += 1
        prov = self.cols.alloc_prov(count)
        self.cols.tree.apply_insert(
            pos, prov, encode_stamp(-1, self._local_seq), self.short_client, ALL_ACKED
        )
        self._submit(
            {"type": "insertCols", "pos": pos, "count": count},
            ("cols", self._local_seq),
        )

    def remove_rows(self, pos: int, count: int) -> None:
        self._require_joined()
        self._local_seq += 1
        self.rows.tree.apply_remove(
            pos, pos + count, encode_stamp(-1, self._local_seq), self.short_client, ALL_ACKED
        )
        self._submit(
            {"type": "removeRows", "pos": pos, "count": count},
            ("rows", self._local_seq),
        )

    def remove_cols(self, pos: int, count: int) -> None:
        self._require_joined()
        self._local_seq += 1
        self.cols.tree.apply_remove(
            pos, pos + count, encode_stamp(-1, self._local_seq), self.short_client, ALL_ACKED
        )
        self._submit(
            {"type": "removeCols", "pos": pos, "count": count},
            ("cols", self._local_seq),
        )

    def set_cell(self, row: int, col: int, value: Any) -> None:
        self._require_joined()
        rh = self.rows.handle_at(row, ALL_ACKED, self.short_client)
        ch = self.cols.handle_at(col, ALL_ACKED, self.short_client)
        self._pending_cells.setdefault((rh, ch), []).append(value)
        self._submit(
            {"type": "set", "row": row, "col": col, "value": value,
             "fwwMode": self._fww},
            ("cell", (rh, ch)),
        )

    # ---------------------------------------------------------------- inbound
    def process(self, msg: SequencedMessage) -> None:
        if msg.type == MessageType.JOIN:
            self._quorum[msg.contents["clientId"]] = msg.contents["short"]
            if msg.client_id == self.client_id and self.short_client < 0:
                self.short_client = msg.contents["short"]
            self._ref_seq = msg.seq
            return
        if msg.type != MessageType.OP:
            self._ref_seq = msg.seq
            return
        if msg.client_id == self.client_id:
            self._ack(msg)
        else:
            self._apply_remote(msg)
        self._ref_seq = msg.seq
        self.rows.tree.update_min_seq(msg.min_seq)
        self.cols.tree.update_min_seq(msg.min_seq)

    def process_nack(self, nack: Nack) -> None:
        raise RuntimeError(
            f"matrix op nacked for {self.client_id!r}: {nack.reason}; "
            "reconnect/resubmit is required"
        )

    def _remap_cells(self, mapping: dict[int, int], axis: int) -> None:
        if not mapping:
            return
        for store in (self.cells, self._last_write, self._pending_cells):
            for key in [k for k in store if k[axis] in mapping]:
                new_key = (
                    (mapping[key[0]], key[1]) if axis == 0 else (key[0], mapping[key[1]])
                )
                store[new_key] = store.pop(key)
        # Pending set-op metadata also references handles by value.
        remapped = deque()
        for kind, meta in self._pending:
            if kind == "set":
                rh, ch = meta[1]
                if axis == 0 and rh in mapping:
                    rh = mapping[rh]
                elif axis == 1 and ch in mapping:
                    ch = mapping[ch]
                meta = ("cell", (rh, ch))
            remapped.append((kind, meta))
        self._pending = remapped

    def _ack(self, msg: SequencedMessage) -> None:
        kind, meta = self._pending.popleft()
        c = msg.contents
        if kind in ("insertRows", "insertCols", "removeRows", "removeCols"):
            axis_name, local_seq = meta
            perm = self.rows if axis_name == "rows" else self.cols
            perm.tree.ack(local_seq, msg.seq)
            if kind.startswith("insert"):
                mapping = perm.remap_acked(msg.seq)
                self._remap_cells(mapping, 0 if axis_name == "rows" else 1)
        elif kind == "set":
            rh, ch = meta[1]
            pending = self._pending_cells.get((rh, ch))
            assert pending, "cell ack without pending write"
            value = pending.pop(0)
            if not pending:
                del self._pending_cells[(rh, ch)]
            if self._should_set(rh, ch, msg):
                self.cells[(rh, ch)] = value
                self._last_write[(rh, ch)] = (msg.seq, msg.client_id)
        else:
            raise ValueError(f"unknown matrix ack kind {kind}")

    def _should_set(self, rh: int, ch: int, msg: SequencedMessage) -> bool:
        if msg.contents.get("fwwMode") and not self._fww:
            self._fww = True
        if not self._fww:
            return True  # LWW: sequence order decides
        last = self._last_write.get((rh, ch))
        return last is None or last[1] == msg.client_id or msg.ref_seq >= last[0]

    def _apply_remote(self, msg: SequencedMessage) -> None:
        c = msg.contents
        kind = c["type"]
        client = self._quorum[msg.client_id]
        key = msg.seq
        if kind == "insertRows":
            self.rows.tree.apply_insert(
                c["pos"], self.rows.alloc(c["count"]), key, client, msg.ref_seq
            )
        elif kind == "insertCols":
            self.cols.tree.apply_insert(
                c["pos"], self.cols.alloc(c["count"]), key, client, msg.ref_seq
            )
        elif kind == "removeRows":
            self.rows.tree.apply_remove(
                c["pos"], c["pos"] + c["count"], key, client, msg.ref_seq
            )
        elif kind == "removeCols":
            self.cols.tree.apply_remove(
                c["pos"], c["pos"] + c["count"], key, client, msg.ref_seq
            )
        elif kind == "set":
            rh = self.rows.handle_at(c["row"], msg.ref_seq, client)
            ch = self.cols.handle_at(c["col"], msg.ref_seq, client)
            if self._should_set(rh, ch, msg):
                self.cells[(rh, ch)] = c["value"]
                self._last_write[(rh, ch)] = (msg.seq, msg.client_id)
        else:
            raise ValueError(f"unknown matrix op {kind}")

    # ------------------------------------------------------------------ views
    @property
    def row_count(self) -> int:
        return len(self.rows.handles(ALL_ACKED, self.short_client))

    @property
    def col_count(self) -> int:
        return len(self.cols.handles(ALL_ACKED, self.short_client))

    def get_cell(self, row: int, col: int) -> Any:
        """Optimistic read: pending local writes mask consensus."""
        rh = self.rows.handle_at(row, ALL_ACKED, self.short_client)
        ch = self.cols.handle_at(col, ALL_ACKED, self.short_client)
        pending = self._pending_cells.get((rh, ch))
        if pending:
            return pending[-1]
        return self.cells.get((rh, ch))

    def to_grid(self) -> list[list[Any]]:
        """Materialized consensus-perspective grid (for convergence tests)."""
        rows = self.rows.handles(ALL_ACKED, self.short_client)
        cols = self.cols.handles(ALL_ACKED, self.short_client)
        return [[self.cells.get((rh, ch)) for ch in cols] for rh in rows]


# ---------------------------------------------------------------------------
# Channel-boundary form
# ---------------------------------------------------------------------------

from ..protocol.channel import Channel, MessageCollection  # noqa: E402


class SharedMatrixChannel(Channel):
    """SharedMatrix over the channel boundary (ref SharedMatrixClass,
    matrix/src/matrix.ts): two permutation-vector merge-trees (rows/cols)
    plus a sparse consensus cell store with LWW or switchable FWW conflict
    policy. Reconnect regenerates row/col ops through the permutation trees
    (regeneratePendingOp) and re-anchors pending cell writes by handle.

    Local metadata per pending op:
      {"axis": "rows"|"cols", "localSeq": n}   for insert/remove ops
      {"cell": [rh, ch]}                       for set ops
    """

    channel_type = "sharedMatrix"

    def __init__(self, channel_id: str) -> None:
        super().__init__(channel_id)
        self.rows = _Perm()
        self.cols = _Perm()
        self.cells: dict[tuple[int, int], Any] = {}
        self._last_write: dict[tuple[int, int], tuple[int, str]] = {}
        self._fww = False
        self._pending_cells: dict[tuple[int, int], list[Any]] = {}
        self._local_seq = 0
        # Metadata dicts minted for in-flight set ops: shared by reference
        # with the PendingStateManager, remapped in place when provisional
        # handles become real (insert ack).
        self._minted_md: list[dict] = []

    def _next_ls(self) -> int:
        self._local_seq += 1
        return self._local_seq

    def _perm(self, axis: str) -> _Perm:
        return self.rows if axis == "rows" else self.cols

    # ------------------------------------------------------------ local edits
    def switch_to_fww(self) -> None:
        self._fww = True

    def _insert(self, axis: str, pos: int, count: int) -> None:
        assert count > 0
        ls = self._next_ls()
        perm = self._perm(axis)
        perm.tree.apply_insert(
            pos, perm.alloc_prov(count), encode_stamp(-1, ls),
            perm.tree.local_client, ALL_ACKED,
        )
        op = "insertRows" if axis == "rows" else "insertCols"
        self.submit_local_message(
            {"type": op, "pos": pos, "count": count},
            {"axis": axis, "localSeq": ls},
        )

    def _remove(self, axis: str, pos: int, count: int) -> None:
        ls = self._next_ls()
        perm = self._perm(axis)
        perm.tree.apply_remove(
            pos, pos + count, encode_stamp(-1, ls), perm.tree.local_client, ALL_ACKED
        )
        op = "removeRows" if axis == "rows" else "removeCols"
        self.submit_local_message(
            {"type": op, "pos": pos, "count": count},
            {"axis": axis, "localSeq": ls},
        )

    def insert_rows(self, pos: int, count: int) -> None:
        self._insert("rows", pos, count)

    def insert_cols(self, pos: int, count: int) -> None:
        self._insert("cols", pos, count)

    def remove_rows(self, pos: int, count: int) -> None:
        self._remove("rows", pos, count)

    def remove_cols(self, pos: int, count: int) -> None:
        self._remove("cols", pos, count)

    def set_cell(self, row: int, col: int, value: Any) -> None:
        rh = self.rows.handle_at(row, ALL_ACKED, self.rows.tree.local_client)
        ch = self.cols.handle_at(col, ALL_ACKED, self.cols.tree.local_client)
        self._pending_cells.setdefault((rh, ch), []).append(value)
        md = {"cell": [rh, ch]}
        self._minted_md.append(md)
        self.submit_local_message(
            {"type": "set", "row": row, "col": col, "value": value, "fwwMode": self._fww},
            md,
        )

    # ---------------------------------------------------------------- inbound
    def _remap_cells(self, mapping: dict[int, int], axis: int) -> None:
        if not mapping:
            return
        for store in (self.cells, self._last_write, self._pending_cells):
            for key in [k for k in store if k[axis] in mapping]:
                nk = (mapping[key[0]], key[1]) if axis == 0 else (key[0], mapping[key[1]])
                store[nk] = store.pop(key)

    def _should_set(self, rh: int, ch: int, seq: int, ref_seq: int, client: str) -> bool:
        if not self._fww:
            return True  # LWW: sequence order decides
        last = self._last_write.get((rh, ch))
        return last is None or last[1] == client or ref_seq >= last[0]

    def process_messages(self, collection: MessageCollection) -> None:
        env = collection.envelope
        for m in collection.messages:
            c = m.contents
            if c.get("fwwMode"):
                self._fww = True  # one-way switch broadcast (matrix.ts:210)
            if m.local:
                self._ack(c, m.local_metadata, env)
            else:
                self._apply_remote(c, env)
        for perm in (self.rows, self.cols):
            perm.tree.update_min_seq(env.min_seq)

    def _ack(self, c: dict, md: dict, env) -> None:
        if "axis" in md:
            perm = self._perm(md["axis"])
            perm.tree.ack(md["localSeq"], env.seq)
            if c["type"].startswith("insert"):
                mapping = perm.remap_acked(env.seq)
                self._remap_cells(mapping, 0 if md["axis"] == "rows" else 1)
                # Re-key pending metadata is unnecessary: channel metadata
                # holds handle VALUES only for cell ops, remapped above via
                # _pending_cells; later acks look up by (rh, ch) post-remap.
                self._md_remap(mapping, 0 if md["axis"] == "rows" else 1)
        else:
            rh, ch = md["cell"]
            if md in self._minted_md:
                self._minted_md.remove(md)
            pending = self._pending_cells.get((rh, ch))
            assert pending, "cell ack without pending write"
            value = pending.pop(0)
            if not pending:
                del self._pending_cells[(rh, ch)]
            if self._should_set(rh, ch, env.seq, env.ref_seq, env.client_id):
                self.cells[(rh, ch)] = value
                self._last_write[(rh, ch)] = (env.seq, env.client_id)

    def _md_remap(self, mapping: dict[int, int], axis: int) -> None:
        """In-flight set-op metadata references provisional handles; the
        dicts are shared by reference with the PendingStateManager, so remap
        them in place."""
        for md in self._minted_md:
            rh, ch = md["cell"]
            if axis == 0 and rh in mapping:
                md["cell"][0] = mapping[rh]
            elif axis == 1 and ch in mapping:
                md["cell"][1] = mapping[ch]

    def _apply_remote(self, c: dict, env) -> None:
        client = self._connection.short_id(env.client_id)
        kind = c["type"]
        key = env.seq
        if kind == "insertRows":
            self.rows.tree.apply_insert(
                c["pos"], self.rows.alloc(c["count"]), key, client, env.ref_seq
            )
        elif kind == "insertCols":
            self.cols.tree.apply_insert(
                c["pos"], self.cols.alloc(c["count"]), key, client, env.ref_seq
            )
        elif kind == "removeRows":
            self.rows.tree.apply_remove(
                c["pos"], c["pos"] + c["count"], key, client, env.ref_seq
            )
        elif kind == "removeCols":
            self.cols.tree.apply_remove(
                c["pos"], c["pos"] + c["count"], key, client, env.ref_seq
            )
        elif kind == "set":
            rh = self.rows.handle_at(c["row"], env.ref_seq, client)
            ch = self.cols.handle_at(c["col"], env.ref_seq, client)
            if self._should_set(rh, ch, env.seq, env.ref_seq, env.client_id):
                self.cells[(rh, ch)] = c["value"]
                self._last_write[(rh, ch)] = (env.seq, env.client_id)
        else:
            raise ValueError(f"unknown matrix op {kind!r}")

    # ----------------------------------------------------- reconnect / stash
    def resubmit(self, contents: Any, local_metadata: Any, squash: bool = False) -> None:
        if "axis" in local_metadata:
            axis = local_metadata["axis"]
            perm = self._perm(axis)
            regenerated = perm.tree.regenerate_pending(
                local_metadata["localSeq"], self._next_ls, squash=squash
            )
            for fresh_ls, op in regenerated:
                if op["type"] == 0:  # merge-tree insert -> matrix insert
                    out = {
                        "type": "insertRows" if axis == "rows" else "insertCols",
                        "pos": op["pos1"],
                        "count": len(op["seg"]),
                    }
                else:  # remove
                    out = {
                        "type": "removeRows" if axis == "rows" else "removeCols",
                        "pos": op["pos1"],
                        "count": op["pos2"] - op["pos1"],
                    }
                self.submit_local_message(out, {"axis": axis, "localSeq": fresh_ls})
            return
        # Cell set: re-anchor by handle in the current local view; a write
        # into a removed row/col drops (reference setCell resubmit).
        rh, ch = local_metadata["cell"]
        rows = self.rows.handles(ALL_ACKED, self.rows.tree.local_client)
        cols = self.cols.handles(ALL_ACKED, self.cols.tree.local_client)
        if rh not in rows or ch not in cols:
            pending = self._pending_cells.get((rh, ch))
            if pending:
                pending.pop(0)
                if not pending:
                    del self._pending_cells[(rh, ch)]
            return
        md = {"cell": [rh, ch]}
        self._minted_md.append(md)
        self.submit_local_message(
            {
                "type": "set",
                "row": rows.index(rh),
                "col": cols.index(ch),
                "value": contents["value"],
                "fwwMode": self._fww,
            },
            md,
        )

    def apply_stashed(self, contents: Any) -> Any:
        c = contents
        kind = c["type"]
        if kind in ("insertRows", "insertCols", "removeRows", "removeCols"):
            axis = "rows" if "Rows" in kind else "cols"
            perm = self._perm(axis)
            ls = self._next_ls()
            if kind.startswith("insert"):
                perm.tree.apply_insert(
                    c["pos"], perm.alloc_prov(c["count"]),
                    encode_stamp(-1, ls), perm.tree.local_client, ALL_ACKED,
                )
            else:
                perm.tree.apply_remove(
                    c["pos"], c["pos"] + c["count"],
                    encode_stamp(-1, ls), perm.tree.local_client, ALL_ACKED,
                )
            return {"axis": axis, "localSeq": ls}
        rh = self.rows.handle_at(c["row"], ALL_ACKED, self.rows.tree.local_client)
        ch = self.cols.handle_at(c["col"], ALL_ACKED, self.cols.tree.local_client)
        self._pending_cells.setdefault((rh, ch), []).append(c["value"])
        md = {"cell": [rh, ch]}
        self._minted_md.append(md)
        return md

    # ------------------------------------------------------------ checkpoint
    def summarize(self) -> dict[str, Any]:
        for perm in (self.rows, self.cols):
            for seg in perm.tree.segments:
                if not acked_key(seg.ins_key) or any(
                    not acked_key(k) for k, _c in seg.removes
                ):
                    raise RuntimeError("summarize with pending matrix state")
        if self._pending_cells:
            raise RuntimeError("summarize with pending matrix cell writes")

        def perm_summary(perm: _Perm) -> dict:
            return {
                "segments": [
                    {
                        "handles": [ord(c) for c in s.text],
                        "ins": [s.ins_key, s.ins_client],
                        "removes": [[k, c] for k, c in s.removes],
                    }
                    for s in perm.tree.segments
                ],
                "minSeq": perm.tree.min_seq,
                "nextHandle": perm.next_handle,
            }

        return {
            "rows": perm_summary(self.rows),
            "cols": perm_summary(self.cols),
            "cells": [[list(k), v] for k, v in self.cells.items()],
            "lastWrite": [[list(k), list(v)] for k, v in self._last_write.items()],
            "fww": self._fww,
        }

    def load(self, summary: dict[str, Any]) -> None:
        from .mergetree_ref import Segment

        def load_perm(perm: _Perm, data: dict) -> None:
            perm.tree.min_seq = data["minSeq"]
            perm.next_handle = data["nextHandle"]
            perm.tree.segments = [
                Segment(
                    text="".join(chr(h) for h in e["handles"]),
                    ins_key=e["ins"][0],
                    ins_client=e["ins"][1],
                    removes=[(k, c) for k, c in e["removes"]],
                )
                for e in data["segments"]
            ]

        load_perm(self.rows, summary["rows"])
        load_perm(self.cols, summary["cols"])
        self.cells = {tuple(k): v for k, v in summary["cells"]}
        self._last_write = {tuple(k): tuple(v) for k, v in summary["lastWrite"]}
        self._fww = summary["fww"]

    # ------------------------------------------------------------------ views
    @property
    def row_count(self) -> int:
        return len(self.rows.handles(ALL_ACKED, self.rows.tree.local_client))

    @property
    def col_count(self) -> int:
        return len(self.cols.handles(ALL_ACKED, self.cols.tree.local_client))

    def get_cell(self, row: int, col: int) -> Any:
        rh = self.rows.handle_at(row, ALL_ACKED, self.rows.tree.local_client)
        ch = self.cols.handle_at(col, ALL_ACKED, self.cols.tree.local_client)
        pending = self._pending_cells.get((rh, ch))
        if pending:
            return pending[-1]
        return self.cells.get((rh, ch))

    def to_grid(self) -> list[list[Any]]:
        rows = self.rows.handles(ALL_ACKED, self.rows.tree.local_client)
        cols = self.cols.handles(ALL_ACKED, self.cols.tree.local_client)
        return [[self.cells.get((rh, ch)) for ch in cols] for rh in rows]


from ..protocol.stamps import acked as acked_key  # noqa: E402


class _MatrixFactory:
    channel_type = SharedMatrixChannel.channel_type

    def create(self, channel_id: str) -> SharedMatrixChannel:
        return SharedMatrixChannel(channel_id)


SharedMatrixFactory = _MatrixFactory()
