"""SharedJson1: the sharejs ot-json1 WIRE-compatible OT type.

Reference parity: `experimental/dds/ot/sharejs/json1/src/json1.ts:28`
(SharedJson1 over the npm ``ot-json1`` library) — the reference's own code
is a thin wrapper; the OT type there lives in the library.  Here the type
is implemented from scratch against ot-json1's documented operation
format, so wire ops interoperate:

- an op is a DESCENT LIST: scalar parts descend (object key / list
  index), dict parts are components at the current path, nested lists are
  sibling branches from the current path;
- components: ``{"i": value}`` insert, ``{"r": value-or-true}`` remove,
  ``{"r":…, "i":…}`` replace, ``{"p": slot}`` pick up, ``{"d": slot}``
  drop (a pick/drop pair is a move);
- apply is two-phase: picks/removes first (right-to-left, so sibling
  list indices stay stable), then drops/inserts (left-to-right) against
  the post-pick document — drop/insert paths read in that context.

Embedded edits (``e``/``es``/``ena`` subtypes) are not supported (raise);
this repo's SharedString is the rich-text surface.

Transform: single-target ops translate onto the repo's JSON OT algebra
(dds/ot.py — annihilation, list shifts, left priority) and translate
back, so the transform laws there carry over.  Ops containing moves
transform conservatively: a move rebased over an overlapping concurrent
op drops (the reference's transformNoConflict likewise refuses genuinely
conflicting moves); a concurrent MOVE transforms later ops as its
remove+insert decomposition.
"""

from __future__ import annotations

import json
from typing import Any

from .ot import SharedOTChannel, _apply_json, _transform_json

Path = tuple


# ------------------------------------------------------------------ builders


def insert_op(path: list, value: Any) -> list:
    return [*path, {"i": value}]


def remove_op(path: list, value: Any = True) -> list:
    return [*path, {"r": value}]


def replace_op(path: list, old: Any, new: Any) -> list:
    return [*path, {"r": old, "i": new}]


def move_op(src: list, dst: list) -> list:
    """ot-json1 moveOp: shared-prefix descent with a pick and a drop
    branch."""
    k = 0
    while k < len(src) and k < len(dst) and src[k] == dst[k]:
        k += 1
    prefix, s_rest, d_rest = list(src[:k]), list(src[k:]), list(dst[k:])
    return [*prefix, [*s_rest, {"p": 0}], [*d_rest, {"d": 0}]]


# -------------------------------------------------------------------- parse


def flatten(op: list | None) -> list[tuple[Path, dict]]:
    """Descent list -> [(path, component)] in document order."""
    if op is None:
        return []
    out: list[tuple[Path, dict]] = []

    def walk(parts: list, path: tuple) -> None:
        cur = list(path)
        for part in parts:
            if isinstance(part, (str, int)):
                cur.append(part)
            elif isinstance(part, dict):
                out.append((tuple(cur), part))
            elif isinstance(part, list):
                walk(part, tuple(cur))
            else:
                raise ValueError(f"bad op part {part!r}")

    walk(op, ())
    return out


def _get(node: Any, path: Path) -> Any:
    for part in path:
        node = node[part]
    return node


def _set_at(state: Any, path: Path, value: Any, insert: bool) -> Any:
    return _apply_json(
        state, {"t": "insert" if insert else "replace", "p": list(path), "v": value}
    )


def _remove_at(state: Any, path: Path) -> Any:
    return _apply_json(state, {"t": "remove", "p": list(path)})


def apply_json1(state: Any, op: list | None) -> Any:
    """Two-phase json1 apply (see module docstring)."""
    entries = flatten(op)
    for _p, comp in entries:
        if "e" in comp or "es" in comp or "ena" in comp:
            raise NotImplementedError("json1 embedded edits unsupported")
    slots: dict[int, Any] = {}
    # Phase 1: removes and pick-ups, right-to-left.
    for path, comp in reversed(entries):
        if "p" in comp:
            slots[comp["p"]] = _get(state, path)
            state = _remove_at(state, path)
        elif "r" in comp:
            if not path:
                state = None
            else:
                state = _remove_at(state, path)
    # Phase 2: inserts and drops, left-to-right (post-pick coordinates).
    for path, comp in entries:
        if "d" in comp:
            value = slots.pop(comp["d"])
            state = value if not path else _set_at(state, path, value, insert=True)
        elif "i" in comp:
            v = comp["i"]
            if not path:
                state = v
            else:
                state = _set_at(state, path, v, insert=True)
    return state


# ---------------------------------------------------------------- transform


def _to_internal(op: list | None) -> dict | None | str:
    """Single-target json1 op -> internal JSON OT op; "move" when the op
    contains pick/drop components; "multi" for multi-target branch ops
    (these APPLY fine but transform conservatively — see
    transform_json1)."""
    entries = flatten(op)
    if not entries:
        return None
    if any("p" in c or "d" in c for _p, c in entries):
        return "move"
    if len(entries) != 1:
        return "multi"
    path, comp = entries[0]
    if "r" in comp and "i" in comp:
        return {"t": "replace", "p": list(path), "v": comp["i"]}
    if "i" in comp:
        return {"t": "insert", "p": list(path), "v": comp["i"]}
    if "r" in comp:
        return {"t": "remove", "p": list(path)}
    return "multi"  # unknown component: conservative, never crash


def _to_json1(op: dict | None) -> list | None:
    if op is None:
        return None
    t, path, v = op["t"], op["p"], op.get("v")
    if t == "insert":
        return insert_op(path, v)
    if t == "remove":
        return remove_op(path)
    return replace_op(path, True, v)


def _move_decomposition(op: list) -> list[dict]:
    """A move op as its remove+insert internal pair (for transforming
    OTHER ops over a sequenced move)."""
    out = []
    for path, comp in flatten(op):
        if "p" in comp or "r" in comp:
            out.append({"t": "remove", "p": list(path)})
    for path, comp in flatten(op):
        if "d" in comp or "i" in comp:
            out.append({"t": "insert", "p": list(path), "v": comp.get("i")})
    return out


def transform_json1(input_op: list | None, earlier: list | None) -> list | None:
    if input_op is None or earlier is None:
        return input_op
    ikind = _to_internal(input_op)
    ekind = _to_internal(earlier)
    if ikind == "multi" or ekind == "multi":
        # Multi-target branch ops apply, but transforming sequential op
        # programs against each other needs the two-sided bridge this
        # windowed model does not carry; refusing deterministically (every
        # replica drops the same later-sequenced op) keeps state identical
        # — same policy as conflicting moves.
        return None
    if ikind == "move":
        if ekind == "move":
            # Concurrent moves: refuse rather than guess (ot-json1
            # transformNoConflict raises on real conflicts; every replica
            # drops the same later-sequenced op, so state stays identical).
            return None
        # Earlier single-target op (multi handled above): carry each move
        # path through it — pick paths with ELEMENT semantics (an earlier
        # remove/replace of the picked node voids the whole move), drop
        # paths with BOUNDARY semantics (they name a gap and just shift).
        parts = []
        for path, comp in flatten(input_op):
            element = "p" in comp or "r" in comp
            shifted = _transform_json(
                {"t": "remove" if element else "insert", "p": list(path)},
                ekind,
            )
            if shifted is None:
                return None
            parts.append((tuple(shifted["p"]), comp))
        out: list = []
        for path, comp in parts:
            out.append([*path, comp])
        return out if len(out) > 1 else [*parts[0][0], parts[0][1]]
    if ekind == "move":
        x: dict | None = ikind
        for e in _move_decomposition(earlier):
            if x is None:
                return None
            x = _transform_json(x, e)
        return _to_json1(x)
    return _to_json1(_transform_json(ikind, ekind))


# ------------------------------------------------------------------ channel


class SharedJson1Channel(SharedOTChannel):
    """The sharejs-json1-compatible DDS (ref json1.ts:28)."""

    channel_type = "sharedJson1"

    def __init__(self, channel_id: str) -> None:
        # RATIONALE (matching the reference): undefined is not preserved
        # by JSON.stringify, so the initial doc is null.
        super().__init__(channel_id, initial=None)

    def apply_core(self, state: Any, op: list | None) -> Any:
        return apply_json1(state, op)

    def transform(self, input_op, earlier):
        return transform_json1(input_op, earlier)

    # ------------------------------------------------------------ public API
    def get(self) -> Any:
        return self.state

    def insert(self, path: list, value: Any) -> None:
        json.dumps(value)  # wire-serializable guard
        self.apply(insert_op(path, value))

    def move(self, src: list, dst: list) -> None:
        self.apply(move_op(src, dst))

    def remove(self, path: list, value: Any = True) -> None:
        self.apply(remove_op(path, value))

    def replace(self, path: list, old: Any, new: Any) -> None:
        json.dumps(new)
        self.apply(replace_op(path, old, new))


class _Json1Factory:
    channel_type = SharedJson1Channel.channel_type

    def create(self, channel_id: str) -> SharedJson1Channel:
        return SharedJson1Channel(channel_id)


SharedJson1Factory = _Json1Factory()
