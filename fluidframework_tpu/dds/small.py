"""The small DDS family: cell, counter, and the consensus DDSes.

Reference parity (SURVEY.md §2.1 "small DDSes" row):

- SharedCell      packages/dds/cell/src/cell.ts — single-value LWW with
                  optimistic pending overlay (a one-key SharedMap).
- SharedCounter   packages/dds/counter/src/counter.ts — commutative
                  increments; value = sequenced sum + pending sum.
- ConsensusQueue  packages/dds/ordered-collection/src/consensusOrderedCollection.ts
                  — ack-gated distributed queue: state changes ONLY on
                  sequenced ops; acquired items are tracked per client and
                  re-queued when that client leaves.
- ConsensusRegisterCollection
                  packages/dds/register-collection/src/consensusRegisterCollection.ts
                  — per-key register keeping all concurrent versions; a
                  write "wins" (atomic update) iff its refSeq saw the
                  previous atomic write.
- TaskManager     packages/dds/task-manager/src/taskManager.ts — per-task
                  volunteer queues; queue head holds the task; leaves
                  evict; complete clears the queue.
- PactMap         packages/dds/pact-map/src/pactMap.ts — consensus KV: a
                  set proposal becomes accepted only after explicit accept
                  ops from every client connected at proposal time (leaves
                  count as implicit signoff).

These are host-side control-plane DDSes: low op volume, consensus-gated —
the TPU payoff lives in the bulk DDSes (string/map/matrix/tree kernels).
All are channels (runtime/channel.py) and resubmit verbatim on reconnect:
every op here is position-free (their conflict rules are seq/refSeq based,
which the sequencer re-stamps on resubmission).
"""

from __future__ import annotations

import uuid as _uuid
from dataclasses import dataclass, field
from typing import Any, Callable

from ..protocol.channel import Channel, MessageCollection
from .channels import ChannelTypeFactory, PendingOverlayChannel


class _VerbatimResubmitChannel(Channel):
    """Base for position-free DDSes: resubmit re-sends contents unchanged.

    Stashed ops apply no optimistic state (consensus semantics — nothing
    changes until sequencing); rehydrate just re-enters them into the pending
    queue for verbatim resubmission, with any local completion handles
    resolving as unavailable (ref consensusOrderedCollection.ts:438 — stashed
    ops are resubmitted with a no-op resolve)."""

    def resubmit(self, contents: Any, local_metadata: Any, squash: bool = False) -> None:
        self.submit_local_message(contents, local_metadata)

    def apply_stashed(self, contents: Any) -> Any:
        return None


# ---------------------------------------------------------------------------
# SharedCell
# ---------------------------------------------------------------------------

class SharedCell(PendingOverlayChannel):
    """Single collaborative value, LWW, with optimistic local overlay —
    a one-key SharedMap, sharing its pending-overlay machinery."""

    channel_type = "sharedCell"

    def __init__(self, channel_id: str) -> None:
        super().__init__(channel_id)
        self.sequenced_value: Any = None
        self.sequenced_empty = True

    def set(self, value: Any) -> None:
        self._submit({"type": "setCell", "value": value})

    def delete(self) -> None:
        self._submit({"type": "deleteCell"})

    def _apply(self, op: dict) -> None:
        if op["type"] == "setCell":
            self.sequenced_value, self.sequenced_empty = op["value"], False
        elif op["type"] == "deleteCell":
            self.sequenced_value, self.sequenced_empty = None, True
        else:
            raise ValueError(f"unknown cell op {op['type']}")

    def get(self) -> Any:
        if self._pending:
            op = self._pending[-1][1]
            return op["value"] if op["type"] == "setCell" else None
        return self.sequenced_value

    @property
    def empty(self) -> bool:
        if self._pending:
            return self._pending[-1][1]["type"] == "deleteCell"
        return self.sequenced_empty

    def summarize(self) -> dict[str, Any]:
        return {"value": self.sequenced_value, "empty": self.sequenced_empty}

    def load(self, summary: dict[str, Any]) -> None:
        self.sequenced_value = summary["value"]
        self.sequenced_empty = summary["empty"]


# ---------------------------------------------------------------------------
# SharedCounter
# ---------------------------------------------------------------------------

class SharedCounter(_VerbatimResubmitChannel):
    """Commutative integer counter (counter.ts): all increments apply; the
    local view adds unacked pending increments to the sequenced sum."""

    channel_type = "sharedCounter"

    def __init__(self, channel_id: str) -> None:
        super().__init__(channel_id)
        self.sequenced_value = 0
        self._pending_sum = 0

    def increment(self, delta: int) -> None:
        if not isinstance(delta, int):
            raise TypeError("SharedCounter increments must be integers")
        self._pending_sum += delta
        self.submit_local_message({"type": "increment", "incrementAmount": delta})

    def process_messages(self, collection: MessageCollection) -> None:
        for m in collection.messages:
            delta = m.contents["incrementAmount"]
            self.sequenced_value += delta
            if m.local:
                self._pending_sum -= delta

    def apply_stashed(self, contents: Any) -> Any:
        self._pending_sum += contents["incrementAmount"]
        return None

    def rollback(self, contents: Any, local_metadata: Any) -> None:
        self._pending_sum -= contents["incrementAmount"]

    @property
    def value(self) -> int:
        return self.sequenced_value + self._pending_sum

    def summarize(self) -> dict[str, Any]:
        return {"value": self.sequenced_value}

    def load(self, summary: dict[str, Any]) -> None:
        self.sequenced_value = summary["value"]


# ---------------------------------------------------------------------------
# ConsensusQueue (ordered collection)
# ---------------------------------------------------------------------------

@dataclass
class AcquireHandle:
    """Resolves when the acquire op is sequenced (ref acquire() promise)."""

    acquire_id: str
    value: Any = None
    acquired: bool = False  # sequenced AND an item was available
    settled: bool = False   # sequenced (either way)


class ConsensusQueue(_VerbatimResubmitChannel):
    """Ack-gated FIFO: nothing changes until ops sequence (no optimistic
    apply — consensus semantics, consensusOrderedCollection.ts)."""

    channel_type = "consensusQueue"

    def __init__(self, channel_id: str) -> None:
        super().__init__(channel_id)
        self.data: list[Any] = []
        # acquireId -> (value, clientId) for in-flight acquired items.
        self.job_tracking: dict[str, tuple[Any, str]] = {}
        self._handles: dict[str, AcquireHandle] = {}

    # ------------------------------------------------------------------- api
    def add(self, value: Any) -> None:
        self.submit_local_message({"opName": "add", "value": value})

    def acquire(self) -> AcquireHandle:
        """Request the head item; resolves at sequencing (consensus).

        The acquire id is a fresh UUID (ref consensusOrderedCollection.ts:411)
        — NOT derived from the client id, which is None for detached
        containers and would collide across clients acquiring pre-connect."""
        acquire_id = _uuid.uuid4().hex
        handle = AcquireHandle(acquire_id)
        self._handles[acquire_id] = handle
        self.submit_local_message({"opName": "acquire", "acquireId": acquire_id})
        return handle

    def complete(self, handle: AcquireHandle) -> None:
        assert handle.acquired
        self.submit_local_message({"opName": "complete", "acquireId": handle.acquire_id})

    def release(self, handle: AcquireHandle) -> None:
        assert handle.acquired
        self.submit_local_message({"opName": "release", "acquireId": handle.acquire_id})

    # --------------------------------------------------------------- inbound
    def process_messages(self, collection: MessageCollection) -> None:
        env = collection.envelope
        for m in collection.messages:
            op = m.contents
            name = op["opName"]
            if name == "add":
                self.data.append(op["value"])
            elif name == "acquire":
                self._acquire_core(op["acquireId"], env.client_id, m.local)
            elif name == "complete":
                self.job_tracking.pop(op["acquireId"], None)
            elif name == "release":
                entry = self.job_tracking.pop(op["acquireId"], None)
                if entry is not None:
                    self.data.append(entry[0])
            else:
                raise ValueError(f"unknown ordered-collection op {name}")

    def _acquire_core(self, acquire_id: str, client_id: str, local: bool) -> None:
        value_available = bool(self.data)
        if value_available:
            value = self.data.pop(0)
            self.job_tracking[acquire_id] = (value, client_id)
        if local:
            handle = self._handles.pop(acquire_id, None)
            if handle is not None:
                handle.settled = True
                if value_available:
                    handle.acquired = True
                    handle.value = value

    def on_client_leave(self, client_id: str, seq: int) -> None:
        # Re-queue everything the departed client had acquired (removeClient).
        for aid, (value, holder) in list(self.job_tracking.items()):
            if holder == client_id:
                del self.job_tracking[aid]
                self.data.append(value)

    def summarize(self) -> dict[str, Any]:
        return {"data": list(self.data), "jobs": {k: list(v) for k, v in self.job_tracking.items()}}

    def load(self, summary: dict[str, Any]) -> None:
        self.data = list(summary["data"])
        self.job_tracking = {k: (v[0], v[1]) for k, v in summary["jobs"].items()}


# ---------------------------------------------------------------------------
# ConsensusRegisterCollection
# ---------------------------------------------------------------------------

ATOMIC = "atomic"
LWW = "lww"


@dataclass
class _Register:
    atomic_value: Any
    atomic_seq: int
    versions: list[tuple[int, Any]] = field(default_factory=list)  # (seq, value)


class ConsensusRegisterCollection(_VerbatimResubmitChannel):
    """Per-key register keeping concurrent versions
    (consensusRegisterCollection.ts processInboundWrite:352):

    - a write carries the refSeq AT CREATION; it wins (updates the atomic
      value) iff refSeq >= the current atomic write's seq (the writer knew
      the latest state);
    - versions the writer had seen (seq <= refSeq) are superseded/dropped;
      the new write is appended — so `versions` holds exactly the writes
      still mutually concurrent.
    """

    channel_type = "consensusRegisterCollection"

    def __init__(self, channel_id: str) -> None:
        super().__init__(channel_id)
        self.data: dict[str, _Register] = {}
        self._write_results: dict[int, bool] = {}
        self._next_write = 0

    def write(self, key: str, value: Any) -> int:
        """Submit a write; returns a write id whose outcome (did it become
        the atomic value?) is readable after sequencing via write_result."""
        self._next_write += 1
        # refSeq at creation rides IN the op: on resubmit the envelope refSeq
        # advances but the conflict rule must use the original knowledge
        # point (consensusRegisterCollection.ts:70-73,302).
        ref_seq = self._connection.ref_seq() if self._connection else 0
        self.submit_local_message(
            {"type": "write", "key": key, "value": value, "refSeq": ref_seq},
            {"writeId": self._next_write},
        )
        return self._next_write

    def write_result(self, write_id: int) -> bool | None:
        return self._write_results.get(write_id)

    def process_messages(self, collection: MessageCollection) -> None:
        env = collection.envelope
        for m in collection.messages:
            op = m.contents
            assert op["type"] == "write"
            is_winner = self._process_write(op["key"], op["value"], op["refSeq"], env.seq)
            if m.local:
                self._write_results[m.local_metadata["writeId"]] = is_winner

    def _process_write(self, key: str, value: Any, ref_seq: int, seq: int) -> bool:
        reg = self.data.get(key)
        is_winner = reg is None or ref_seq >= reg.atomic_seq
        if reg is None:
            reg = _Register(atomic_value=value, atomic_seq=seq)
            self.data[key] = reg
        elif is_winner:
            reg.atomic_value, reg.atomic_seq = value, seq
        # Drop versions the writer had seen; append the new one.
        reg.versions = [(s, v) for s, v in reg.versions if s > ref_seq]
        reg.versions.append((seq, value))
        return is_winner

    def read(self, key: str, policy: str = ATOMIC) -> Any:
        reg = self.data.get(key)
        if reg is None:
            return None
        if policy == ATOMIC:
            return reg.atomic_value
        return reg.versions[-1][1]  # LWW: latest concurrent version

    def read_versions(self, key: str) -> list[Any]:
        reg = self.data.get(key)
        return [v for _s, v in reg.versions] if reg else []

    def keys(self) -> list[str]:
        return list(self.data)

    def apply_stashed(self, contents: Any) -> Any:
        # Mint a fresh writeId so the ack path can record the outcome; the
        # original promise is gone with the stashed session (ref
        # consensusRegisterCollection.ts:434).
        self._next_write += 1
        return {"writeId": self._next_write}

    def summarize(self) -> dict[str, Any]:
        return {
            k: {"atomic": [r.atomic_seq, r.atomic_value], "versions": [list(t) for t in r.versions]}
            for k, r in self.data.items()
        }

    def load(self, summary: dict[str, Any]) -> None:
        for k, e in summary.items():
            self.data[k] = _Register(
                atomic_value=e["atomic"][1],
                atomic_seq=e["atomic"][0],
                versions=[(s, v) for s, v in e["versions"]],
            )


# ---------------------------------------------------------------------------
# TaskManager
# ---------------------------------------------------------------------------

class TaskManager(_VerbatimResubmitChannel):
    """Distributed task election (taskManager.ts): per-task FIFO queue of
    volunteering clients; the queue head is the assignee. Consensus-gated —
    assignment changes only on sequenced ops or sequenced leaves."""

    channel_type = "taskManager"

    def __init__(self, channel_id: str) -> None:
        super().__init__(channel_id)
        self.queues: dict[str, list[str]] = {}
        # task -> (seq, completer client id) of its latest COMPLETE: a
        # volunteer authored before seeing the completion (ref_seq < that
        # seq) is dropped on every replica — an in-flight volunteer racing
        # a complete must not resurrect the finished task as a zombie
        # assignee. Two exemptions keep deliberate restarts working: the
        # COMPLETER's own volunteers (it has seen its completion by
        # definition, even before the ack), and any volunteer sent after
        # seeing the completion.
        self.completed_at: dict[str, tuple[int, str]] = {}
        # Tasks THIS instance has completed (local knowledge: flags its own
        # post-completion volunteers as deliberate restarts).
        self._locally_completed: set[str] = set()
        # (task_id, current_assignee | None, reason) after every sequenced
        # queue mutation — the hook the agent-scheduler layer drives
        # workers from. Fires on ANY membership change (not just head
        # changes), so a scheduler can notice its own eviction (reconnect
        # under a new id) even while another client holds the task. The
        # reason distinguishes a COMPLETED task (queue cleared for good)
        # from ordinary churn.
        self.assignment_listeners: list = []

    def _notify(self, task_id: str, reason: str = "change") -> None:
        after = self.assignee(task_id)
        for fn in list(self.assignment_listeners):
            fn(task_id, after, reason)

    def volunteer(self, task_id: str) -> None:
        # The authored refSeq rides the local metadata: resubmission stamps
        # a fresh wire ref_seq, and the tombstone check needs the ORIGINAL
        # perspective to tell a stale replay from a deliberate restart. A
        # volunteer following THIS client's own complete() is a deliberate
        # restart even before the complete acks ("restart" flag — the
        # completer exemption must survive reconnect resubmission, where
        # the client id changes and tomb[1] can no longer match).
        ref = self._connection.ref_seq() if self._connection is not None else 0
        self.submit_local_message(
            {"type": "volunteer", "taskId": task_id},
            {"ref": ref, "restart": task_id in self._locally_completed},
        )

    def abandon(self, task_id: str) -> None:
        self.submit_local_message({"type": "abandon", "taskId": task_id})

    def complete(self, task_id: str) -> None:
        """Only the current assignee may complete (clears the whole queue —
        other volunteers must not pick up a finished task)."""
        if not self.assigned(task_id):
            raise RuntimeError("complete() requires holding the task")
        self._locally_completed.add(task_id)
        self.submit_local_message({"type": "complete", "taskId": task_id})

    def process_messages(self, collection: MessageCollection) -> None:
        env = collection.envelope
        for m in collection.messages:
            op = m.contents
            queue = self.queues.setdefault(op["taskId"], [])
            if op["type"] == "volunteer":
                tomb = self.completed_at.get(op["taskId"])
                if (
                    tomb is not None
                    and env.ref_seq < tomb[0]
                    and env.client_id != tomb[1]
                ):
                    continue  # authored before seeing the completion
                if env.client_id not in queue:
                    queue.append(env.client_id)
            elif op["type"] == "abandon":
                if env.client_id in queue:
                    queue.remove(env.client_id)
            elif op["type"] == "complete":
                queue.clear()
                self.completed_at[op["taskId"]] = (env.seq, env.client_id)
            else:
                raise ValueError(f"unknown task op {op['type']}")
            self._notify(
                op["taskId"],
                "complete" if op["type"] == "complete" else "change",
            )

    def on_client_leave(self, client_id: str, seq: int) -> None:
        for task_id, queue in self.queues.items():
            if client_id in queue:
                queue.remove(client_id)
                self._notify(task_id)

    def assignee(self, task_id: str) -> str | None:
        queue = self.queues.get(task_id)
        return queue[0] if queue else None

    def assigned(self, task_id: str) -> bool:
        return (
            self._connection is not None
            and self.assignee(task_id) == self._connection.client_id()
        )

    def queued(self, task_id: str) -> bool:
        return (
            self._connection is not None
            and self._connection.client_id() in self.queues.get(task_id, [])
        )

    def resubmit(self, contents: Any, local_metadata: Any, squash: bool = False) -> None:
        # A replayed volunteer is resubmitted with a FRESH wire ref_seq,
        # which would blind the tombstone's authored-before-completion
        # check: compare the ORIGINAL authored refSeq (ridden in local
        # metadata) instead, and drop the volunteer when the task completed
        # after it was authored — a deliberate post-completion restart has
        # an authored ref at/after the completion and goes through.
        if contents.get("type") == "volunteer":
            tomb = self.completed_at.get(contents.get("taskId"))
            meta = local_metadata or {}
            # No metadata (stashed-op rehydrate drops it) reads as authored
            # ref 0: conservatively stale — a stashed volunteer surviving
            # into a completed task is a replay, never a restart.
            authored = meta.get("ref", 0)
            if tomb is not None and authored < tomb[0] and not meta.get("restart"):
                return
        super().resubmit(contents, local_metadata, squash)

    def on_min_seq(self, min_seq: int) -> None:
        # A completion below the collab-window floor can never race a live
        # volunteer (its ref_seq would be >= min_seq): drop the tombstone.
        self.completed_at = {
            t: e for t, e in self.completed_at.items() if e[0] > min_seq
        }

    def summarize(self) -> dict[str, Any]:
        return {
            "queues": {k: list(v) for k, v in self.queues.items()},
            "completedAt": {t: list(e) for t, e in self.completed_at.items()},
        }

    def load(self, summary: dict[str, Any]) -> None:
        self.queues = {k: list(v) for k, v in summary["queues"].items()}
        self.completed_at = {
            # Pre-(seq, clientId) summaries stored a bare int seq.
            t: (e, "") if isinstance(e, int) else (e[0], e[1])
            for t, e in summary.get("completedAt", {}).items()
        }


# ---------------------------------------------------------------------------
# PactMap
# ---------------------------------------------------------------------------

@dataclass
class _Pact:
    accepted_value: Any = None
    accepted_seq: int = -1
    has_accepted: bool = False
    pending_value: Any = None
    expected_signoffs: list[str] | None = None  # None = nothing pending


class PactMap(_VerbatimResubmitChannel):
    """Consensus key-value (pactMap.ts): a set proposal goes "pending" and
    becomes "accepted" only once every client connected at proposal time
    has signed off via an accept op (or left). Invalid proposals — made
    without knowledge of the latest accepted value, or while another is
    pending — are dropped on the floor."""

    channel_type = "pactMap"

    def __init__(self, channel_id: str) -> None:
        super().__init__(channel_id)
        self.values: dict[str, _Pact] = {}

    # ------------------------------------------------------------------- api
    def set(self, key: str, value: Any) -> None:
        pact = self.values.get(key)
        if pact is not None and pact.expected_signoffs is not None:
            return  # a proposal is already pending; ours would be invalid
        ref_seq = self._connection.ref_seq() if self._connection else 0
        self.submit_local_message(
            {"type": "set", "key": key, "value": value, "refSeq": ref_seq}
        )

    def get(self, key: str) -> Any:
        pact = self.values.get(key)
        return pact.accepted_value if pact and pact.has_accepted else None

    def get_pending(self, key: str) -> Any:
        pact = self.values.get(key)
        return pact.pending_value if pact and pact.expected_signoffs is not None else None

    def is_pending(self, key: str) -> bool:
        pact = self.values.get(key)
        return pact is not None and pact.expected_signoffs is not None

    # --------------------------------------------------------------- inbound
    def process_messages(self, collection: MessageCollection) -> None:
        env = collection.envelope
        for m in collection.messages:
            op = m.contents
            if op["type"] == "set":
                self._handle_set(op["key"], op["value"], op["refSeq"], env.seq)
            elif op["type"] == "accept":
                self._handle_accept(op["key"], env.client_id, env.seq)
            else:
                raise ValueError(f"unknown pact op {op['type']}")

    def _handle_set(self, key: str, value: Any, ref_seq: int, seq: int) -> None:
        pact = self.values.get(key)
        proposal_valid = pact is None or (
            pact.expected_signoffs is None and pact.accepted_seq <= ref_seq
        )
        if not proposal_valid:
            return
        if pact is None:
            pact = _Pact()
            self.values[key] = pact
        # Signoff set = clients connected when the set sequenced, including
        # the proposer (pactMap.ts getSignoffClients).
        pact.pending_value = value
        pact.expected_signoffs = list(self._connection.quorum_members())
        if not pact.expected_signoffs:
            self._settle(pact, seq)
        elif self._connection.client_id() in pact.expected_signoffs:
            # Minted while processing inbound ops: protocol-internal.
            self.submit_local_message({"type": "accept", "key": key}, internal=True)

    def _handle_accept(self, key: str, client_id: str, seq: int) -> None:
        pact = self.values.get(key)
        if pact is None or pact.expected_signoffs is None:
            return  # already settled
        if client_id in pact.expected_signoffs:
            pact.expected_signoffs.remove(client_id)
        if not pact.expected_signoffs:
            self._settle(pact, seq)

    def _settle(self, pact: _Pact, seq: int) -> None:
        pact.accepted_value = pact.pending_value
        pact.accepted_seq = seq
        pact.has_accepted = True
        pact.pending_value = None
        pact.expected_signoffs = None

    def on_client_leave(self, client_id: str, seq: int) -> None:
        for pact in self.values.values():
            if pact.expected_signoffs is not None and client_id in pact.expected_signoffs:
                pact.expected_signoffs.remove(client_id)
                if not pact.expected_signoffs:
                    self._settle(pact, seq)  # accepted at the leave's seq

    def summarize(self) -> dict[str, Any]:
        out = {}
        for k, p in self.values.items():
            out[k] = {
                "accepted": [p.accepted_seq, p.accepted_value] if p.has_accepted else None,
                "pending": (
                    {"value": p.pending_value, "signoffs": p.expected_signoffs}
                    if p.expected_signoffs is not None
                    else None
                ),
            }
        return out

    def load(self, summary: dict[str, Any]) -> None:
        for k, e in summary.items():
            pact = _Pact()
            if e["accepted"] is not None:
                pact.accepted_seq, pact.accepted_value = e["accepted"]
                pact.has_accepted = True
            if e["pending"] is not None:
                pact.pending_value = e["pending"]["value"]
                pact.expected_signoffs = list(e["pending"]["signoffs"])
            self.values[k] = pact


# ---------------------------------------------------------------------------
# registry
# ---------------------------------------------------------------------------

SMALL_DDS_FACTORIES: dict[str, ChannelTypeFactory] = {
    cls.channel_type: ChannelTypeFactory(cls)
    for cls in (
        SharedCell,
        SharedCounter,
        ConsensusQueue,
        ConsensusRegisterCollection,
        TaskManager,
        PactMap,
    )
}
