"""Remaining DDS family members: SharedDirectory, Ink, SharedSummaryBlock.

Reference parity:
- ``SharedDirectory`` (packages/dds/map/src/directory.ts): hierarchical
  key-value store — a tree of subdirectories each holding a LWW map, with
  create/delete of subdirectories sequenced like keys.
- ``Ink`` (packages/dds/ink/src/ink.ts): append-only stroke collection
  (createStroke/appendPointToStroke); ops commute per-stroke so application
  is order-insensitive beyond sequencing.
- ``SharedSummaryBlock`` (packages/dds/shared-summary-block): write-locally,
  read-after-summary block — data travels ONLY via summaries, never ops.
"""

from __future__ import annotations

from typing import Any

from .channels import ChannelTypeFactory, PendingOverlayChannel
from ..protocol.channel import Channel, MessageCollection


def _split_path(path: str) -> list[str]:
    return [p for p in path.split("/") if p]


class SharedDirectory(PendingOverlayChannel):
    """Hierarchical LWW key-value store (ref SharedDirectory).

    Sequenced state: nested dict of {"keys": {...}, "subdirs": {...}}.
    Ops carry an absolute subdirectory path; missing intermediate
    subdirectories are created implicitly (directory.ts ensureSubDirectory).
    Deleting a subdirectory drops its whole subtree (LWW by sequence order).
    """

    channel_type = "sharedDirectory"

    def __init__(self, channel_id: str) -> None:
        super().__init__(channel_id)
        self.root: dict[str, Any] = {"keys": {}, "subdirs": {}}
        self._root_version = 0  # bumps on every sequenced apply (view cache)
        self._overlay_cache: tuple | None = None  # (key, view)

    # ------------------------------------------------------------ local edits
    def set(self, path: str, key: str, value: Any) -> None:
        self._submit({"type": "set", "path": path, "key": key, "value": value})

    def delete(self, path: str, key: str) -> None:
        self._submit({"type": "delete", "path": path, "key": key})

    def create_subdirectory(self, path: str) -> None:
        self._submit({"type": "createSubdir", "path": path})

    def delete_subdirectory(self, path: str) -> None:
        assert _split_path(path), "cannot delete the root directory"
        self._submit({"type": "deleteSubdir", "path": path})

    def clear(self, path: str = "") -> None:
        self._submit({"type": "clear", "path": path})

    # ---------------------------------------------------------------- applies
    def _node(self, state: dict, path: str, create: bool) -> dict | None:
        node = state
        for part in _split_path(path):
            sub = node["subdirs"].get(part)
            if sub is None:
                if not create:
                    return None
                sub = node["subdirs"][part] = {"keys": {}, "subdirs": {}}
            node = sub
        return node

    def _apply(self, op: dict) -> None:
        self._root_version += 1
        kind = op["type"]
        if kind == "set":
            self._node(self.root, op["path"], create=True)["keys"][op["key"]] = op["value"]
        elif kind == "delete":
            node = self._node(self.root, op["path"], create=False)
            if node is not None:
                node["keys"].pop(op["key"], None)
        elif kind == "createSubdir":
            self._node(self.root, op["path"], create=True)
        elif kind == "deleteSubdir":
            parts = _split_path(op["path"])
            parent = self._node(self.root, "/".join(parts[:-1]), create=False)
            if parent is not None:
                parent["subdirs"].pop(parts[-1], None)
        elif kind == "clear":
            node = self._node(self.root, op["path"], create=False)
            if node is not None:
                node["keys"].clear()
        else:
            raise ValueError(f"unknown directory op {kind!r}")

    # ------------------------------------------------------------------ views
    def _overlay(self) -> dict:
        """Optimistic view: sequenced state + pending ops applied on a
        copy, memoized until either side changes (repeated reads while ops
        are in flight would otherwise deepcopy the whole tree each time)."""
        import copy

        if not self._pending:
            return self.root
        key = (self._root_version, tuple(pid for pid, _op in self._pending))
        if self._overlay_cache is not None and self._overlay_cache[0] == key:
            return self._overlay_cache[1]
        view = copy.deepcopy(self.root)
        saved_version = self._root_version
        saved, self.root = self.root, view
        try:
            for _pid, op in self._pending:
                self._apply(op)
        finally:
            self.root = saved
            self._root_version = saved_version
        self._overlay_cache = (key, view)
        return view

    def get(self, path: str, key: str) -> Any:
        node = self._node(self._overlay(), path, create=False)
        return None if node is None else node["keys"].get(key)

    def keys(self, path: str = "") -> set[str]:
        node = self._node(self._overlay(), path, create=False)
        return set() if node is None else set(node["keys"])

    def subdirectories(self, path: str = "") -> set[str]:
        node = self._node(self._overlay(), path, create=False)
        return set() if node is None else set(node["subdirs"])

    def has_subdirectory(self, path: str) -> bool:
        return self._node(self._overlay(), path, create=False) is not None

    # ------------------------------------------------------------ checkpoint
    def summarize(self) -> dict[str, Any]:
        import copy

        return {"root": copy.deepcopy(self.root)}

    def load(self, summary: dict[str, Any]) -> None:
        import copy

        self.root = copy.deepcopy(summary["root"])


class Ink(PendingOverlayChannel):
    """Append-only ink strokes (ref Ink: createStroke + appendPointToStroke).

    Points are (x, y, time, pressure) tuples; per-stroke append order is the
    author's order (single-author strokes in practice), cross-stroke order
    is sequencing order.
    """

    channel_type = "ink"

    def __init__(self, channel_id: str) -> None:
        super().__init__(channel_id)
        self.strokes: dict[str, dict] = {}
        self._stroke_counter = 0

    def create_stroke(self, pen: dict | None = None) -> str:
        self._stroke_counter += 1
        owner = self._connection.client_id() if self._connection else self.id
        sid = f"{owner}-s{self._stroke_counter}"
        self._submit({"type": "createStroke", "id": sid, "pen": dict(pen or {})})
        return sid

    def append_point(self, stroke_id: str, x: float, y: float, t: float = 0.0, pressure: float = 0.5) -> None:
        self._submit(
            {"type": "stylus", "id": stroke_id, "point": [x, y, t, pressure]}
        )

    def _apply(self, op: dict) -> None:
        if op["type"] == "createStroke":
            self.strokes.setdefault(op["id"], {"pen": op["pen"], "points": []})
        elif op["type"] == "stylus":
            stroke = self.strokes.get(op["id"])
            if stroke is not None:  # points to a deleted/unknown stroke drop
                stroke["points"].append(tuple(op["point"]))
        else:
            raise ValueError(f"unknown ink op {op['type']!r}")

    # ------------------------------------------------------------------ views
    def get_stroke(self, stroke_id: str) -> dict | None:
        base = self.strokes.get(stroke_id)
        out = (
            {"pen": dict(base["pen"]), "points": list(base["points"])}
            if base is not None
            else None
        )
        for _pid, op in self._pending:
            if op["id"] != stroke_id:
                continue
            if op["type"] == "createStroke" and out is None:
                out = {"pen": dict(op["pen"]), "points": []}
            elif op["type"] == "stylus" and out is not None:
                out["points"].append(tuple(op["point"]))
        return out

    def stroke_ids(self) -> set[str]:
        out = set(self.strokes)
        out.update(op["id"] for _pid, op in self._pending if op["type"] == "createStroke")
        return out

    # ------------------------------------------------------------ checkpoint
    def summarize(self) -> dict[str, Any]:
        return {
            "strokes": {
                sid: {"pen": s["pen"], "points": [list(p) for p in s["points"]]}
                for sid, s in self.strokes.items()
            }
        }

    def load(self, summary: dict[str, Any]) -> None:
        self.strokes = {
            sid: {"pen": dict(s["pen"]), "points": [tuple(p) for p in s["points"]]}
            for sid, s in summary["strokes"].items()
        }


class SharedSummaryBlock(Channel):
    """Summary-only data block (ref shared-summary-block): writes are local
    and surface to other clients ONLY through summary load — no ops ever.
    """

    channel_type = "sharedSummaryBlock"

    def __init__(self, channel_id: str) -> None:
        super().__init__(channel_id)
        self.data: dict[str, Any] = {}

    def set(self, key: str, value: Any) -> None:
        self.data[key] = value  # local only; never submitted

    def get(self, key: str) -> Any:
        return self.data.get(key)

    def process_messages(self, collection: MessageCollection) -> None:
        raise RuntimeError("sharedSummaryBlock never receives ops")

    def resubmit(self, contents: Any, local_metadata: Any, squash: bool = False) -> None:
        raise RuntimeError("sharedSummaryBlock never submits ops")

    def summarize(self) -> dict[str, Any]:
        return {"data": dict(self.data)}

    def load(self, summary: dict[str, Any]) -> None:
        self.data = dict(summary["data"])


EXTRA_DDS_FACTORIES: dict[str, ChannelTypeFactory] = {
    cls.channel_type: ChannelTypeFactory(cls)
    for cls in (SharedDirectory, Ink, SharedSummaryBlock)
}
