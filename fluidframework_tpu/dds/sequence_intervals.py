"""Interval collections: ranges anchored to SharedString positions.

Reference parity: sequence's ``IntervalCollection``
(packages/dds/sequence/src/intervalCollection.ts:736) — named collections of
intervals (id, start, end, properties) anchored into a SharedString, with
add/change/delete ops sequenced through the string's channel, slide-on-remove
endpoint semantics, and overlap queries (intervalIndex/).

Design (derived, not ported): the reference anchors endpoints with merge-tree
local references that slide when segments are removed. Here endpoints live in
the string's current acked coordinate space and are TRANSFORMED by every
sequenced string op; incoming interval ops are first transformed over the
string ops the sender had not seen (its refSeq → now), using a collab-window
log of string ops. Every replica performs identical deterministic transforms
in sequence order, so interval state converges exactly like the string
itself. Endpoint rules (matching reference slide semantics):
- insert at p, length L: positions > p shift by +L; an endpoint exactly at p
  stays (anchors bind to the character they precede).
- remove [a, b): endpoints inside clamp (slide) to a; later positions shift
  by -(b-a).

Conflict rules: last-writer-wins per interval id for change/delete (delete
wins over a concurrent change it hasn't seen; a change to a deleted interval
is a no-op), mirroring intervalCollection.ts ack logic.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Iterator


@dataclass
class SequenceInterval:
    interval_id: str
    start: int
    end: int
    props: dict[str, Any] = field(default_factory=dict)

    def to_json(self) -> dict:
        return {
            "id": self.interval_id,
            "start": self.start,
            "end": self.end,
            "props": dict(self.props),
        }

    @staticmethod
    def from_json(d: dict) -> "SequenceInterval":
        return SequenceInterval(d["id"], d["start"], d["end"], dict(d["props"]))


def transform_position(
    pos: int, kind: str, op_pos: int, length: int, after: bool = False
) -> int:
    """Slide one endpoint over one sequenced string op.

    ``after`` is the insert tie-bias (the reference's reference-type
    before/after slide flags): when an insert lands exactly AT ``pos``,
    after=False keeps the position (it binds to the character it precedes;
    interval semantics), after=True shifts right past the inserted content
    (range-start tracking for undo)."""
    if kind == "insert":
        shift = pos >= op_pos if after else pos > op_pos
        return pos + length if shift else pos
    # remove of [op_pos, op_pos + length)
    if pos <= op_pos:
        return pos
    if pos < op_pos + length:
        return op_pos  # inside the removed range: slide to its start
    return pos - length


class StringOpLog:
    """Collab-window log of sequenced string edits, for transforming interval
    ops issued against an older refSeq (the positional analog of creating a
    merge-tree reference under the op's perspective)."""

    def __init__(self) -> None:
        self._log: list[tuple[int, str, int, int]] = []  # (seq, kind, pos, len)

    def record(self, seq: int, kind: str, pos: int, length: int) -> None:
        """Append, coalescing contiguous same-seq runs: a pending insert the
        author's own later edits split acks as several adjacent converged
        fragments where remote replicas saw one segment — the transform
        effect is identical (adjacent splits compose), so the log normalizes
        to the merged form and summaries stay byte-identical across
        replicas. Inserts record ascending (extend right); removes record
        back-to-front (extend left)."""
        if self._log:
            lseq, lkind, lpos, llen = self._log[-1]
            if lseq == seq and lkind == kind:
                if kind == "insert" and lpos + llen == pos:
                    self._log[-1] = (seq, kind, lpos, llen + length)
                    return
                if kind == "remove" and pos + length == lpos:
                    self._log[-1] = (seq, kind, pos, llen + length)
                    return
        self._log.append((seq, kind, pos, length))

    def transform_from(self, pos: int, ref_seq: int) -> int:
        for seq, kind, op_pos, length in self._log:
            if seq > ref_seq:
                pos = transform_position(pos, kind, op_pos, length)
        return pos

    def trim(self, min_seq: int) -> None:
        self._log = [e for e in self._log if e[0] > min_seq]

    def to_json(self) -> list:
        return [list(e) for e in self._log]

    def load_json(self, data: list) -> None:
        self._log = [tuple(e) for e in data]


class IntervalCollection:
    """One named collection. Sequenced state + optimistic pending overlay
    (pending local add/change/delete mask remote state until acked)."""

    def __init__(self, label: str, submit_fn) -> None:
        self.label = label
        self._submit = submit_fn
        self.sequenced: dict[str, SequenceInterval] = {}
        self._pending: list[dict] = []  # local ops in flight, in order
        self._id_counter = 0

    # ------------------------------------------------------------ local edits
    def add(self, start: int, end: int, props: dict | None = None, interval_id: str | None = None) -> str:
        assert 0 <= start <= end
        if interval_id is None:
            self._id_counter += 1
            interval_id = f"{self.label}-{self._id_counter}"
        op = {
            "action": "add",
            "id": interval_id,
            "start": start,
            "end": end,
            "props": dict(props or {}),
        }
        self._pending.append(op)
        self._submit(self.label, op)
        return interval_id

    def change(self, interval_id: str, start: int | None = None, end: int | None = None, props: dict | None = None) -> None:
        op = {"action": "change", "id": interval_id, "start": start, "end": end, "props": props}
        self._pending.append(op)
        self._submit(self.label, op)

    def delete(self, interval_id: str) -> None:
        op = {"action": "delete", "id": interval_id}
        self._pending.append(op)
        self._submit(self.label, op)

    # ---------------------------------------------------------------- inbound
    def apply_sequenced(self, op: dict, local: bool) -> None:
        if local:
            head = self._pending.pop(0)
            assert head["action"] == op["action"] and head["id"] == op["id"], (
                "interval pending skew"
            )
        action = op["action"]
        if action == "add":
            self.sequenced[op["id"]] = SequenceInterval(
                op["id"], op["start"], op["end"], dict(op["props"])
            )
        elif action == "delete":
            self.sequenced.pop(op["id"], None)
        elif action == "change":
            iv = self.sequenced.get(op["id"])
            if iv is None:
                return  # changed a concurrently-deleted interval: no-op
            if op["start"] is not None:
                iv.start = op["start"]
            if op["end"] is not None:
                iv.end = op["end"]
            if op["props"]:
                iv.props.update(op["props"])
        else:
            raise ValueError(f"unknown interval action {action!r}")

    def transform_endpoints(self, kind: str, pos: int, length: int) -> None:
        """A sequenced string edit landed: slide every acked endpoint."""
        for iv in self.sequenced.values():
            iv.start = transform_position(iv.start, kind, pos, length)
            iv.end = transform_position(iv.end, kind, pos, length)
            if iv.end < iv.start:
                iv.end = iv.start

    # ------------------------------------------------------------------ views
    def get(self, interval_id: str) -> SequenceInterval | None:
        """Optimistic read: pending local ops overlay the sequenced state."""
        iv = self.sequenced.get(interval_id)
        iv = SequenceInterval.from_json(iv.to_json()) if iv is not None else None
        for op in self._pending:
            if op["id"] != interval_id:
                continue
            if op["action"] == "add":
                iv = SequenceInterval(op["id"], op["start"], op["end"], dict(op["props"]))
            elif op["action"] == "delete":
                iv = None
            elif op["action"] == "change" and iv is not None:
                if op["start"] is not None:
                    iv.start = op["start"]
                if op["end"] is not None:
                    iv.end = op["end"]
                if op["props"]:
                    iv.props.update(op["props"])
        return iv

    def ids(self) -> set[str]:
        out = set(self.sequenced)
        for op in self._pending:
            if op["action"] == "add":
                out.add(op["id"])
            elif op["action"] == "delete":
                out.discard(op["id"])
        return out

    def __iter__(self) -> Iterator[SequenceInterval]:
        return iter(sorted((self.get(i) for i in self.ids()), key=lambda v: (v.start, v.end, v.interval_id)))

    def overlapping(self, start: int, end: int) -> list[SequenceInterval]:
        """Intervals intersecting [start, end], bounds inclusive — the
        reference's findOverlappingIntervals contract
        (intervalIndex/overlappingIntervalsIndex.ts)."""
        return [iv for iv in self if iv.start <= end and iv.end >= start]

    # ------------------------------------------------------------ checkpoint
    def summarize(self) -> dict:
        if self._pending:
            raise RuntimeError("summarize with pending interval ops")
        return {"intervals": [iv.to_json() for iv in self.sequenced.values()]}

    def load(self, data: dict) -> None:
        self.sequenced = {
            e["id"]: SequenceInterval.from_json(e) for e in data["intervals"]
        }
