"""Interval collections: ranges anchored to SharedString positions.

Reference parity: sequence's ``IntervalCollection``
(packages/dds/sequence/src/intervalCollection.ts:736) — named collections of
intervals (id, start, end, properties) anchored into a SharedString, with
add/change/delete ops sequenced through the string's channel, slide-on-remove
endpoint semantics, and overlap queries (intervalIndex/).

Design (derived, not ported): the reference anchors endpoints with merge-tree
local references that slide when segments are removed. Here endpoints live in
the string's current acked coordinate space and are TRANSFORMED by every
sequenced string op; incoming interval ops are first transformed over the
string ops the sender had not seen (its refSeq → now), using a collab-window
log of string ops. Every replica performs identical deterministic transforms
in sequence order, so interval state converges exactly like the string
itself. Endpoint rules (matching reference slide semantics):
- insert at p, length L: positions > p shift by +L; an endpoint exactly at p
  stays (anchors bind to the character they precede).
- remove [a, b): endpoints inside clamp (slide) to a; later positions shift
  by -(b-a).

Conflict rules: last-writer-wins per interval id for change/delete (delete
wins over a concurrent change it hasn't seen; a change to a deleted interval
is a no-op), mirroring intervalCollection.ts ack logic.

Sided endpoints (opt-in, like the reference's intervalStickinessEnabled /
InteriorSequencePlace path, merge-tree/src/sequencePlace.ts:50 +
sequence/src/intervals/intervalUtils.ts computeStickinessFromSide): an
endpoint may be a ``(pos, Side)`` place — the anchor binds to the CHARACTER
at ``pos``, on the flank the side names — or the literals ``"start"`` /
``"end"`` (the special endpoint segments, normalized to pos=-1 exactly as
``normalizePlace`` does). Sides determine:
- inclusion: start Side.BEFORE includes char pos, start Side.AFTER starts at
  pos+1 (exclusive); end Side.AFTER includes char pos, end Side.BEFORE ends
  at pos-1 (exclusive);
- stickiness (emergent): a start bound AFTER keeps its anchor when text is
  inserted just after it, so the inserted text falls inside the interval
  (START sticky); an end bound BEFORE follows its char right when text is
  inserted just before it, pulling the insert inside (END sticky — the
  reference's default);
- slide-on-remove direction: a BEFORE anchor whose character is removed
  slides FORWARD to the next surviving character (or the "end" sentinel
  when none survives); an AFTER anchor slides BACKWARD (or to "start").
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Iterator


class Side:
    """Endpoint flank (ref merge-tree sequencePlace.ts:50)."""

    BEFORE = 0
    AFTER = 1


class IntervalStickiness:
    """Which flanks an interval expands across (ref
    sequence/src/intervals/intervalUtils.ts IntervalStickiness)."""

    NONE = 0b00
    START = 0b01
    END = 0b10
    FULL = 0b11


# Sentinel position for the special endpoint segments ("start"/"end"), as
# normalizePlace encodes them: pos=-1, side AFTER = start-of-string anchor,
# pos=-1, side BEFORE = end-of-string anchor.
SENTINEL_POS = -1


def normalize_place(place) -> tuple[int, int]:
    """``pos | (pos, side) | "start" | "end"`` -> (pos, side), mirroring
    normalizePlace (sequencePlace.ts:103): bare ints get Side.BEFORE."""
    if place == "start":
        return (SENTINEL_POS, Side.AFTER)
    if place == "end":
        return (SENTINEL_POS, Side.BEFORE)
    if isinstance(place, int):
        return (place, Side.BEFORE)
    pos, side = place
    return (int(pos), int(side))


def compute_stickiness(start_side: int, end_side: int) -> int:
    """ref intervalUtils.ts computeStickinessFromSide (sentinel endpoints
    are already encoded with the sticky side by normalize_place)."""
    s = IntervalStickiness.NONE
    if start_side == Side.AFTER:
        s |= IntervalStickiness.START
    if end_side == Side.BEFORE:
        s |= IntervalStickiness.END
    return s


def place_boundary(pos: int, side: int) -> float:
    """Order key for validity checks: the inter-character boundary the place
    names (sentinels at +-inf)."""
    if pos == SENTINEL_POS:
        return float("-inf") if side == Side.AFTER else float("inf")
    return pos + (1 if side == Side.AFTER else 0)


def transform_place(
    pos: int, side: int, kind: str, op_pos: int, length: int
) -> tuple[int, int]:
    """Slide one SIDED endpoint over one sequenced string op.

    Char-bound anchor semantics: the anchor follows its character, so an
    insert shifts it iff the insert lands at or before the character. A
    remove that swallows the character slides BEFORE-anchors forward to the
    first survivor (op_pos after the splice) and AFTER-anchors backward to
    op_pos-1, degrading to the start/end sentinels at the string edges —
    the reference's slide with canSlideToEndpoint
    (sequence/src/intervals/sequenceInterval.ts:967)."""
    if pos == SENTINEL_POS:
        return (pos, side)
    if kind == "insert":
        return (pos + length, side) if op_pos <= pos else (pos, side)
    # remove of [op_pos, op_pos + length)
    if pos < op_pos:
        return (pos, side)
    if pos >= op_pos + length:
        return (pos - length, side)
    if side == Side.BEFORE:
        return (op_pos, side)  # forward; may now name one-past-the-end —
        # the caller clamps to the end sentinel when it knows the length
    if op_pos == 0:
        return (SENTINEL_POS, Side.AFTER)  # backward off the front: "start"
    return (op_pos - 1, side)


@dataclass
class SequenceInterval:
    """``start_side``/``end_side`` of ``None`` mark a legacy (unsided)
    interval: plain positions with the original transform rules, byte-stable
    against old summaries."""

    interval_id: str
    start: int
    end: int
    props: dict[str, Any] = field(default_factory=dict)
    start_side: int | None = None
    end_side: int | None = None

    @property
    def sided(self) -> bool:
        return self.start_side is not None

    @property
    def stickiness(self) -> int:
        if not self.sided:
            return IntervalStickiness.END  # the reference default
        return compute_stickiness(self.start_side, self.end_side)

    def first_char(self, length: int) -> int:
        """Smallest character index inside the interval. ``length`` resolves
        a start pinned at the "end" sentinel (empty interval at the back)."""
        if not self.sided:
            return self.start
        if self.start == SENTINEL_POS:
            return 0 if self.start_side == Side.AFTER else length
        return self.start if self.start_side == Side.BEFORE else self.start + 1

    def last_char(self, length: int) -> int:
        """Largest character index inside the interval. ``length`` resolves
        the "end" sentinel; an end pinned at "start" (empty interval at the
        front) reads as -1."""
        if not self.sided:
            return self.end
        if self.end == SENTINEL_POS:
            return length - 1 if self.end_side == Side.BEFORE else -1
        return self.end if self.end_side == Side.AFTER else self.end - 1

    def to_json(self) -> dict:
        out = {
            "id": self.interval_id,
            "start": self.start,
            "end": self.end,
            "props": dict(self.props),
        }
        if self.sided:
            out["startSide"] = self.start_side
            out["endSide"] = self.end_side
        return out

    @staticmethod
    def from_json(d: dict) -> "SequenceInterval":
        return SequenceInterval(
            d["id"], d["start"], d["end"], dict(d["props"]),
            d.get("startSide"), d.get("endSide"),
        )


def transform_position(
    pos: int, kind: str, op_pos: int, length: int, after: bool = False
) -> int:
    """Slide one endpoint over one sequenced string op.

    ``after`` is the insert tie-bias (the reference's reference-type
    before/after slide flags): when an insert lands exactly AT ``pos``,
    after=False keeps the position (it binds to the character it precedes;
    interval semantics), after=True shifts right past the inserted content
    (range-start tracking for undo)."""
    if kind == "insert":
        shift = pos >= op_pos if after else pos > op_pos
        return pos + length if shift else pos
    # remove of [op_pos, op_pos + length)
    if pos <= op_pos:
        return pos
    if pos < op_pos + length:
        return op_pos  # inside the removed range: slide to its start
    return pos - length


class StringOpLog:
    """Collab-window log of sequenced string edits, for transforming interval
    ops issued against an older refSeq (the positional analog of creating a
    merge-tree reference under the op's perspective)."""

    def __init__(self) -> None:
        self._log: list[tuple[int, str, int, int]] = []  # (seq, kind, pos, len)

    def record(self, seq: int, kind: str, pos: int, length: int) -> None:
        """Append, coalescing contiguous same-seq runs: a pending insert the
        author's own later edits split acks as several adjacent converged
        fragments where remote replicas saw one segment — the transform
        effect is identical (adjacent splits compose), so the log normalizes
        to the merged form and summaries stay byte-identical across
        replicas. Inserts record ascending (extend right); removes record
        back-to-front (extend left)."""
        if self._log:
            lseq, lkind, lpos, llen = self._log[-1]
            if lseq == seq and lkind == kind:
                if kind == "insert" and lpos + llen == pos:
                    self._log[-1] = (seq, kind, lpos, llen + length)
                    return
                if kind == "remove" and pos + length == lpos:
                    self._log[-1] = (seq, kind, pos, llen + length)
                    return
        self._log.append((seq, kind, pos, length))

    def transform_from(self, pos: int, ref_seq: int) -> int:
        for seq, kind, op_pos, length in self._log:
            if seq > ref_seq:
                pos = transform_position(pos, kind, op_pos, length)
        return pos

    def transform_place_from(self, pos: int, side: int, ref_seq: int) -> tuple[int, int]:
        """Sided-endpoint form of transform_from (resubmit of pending sided
        interval ops)."""
        for seq, kind, op_pos, length in self._log:
            if seq > ref_seq:
                pos, side = transform_place(pos, side, kind, op_pos, length)
        return pos, side

    def trim(self, min_seq: int) -> None:
        self._log = [e for e in self._log if e[0] > min_seq]

    def to_json(self) -> list:
        return [list(e) for e in self._log]

    def load_json(self, data: list) -> None:
        self._log = [tuple(e) for e in data]


def _apply_change_endpoints(iv: SequenceInterval, op: dict) -> None:
    """Endpoint-moving changes set the interval's sidedness as a whole:
    a sided op (both sides present, enforced at submit) makes it sided,
    a plain-int op reverts it to legacy. Never leaves one side set."""
    if op.get("start") is None and op.get("end") is None:
        return
    if "startSide" in op or "endSide" in op:
        iv.start, iv.end = op["start"], op["end"]
        iv.start_side = op.get("startSide", Side.BEFORE)
        iv.end_side = op.get("endSide", Side.BEFORE)
        return
    if op.get("start") is not None:
        iv.start = op["start"]
    if op.get("end") is not None:
        iv.end = op["end"]
    if iv.sided:
        # Reverting a sided interval via a single-endpoint legacy change:
        # resolve any sentinel left behind to a deterministic legacy pos.
        if iv.start == SENTINEL_POS and op.get("start") is None:
            iv.start = 0
        if iv.end == SENTINEL_POS and op.get("end") is None:
            iv.end = max(iv.start, 1 << 30)
    iv.start_side = iv.end_side = None


class IntervalCollection:
    """One named collection. Sequenced state + optimistic pending overlay
    (pending local add/change/delete mask remote state until acked).

    ``length_fn`` resolves the current string length (for the "end" sentinel
    and forward-slide clamping); hosts that never use sided endpoints may
    omit it."""

    def __init__(self, label: str, submit_fn, length_fn=None) -> None:
        self.label = label
        self._submit = submit_fn
        self._length = length_fn or (lambda: 1 << 30)
        self.sequenced: dict[str, SequenceInterval] = {}
        self._pending: list[dict] = []  # local ops in flight, in order
        self._id_counter = 0

    @staticmethod
    def _is_sided(start, end) -> bool:
        return not (isinstance(start, int) and isinstance(end, int))

    def _validate_places(self, sp, ss, ep, es) -> None:
        n = self._length()
        for pos in (sp, ep):
            assert pos == SENTINEL_POS or 0 <= pos < n, (
                f"interval place {pos} outside string of length {n}"
            )
        assert place_boundary(sp, ss) <= place_boundary(ep, es), (
            "interval end before start"
        )

    # ------------------------------------------------------------ local edits
    def add(self, start, end, props: dict | None = None, interval_id: str | None = None) -> str:
        if interval_id is None:
            self._id_counter += 1
            interval_id = f"{self.label}-{self._id_counter}"
        op = {
            "action": "add",
            "id": interval_id,
            "props": dict(props or {}),
        }
        if self._is_sided(start, end):
            sp, ss = normalize_place(start)
            ep, es = normalize_place(end)
            self._validate_places(sp, ss, ep, es)
            op.update(start=sp, end=ep, startSide=ss, endSide=es)
        else:
            assert 0 <= start <= end
            op.update(start=start, end=end)
        self._pending.append(op)
        self._submit(self.label, op)
        return interval_id

    def change(self, interval_id: str, start=None, end=None, props: dict | None = None) -> None:
        """A change that moves endpoints fully determines the interval's
        sidedness: sided places require BOTH endpoints (like the reference's
        change({start, end}) with InteriorSequencePlaces), plain ints revert
        the interval to legacy semantics."""
        op = {"action": "change", "id": interval_id, "start": start, "end": end, "props": props}
        if (start is not None or end is not None) and self._is_sided(
            start if start is not None else 0, end if end is not None else 0
        ):
            assert start is not None and end is not None, (
                "sided change requires both endpoints"
            )
            sp, ss = normalize_place(start)
            ep, es = normalize_place(end)
            self._validate_places(sp, ss, ep, es)
            op.update(start=sp, end=ep, startSide=ss, endSide=es)
        self._pending.append(op)
        self._submit(self.label, op)

    def delete(self, interval_id: str) -> None:
        op = {"action": "delete", "id": interval_id}
        self._pending.append(op)
        self._submit(self.label, op)

    # ---------------------------------------------------------------- inbound
    def apply_sequenced(self, op: dict, local: bool) -> None:
        if local:
            head = self._pending.pop(0)
            assert head["action"] == op["action"] and head["id"] == op["id"], (
                "interval pending skew"
            )
        action = op["action"]
        if action == "add":
            self.sequenced[op["id"]] = SequenceInterval(
                op["id"], op["start"], op["end"], dict(op["props"]),
                op.get("startSide"), op.get("endSide"),
            )
        elif action == "delete":
            self.sequenced.pop(op["id"], None)
        elif action == "change":
            iv = self.sequenced.get(op["id"])
            if iv is None:
                return  # changed a concurrently-deleted interval: no-op
            _apply_change_endpoints(iv, op)
            if op["props"]:
                iv.props.update(op["props"])
        else:
            raise ValueError(f"unknown interval action {action!r}")

    def transform_endpoints(self, kind: str, pos: int, length: int) -> None:
        """A sequenced string edit landed: slide every acked endpoint.
        Sided endpoints may transiently name one-past-the-end mid-op (a
        forward slide off a removed suffix); ``finalize_op`` clamps them
        once the whole op's ranges have been applied."""
        for iv in self.sequenced.values():
            if iv.sided:
                iv.start, iv.start_side = transform_place(
                    iv.start, iv.start_side, kind, pos, length
                )
                iv.end, iv.end_side = transform_place(
                    iv.end, iv.end_side, kind, pos, length
                )
                continue
            iv.start = transform_position(iv.start, kind, pos, length)
            iv.end = transform_position(iv.end, kind, pos, length)
            if iv.end < iv.start:
                iv.end = iv.start

    def has_sided(self) -> bool:
        return any(iv.sided for iv in self.sequenced.values())

    def finalize_op(self, new_length: int) -> None:
        """After all ranges of one sequenced string op: degrade forward
        slides off the back of the string to the "end" sentinel, and
        collapse crossed endpoints to an empty interval at the start place
        (same boundary on both sides)."""
        for iv in self.sequenced.values():
            if not iv.sided:
                continue
            if iv.start != SENTINEL_POS and iv.start >= new_length:
                iv.start, iv.start_side = SENTINEL_POS, Side.BEFORE
            if iv.end != SENTINEL_POS and iv.end >= new_length:
                iv.end, iv.end_side = SENTINEL_POS, Side.BEFORE
            if place_boundary(iv.start, iv.start_side) > place_boundary(
                iv.end, iv.end_side
            ):
                iv.end, iv.end_side = iv.start, iv.start_side

    # ------------------------------------------------------------------ views
    def get(self, interval_id: str) -> SequenceInterval | None:
        """Optimistic read: pending local ops overlay the sequenced state."""
        iv = self.sequenced.get(interval_id)
        iv = SequenceInterval.from_json(iv.to_json()) if iv is not None else None
        for op in self._pending:
            if op["id"] != interval_id:
                continue
            if op["action"] == "add":
                iv = SequenceInterval(
                    op["id"], op["start"], op["end"], dict(op["props"]),
                    op.get("startSide"), op.get("endSide"),
                )
            elif op["action"] == "delete":
                iv = None
            elif op["action"] == "change" and iv is not None:
                _apply_change_endpoints(iv, op)
                if op["props"]:
                    iv.props.update(op["props"])
        return iv

    def ids(self) -> set[str]:
        out = set(self.sequenced)
        for op in self._pending:
            if op["action"] == "add":
                out.add(op["id"])
            elif op["action"] == "delete":
                out.discard(op["id"])
        return out

    def __iter__(self) -> Iterator[SequenceInterval]:
        n = self._length()
        return iter(sorted(
            (self.get(i) for i in self.ids()),
            key=lambda v: (v.first_char(n), v.last_char(n), v.interval_id),
        ))

    def overlapping(self, start: int, end: int) -> list[SequenceInterval]:
        """Intervals whose covered characters intersect [start, end], bounds
        inclusive — the reference's findOverlappingIntervals contract
        (intervalIndex/overlappingIntervalsIndex.ts)."""
        n = self._length()
        return [
            iv for iv in self
            if iv.first_char(n) <= end and iv.last_char(n) >= start
        ]

    # ------------------------------------------------------------ checkpoint
    def summarize(self) -> dict:
        if self._pending:
            raise RuntimeError("summarize with pending interval ops")
        return {"intervals": [iv.to_json() for iv in self.sequenced.values()]}

    def load(self, data: dict) -> None:
        self.sequenced = {
            e["id"]: SequenceInterval.from_json(e) for e in data["intervals"]
        }
