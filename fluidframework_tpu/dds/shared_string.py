"""SharedString: host-side client over a merge-tree backend.

Reference parity: merge-tree ``Client`` (client.ts — applyMsg:1358, local op
mint + pending-ack bookkeeping) and sequence ``SharedStringClass``.  The
backend is pluggable (the channel-boundary analog, ref
datastore-definitions/src/channel.ts): the pure-Python oracle
(``RefMergeTree``) or a slot in a batched TPU document store.

Wire op format (contents of a SequencedMessage for this channel):
    {"type": 0, "pos1": P, "seg": "text"}              insert
    {"type": 1, "pos1": A, "pos2": B}                  set-remove
    {"type": 2, "pos1": A, "pos2": B, "props": {...}}  annotate
mirroring merge-tree/src/ops.ts IMergeTreeOp (JSON-compatible so traces can
be replayed across implementations).
"""

from __future__ import annotations

from collections import deque
from dataclasses import dataclass
from typing import Any, Protocol

from ..protocol.messages import (
    DeltaType,
    MessageType,
    Nack,
    SequencedMessage,
    UnsequencedMessage,
)
from ..protocol.stamps import ALL_ACKED, encode_stamp
from .mergetree_ref import SIDE_AFTER, SIDE_BEFORE, RefMergeTree


def decode_obliterate_places(c: dict) -> tuple[int, int, int, int]:
    """Wire op -> (pos1, side1, pos2, side2) endpoint places.  The plain
    OBLITERATE form {pos1, pos2} is the sided range (pos1, Before) ..
    (pos2-1, After) (ref mergeTree.ts obliterateRange:2282)."""
    if c["type"] == int(DeltaType.OBLITERATE):
        return c["pos1"], SIDE_BEFORE, c["pos2"] - 1, SIDE_AFTER
    p1, p2 = c["pos1"], c["pos2"]
    return (
        p1["pos"], SIDE_BEFORE if p1["before"] else SIDE_AFTER,
        p2["pos"], SIDE_BEFORE if p2["before"] else SIDE_AFTER,
    )


def validate_obliterate_places(
    pos1: int, side1: int, pos2: int, side2: int, vis_len: int
) -> None:
    """Reject invalid sided places BEFORE submission: a backend that only
    latches error flags (the kernel) must not broadcast an op that would
    make every oracle-backed remote raise."""
    start = pos1 + (1 if side1 == SIDE_AFTER else 0)
    end = pos2 + (1 if side2 == SIDE_AFTER else 0)
    if not (0 <= pos1 <= pos2 < vis_len and start <= end):
        raise ValueError(
            f"obliterate places ({pos1},{side1})..({pos2},{side2}) invalid "
            f"for visible length {vis_len}"
        )


class MergeTreeBackend(Protocol):
    """What a merge-tree replica must support (oracle or TPU kernel slot)."""

    def apply_insert(self, pos: int, text: str, op_key: int, op_client: int, ref_seq: int) -> None: ...
    def apply_remove(self, pos1: int, pos2: int, op_key: int, op_client: int, ref_seq: int) -> None: ...
    def apply_annotate(self, pos1: int, pos2: int, prop: int, value: int, op_key: int, op_client: int, ref_seq: int) -> None: ...
    def apply_obliterate(self, pos1: int, side1: int, pos2: int, side2: int, op_key: int, op_client: int, ref_seq: int) -> None: ...
    def ack(self, local_seq: int, seq: int) -> None: ...
    def update_min_seq(self, min_seq: int) -> None: ...
    def visible_text(self, ref_seq: int = ALL_ACKED, view_client: int | None = None) -> str: ...


@dataclass
class PendingOp:
    local_seq: int
    contents: dict[str, Any]


class SharedString:
    """One client replica of a collaborative string.

    Local edits apply optimistically with pending stamps and are queued for
    the ordering service; sequenced messages flow back through ``process``
    (own ops ack, remote ops apply under the sender's perspective).
    """

    def __init__(self, client_id: str, backend: MergeTreeBackend | None = None) -> None:
        self.client_id = client_id
        self.short_client = -1  # assigned by our join message
        self.backend: MergeTreeBackend = backend if backend is not None else RefMergeTree()
        self._local_seq = 0
        self._client_seq = 0
        self._pending: deque[PendingOp] = deque()
        self._ref_seq = 0
        # clientId -> short numeric id, built from sequenced join messages
        # (the quorum table; reference derives stamp client ids the same way).
        self._quorum: dict[str, int] = {}
        self.outbox: list[UnsequencedMessage] = []

    def _require_joined(self) -> None:
        if self.short_client < 0:
            raise RuntimeError(
                f"client {self.client_id!r} cannot edit before its join is "
                "sequenced and delivered (short client id unassigned)"
            )

    # ------------------------------------------------------------- local edits
    def insert_text(self, pos: int, text: str) -> None:
        assert text
        from .markers import assert_no_marker_plane

        assert_no_marker_plane(text)
        self._require_joined()
        self._local_seq += 1
        self.backend.apply_insert(
            pos, text, encode_stamp(-1, self._local_seq), self.short_client, ALL_ACKED
        )
        self._submit({"type": int(DeltaType.INSERT), "pos1": pos, "seg": text})

    def remove_range(self, pos1: int, pos2: int) -> None:
        assert pos1 < pos2
        self._require_joined()
        self._local_seq += 1
        self.backend.apply_remove(
            pos1, pos2, encode_stamp(-1, self._local_seq), self.short_client, ALL_ACKED
        )
        self._submit({"type": int(DeltaType.REMOVE), "pos1": pos1, "pos2": pos2})

    def obliterate_range(self, pos1: int, pos2: int) -> None:
        """Slice-remove [pos1, pos2): also swallows concurrent inserts into
        the range (ref client.ts applyObliterateRangeOp:558)."""
        assert pos1 < pos2
        self._require_joined()
        self._local_seq += 1
        self.backend.apply_obliterate(
            pos1, SIDE_BEFORE, pos2 - 1, SIDE_AFTER,
            encode_stamp(-1, self._local_seq), self.short_client, ALL_ACKED,
        )
        self._submit(
            {"type": int(DeltaType.OBLITERATE), "pos1": pos1, "pos2": pos2}
        )

    def obliterate_range_sided(
        self, start: tuple[int, bool], end: tuple[int, bool]
    ) -> None:
        """Sided obliterate: endpoints are (char pos, before) places
        (ref ops.ts OBLITERATE_SIDED, client.ts:568)."""
        self._require_joined()
        s1 = SIDE_BEFORE if start[1] else SIDE_AFTER
        s2 = SIDE_BEFORE if end[1] else SIDE_AFTER
        validate_obliterate_places(
            start[0], s1, end[0], s2,
            self.backend.visible_length(ALL_ACKED, self.short_client),
        )
        self._local_seq += 1
        self.backend.apply_obliterate(
            start[0], s1, end[0], s2,
            encode_stamp(-1, self._local_seq), self.short_client, ALL_ACKED,
        )
        self._submit(
            {
                "type": int(DeltaType.OBLITERATE_SIDED),
                "pos1": {"pos": start[0], "before": start[1]},
                "pos2": {"pos": end[0], "before": end[1]},
            }
        )

    def annotate_range(self, pos1: int, pos2: int, prop: int, value: int) -> None:
        assert pos1 < pos2
        self._require_joined()
        self._local_seq += 1
        self.backend.apply_annotate(
            pos1, pos2, prop, value,
            encode_stamp(-1, self._local_seq), self.short_client, ALL_ACKED,
        )
        self._submit(
            {"type": int(DeltaType.ANNOTATE), "pos1": pos1, "pos2": pos2,
             "props": {str(prop): value}}
        )

    def _submit(self, contents: dict[str, Any]) -> None:
        self._client_seq += 1
        self._pending.append(PendingOp(self._local_seq, contents))
        self.outbox.append(
            UnsequencedMessage(
                client_id=self.client_id,
                client_seq=self._client_seq,
                ref_seq=self._ref_seq,
                type=MessageType.OP,
                contents=contents,
            )
        )

    def take_outbox(self) -> list[UnsequencedMessage]:
        out = self.outbox
        self.outbox = []
        return out

    # --------------------------------------------------------------- inbound
    def process(self, msg: SequencedMessage) -> None:
        """Apply one sequenced message (ref Client.applyMsg)."""
        if msg.type == MessageType.JOIN:
            self._quorum[msg.contents["clientId"]] = msg.contents["short"]
            if msg.client_id == self.client_id and self.short_client < 0:
                self.short_client = msg.contents["short"]
            self._after_apply(msg)
            return
        if msg.type != MessageType.OP:
            self._after_apply(msg)
            return

        if msg.client_id == self.client_id:
            pending = self._pending.popleft()
            self.backend.ack(pending.local_seq, msg.seq)
        else:
            self._apply_remote(msg)
        self._after_apply(msg)

    def process_nack(self, nack: Nack) -> None:
        """A nacked op invalidates this replica's pending state.

        The reference reacts by disconnecting and replaying pending ops on a
        fresh connection (PendingStateManager.replayPendingStates); until the
        resubmit path lands in the runtime layer, fail fast rather than wedge
        with a permanently mismatched pending queue.
        """
        raise RuntimeError(
            f"op nacked for {self.client_id!r} (clientSeq {nack.client_seq}): "
            f"{nack.reason}; reconnect/resubmit is required"
        )

    def _after_apply(self, msg: SequencedMessage) -> None:
        self._ref_seq = msg.seq
        self.backend.update_min_seq(msg.min_seq)

    def _apply_remote(self, msg: SequencedMessage) -> None:
        c = msg.contents
        kind = c["type"]
        key = msg.seq
        # Stamp client comes from the quorum table (join order), not from any
        # out-of-band field — keeps replicas wire-faithful for trace replay.
        client = self._quorum[msg.client_id]
        ref_seq = msg.ref_seq
        if kind == DeltaType.INSERT:
            self.backend.apply_insert(c["pos1"], c["seg"], key, client, ref_seq)
        elif kind == DeltaType.REMOVE:
            self.backend.apply_remove(c["pos1"], c["pos2"], key, client, ref_seq)
        elif kind == DeltaType.ANNOTATE:
            for prop, value in c["props"].items():
                self.backend.apply_annotate(
                    c["pos1"], c["pos2"], int(prop), value, key, client, ref_seq
                )
        elif kind in (DeltaType.OBLITERATE, DeltaType.OBLITERATE_SIDED):
            p1, s1, p2, s2 = decode_obliterate_places(c)
            self.backend.apply_obliterate(p1, s1, p2, s2, key, client, ref_seq)
        else:
            raise ValueError(f"unsupported merge-tree op type {kind}")

    # ----------------------------------------------------------------- views
    @property
    def text(self) -> str:
        return self.backend.visible_text(ALL_ACKED, self.short_client)

    @property
    def current_seq(self) -> int:
        """Last sequence number this replica has applied (reference
        Client.getCurrentSeq)."""
        return self._ref_seq
