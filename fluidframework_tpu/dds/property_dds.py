"""PropertyDDS seed: a typed property tree over OT changesets.

Reference parity: `experimental/PropertyDDS/packages/` — property-dds
(SharedPropertyTree), property-changeset (SerializedChangeSet algebra),
property-properties (the typed property model).  The reference's data
model: a document is a tree of TYPED properties — primitive leaves
(Int32/Float64/String/Bool) and ``NodeProperty`` containers — mutated by
changesets with ``insert``/``modify``/``remove`` sections keyed by type id
then property name, nested recursively for containers.

This seed reproduces that model on this repo's SharedOT base
(MSN-windowed transform, dds/ot.py): a changeset is the OT op;
``transform`` implements the property-changeset rebase rules —

- edits under a concurrently removed property drop (the subtree is gone);
- insert/insert on one name: the later-sequenced insert wins (LWW);
- modify/modify on one primitive: later wins; on one container: recurse;
- disjoint names commute untouched.

Serialized state/changeset shapes follow the reference's nesting
(`{"insert": {typeid: {name: payload}}, "modify": …, "remove": [names]}`),
so property-changeset-shaped documents read naturally.
"""

from __future__ import annotations

import json
from typing import Any

from .ot import SharedOTChannel

NODE_TYPE = "NodeProperty"
PRIMITIVES = {"Int32", "Float64", "String", "Bool"}


# ---------------------------------------------------------------- documents
# state = {name: prop}; prop = {"typeid": t, "value": v} (primitive)
#                        | {"typeid": "NodeProperty", "children": {…}}


def _prop(typeid: str, payload: Any) -> dict:
    if typeid == NODE_TYPE:
        return {"typeid": NODE_TYPE, "children": dict(payload or {})}
    assert typeid in PRIMITIVES, f"unknown property type {typeid!r}"
    return {"typeid": typeid, "value": payload}


# ---------------------------------------------------------------- changesets


def make_insert(path: list[str], typeid: str, payload: Any = None) -> dict:
    """Insert a property at ``path`` (last part = new name)."""
    cs: dict = {"insert": {typeid: {path[-1]: payload}}}
    return _nest(path[:-1], cs)


def make_remove(path: list[str]) -> dict:
    return _nest(path[:-1], {"remove": [path[-1]]})


def make_modify(path: list[str], typeid: str, value: Any) -> dict:
    return _nest(path[:-1], {"modify": {typeid: {path[-1]: value}}})


def _nest(prefix: list[str], cs: dict) -> dict:
    for name in reversed(prefix):
        cs = {"modify": {NODE_TYPE: {name: cs}}}
    return cs


def apply_changeset(state: dict | None, cs: dict) -> dict:
    """Functional apply of one changeset to a {name: prop} map."""
    out = dict(state or {})
    for name in cs.get("remove", []):
        out.pop(name, None)
    for typeid, entries in cs.get("insert", {}).items():
        for name, payload in entries.items():
            out[name] = _prop(typeid, payload)
    for typeid, entries in cs.get("modify", {}).items():
        for name, change in entries.items():
            cur = out.get(name)
            if cur is None or cur["typeid"] != typeid:
                continue  # target gone (post-rebase residue): no-op
            if typeid == NODE_TYPE:
                out[name] = {
                    "typeid": NODE_TYPE,
                    "children": apply_changeset(cur["children"], change),
                }
            else:
                out[name] = {"typeid": typeid, "value": change}
    return out


def transform_changeset(input_cs: dict | None, earlier: dict | None) -> dict | None:
    """Rebase ``input_cs`` over ``earlier`` (applied first) — the
    property-changeset rebase rules (see module docstring)."""
    if input_cs is None or earlier is None:
        return input_cs
    removed = set(earlier.get("remove", []))
    e_ins = {
        name: typeid
        for typeid, entries in earlier.get("insert", {}).items()
        for name in entries
    }
    e_mod: dict[str, tuple[str, Any]] = {
        name: (typeid, change)
        for typeid, entries in earlier.get("modify", {}).items()
        for name, change in entries.items()
    }

    out: dict = {}
    rm = [n for n in input_cs.get("remove", []) if n not in removed]
    if rm:
        out["remove"] = rm
    for typeid, entries in input_cs.get("insert", {}).items():
        # Later insert wins over an earlier insert OR remove of the name.
        kept = dict(entries)
        if kept:
            out.setdefault("insert", {})[typeid] = kept
    for typeid, entries in input_cs.get("modify", {}).items():
        kept = {}
        for name, change in entries.items():
            if name in removed:
                continue  # subtree gone
            if name in e_ins and e_ins[name] != typeid:
                continue  # replaced by a different type
            if typeid == NODE_TYPE and name in e_mod and e_mod[name][0] == NODE_TYPE:
                nested = transform_changeset(change, e_mod[name][1])
                if nested:
                    kept[name] = nested
                continue
            # Primitive modify-modify: the later op simply applies after
            # (LWW by order) — keep as-is.
            kept[name] = change
        if kept:
            out.setdefault("modify", {})[typeid] = kept
    return out or None


# ------------------------------------------------------------------ channel


class PropertyTreeChannel(SharedOTChannel):
    """SharedPropertyTree seed (ref property-dds/src/propertyTree.ts)."""

    channel_type = "propertyTree"

    def __init__(self, channel_id: str) -> None:
        super().__init__(channel_id, initial={})

    def apply_core(self, state: Any, cs: dict | None) -> Any:
        return apply_changeset(state, cs) if cs else state

    def transform(self, input_op, earlier):
        return transform_changeset(input_op, earlier)

    # ------------------------------------------------------------ public API
    def root(self) -> dict:
        return self.state

    def resolve_path(self, path: list[str]) -> dict | None:
        """The property at a name path, or None (ref resolvePath)."""
        node: Any = {"typeid": NODE_TYPE, "children": self.state}
        for name in path:
            if node is None or node["typeid"] != NODE_TYPE:
                return None
            node = node["children"].get(name)
        return node

    def value_at(self, path: list[str]) -> Any:
        prop = self.resolve_path(path)
        return None if prop is None else prop.get("value")

    def insert_property(self, path: list[str], typeid: str, payload: Any = None) -> None:
        json.dumps(payload)
        self.apply(make_insert(path, typeid, payload))

    def remove_property(self, path: list[str]) -> None:
        self.apply(make_remove(path))

    def set_value(self, path: list[str], value: Any) -> None:
        prop = self.resolve_path(path)
        assert prop is not None and prop["typeid"] in PRIMITIVES, path
        json.dumps(value)
        self.apply(make_modify(path, prop["typeid"], value))


class _PropertyTreeFactory:
    channel_type = PropertyTreeChannel.channel_type

    def create(self, channel_id: str) -> PropertyTreeChannel:
        return PropertyTreeChannel(channel_id)


PropertyTreeFactory = _PropertyTreeFactory()
